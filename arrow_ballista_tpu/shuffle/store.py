"""Pluggable shuffle storage: the external (object-store-style) backend.

The shuffle data plane has three backends (``ballista.shuffle.store``):

* ``local`` — today's fast path, unchanged: Arrow IPC files under the
  producing executor's work_dir, served over Flight (and read directly
  when the consumer shares the filesystem);
* ``mem`` — the executor-memory store (:mod:`shuffle.memory_store`),
  equivalent to the pre-existing ``ballista.shuffle.to_memory``;
* ``external`` — a shared directory (``ballista.shuffle.external_path``)
  standing in for S3/GCS/a dedicated shuffle service: partitions written
  there survive their producer, so executors become disposable.

On top of the local/mem backends, ``ballista.shuffle.replication``
uploads a **replica** of each finished partition into the external
directory — ``sync`` before the task reports, ``async`` via the
process-wide :class:`Replicator` background uploader.  The replica path
is a pure function of the primary path (:func:`external_replica_path`),
so the write side, the executor's drain-time upload and the scheduler's
repoint-at-executor-loss all agree on where a copy lives without any
extra wire protocol.

Layout under the external root mirrors the work_dir layout exactly::

    <root>/<job>/<stage>/<out_partition>/data-<in>.arrow   (file primary)
    <root>/<job>/<stage>/<out_partition>/mem-<in>.arrow    (mem primary)

Uploads are atomic (tmp + rename) so a reader never sees half a replica,
and both directions carry fault points (``shuffle.store.upload`` /
``shuffle.store.download``) so the degradation paths are testable: a
replica-upload failure degrades to single-copy, never fails the task.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Iterator, List, Optional, Tuple

import pyarrow as pa

from ..serde.scheduler_types import ExecutorMetadata

log = logging.getLogger(__name__)

# Sentinel executor identity stamped on PartitionLocations that point at
# the external store: no Flight endpoint, never matches a lost executor,
# so ``reset_stages``/``remove_input_partitions`` can never strip it.
EXTERNAL_EXECUTOR_ID = "__external__"
EXTERNAL_EXECUTOR = ExecutorMetadata(EXTERNAL_EXECUTOR_ID, "", 0, 0)

_ARROW_FILE_MAGIC = b"ARROW1"


def is_external_location(loc) -> bool:
    meta = getattr(loc, "executor_meta", None)
    return getattr(meta, "id", "") == EXTERNAL_EXECUTOR_ID


def is_under_root(root: str, path: str) -> bool:
    """Is ``path`` inside the external root DIRECTORY?  A raw prefix test
    would let '/data/ext-work/...' pass for root '/data/ext' and make the
    scheduler mistake a dead executor's private file for a surviving
    external copy — normalize and require a separator boundary."""
    if not root or not path:
        return False
    root_n = os.path.normpath(root)
    path_n = os.path.normpath(path)
    return path_n == root_n or path_n.startswith(root_n + os.sep)


def external_replica_path(external_root: str, primary_path: str) -> Optional[str]:
    """The external-store path holding (or destined to hold) the replica
    of ``primary_path`` — a pure function so writer, drain upload and
    scheduler repoint agree without coordination.

    File primaries live at ``work_dir/<job>/<stage>/<out>/<name>``: the
    last four components relocate under the root.  Memory primaries
    (``mem://job/stage/out/in``) map to ``<job>/<stage>/<out>/mem-<in>.arrow``.
    Returns None when the path has no derivable key."""
    if not external_root or not primary_path:
        return None
    from . import memory_store

    key = memory_store.parse_path(primary_path)
    if key is not None:
        job, stage, out, in_part = key
        return os.path.join(
            external_root, job, str(stage), str(out), f"mem-{in_part}.arrow"
        )
    parts = [p for p in primary_path.replace("\\", "/").split("/") if p]
    if len(parts) < 4:
        return None
    return os.path.join(external_root, *parts[-4:])


# ------------------------------------------------------------------ uploads
def _atomic_write(dest: str, writer_fn) -> None:
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    tmp = f"{dest}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        writer_fn(tmp)
        os.replace(tmp, dest)  # atomic: a reader never sees half a replica
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def upload_file(src: str, dest: str) -> int:
    """Copy one finished partition file into the external store.
    Returns the bytes uploaded; raises on failure (callers degrade)."""
    from ..testing.faults import fault_point

    fault_point("shuffle.store.upload", src=src, dest=dest)
    _atomic_write(dest, lambda tmp: shutil.copyfile(src, tmp))
    _count_upload(os.path.getsize(dest))
    return os.path.getsize(dest)


def upload_buffer(buf, dest: str) -> int:
    """Write an already-serialized IPC buffer (a mem:// partition) into
    the external store."""
    from ..testing.faults import fault_point

    fault_point("shuffle.store.upload", src="<buffer>", dest=dest)

    def _write(tmp: str) -> None:
        with open(tmp, "wb") as f:
            f.write(buf)

    _atomic_write(dest, _write)
    _count_upload(len(buf) if hasattr(buf, "__len__") else buf.size)
    return os.path.getsize(dest)


def read_batches(path: str) -> Iterator[pa.RecordBatch]:
    """Stream one external-store partition.  Sniffs the container format:
    file primaries replicate as Arrow IPC FILES, mem primaries as IPC
    STREAMS — the magic bytes disambiguate.  The download fault point
    lets tests fail/delay replica reads deterministically."""
    from ..testing.faults import fault_point

    fault_point("shuffle.store.download", path=path)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no such external shuffle partition {path!r}")
    with open(path, "rb") as probe:
        magic = probe.read(len(_ARROW_FILE_MAGIC))
    with pa.OSFile(path, "rb") as f:
        if magic == _ARROW_FILE_MAGIC:
            reader = pa.ipc.open_file(f)
            for i in range(reader.num_record_batches):
                yield reader.get_batch(i)
        else:
            with pa.ipc.open_stream(f) as reader:
                yield from reader


def read_schema(path: str) -> pa.Schema:
    """Schema of one external-store partition (same format sniff as
    :func:`read_batches`) — zero-row partitions still need one."""
    with open(path, "rb") as probe:
        magic = probe.read(len(_ARROW_FILE_MAGIC))
    with pa.OSFile(path, "rb") as f:
        if magic == _ARROW_FILE_MAGIC:
            return pa.ipc.open_file(f).schema
        with pa.ipc.open_stream(f) as reader:
            return reader.schema


def delete_job(external_root: str, job_id: str) -> None:
    """External-store analogue of the work-dir janitor's job sweep."""
    if not external_root or not job_id:
        return
    path = os.path.join(external_root, job_id)
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)


# ------------------------------------------------- process-wide bookkeeping
# The executor learns the external root from task props (session config)
# — drain-time uploads need it after the last task finished, so the most
# recent value is remembered process-wide.
_noted_lock = threading.Lock()
_noted_external_root = ""


def note_external_root(path: str) -> None:
    global _noted_external_root
    if path:
        with _noted_lock:
            _noted_external_root = path


def noted_external_root() -> str:
    with _noted_lock:
        return _noted_external_root


def _counter(name: str, desc: str):
    # process_registry().counter is idempotent (returns the existing
    # counter by name), so no extra caching layer is needed here — the
    # upload paths are not hot enough to warrant one
    from ..obs.registry import process_registry

    return process_registry().counter(name, desc)


def _count_upload(nbytes: int) -> None:
    _counter(
        "shuffle_replicas_written_total",
        "shuffle partition replicas uploaded to the external store",
    ).inc()
    _counter(
        "shuffle_replica_bytes_total",
        "bytes uploaded to the external shuffle store",
    ).inc(int(nbytes))


def count_upload_failure() -> None:
    _counter(
        "shuffle_replica_upload_failures_total",
        "replica uploads that failed (degraded to single copy)",
    ).inc()


# --------------------------------------------------------------- replicator
class Replicator:
    """Process-wide background uploader for ``replication=async``: the
    writer pool hands finished partitions here and task completion never
    waits on the external store.  Failures degrade to single copy (the
    scheduler's failover then falls back to recompute if the primary is
    also gone) — they are counted, logged and otherwise swallowed."""

    def __init__(self, max_queue: int = 1024):
        import queue

        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # in-flight accounting under a condition variable: flush() must
        # not return while ANY submitted upload is unfinished — an
        # Event-based "queue looked empty" check races submit and would
        # let a drain exit with an upload still pending
        self._pending = 0
        self._cv = threading.Condition(self._lock)

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="shuffle-replicator", daemon=True
            )
            self._thread.start()

    def _submit(self, item) -> None:
        with self._cv:
            self._pending += 1
            self._ensure_thread()
        self._q.put(item)

    def submit_file(self, src: str, dest: str) -> None:
        self._submit(("file", src, dest))

    def submit_buffer(self, buf, dest: str) -> None:
        self._submit(("buffer", buf, dest))

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every SUBMITTED upload finished (drain path).
        True when the backlog drained inside the timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    def _run(self) -> None:
        import queue

        while True:
            try:
                kind, src, dest = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                if kind == "file":
                    upload_file(src, dest)
                else:
                    upload_buffer(src, dest)
            except Exception as e:  # noqa: BLE001 - degrade, never propagate
                count_upload_failure()
                log.warning("async replica upload to %s failed: %s", dest, e)
            finally:
                self._q.task_done()
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()


_replicator: Optional[Replicator] = None
_replicator_lock = threading.Lock()


def replicator() -> Replicator:
    global _replicator
    with _replicator_lock:
        if _replicator is None:
            _replicator = Replicator()
        return _replicator


def replicator_backlog() -> int:
    """Uploads submitted but unfinished, WITHOUT creating the replicator
    (the telemetry sampler reads this every heartbeat on executors that
    may never replicate anything)."""
    with _replicator_lock:
        rep = _replicator
    if rep is None:
        return 0
    with rep._cv:
        return rep._pending


# ------------------------------------------------------------- drain upload
def drain_upload(
    work_dir: str, external_root: str
) -> Tuple[int, List[str]]:
    """Decommission path: upload every shuffle partition still held by
    this executor — work_dir IPC files and mem:// store buffers — that
    the external store doesn't already have.  Returns
    ``(uploaded_count, failed_dests)``; failures degrade (the scheduler's
    recompute path covers whatever didn't make it)."""
    from . import memory_store

    uploaded = 0
    failed: List[str] = []
    if not external_root:
        return 0, []
    # 1) file partitions: work_dir/<job>/<stage>/<out>/<name>.arrow
    try:
        jobs = sorted(os.listdir(work_dir)) if work_dir else []
    except OSError:
        jobs = []
    for job in jobs:
        job_dir = os.path.join(work_dir, job)
        if job == ".memspool" or not os.path.isdir(job_dir):
            continue
        for root, _dirs, files in os.walk(job_dir):
            for name in files:
                if not name.endswith(".arrow"):
                    continue
                src = os.path.join(root, name)
                dest = external_replica_path(external_root, src)
                if dest is None or os.path.exists(dest):
                    continue
                try:
                    upload_file(src, dest)
                    uploaded += 1
                except Exception as e:  # noqa: BLE001 - degrade
                    count_upload_failure()
                    failed.append(dest)
                    log.warning("drain upload of %s failed: %s", src, e)
    # 2) memory partitions
    for job in memory_store.job_ids():
        for path, buf in memory_store.job_entries(job):
            dest = external_replica_path(external_root, path)
            if dest is None or os.path.exists(dest):
                continue
            try:
                upload_buffer(buf, dest)
                uploaded += 1
            except Exception as e:  # noqa: BLE001 - degrade
                count_upload_failure()
                failed.append(dest)
                log.warning("drain upload of %s failed: %s", path, e)
    return uploaded, failed
