from .execution_plans import (
    WRITE_STATS_SCHEMA,
    ShuffleReaderExec,
    ShuffleWriterExec,
    UnresolvedShuffleExec,
    partition_indices,
)

__all__ = [
    "ShuffleReaderExec",
    "ShuffleWriterExec",
    "UnresolvedShuffleExec",
    "WRITE_STATS_SCHEMA",
    "partition_indices",
]
