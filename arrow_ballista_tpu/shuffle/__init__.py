from .execution_plans import (
    WRITE_STATS_SCHEMA,
    ShuffleReaderExec,
    ShuffleWriterExec,
    UnresolvedShuffleExec,
    partition_indices,
)
from .fetcher import FetchPolicy, ShuffleFetcher, fetch_location

__all__ = [
    "FetchPolicy",
    "ShuffleFetcher",
    "ShuffleReaderExec",
    "ShuffleWriterExec",
    "UnresolvedShuffleExec",
    "WRITE_STATS_SCHEMA",
    "fetch_location",
    "partition_indices",
]
