"""Concurrent pipelined shuffle fetch.

The reduce side of every multi-stage query reads N map-side
``PartitionLocation``s.  The original ``ShuffleReaderExec`` walked them one
at a time and fully materialized each location before yielding — a 64-map
stage paid 64 serial round trips with the device idle during every one.
This module rebuilds that data plane as a pipeline (PAPERS.md
"Benchmarking Apache Arrow Flight": wire speed needs multiple concurrent
DoGet streams):

* a per-reader pool of daemon threads fans out over the locations,
  claiming them from a shared cursor — local-file, memory-store and
  Flight sources stream through the same :func:`fetch_location` path;
* batches flow into a :class:`_PrefetchQueue` bounded by BYTES (not batch
  count — map fragments vary from KBs to tens of MBs), so a fast producer
  backpressures instead of buffering the whole stage in host memory;
* the consumer yields batches as they arrive, in whatever order the
  locations complete — merged-multiset semantics, same rows;
* each location gets retry with exponential backoff; a failed attempt
  drops the cached Flight connection (``BallistaClient.invalidate``) so
  the retry reconnects instead of reusing a dead channel, and a retry
  after a mid-stream failure skips the batches already delivered (per
  location the serving order is deterministic: IPC file order).

Metrics (into the owning operator's registry): ``bytes_fetched``,
``fetch_time_ns`` (summed per-location latency), ``locations_fetched``,
``fetch_retries``, ``fetch_queue_full_ns`` (producer backpressure time),
``fetch_wait_time_ns`` (consumer starvation time) and
``peak_locations_in_flight`` (peak concurrency per execute; sums across
executes of the same operator).

Queued-but-unconsumed bytes are tracked by this module's jax-free
staging counters; ``ops.device_cache.stats()`` surfaces them as
``staging_bytes`` next to pinned HBM.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

import pyarrow as pa

from ..obs import trace as obs_trace

log = logging.getLogger(__name__)


class _TeeMetrics:
    """Forward operator-metric adds into the process-wide registry
    (obs/registry.py) so data-plane totals are scrapable per process,
    while the per-operator set keeps feeding stage metrics unchanged.
    ``names`` maps operator metric -> registry counter; the default map
    covers the fetch side, ``shuffle/writer.py`` passes the write map."""

    _FETCH_NAMES = {
        "bytes_fetched": "shuffle_bytes_fetched_total",
        "fetch_retries": "shuffle_fetch_retries_total",
        "locations_fetched": "shuffle_locations_fetched_total",
        "fetch_queue_full_ns": "shuffle_fetch_queue_full_ns_total",
        "fetch_wait_time_ns": "shuffle_fetch_wait_ns_total",
        "replica_fetches": "shuffle_replica_fetches_total",
    }
    _counters: dict = {}
    _counters_lock = threading.Lock()

    __slots__ = ("_inner", "_names")

    def __init__(self, inner, names: Optional[dict] = None):
        self._inner = inner
        self._names = names if names is not None else self._FETCH_NAMES

    @classmethod
    def _counter(cls, name: str):
        c = cls._counters.get(name)
        if c is None:
            from ..obs.registry import process_registry

            with cls._counters_lock:
                c = cls._counters.get(name)
                if c is None:
                    c = process_registry().counter(
                        name, "shuffle data-plane total"
                    )
                    cls._counters[name] = c
        return c

    def add(self, name: str, v: int) -> None:
        self._inner.add(name, v)
        reg_name = self._names.get(name)
        if reg_name is not None:
            self._counter(reg_name).inc(v)

# Host-side staging accounting: bytes sitting in prefetch queues (fetched
# but not yet consumed).  Lives HERE, jax-free — ops.device_cache.stats()
# surfaces it next to pinned HBM, but a CPU-only executor must not pay
# the ops-package jax import just to count queue bytes.
_staging_lock = threading.Lock()
_staging_bytes = 0


def staging_add(n_bytes: int) -> None:
    global _staging_bytes
    with _staging_lock:
        _staging_bytes += n_bytes


def staging_sub(n_bytes: int) -> None:
    global _staging_bytes
    with _staging_lock:
        _staging_bytes -= n_bytes
        if _staging_bytes < 0:  # defensive: never report negative pressure
            _staging_bytes = 0


def staging_bytes() -> int:
    with _staging_lock:
        return _staging_bytes


@dataclass(frozen=True)
class FetchPolicy:
    """Reader-side fetch knobs (see ``ballista.shuffle.fetch_*``)."""

    concurrency: int = 8
    prefetch_bytes: int = 64 << 20
    retries: int = 3
    backoff_s: float = 0.05

    @staticmethod
    def from_config(config) -> "FetchPolicy":
        return FetchPolicy(
            concurrency=config.shuffle_fetch_concurrency,
            prefetch_bytes=config.shuffle_prefetch_bytes,
            retries=config.shuffle_fetch_retries,
            backoff_s=config.shuffle_fetch_backoff_ms / 1000.0,
        )


def fetch_location(loc) -> Iterator[pa.RecordBatch]:
    """Stream one map-side partition: external store, memory-store fast
    path, local IPC file, Arrow Flight otherwise — the single
    source-dispatch behind every shuffle read."""
    from . import memory_store, store

    if store.is_external_location(loc):
        # external-store partition (replica failover or store=external):
        # read the shared path directly; there is no Flight endpoint to
        # fall back to, so a missing file fails fast into the retry loop
        yield from store.read_batches(loc.path)
        return
    if loc.path and loc.path.startswith(memory_store.SCHEME):
        hit = memory_store.get(loc.path)
        if hit is not None:
            yield from hit[1]
            return
        # A miss here is either janitor eviction or a partition produced
        # by ANOTHER executor (whose Flight service serves mem:// paths
        # from its own store).  Never silent: recovery from a genuinely
        # lost partition starts from this line.
        log.warning(
            "memory shuffle partition %s not in the local store (evicted "
            "or remote); falling back to Flight from %s:%s",
            loc.path,
            loc.executor_meta.host,
            loc.executor_meta.flight_port,
        )
    elif loc.path and os.path.exists(loc.path):
        with pa.OSFile(loc.path, "rb") as f:
            reader = pa.ipc.open_file(f)
            for i in range(reader.num_record_batches):
                yield reader.get_batch(i)
        return
    from ..flight.client import BallistaClient

    client = BallistaClient.get(
        loc.executor_meta.host, loc.executor_meta.flight_port
    )
    # trace context crosses the Flight hop as gRPC metadata so the
    # SERVING executor's do_get span stitches into this job's trace;
    # the kwarg is only passed when tracing — client doubles without it
    # keep working untraced
    headers = obs_trace.propagation_headers()
    if headers:
        yield from client.fetch_partition(
            loc.partition_id.job_id,
            loc.partition_id.stage_id,
            loc.partition_id.partition_id,
            loc.path,
            headers=headers,
        )
    else:
        yield from client.fetch_partition(
            loc.partition_id.job_id,
            loc.partition_id.stage_id,
            loc.partition_id.partition_id,
            loc.path,
        )


def fetch_candidates(loc) -> list:
    """Every known copy of one map-side partition, in preference order:
    the executor-served primary first, the external-store replica second.
    The scheduler threads the full replica set through the location
    itself (``PartitionLocation.replica_path``), so each candidate gets
    an INDEPENDENT retry budget instead of the whole budget burning on a
    dead primary while a live copy waits."""
    candidates = [loc]
    replica = getattr(loc, "replica_path", "")
    if replica and replica != getattr(loc, "path", ""):
        candidates.append(_ReplicaCandidate(loc, replica))
    return candidates


class _ReplicaCandidate:
    """External-store copy of a location: duck-types the
    PartitionLocation surface the fetch path reads (path / executor_meta
    / partition_id) without requiring the caller's location to be the
    real dataclass — test doubles ride through unchanged."""

    __slots__ = ("partition_id", "executor_meta", "path", "replica_path")

    def __init__(self, loc, replica_path: str):
        from .store import EXTERNAL_EXECUTOR

        self.partition_id = getattr(loc, "partition_id", None)
        self.executor_meta = EXTERNAL_EXECUTOR
        self.path = replica_path
        self.replica_path = ""


def retrying_fetch(
    loc,
    policy: FetchPolicy,
    metrics,
    fetch_fn: Optional[Callable[[object], Iterator[pa.RecordBatch]]] = None,
    stop_event: Optional[threading.Event] = None,
) -> Iterator[pa.RecordBatch]:
    """Stream one location with retry + exponential backoff and replica
    failover.

    Candidates (executor-served primary, then the external-store replica
    when the location names one) each get an INDEPENDENT
    ``fetch_retries`` budget; only when every copy is exhausted does the
    structured :class:`ShuffleFetchFailed` surface.  A retry or failover
    after a mid-stream failure skips the batches already delivered (per
    partition the serving order is deterministic: IPC file order — the
    replica is a byte copy of the primary), so failures never duplicate
    rows.  ``stop_event`` cuts a backoff wait short (the original error
    re-raises).
    """
    from ..errors import Cancelled
    from ..testing.faults import fault_point

    fetch = fetch_fn or fetch_location
    delivered = 0
    last_error: Optional[BaseException] = None
    candidates = fetch_candidates(loc)
    for ci, cand in enumerate(candidates):
        attempt = 0
        while True:
            try:
                fault_point(
                    "shuffle.fetch",
                    path=getattr(cand, "path", ""),
                    attempt=attempt,
                )
                skip = delivered
                for batch in fetch(cand):
                    if skip > 0:
                        skip -= 1
                        continue
                    yield batch
                    delivered += 1
                if ci > 0:
                    metrics.add("replica_fetches", 1)
                return
            except Exception as e:
                if isinstance(e, Cancelled):
                    raise
                last_error = e
                attempt += 1
                if attempt > policy.retries:
                    break  # this copy is spent: fail over to the next
                metrics.add("fetch_retries", 1)
                delay = policy.backoff_s * (2 ** (attempt - 1))
                log.warning(
                    "shuffle fetch of %s failed (attempt %d/%d): %s; "
                    "retrying in %.0fms",
                    getattr(cand, "path", cand),
                    attempt,
                    policy.retries,
                    e,
                    delay * 1e3,
                )
                if stop_event is not None:
                    if stop_event.wait(delay):
                        raise
                else:
                    time.sleep(delay)
        if ci + 1 < len(candidates):
            log.warning(
                "shuffle fetch of %s exhausted its budget; failing over "
                "to replica %s",
                getattr(cand, "path", cand),
                getattr(candidates[ci + 1], "path", ""),
            )
    raise _exhausted(loc, last_error) from last_error


def _exhausted(loc, error: BaseException) -> BaseException:
    """Retry budget spent on one location: surface a structured
    :class:`ShuffleFetchFailed` naming the producer partition and serving
    executor, so the scheduler can recompute exactly the lost map output
    (``scheduler/failure.py``).  Cancellation and bare test doubles
    (locations without scheduler coordinates) re-raise unchanged."""
    from ..errors import Cancelled, ShuffleFetchFailed

    if isinstance(error, (Cancelled, ShuffleFetchFailed)):
        return error
    pid = getattr(loc, "partition_id", None)
    meta = getattr(loc, "executor_meta", None)
    if pid is None or meta is None:
        return error
    return ShuffleFetchFailed(
        pid.stage_id,
        pid.partition_id,
        getattr(meta, "id", ""),
        detail=f"{type(error).__name__}: {error}",
    )


class _Closed(Exception):
    """Internal: the pipeline was torn down (consumer gone or error)."""


class _PrefetchQueue:
    """Bounded-by-bytes handoff between fetch workers and the consumer.

    ``put`` blocks while the byte budget is exhausted — but always admits
    a batch when the queue is EMPTY, so a single batch larger than the
    whole budget cannot deadlock the pipeline.
    """

    def __init__(self, max_bytes: int, metrics) -> None:
        self._max = max(1, max_bytes)
        self._metrics = metrics
        self._dq: deque = deque()
        self._bytes = 0
        self._cv = threading.Condition()
        self._producers = 0
        self._closed = False

    def add_producer(self) -> None:
        with self._cv:
            self._producers += 1

    def producer_done(self) -> None:
        with self._cv:
            self._producers -= 1
            self._cv.notify_all()

    def put(self, batch: pa.RecordBatch, nbytes: int) -> None:
        with self._cv:
            t0 = None
            while self._bytes >= self._max and self._dq and not self._closed:
                if t0 is None:
                    t0 = time.monotonic_ns()
                self._cv.wait()
            if t0 is not None:
                self._metrics.add(
                    "fetch_queue_full_ns", time.monotonic_ns() - t0
                )
            if self._closed:
                raise _Closed()
            self._dq.append((batch, nbytes))
            self._bytes += nbytes
            staging_add(nbytes)
            self._cv.notify_all()

    def get(
        self, abort_event: Optional[threading.Event] = None
    ) -> Optional[pa.RecordBatch]:
        """Next batch, or None when every producer has finished, the
        queue was closed on error, or ``abort_event`` is set (nothing
        else can wake a consumer whose workers are all stuck inside a
        hung remote read — the caller re-checks the event on None)."""
        with self._cv:
            t0 = None
            while not self._dq and self._producers > 0 and not self._closed:
                if abort_event is not None and abort_event.is_set():
                    break
                if t0 is None:
                    t0 = time.monotonic_ns()
                self._cv.wait(0.25 if abort_event is not None else None)
            if t0 is not None:
                self._metrics.add(
                    "fetch_wait_time_ns", time.monotonic_ns() - t0
                )
            if not self._dq:
                return None
            batch, nbytes = self._dq.popleft()
            self._bytes -= nbytes
            staging_sub(nbytes)
            self._cv.notify_all()
            return batch

    def close(self) -> None:
        with self._cv:
            self._closed = True
            if self._bytes:
                staging_sub(self._bytes)
            self._dq.clear()
            self._bytes = 0
            self._cv.notify_all()


# Executor shutdown must be able to abort in-flight fetch pipelines (a
# worker blocked on a dead peer would otherwise pin its task thread):
# every live fetcher registers here with its owner token (the executing
# task's work_dir — unique per executor unless explicitly shared), so
# stopping ONE executor in a multi-executor process does not abort the
# others' fetches.
_active: "weakref.WeakSet[ShuffleFetcher]" = weakref.WeakSet()
_active_lock = threading.Lock()


def shutdown_active_fetchers(owner: Optional[str] = None) -> int:
    """Close in-flight fetch pipelines: those registered under ``owner``
    (an executor's work_dir), or every one in the process when None.
    Returns how many were closed (executor shutdown path)."""
    with _active_lock:
        fetchers = [
            f for f in _active if owner is None or f.owner == owner
        ]
    for f in fetchers:
        f.close(error=_aborted())
    return len(fetchers)


def _aborted():
    from ..errors import ExecutionError

    return ExecutionError("shuffle fetch aborted: executor shutting down")


class ShuffleFetcher:
    """One reader partition's fetch pipeline over its locations.

    ``fetch_fn`` is the per-location stream factory — injectable so tests
    can add deterministic latency or faults without a network.
    """

    def __init__(
        self,
        locations: list,
        policy: FetchPolicy,
        metrics,
        cancel_event: Optional[threading.Event] = None,
        fetch_fn: Optional[Callable[[object], Iterator[pa.RecordBatch]]] = None,
        owner: Optional[str] = None,
        trace_parent=None,
    ) -> None:
        self.owner = owner
        self._locations = list(locations)
        self._policy = policy
        self._metrics = _TeeMetrics(metrics)
        # explicit parent for per-location spans: fetch workers run on
        # their own threads, so thread-local context can't propagate
        self._trace_parent = trace_parent
        self._cancel = cancel_event
        self._fetch_fn = fetch_fn or fetch_location
        self._q = _PrefetchQueue(policy.prefetch_bytes, self._metrics)
        self._cursor = 0
        self._cursor_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._in_flight = 0
        self._peak_in_flight = 0
        self._peak_reported = False
        self._consumed = False

    # ------------------------------------------------------------ consumer
    def __iter__(self) -> Iterator[pa.RecordBatch]:
        # single-use: the location cursor is spent after one pass, so a
        # second iteration would silently yield nothing instead of rows
        if self._consumed:
            raise RuntimeError(
                "ShuffleFetcher is single-use; construct a new one to re-read"
            )
        self._consumed = True
        return self._iterate()

    def _iterate(self) -> Iterator[pa.RecordBatch]:
        n_workers = max(1, min(self._policy.concurrency, len(self._locations)))
        with _active_lock:
            _active.add(self)
        try:
            for i in range(n_workers):
                self._q.add_producer()
                try:
                    t = threading.Thread(
                        target=self._worker,
                        name=f"shuffle-fetch-{i}",
                        daemon=True,
                    )
                    t.start()
                except BaseException:
                    # the slot was counted but its worker never ran
                    self._q.producer_done()
                    raise
        except BaseException:
            # a failed spawn (e.g. thread exhaustion) must not leak the
            # already-started workers into a queue nobody will drain
            self.close()
            raise
        try:
            while True:
                batch = self._q.get(abort_event=self._cancel)
                if batch is None:
                    if self._cancel is not None and self._cancel.is_set():
                        raise _cancelled()
                    break
                yield batch
            if self._error is not None:
                raise self._error
        finally:
            self.close()
            self._report_peak()

    def close(self, error: Optional[BaseException] = None) -> None:
        """Tear the pipeline down.  ``error`` (external aborts) surfaces
        to the consumer instead of silently truncating the stream; the
        consumer's own finally-close passes None and raises nothing."""
        if error is not None and self._error is None:
            self._error = error
        self._stop.set()
        self._q.close()

    # ------------------------------------------------------------ producers
    def _next_index(self) -> Optional[int]:
        with self._cursor_lock:
            if self._cursor >= len(self._locations):
                return None
            i = self._cursor
            self._cursor += 1
            return i

    def _worker(self) -> None:
        try:
            while not self._stop.is_set():
                idx = self._next_index()
                if idx is None:
                    break
                self._fetch_one(self._locations[idx])
        except _Closed:
            pass
        except BaseException as e:  # first error wins; tears the pipe down
            if self._error is None:
                self._error = e
            self.close()
        finally:
            self._q.producer_done()

    def _enter_location(self) -> None:
        with self._cursor_lock:
            self._in_flight += 1
            self._peak_in_flight = max(self._peak_in_flight, self._in_flight)

    def _exit_location(self) -> None:
        with self._cursor_lock:
            self._in_flight -= 1

    def _report_peak(self) -> None:
        """Record peak concurrency once per pipeline — in the consumer's
        finally, so failed or aborted runs (where concurrency data
        matters most) still report it."""
        with self._cursor_lock:
            if self._peak_reported or self._peak_in_flight == 0:
                return
            self._peak_reported = True
            peak = self._peak_in_flight
        self._metrics.add("peak_locations_in_flight", peak)

    def _fetch_one(self, loc) -> None:
        """Stream one location into the queue via :func:`retrying_fetch`
        (retry/backoff + mid-stream resume shared with the sequential
        reader).  The location span (explicit parent — this is a worker
        thread) also installs the trace context this thread forwards over
        Flight metadata."""
        t0 = time.monotonic_ns()
        self._enter_location()
        try:
            if self._cancel is not None and self._cancel.is_set():
                raise _cancelled()
            span_cm = (
                obs_trace.span(
                    "shuffle.fetch.location",
                    parent=self._trace_parent,
                    path=getattr(loc, "path", ""),
                )
                if self._trace_parent is not None
                else obs_trace.NOOP
            )
            with span_cm as sp:
                total = 0
                for batch in retrying_fetch(
                    loc,
                    self._policy,
                    self._metrics,
                    fetch_fn=self._fetch_fn,
                    stop_event=self._stop,
                ):
                    nbytes = int(getattr(batch, "nbytes", 0) or 0)
                    self._q.put(batch, nbytes)
                    self._metrics.add("bytes_fetched", nbytes)
                    total += nbytes
                sp.set_attr("bytes", total)
            self._metrics.add("fetch_time_ns", time.monotonic_ns() - t0)
            self._metrics.add("locations_fetched", 1)
        finally:
            self._exit_location()


def _cancelled():
    from ..errors import Cancelled

    return Cancelled("task cancelled")
