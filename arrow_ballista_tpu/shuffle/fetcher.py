"""Concurrent pipelined shuffle fetch.

The reduce side of every multi-stage query reads N map-side
``PartitionLocation``s.  The original ``ShuffleReaderExec`` walked them one
at a time and fully materialized each location before yielding — a 64-map
stage paid 64 serial round trips with the device idle during every one.
This module rebuilds that data plane as a pipeline (PAPERS.md
"Benchmarking Apache Arrow Flight": wire speed needs multiple concurrent
DoGet streams):

* a per-reader pool of daemon threads fans out over the locations,
  claiming them from a shared cursor — local-file, memory-store and
  Flight sources stream through the same :func:`fetch_location` path;
* batches flow into a :class:`_PrefetchQueue` bounded by BYTES (not batch
  count — map fragments vary from KBs to tens of MBs), so a fast producer
  backpressures instead of buffering the whole stage in host memory;
* the consumer yields batches as they arrive, in whatever order the
  locations complete — merged-multiset semantics, same rows;
* each location gets retry with exponential backoff; a failed attempt
  drops the cached Flight connection (``BallistaClient.invalidate``) so
  the retry reconnects instead of reusing a dead channel, and a retry
  after a mid-stream failure skips the batches already delivered (per
  location the serving order is deterministic: IPC file order).

Metrics (into the owning operator's registry): ``bytes_fetched``,
``fetch_time_ns`` (summed per-location latency), ``locations_fetched``,
``fetch_retries``, ``fetch_queue_full_ns`` (producer backpressure time),
``fetch_wait_time_ns`` (consumer starvation time) and
``peak_locations_in_flight`` (peak concurrency per execute; sums across
executes of the same operator).

Queued-but-unconsumed bytes are tracked by this module's jax-free
staging counters; ``ops.device_cache.stats()`` surfaces them as
``staging_bytes`` next to pinned HBM.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

import pyarrow as pa

from ..obs import trace as obs_trace

log = logging.getLogger(__name__)


class _TeeMetrics:
    """Forward operator-metric adds into the process-wide registry
    (obs/registry.py) so data-plane totals are scrapable per process,
    while the per-operator set keeps feeding stage metrics unchanged.
    ``names`` maps operator metric -> registry counter; the default map
    covers the fetch side, ``shuffle/writer.py`` passes the write map."""

    _FETCH_NAMES = {
        "bytes_fetched": "shuffle_bytes_fetched_total",
        "fetch_retries": "shuffle_fetch_retries_total",
        "locations_fetched": "shuffle_locations_fetched_total",
        "fetch_queue_full_ns": "shuffle_fetch_queue_full_ns_total",
        "fetch_wait_time_ns": "shuffle_fetch_wait_ns_total",
        "replica_fetches": "shuffle_replica_fetches_total",
        # locality-aware data plane: how many locations were served
        # zero-copy off the local filesystem/memory store vs over Flight,
        # how many local bytes never crossed the wire, and how many DoGet
        # round trips the remote legs actually paid (batched multi-
        # partition fetch collapses N per-partition calls into few)
        "local_fetches": "shuffle_local_fetches_total",
        "remote_fetches": "shuffle_remote_fetches_total",
        "local_bytes": "shuffle_local_bytes_total",
        "fetch_round_trips": "shuffle_fetch_round_trips_total",
    }
    _counters: dict = {}
    _counters_lock = threading.Lock()

    __slots__ = ("_inner", "_names")

    def __init__(self, inner, names: Optional[dict] = None):
        self._inner = inner
        self._names = names if names is not None else self._FETCH_NAMES

    @classmethod
    def _counter(cls, name: str):
        c = cls._counters.get(name)
        if c is None:
            from ..obs.registry import process_registry

            with cls._counters_lock:
                c = cls._counters.get(name)
                if c is None:
                    c = process_registry().counter(
                        name, "shuffle data-plane total"
                    )
                    cls._counters[name] = c
        return c

    def add(self, name: str, v: int) -> None:
        self._inner.add(name, v)
        reg_name = self._names.get(name)
        if reg_name is not None:
            self._counter(reg_name).inc(v)

# Host-side staging accounting: bytes sitting in prefetch queues (fetched
# but not yet consumed).  Lives HERE, jax-free — ops.device_cache.stats()
# surfaces it next to pinned HBM, but a CPU-only executor must not pay
# the ops-package jax import just to count queue bytes.
_staging_lock = threading.Lock()
_staging_bytes = 0


def staging_add(n_bytes: int) -> None:
    global _staging_bytes
    with _staging_lock:
        _staging_bytes += n_bytes


def staging_sub(n_bytes: int) -> None:
    global _staging_bytes
    with _staging_lock:
        _staging_bytes -= n_bytes
        if _staging_bytes < 0:  # defensive: never report negative pressure
            _staging_bytes = 0


def staging_bytes() -> int:
    with _staging_lock:
        return _staging_bytes


@dataclass(frozen=True)
class FetchPolicy:
    """Reader-side fetch knobs (see ``ballista.shuffle.fetch_*`` and
    ``ballista.shuffle.local_transport``)."""

    concurrency: int = 8
    prefetch_bytes: int = 64 << 20
    retries: int = 3
    backoff_s: float = 0.05
    # same-host zero-copy transport: "auto" (executor host-identity
    # gated) or "off" (always Flight — the forced-remote A/B leg)
    local_transport: str = "auto"
    # one multi-partition DoGet per (host, chunk) instead of one round
    # trip per location (ballista.shuffle.fetch_batched)
    batched: bool = True

    @staticmethod
    def from_config(config) -> "FetchPolicy":
        return FetchPolicy(
            concurrency=config.shuffle_fetch_concurrency,
            prefetch_bytes=config.shuffle_prefetch_bytes,
            retries=config.shuffle_fetch_retries,
            backoff_s=config.shuffle_fetch_backoff_ms / 1000.0,
            local_transport=config.shuffle_local_transport,
            batched=config.shuffle_fetch_batched,
        )


def _count(metrics, name: str, v: int = 1) -> None:
    if metrics is not None and v:
        metrics.add(name, v)


def _counted_local(batches, metrics) -> Iterator[pa.RecordBatch]:
    """Yield a local zero-copy stream, accounting the bytes that never
    crossed the wire.  Like the transport-split counters generally,
    ``local_bytes`` counts per fetch ATTEMPT (a rare mid-stream retry of
    a local read re-counts the prefix it re-reads) — ``bytes_fetched``
    remains the exact delivered-bytes number."""
    for b in batches:
        _count(metrics, "local_bytes", int(getattr(b, "nbytes", 0) or 0))
        yield b


def fetch_location(
    loc, policy: Optional[FetchPolicy] = None, metrics=None
) -> Iterator[pa.RecordBatch]:
    """Stream one map-side partition: external store, memory-store fast
    path, same-host zero-copy mmap, Arrow Flight otherwise — the single
    source-dispatch behind every shuffle read.

    The local-vs-Flight choice for file partitions is a DELIBERATE
    transport decision (``shuffle/transport.py``): executor host
    identity, not the old accidental ``os.path.exists`` probe — on a
    multi-host deployment a coincidentally-existing foreign path must
    never be read as shuffle input.  ``policy.local_transport="off"``
    forces Flight (the A/B baseline); ``metrics`` (optional) receives
    the ``local_fetches``/``remote_fetches``/``local_bytes``/
    ``fetch_round_trips`` accounting."""
    from . import memory_store, store, transport

    local_transport = policy.local_transport if policy is not None else "auto"
    if store.is_external_location(loc):
        # external-store partition (replica failover or store=external):
        # read the shared path directly; there is no Flight endpoint to
        # fall back to, so a missing file fails fast into the retry loop
        _count(metrics, "remote_fetches")
        yield from store.read_batches(loc.path)
        return
    if loc.path and loc.path.startswith(memory_store.SCHEME):
        buf = memory_store.get_buffer(loc.path)
        if buf is not None:
            # zero-copy: batches are views over the stored IPC buffer
            _count(metrics, "local_fetches")
            with pa.ipc.open_stream(buf) as reader:
                yield from _counted_local(reader, metrics)
            return
        # A miss here is either janitor eviction or a partition produced
        # by ANOTHER executor (whose Flight service serves mem:// paths
        # from its own store).  Never silent: recovery from a genuinely
        # lost partition starts from this line.
        log.warning(
            "memory shuffle partition %s not in the local store (evicted "
            "or remote); falling back to Flight from %s:%s",
            loc.path,
            loc.executor_meta.host,
            loc.executor_meta.flight_port,
        )
    elif loc.path and transport.decide(loc, local_transport) == transport.LOCAL:
        if os.path.exists(loc.path):
            _count(metrics, "local_fetches")
            yield from _counted_local(
                transport.read_local_batches(loc.path), metrics
            )
            return
        # identity said local but the file is not visible here: two
        # co-hosted executors may run on ISOLATED filesystems (separate
        # containers/volumes advertising one IP) — degrade to Flight,
        # which serves from the producer's own filesystem, exactly like
        # the mem:// miss above.  A genuinely lost partition fails over
        # Flight too and lands in the same retry/recovery machinery.
        log.warning(
            "host-matched shuffle partition %s is not visible on this "
            "filesystem; falling back to Flight from %s:%s",
            loc.path,
            loc.executor_meta.host,
            loc.executor_meta.flight_port,
        )
    from ..flight.client import BallistaClient

    client = BallistaClient.get(
        loc.executor_meta.host, loc.executor_meta.flight_port
    )
    # trace context crosses the Flight hop as gRPC metadata so the
    # SERVING executor's do_get span stitches into this job's trace;
    # the kwarg is only passed when tracing — client doubles without it
    # keep working untraced
    headers = obs_trace.propagation_headers() or None
    _count(metrics, "remote_fetches")
    _count(metrics, "fetch_round_trips")
    if headers:
        yield from client.fetch_partition(
            loc.partition_id.job_id,
            loc.partition_id.stage_id,
            loc.partition_id.partition_id,
            loc.path,
            headers=headers,
        )
    else:
        yield from client.fetch_partition(
            loc.partition_id.job_id,
            loc.partition_id.stage_id,
            loc.partition_id.partition_id,
            loc.path,
        )


def fetch_candidates(loc) -> list:
    """Every known copy of one map-side partition, in preference order:
    the executor-served primary first, the external-store replica second.
    The scheduler threads the full replica set through the location
    itself (``PartitionLocation.replica_path``), so each candidate gets
    an INDEPENDENT retry budget instead of the whole budget burning on a
    dead primary while a live copy waits."""
    candidates = [loc]
    replica = getattr(loc, "replica_path", "")
    if replica and replica != getattr(loc, "path", ""):
        candidates.append(_ReplicaCandidate(loc, replica))
    return candidates


class _ReplicaCandidate:
    """External-store copy of a location: duck-types the
    PartitionLocation surface the fetch path reads (path / executor_meta
    / partition_id) without requiring the caller's location to be the
    real dataclass — test doubles ride through unchanged."""

    __slots__ = ("partition_id", "executor_meta", "path", "replica_path")

    def __init__(self, loc, replica_path: str):
        from .store import EXTERNAL_EXECUTOR

        self.partition_id = getattr(loc, "partition_id", None)
        self.executor_meta = EXTERNAL_EXECUTOR
        self.path = replica_path
        self.replica_path = ""


def retrying_fetch(
    loc,
    policy: FetchPolicy,
    metrics,
    fetch_fn: Optional[Callable[[object], Iterator[pa.RecordBatch]]] = None,
    stop_event: Optional[threading.Event] = None,
    delivered_hint: int = 0,
) -> Iterator[pa.RecordBatch]:
    """Stream one location with retry + exponential backoff and replica
    failover.

    Candidates (executor-served primary, then the external-store replica
    when the location names one) each get an INDEPENDENT
    ``fetch_retries`` budget; only when every copy is exhausted does the
    structured :class:`ShuffleFetchFailed` surface.  A retry or failover
    after a mid-stream failure skips the batches already delivered (per
    partition the serving order is deterministic: IPC file order — the
    replica is a byte copy of the primary), so failures never duplicate
    rows.  ``stop_event`` cuts a backoff wait short (the original error
    re-raises).  ``delivered_hint`` pre-counts batches the CALLER already
    delivered for this location (the batched-fetch fallback hands a
    partially-streamed location here), so the first attempt skips them
    instead of duplicating.
    """
    from ..errors import Cancelled
    from ..testing.faults import fault_point

    if fetch_fn is not None:
        fetch = fetch_fn
    else:

        def fetch(l):
            # late-bound module global so monkeypatched doubles win; a
            # single-arg double raises TypeError at GENERATOR CREATION
            # (argument binding, before any body runs), so the fallback
            # call is safe and keeps the old fetch_location(loc) contract
            fl = fetch_location
            try:
                return fl(l, policy=policy, metrics=metrics)
            except TypeError:
                return fl(l)

    delivered = max(0, delivered_hint)
    last_error: Optional[BaseException] = None
    candidates = fetch_candidates(loc)
    for ci, cand in enumerate(candidates):
        attempt = 0
        while True:
            try:
                fault_point(
                    "shuffle.fetch",
                    path=getattr(cand, "path", ""),
                    attempt=attempt,
                )
                skip = delivered
                for batch in fetch(cand):
                    if skip > 0:
                        skip -= 1
                        continue
                    yield batch
                    delivered += 1
                if ci > 0:
                    metrics.add("replica_fetches", 1)
                return
            except Exception as e:
                if isinstance(e, Cancelled):
                    raise
                last_error = e
                attempt += 1
                if attempt > policy.retries:
                    break  # this copy is spent: fail over to the next
                metrics.add("fetch_retries", 1)
                delay = policy.backoff_s * (2 ** (attempt - 1))
                log.warning(
                    "shuffle fetch of %s failed (attempt %d/%d): %s; "
                    "retrying in %.0fms",
                    getattr(cand, "path", cand),
                    attempt,
                    policy.retries,
                    e,
                    delay * 1e3,
                )
                if stop_event is not None:
                    if stop_event.wait(delay):
                        raise
                else:
                    time.sleep(delay)
        if ci + 1 < len(candidates):
            log.warning(
                "shuffle fetch of %s exhausted its budget; failing over "
                "to replica %s",
                getattr(cand, "path", cand),
                getattr(candidates[ci + 1], "path", ""),
            )
    raise _exhausted(loc, last_error) from last_error


def _exhausted(loc, error: BaseException) -> BaseException:
    """Retry budget spent on one location: surface a structured
    :class:`ShuffleFetchFailed` naming the producer partition and serving
    executor, so the scheduler can recompute exactly the lost map output
    (``scheduler/failure.py``).  Cancellation and bare test doubles
    (locations without scheduler coordinates) re-raise unchanged."""
    from ..errors import Cancelled, ShuffleFetchFailed

    if isinstance(error, (Cancelled, ShuffleFetchFailed)):
        return error
    pid = getattr(loc, "partition_id", None)
    meta = getattr(loc, "executor_meta", None)
    if pid is None or meta is None:
        return error
    return ShuffleFetchFailed(
        pid.stage_id,
        pid.partition_id,
        getattr(meta, "id", ""),
        detail=f"{type(error).__name__}: {error}",
    )


def _classify_unit(loc, policy: FetchPolicy):
    """Batched-fetch grouping key for one location: ``"single"`` when it
    is served without a per-partition Flight call (external store, local
    memory-store hit, same-host zero-copy file), else the Flight
    endpoint ``(host, flight_port)`` it must be streamed from."""
    from . import memory_store, store, transport

    if store.is_external_location(loc):
        return "single"
    path = getattr(loc, "path", "") or ""
    meta = getattr(loc, "executor_meta", None)
    if path.startswith(memory_store.SCHEME):
        if memory_store.get_buffer(path) is not None:
            return "single"
    elif (
        transport.decide(loc, policy.local_transport) == transport.LOCAL
        and os.path.exists(path)
    ):
        # existence-checked: an identity-matched but filesystem-invisible
        # partition (isolated co-hosted executors) rides the Flight batch
        return "single"
    host = getattr(meta, "host", "") if meta is not None else ""
    port = getattr(meta, "flight_port", 0) if meta is not None else 0
    if not host or not port:
        return "single"  # nothing to dial: let the single path error out
    return (host, port)


def plan_fetch_units(
    locations: list, policy: FetchPolicy, allow_batched: bool = True
) -> list:
    """Partition a reader's locations into fetch units (each a list of
    locations a worker claims atomically).

    Local/external/memory locations stay one-per-unit.  Remote Flight
    locations group by serving endpoint, and each endpoint's group splits
    into at most ``concurrency // n_endpoints`` chunks — so a 64-location
    single-host stage pays ~``concurrency`` multi-partition round trips
    (streams still overlap) instead of 64 per-partition DoGets, and a
    many-host stage keeps one stream per host."""
    if not allow_batched or not policy.batched or len(locations) <= 1:
        return [[l] for l in locations]
    units: list = []
    groups: dict = {}
    order: list = []  # deterministic unit order: first-seen endpoint
    for l in locations:
        key = _classify_unit(l, policy)
        if key == "single":
            units.append([l])
            continue
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(l)
    n_hosts = max(1, len(groups))
    chunks_per_host = max(1, policy.concurrency // n_hosts)
    for key in order:
        group = groups[key]
        # at least 2 locations per chunk (else batching buys nothing):
        # round trips at most halve even when concurrency >> group size
        n_chunks = min(chunks_per_host, (len(group) + 1) // 2)
        n_chunks = max(1, n_chunks)
        size = (len(group) + n_chunks - 1) // n_chunks
        for lo in range(0, len(group), size):
            units.append(group[lo : lo + size])
    return units


class _Closed(Exception):
    """Internal: the pipeline was torn down (consumer gone or error)."""


class _PrefetchQueue:
    """Bounded-by-bytes handoff between fetch workers and the consumer.

    ``put`` blocks while the byte budget is exhausted — but always admits
    a batch when the queue is EMPTY, so a single batch larger than the
    whole budget cannot deadlock the pipeline.
    """

    def __init__(self, max_bytes: int, metrics) -> None:
        self._max = max(1, max_bytes)
        self._metrics = metrics
        self._dq: deque = deque()
        self._bytes = 0
        self._cv = threading.Condition()
        self._producers = 0
        self._closed = False

    def add_producer(self) -> None:
        with self._cv:
            self._producers += 1

    def producer_done(self) -> None:
        with self._cv:
            self._producers -= 1
            self._cv.notify_all()

    def put(self, batch: pa.RecordBatch, nbytes: int) -> None:
        with self._cv:
            t0 = None
            while self._bytes >= self._max and self._dq and not self._closed:
                if t0 is None:
                    t0 = time.monotonic_ns()
                self._cv.wait()
            if t0 is not None:
                self._metrics.add(
                    "fetch_queue_full_ns", time.monotonic_ns() - t0
                )
            if self._closed:
                raise _Closed()
            self._dq.append((batch, nbytes))
            self._bytes += nbytes
            staging_add(nbytes)
            self._cv.notify_all()

    def get(
        self, abort_event: Optional[threading.Event] = None
    ) -> Optional[pa.RecordBatch]:
        """Next batch, or None when every producer has finished, the
        queue was closed on error, or ``abort_event`` is set (nothing
        else can wake a consumer whose workers are all stuck inside a
        hung remote read — the caller re-checks the event on None)."""
        with self._cv:
            t0 = None
            while not self._dq and self._producers > 0 and not self._closed:
                if abort_event is not None and abort_event.is_set():
                    break
                if t0 is None:
                    t0 = time.monotonic_ns()
                self._cv.wait(0.25 if abort_event is not None else None)
            if t0 is not None:
                self._metrics.add(
                    "fetch_wait_time_ns", time.monotonic_ns() - t0
                )
            if not self._dq:
                return None
            batch, nbytes = self._dq.popleft()
            self._bytes -= nbytes
            staging_sub(nbytes)
            self._cv.notify_all()
            return batch

    def close(self) -> None:
        with self._cv:
            self._closed = True
            if self._bytes:
                staging_sub(self._bytes)
            self._dq.clear()
            self._bytes = 0
            self._cv.notify_all()


# Executor shutdown must be able to abort in-flight fetch pipelines (a
# worker blocked on a dead peer would otherwise pin its task thread):
# every live fetcher registers here with its owner token (the executing
# task's work_dir — unique per executor unless explicitly shared), so
# stopping ONE executor in a multi-executor process does not abort the
# others' fetches.
_active: "weakref.WeakSet[ShuffleFetcher]" = weakref.WeakSet()
_active_lock = threading.Lock()


def shutdown_active_fetchers(owner: Optional[str] = None) -> int:
    """Close in-flight fetch pipelines: those registered under ``owner``
    (an executor's work_dir), or every one in the process when None.
    Returns how many were closed (executor shutdown path)."""
    with _active_lock:
        fetchers = [
            f for f in _active if owner is None or f.owner == owner
        ]
    for f in fetchers:
        f.close(error=_aborted())
    return len(fetchers)


def _aborted():
    from ..errors import ExecutionError

    return ExecutionError("shuffle fetch aborted: executor shutting down")


class ShuffleFetcher:
    """One reader partition's fetch pipeline over its locations.

    ``fetch_fn`` is the per-location stream factory — injectable so tests
    can add deterministic latency or faults without a network.
    """

    def __init__(
        self,
        locations: list,
        policy: FetchPolicy,
        metrics,
        cancel_event: Optional[threading.Event] = None,
        fetch_fn: Optional[Callable[[object], Iterator[pa.RecordBatch]]] = None,
        owner: Optional[str] = None,
        trace_parent=None,
    ) -> None:
        self.owner = owner
        self._locations = list(locations)
        self._policy = policy
        self._metrics = _TeeMetrics(metrics)
        # batched multi-partition fetch only applies to the REAL location
        # dispatch: an injected fetch_fn is per-location by contract
        self._units = plan_fetch_units(
            self._locations, policy, allow_batched=fetch_fn is None
        )
        # explicit parent for per-location spans: fetch workers run on
        # their own threads, so thread-local context can't propagate
        self._trace_parent = trace_parent
        self._cancel = cancel_event
        # None → retrying_fetch builds the policy/metrics-aware
        # fetch_location default (transport decision + locality counters)
        self._fetch_fn = fetch_fn
        self._q = _PrefetchQueue(policy.prefetch_bytes, self._metrics)
        self._cursor = 0
        self._cursor_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._in_flight = 0
        self._peak_in_flight = 0
        self._peak_reported = False
        self._consumed = False

    # ------------------------------------------------------------ consumer
    def __iter__(self) -> Iterator[pa.RecordBatch]:
        # single-use: the location cursor is spent after one pass, so a
        # second iteration would silently yield nothing instead of rows
        if self._consumed:
            raise RuntimeError(
                "ShuffleFetcher is single-use; construct a new one to re-read"
            )
        self._consumed = True
        return self._iterate()

    def _iterate(self) -> Iterator[pa.RecordBatch]:
        n_workers = max(1, min(self._policy.concurrency, len(self._units)))
        with _active_lock:
            _active.add(self)
        try:
            for i in range(n_workers):
                self._q.add_producer()
                try:
                    t = threading.Thread(
                        target=self._worker,
                        name=f"shuffle-fetch-{i}",
                        daemon=True,
                    )
                    t.start()
                except BaseException:
                    # the slot was counted but its worker never ran
                    self._q.producer_done()
                    raise
        except BaseException:
            # a failed spawn (e.g. thread exhaustion) must not leak the
            # already-started workers into a queue nobody will drain
            self.close()
            raise
        try:
            while True:
                batch = self._q.get(abort_event=self._cancel)
                if batch is None:
                    if self._cancel is not None and self._cancel.is_set():
                        raise _cancelled()
                    break
                yield batch
            if self._error is not None:
                raise self._error
        finally:
            self.close()
            self._report_peak()

    def close(self, error: Optional[BaseException] = None) -> None:
        """Tear the pipeline down.  ``error`` (external aborts) surfaces
        to the consumer instead of silently truncating the stream; the
        consumer's own finally-close passes None and raises nothing."""
        if error is not None and self._error is None:
            self._error = error
        self._stop.set()
        self._q.close()

    # ------------------------------------------------------------ producers
    def _next_index(self) -> Optional[int]:
        with self._cursor_lock:
            if self._cursor >= len(self._units):
                return None
            i = self._cursor
            self._cursor += 1
            return i

    def _worker(self) -> None:
        try:
            while not self._stop.is_set():
                idx = self._next_index()
                if idx is None:
                    break
                unit = self._units[idx]
                if len(unit) == 1:
                    self._fetch_one(unit[0])
                else:
                    self._fetch_unit(unit)
        except _Closed:
            pass
        except BaseException as e:  # first error wins; tears the pipe down
            if self._error is None:
                self._error = e
            self.close()
        finally:
            self._q.producer_done()

    def _enter_location(self) -> None:
        with self._cursor_lock:
            self._in_flight += 1
            self._peak_in_flight = max(self._peak_in_flight, self._in_flight)

    def _exit_location(self) -> None:
        with self._cursor_lock:
            self._in_flight -= 1

    def _report_peak(self) -> None:
        """Record peak concurrency once per pipeline — in the consumer's
        finally, so failed or aborted runs (where concurrency data
        matters most) still report it."""
        with self._cursor_lock:
            if self._peak_reported or self._peak_in_flight == 0:
                return
            self._peak_reported = True
            peak = self._peak_in_flight
        self._metrics.add("peak_locations_in_flight", peak)

    def _fetch_one(self, loc) -> None:
        """Stream one location into the queue via :func:`retrying_fetch`
        (retry/backoff + mid-stream resume shared with the sequential
        reader).  The location span (explicit parent — this is a worker
        thread) also installs the trace context this thread forwards over
        Flight metadata."""
        t0 = time.monotonic_ns()
        self._enter_location()
        try:
            if self._cancel is not None and self._cancel.is_set():
                raise _cancelled()
            span_cm = (
                obs_trace.span(
                    "shuffle.fetch.location",
                    parent=self._trace_parent,
                    path=getattr(loc, "path", ""),
                )
                if self._trace_parent is not None
                else obs_trace.NOOP
            )
            with span_cm as sp:
                total = 0
                for batch in retrying_fetch(
                    loc,
                    self._policy,
                    self._metrics,
                    fetch_fn=self._fetch_fn,
                    stop_event=self._stop,
                ):
                    nbytes = int(getattr(batch, "nbytes", 0) or 0)
                    self._q.put(batch, nbytes)
                    self._metrics.add("bytes_fetched", nbytes)
                    total += nbytes
                sp.set_attr("bytes", total)
            self._metrics.add("fetch_time_ns", time.monotonic_ns() - t0)
            self._metrics.add("locations_fetched", 1)
        finally:
            self._exit_location()

    def _fetch_unit(self, locs: list) -> None:
        """Stream one BATCHED unit (several same-endpoint locations) over
        a single multi-partition DoGet, with retry + mid-stream resume;
        a unit that exhausts its retry budget degrades to the
        per-location path (which adds replica failover) for whatever it
        had not finished."""
        from ..errors import Cancelled

        t0 = time.monotonic_ns()
        self._enter_location()
        try:
            if self._cancel is not None and self._cancel.is_set():
                raise _cancelled()
            span_cm = (
                obs_trace.span(
                    "shuffle.fetch.batched",
                    parent=self._trace_parent,
                    host=getattr(locs[0].executor_meta, "host", ""),
                    locations=len(locs),
                )
                if self._trace_parent is not None
                else obs_trace.NOOP
            )
            with span_cm as sp:
                delivered = [0] * len(locs)
                # frontier: locations BELOW it were fully streamed by
                # some attempt (serving order is deterministic — seeing
                # index j proves every i < j completed), so the fallback
                # never re-fetches their bytes
                frontier = [0]
                try:
                    total = self._stream_batched(locs, delivered, frontier)
                except (Cancelled, _Closed):
                    raise
                except Exception as e:  # noqa: BLE001 - degrade, see below
                    log.warning(
                        "batched fetch of %d partition(s) from %s failed "
                        "(%s); falling back to per-location fetch from "
                        "location %d",
                        len(locs),
                        getattr(locs[0].executor_meta, "host", ""),
                        e,
                        frontier[0],
                    )
                    total = self._fallback_per_location(
                        locs, delivered, frontier[0]
                    )
                sp.set_attr("bytes", total)
            self._metrics.add("fetch_time_ns", time.monotonic_ns() - t0)
        finally:
            self._exit_location()

    def _stream_batched(
        self, locs: list, delivered: list, frontier: list
    ) -> int:
        """One multi-partition stream with bounded retries; ``delivered``
        (per-location committed batch counts) persists across attempts so
        a mid-stream retry resumes without duplicating rows (the server's
        serving order is deterministic: ticket path order, IPC batch
        order within each partition).  ``frontier`` (1-elem list) records
        the highest partition index ever seen: every lower index is
        proven complete.  Protocol violations
        (:class:`BatchedFetchProtocolError`) are deterministic and skip
        the retry budget entirely — the caller degrades straight to
        per-location DoGets."""
        from ..errors import BatchedFetchProtocolError
        from ..flight.client import BallistaClient
        from ..testing.faults import fault_point

        meta = locs[0].executor_meta
        pid0 = locs[0].partition_id
        parts = [
            (getattr(l.partition_id, "partition_id", 0), l.path) for l in locs
        ]
        attempt = 0
        total = 0
        while True:
            try:
                fault_point(
                    "shuffle.fetch",
                    path=getattr(locs[0], "path", ""),
                    attempt=attempt,
                )
                client = BallistaClient.get(meta.host, meta.flight_port)
                headers = obs_trace.propagation_headers() or None
                self._metrics.add("fetch_round_trips", 1)
                _schema, stream = client.fetch_partitions(
                    pid0.job_id, pid0.stage_id, parts, headers=headers
                )
                seen = [0] * len(locs)
                n_streamed = 0
                for idx, batch in stream:
                    fault_point(
                        "shuffle.fetch.batched",
                        host=meta.host,
                        attempt=attempt,
                        batches=n_streamed,
                    )
                    n_streamed += 1
                    if not (0 <= idx < len(locs)):
                        raise _protocol_error(idx, len(locs))
                    frontier[0] = max(frontier[0], idx)
                    seen[idx] += 1
                    if seen[idx] <= delivered[idx]:
                        continue  # resume: already delivered pre-failure
                    nbytes = int(getattr(batch, "nbytes", 0) or 0)
                    self._q.put(batch, nbytes)
                    self._metrics.add("bytes_fetched", nbytes)
                    total += nbytes
                    delivered[idx] += 1
                self._metrics.add("remote_fetches", len(locs))
                self._metrics.add("locations_fetched", len(locs))
                return total
            except Exception as e:
                from ..errors import Cancelled

                if isinstance(
                    e, (Cancelled, _Closed, BatchedFetchProtocolError)
                ):
                    raise
                attempt += 1
                if attempt > self._policy.retries:
                    raise
                self._metrics.add("fetch_retries", 1)
                delay = self._policy.backoff_s * (2 ** (attempt - 1))
                log.warning(
                    "batched shuffle fetch from %s:%s failed "
                    "(attempt %d/%d): %s; retrying in %.0fms",
                    meta.host,
                    meta.flight_port,
                    attempt,
                    self._policy.retries,
                    e,
                    delay * 1e3,
                )
                if self._stop.wait(delay):
                    raise

    def _fallback_per_location(
        self, locs: list, delivered: list, frontier: int = 0
    ) -> int:
        """Finish a failed batched unit location by location: each gets a
        fresh per-copy retry budget PLUS external-replica failover, with
        ``delivered_hint`` skipping what the batched stream already
        committed.  Locations below ``frontier`` were FULLY streamed
        (deterministic serving order proved it) — they are not
        re-fetched at all, so a unit that died on its last partition
        never re-pays the wire cost of the completed ones."""
        total = 0
        for i, loc in enumerate(locs):
            if i < frontier:
                # these WERE wire-served (by the failed batched stream):
                # the transport split must still count them remote
                self._metrics.add("locations_fetched", 1)
                self._metrics.add("remote_fetches", 1)
                continue
            for batch in retrying_fetch(
                loc,
                self._policy,
                self._metrics,
                stop_event=self._stop,
                delivered_hint=delivered[i],
            ):
                nbytes = int(getattr(batch, "nbytes", 0) or 0)
                self._q.put(batch, nbytes)
                self._metrics.add("bytes_fetched", nbytes)
                total += nbytes
            self._metrics.add("locations_fetched", 1)
        return total


class TailingShuffleFetcher:
    """Streaming pipelined fetch (ISSUE 15): one reader partition's tail
    over a producer stage's shuffle-location feed.

    Unlike :class:`ShuffleFetcher` — whose location set is fixed at
    construction — this pipeline's locations ARRIVE over time: the
    executor-side delta store (``shuffle/delta_store.py``) mirrors the
    scheduler's per-producer feed (push notifications in push mode,
    ``GetShuffleLocationDelta`` polls in pull mode), and this fetcher
    streams each location the moment it lands, finishing when the feed
    reports complete.  A consumer keeping pace with its producers sees
    one location per feed drain and fetches it inline; a consumer that
    fell behind (slow first fetch, late start against an almost-complete
    feed) drains a multi-location BACKLOG and fans it out over the
    standard :class:`ShuffleFetcher` pool so the wire is never idle
    while queued locations wait their turn
    (``ballista.shuffle.fetch_concurrency=1`` pins the ordered
    sequential drain).  Either way each location gets the full
    :func:`retrying_fetch` treatment — retry/backoff, replica failover,
    mid-stream resume and the structured ``ShuffleFetchFailed`` that
    drives producer recovery.

    Stall-on-producer time lands in ``fetch_wait_time_ns`` (accounted by
    the delta store's tail), so the query doctor's attribution of a
    pipelined consumer stays exact.  Registered with the active-fetcher
    table like the static pipeline, so executor shutdown aborts it.
    """

    def __init__(
        self,
        job_id: str,
        stage_id: int,
        partition: int,
        policy: FetchPolicy,
        metrics,
        cancel_event: Optional[threading.Event] = None,
        owner: Optional[str] = None,
        trace_parent=None,
        fetch_fn: Optional[Callable[[object], Iterator[pa.RecordBatch]]] = None,
    ) -> None:
        self.owner = owner
        self._job_id = job_id
        self._stage_id = stage_id
        self._partition = partition
        self._policy = policy
        self._metrics = _TeeMetrics(metrics)
        self._cancel = cancel_event
        self._trace_parent = trace_parent
        self._fetch_fn = fetch_fn
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._consumed = False

    def close(self, error: Optional[BaseException] = None) -> None:
        if error is not None and self._error is None:
            self._error = error
        self._stop.set()

    def __iter__(self) -> Iterator[pa.RecordBatch]:
        if self._consumed:
            raise RuntimeError(
                "TailingShuffleFetcher is single-use; construct a new one"
            )
        self._consumed = True
        return self._iterate()

    def _iterate(self) -> Iterator[pa.RecordBatch]:
        from . import delta_store

        with _active_lock:
            _active.add(self)
        span_cm = (
            obs_trace.span(
                "shuffle.fetch.tail",
                parent=self._trace_parent,
                stage=self._stage_id,
                partition=self._partition,
            )
            if self._trace_parent is not None
            else obs_trace.NOOP
        )
        try:
            with span_cm as sp:
                total = 0
                n_locs = 0
                for chunk in delta_store.tail_location_batches(
                    self._job_id,
                    self._stage_id,
                    self._partition,
                    stop_event=self._stop,
                    cancel_event=self._cancel,
                    metrics=self._metrics,
                ):
                    if len(chunk) > 1 and self._policy.concurrency > 1:
                        # backlog drain: fan the queued locations out over
                        # the concurrent pool (it accounts bytes/locations/
                        # fetch_time/peak itself; pass the unwrapped
                        # metrics so the registry tee isn't paid twice)
                        pool = ShuffleFetcher(
                            chunk,
                            self._policy,
                            self._metrics._inner,
                            cancel_event=self._cancel,
                            fetch_fn=self._fetch_fn,
                            owner=self.owner,
                            trace_parent=self._trace_parent,
                        )
                        for batch in pool:
                            if self._error is not None:
                                raise self._error
                            yield batch
                            total += int(getattr(batch, "nbytes", 0) or 0)
                        n_locs += len(chunk)
                        continue
                    for loc in chunk:
                        t0 = time.monotonic_ns()
                        for batch in retrying_fetch(
                            loc,
                            self._policy,
                            self._metrics,
                            fetch_fn=self._fetch_fn,
                            stop_event=self._stop,
                        ):
                            if self._error is not None:
                                raise self._error
                            yield batch
                            nbytes = int(getattr(batch, "nbytes", 0) or 0)
                            self._metrics.add("bytes_fetched", nbytes)
                            total += nbytes
                        self._metrics.add(
                            "fetch_time_ns", time.monotonic_ns() - t0
                        )
                        self._metrics.add("locations_fetched", 1)
                        n_locs += 1
                if self._error is not None:
                    raise self._error
                sp.set_attr("bytes", total)
                sp.set_attr("locations", n_locs)
        finally:
            self.close()


def _cancelled():
    from ..errors import Cancelled

    return Cancelled("task cancelled")


def _protocol_error(idx, n):
    from ..errors import BatchedFetchProtocolError

    return BatchedFetchProtocolError(
        f"batched shuffle fetch: server sent partition index {idx} "
        f"outside the requested range [0, {n})"
    )
