"""Distributed shuffle operators.

Counterparts of the reference's ``core/src/execution_plans/{shuffle_writer,
shuffle_reader,unresolved_shuffle}.rs``:

* :class:`ShuffleWriterExec` — stage-root operator; executes the stage
  subplan for one input partition, hash-repartitions batches, persists each
  output partition as an Arrow IPC file under
  ``work_dir/<job>/<stage>/<out_part>/data-<in_part>.arrow`` and returns
  per-partition :class:`ShuffleWritePartition` stats.
* :class:`ShuffleReaderExec` — leaf operator of downstream stages; fetches
  the map-side partitions (local file fast path, Arrow Flight otherwise).
* :class:`UnresolvedShuffleExec` — placeholder leaf marking a dependency on
  a not-yet-completed stage; refuses to execute.

Hash partitioning runs through the native C++ kernel when available
(:mod:`arrow_ballista_tpu.native`), falling back to the vectorized numpy
implementation; both produce identical assignments by construction.
"""

from __future__ import annotations

import logging
import os
from typing import TYPE_CHECKING, Iterator, Optional

import pyarrow as pa

from ..errors import ExecutionError
from ..exec.expressions import PhysicalExpr
from ..exec.operators import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    hash_partition_indices,
)

if TYPE_CHECKING:  # runtime import is lazy: serde.physical_plan imports
    # THIS module back, and an eager import here made the package cycle
    # unenterable from the shuffle side (ImportError when
    # arrow_ballista_tpu.shuffle was the first package imported)
    from ..serde.scheduler_types import PartitionLocation, ShuffleWritePartition

try:  # native partitioner (C++); optional
    from ..native import native_hash_partition_indices
except Exception:  # pragma: no cover - toolchain-less environments
    native_hash_partition_indices = None

log = logging.getLogger(__name__)


def partition_indices(batch: pa.RecordBatch, exprs: list[PhysicalExpr], n: int):
    """Partition id per row; native kernel with Python fallback."""
    if native_hash_partition_indices is not None:
        out = native_hash_partition_indices(batch, exprs, n)
        if out is not None:
            return out
    return hash_partition_indices(batch, exprs, n)


# The stats schema ShuffleWriterExec yields from execute() — one row per
# written output partition (reference: shuffle_writer.rs:295+ returns an
# equivalent stats batch).
WRITE_STATS_SCHEMA = pa.schema(
    [
        pa.field("partition_id", pa.int64()),
        pa.field("path", pa.string()),
        pa.field("num_batches", pa.int64()),
        pa.field("num_rows", pa.int64()),
        pa.field("num_bytes", pa.int64()),
    ]
)


class _IpcFileSink:
    """Arrow IPC file writer with write stats (reference:
    core/src/utils.rs:60-97 write_stream_to_disk).

    ``options`` enables IPC body compression; ``ensure_dir`` is the write
    task's memoized mkdir (one syscall per output-partition dir instead
    of one per sink).  ``wire_bytes`` is set by :meth:`close` — None
    means the OS handle may still be open (the writer pool's abort path
    keys off it)."""

    def __init__(
        self,
        path: str,
        schema: pa.Schema,
        options=None,
        ensure_dir=None,
    ):
        d = os.path.dirname(path)
        if ensure_dir is not None:
            ensure_dir(d)
        else:
            os.makedirs(d, exist_ok=True)
        self.path = path
        self.num_rows = 0
        self.num_batches = 0
        self.wire_bytes: Optional[int] = None
        self.replica_path = ""  # set post-close by the replication hook
        self._sink = pa.OSFile(path, "wb")
        try:
            self._writer = pa.ipc.new_file(self._sink, schema, options=options)
        except BaseException:
            self._sink.close()
            raise

    def write(self, batch: pa.RecordBatch) -> None:
        self._writer.write_batch(batch)
        self.num_rows += batch.num_rows
        self.num_batches += 1

    def close(self) -> int:
        # try/finally: a failed footer write (disk full, injected fault)
        # must still release the OS file handle — a leaked fd per retry
        # starves the executor of descriptors long before it fails tasks
        try:
            self._writer.close()
        finally:
            self._sink.close()
        self.wire_bytes = os.path.getsize(self.path)
        return self.wire_bytes

    def abandon(self) -> None:
        """Failed-task teardown: release the OS handle and delete the
        partial file.  Closing the IPC writer leaves a READABLE file
        (valid footer over the batches written so far) at the canonical
        partition path — if it survived, a drain-time upload would
        publish it as a complete replica and a consumer would silently
        read fewer rows."""
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001 - handle release is what matters
            pass
        finally:
            self._sink.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _MemSink:
    """Memory-store sink with the same stats interface as _IpcFileSink.

    TPU-first data plane: gang-stage outputs (and, with
    ``ballista.shuffle.to_memory``, every shuffle partition) stay in
    executor RAM and stream out of the Flight service without disk I/O.
    Batches serialize into the IPC stream buffer AS THEY ARRIVE — the
    partition is never held twice (batch list + serialized bytes), so
    peak memory is the partition's wire size, not 2x its raw size.
    """

    def __init__(
        self, job_id: str, stage_id: int, out_part: int, in_part: int,
        schema: pa.Schema, options=None,
    ):
        from . import memory_store

        self.path = memory_store.make_path(job_id, stage_id, out_part, in_part)
        self._key = (job_id, stage_id, out_part, in_part)
        self.num_rows = 0
        self.num_batches = 0
        self.wire_bytes: Optional[int] = None
        self.replica_path = ""  # set post-close by the replication hook
        self.serialized: Optional[pa.Buffer] = None  # the closed IPC bytes
        self._buf = pa.BufferOutputStream()
        self._writer = pa.ipc.new_stream(self._buf, schema, options=options)

    def write(self, batch: pa.RecordBatch) -> None:
        self._writer.write_batch(batch)
        self.num_rows += batch.num_rows
        self.num_batches += 1

    def close(self) -> int:
        from . import memory_store

        self._writer.close()
        buf = self._buf.getvalue()
        # keep the reference for the replication hook: the store holds the
        # same buffer, so this pins no extra memory
        self.serialized = buf
        memory_store.put_buffer(*self._key, buf)
        self.wire_bytes = memory_store.put_size(self.path)
        return self.wire_bytes

    def abandon(self) -> None:
        """Failed-task teardown: drop the buffer WITHOUT publishing — a
        partial partition stored under the canonical mem:// key would
        shadow the retry's real output."""
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001
            pass


class ShuffleWriterExec(ExecutionPlan):
    def __init__(
        self,
        job_id: str,
        stage_id: int,
        input: ExecutionPlan,
        work_dir: str,
        shuffle_output_partitioning: Optional[Partitioning] = None,
    ):
        super().__init__()
        self.job_id = job_id
        self.stage_id = stage_id
        self.input = input
        self.work_dir = work_dir
        self.shuffle_output_partitioning = shuffle_output_partitioning
        # True only after THIS writer asked its input stage for device
        # partition ids — the pid-column pop is gated on it so a user
        # column that happens to be named __shuffle_pid__ is never eaten
        self._hint_installed = False

    @property
    def schema(self) -> pa.Schema:
        return WRITE_STATS_SCHEMA

    @property
    def input_schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        # one write task per *input* partition
        return Partitioning.unknown(self.input.output_partitioning().n)

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        return ShuffleWriterExec(
            self.job_id,
            self.stage_id,
            children[0],
            self.work_dir,
            self.shuffle_output_partitioning,
        )

    def _store_kind(self, policy) -> str:
        """Resolve the shuffle store for this write: a mesh stage (gang
        or ICI-exchanged repartition) always stays in memory — its output
        never belongs on disk — otherwise ``ballista.shuffle.store``
        (with the legacy ``shuffle.to_memory`` folded in by
        WritePolicy.from_config)."""
        from ..parallel.mesh_stage import MeshGangExec, MeshRepartitionExec

        if isinstance(self.input, (MeshGangExec, MeshRepartitionExec)):
            return "mem"
        return policy.store

    def _stage_base_dir(self, kind: str, policy) -> str:
        """Root under which this stage's partition files land: the shared
        external store when it IS the primary, the executor work_dir
        otherwise."""
        return policy.external_path if kind == "external" else self.work_dir

    def _replicate_hook(self):
        """Post-close replication hook for sinks (None when replication
        is off).  Runs on writer-pool threads (pipelined path) or inline
        (legacy path); NEVER raises — a failed upload degrades to a
        single copy and the task still completes (the recompute path of
        PR 5 covers a later loss)."""
        policy = self._policy(None)
        if not policy.replicate:
            return None
        from . import store as shuffle_store

        sync = policy.replication == "sync"

        def replicate(sink) -> None:
            try:
                if sink is None or getattr(sink, "wire_bytes", None) is None:
                    return  # never closed: nothing durable to copy
                dest = shuffle_store.external_replica_path(
                    policy.external_path, sink.path
                )
                if dest is None:
                    return
                buf = getattr(sink, "serialized", None)
                if sync:
                    if buf is not None:
                        shuffle_store.upload_buffer(buf, dest)
                    else:
                        shuffle_store.upload_file(sink.path, dest)
                elif buf is not None:
                    shuffle_store.replicator().submit_buffer(buf, dest)
                else:
                    shuffle_store.replicator().submit_file(sink.path, dest)
                # async reports the destination optimistically: a failed
                # background upload leaves a dangling replica_path, which
                # the fetch failover treats as one more miss before the
                # recompute path fires
                sink.replica_path = dest
                self.metrics.add("replicas_written", 1)
            except Exception as e:  # noqa: BLE001 - degrade to single copy
                shuffle_store.count_upload_failure()
                self.metrics.add("replica_upload_failures", 1)
                log.warning(
                    "replica upload of %s failed (single copy only): %s",
                    getattr(sink, "path", sink),
                    e,
                )

        return replicate

    def _dir_memo(self):
        """Memoized mkdir for this write task: one ``os.makedirs`` per
        output-partition directory instead of one per sink.  Workers of
        the writer pool shard partitions, so a duplicate check-then-add
        race costs at most one extra (idempotent) makedirs."""
        made: set = set()

        def ensure(d: str) -> None:
            if d not in made:
                os.makedirs(d, exist_ok=True)
                made.add(d)

        return ensure

    def _sink(
        self, to_mem: bool, stage_dir: str, out_part: int, in_part: int,
        schema: pa.Schema, single_file: bool, options=None, ensure_dir=None,
    ):
        if to_mem:
            return _MemSink(
                self.job_id, self.stage_id, out_part, in_part, schema,
                options=options,
            )
        name = "data.arrow" if single_file else f"data-{in_part}.arrow"
        return _IpcFileSink(
            os.path.join(stage_dir, str(out_part), name), schema,
            options=options, ensure_dir=ensure_dir,
        )

    def _sink_factory(
        self, to_mem: bool, stage_dir: str, in_part: int, schema: pa.Schema,
        single_file: bool = False, fixed_out: Optional[int] = None,
    ):
        """Per-output-partition sink factory for the async writer pool —
        invoked on the pool's threads, so opens/mkdirs stay off the
        compute thread."""
        from .writer import ipc_write_options

        options = ipc_write_options(self._policy(None).compression)
        ensure_dir = self._dir_memo()

        def factory(out_part: int):
            p = fixed_out if fixed_out is not None else out_part
            return self._sink(
                to_mem, stage_dir, p, in_part, schema, single_file,
                options=options, ensure_dir=ensure_dir,
            )

        return factory

    def _policy(self, ctx: Optional[TaskContext]):
        from .writer import WritePolicy

        if ctx is not None:
            self._write_policy = WritePolicy.from_config(ctx.config)
        return getattr(self, "_write_policy", None) or WritePolicy()

    # ------------------------------------------------------------- core
    def execute_shuffle_write(
        self, input_partition: int, ctx: TaskContext
    ) -> list[ShuffleWritePartition]:
        """Run the stage subplan for ``input_partition`` and persist its
        output (reference: shuffle_writer.rs:142-292) through the
        slab-buffered async writer pool (``shuffle/writer.py``); the
        pre-pipelining synchronous path stays callable via
        ``ballista.shuffle.write_pipelined=false`` (A/B baseline)."""
        part = self.shuffle_output_partitioning
        policy = self._policy(ctx)
        kind = self._store_kind(policy)
        to_mem = kind == "mem"
        stage_dir = os.path.join(
            self._stage_base_dir(kind, policy), self.job_id, str(self.stage_id)
        )

        if part is None:
            return self._single_sink_write(
                input_partition, ctx, stage_dir, to_mem, policy.pipelined
            )

        if part.kind != "hash":
            raise ExecutionError(f"unsupported shuffle partitioning {part.kind}")

        from ..parallel.mesh_stage import MeshExchangeError, MeshRepartitionExec

        if isinstance(self.input, MeshRepartitionExec):
            # the stage body already routed rows to their destination over
            # ICI: write each received output partition directly (one task,
            # zero hash-split work here).  Only exchange-specific failures
            # fall back; inner-plan errors propagate to stage retry.
            try:
                return self._exchanged_write(input_partition, ctx, stage_dir)
            except MeshExchangeError:
                self.metrics.add("mesh_exchange_fallback", 1)
                return self._fallback_hash_write(ctx, stage_dir, part)

        if not policy.pipelined:
            sinks: list = [None] * part.n
            for batch in self.input.execute(input_partition, ctx):
                ctx.check_cancelled()
                self._hash_split_into_sinks(
                    batch, part, sinks, to_mem, stage_dir, input_partition
                )
            return self._close_sinks(
                sinks, to_mem, stage_dir, input_partition, self.input.schema
            )

        # device stages compute the hash on device and attach the pid
        # column; every other input hashes on host inside the split
        if hasattr(self.input, "install_shuffle_hint"):
            self.input.install_shuffle_hint(list(part.exprs), part.n)
            self._hint_installed = True

        def batches():
            for batch in self.input.execute(input_partition, ctx):
                ctx.check_cancelled()
                yield batch

        return self._pipelined_hash_write(
            batches(), part, ctx, stage_dir, to_mem, input_partition
        )

    def _single_sink_write(
        self, input_partition: int, ctx: TaskContext, stage_dir: str,
        to_mem: bool, pipelined: bool,
    ) -> list[ShuffleWritePartition]:
        """No repartition: one output sink for this input partition."""
        from ..serde.scheduler_types import ShuffleWritePartition

        if pipelined:
            from .writer import AsyncShuffleWriter

            writer = AsyncShuffleWriter(
                1,
                self._sink_factory(
                    to_mem, stage_dir, input_partition, self.input.schema,
                    single_file=True, fixed_out=input_partition,
                ),
                self._policy(None),
                self.metrics,
                cancel_event=ctx.cancel_event,
                replicate_fn=self._replicate_hook(),
            )
            try:
                for batch in self.input.execute(input_partition, ctx):
                    ctx.check_cancelled()
                    writer.append(0, batch)
                (sink,) = writer.finish()
            except BaseException:
                writer.abort()
                raise
            self.metrics.add("output_rows", sink.num_rows)
            return [
                ShuffleWritePartition(
                    input_partition, sink.path, sink.num_batches,
                    sink.num_rows, sink.wire_bytes,
                    replica_path=sink.replica_path,
                )
            ]
        sink = None
        replicate = self._replicate_hook()
        with self.metrics.timer("write_time_ns"):
            for batch in self.input.execute(input_partition, ctx):
                ctx.check_cancelled()
                if sink is None:
                    sink = self._sink(
                        to_mem, stage_dir, input_partition,
                        input_partition, batch.schema, True,
                    )
                sink.write(batch)
            if sink is None:
                sink = self._sink(
                    to_mem, stage_dir, input_partition, input_partition,
                    self.input.schema, True,
                )
            nbytes = sink.close()
        if replicate is not None:
            replicate(sink)
        self.metrics.add("output_rows", sink.num_rows)
        return [
            ShuffleWritePartition(
                input_partition, sink.path, sink.num_batches, sink.num_rows,
                nbytes, replica_path=sink.replica_path,
            )
        ]

    def _pipelined_hash_write(
        self, batch_iter, part: Partitioning, ctx: TaskContext,
        stage_dir: str, to_mem: bool, in_part: int,
        schema: Optional[pa.Schema] = None,
    ) -> list[ShuffleWritePartition]:
        """Hash-split a batch stream into the async writer pool: the
        compute thread pays only the O(n) counting-sort permutation and
        one ``take`` per batch; slab coalescing, IPC serialization
        (+compression) and sink I/O run on the pool."""
        from .writer import AsyncShuffleWriter

        writer = AsyncShuffleWriter(
            part.n,
            self._sink_factory(
                to_mem, stage_dir, in_part,
                schema if schema is not None else self.input.schema,
            ),
            self._policy(None),
            self.metrics,
            cancel_event=ctx.cancel_event,
            replicate_fn=self._replicate_hook(),
        )
        try:
            for batch in batch_iter:
                self._split_into_writer(batch, part, writer)
            sinks = writer.finish()
        except BaseException:
            writer.abort()
            raise
        return self._stats_from_sinks(sinks)

    def _split_into_writer(
        self, batch: pa.RecordBatch, part: Partitioning, writer
    ) -> None:
        from ..exec.operators import partition_permutation

        n_out = part.n
        with self.metrics.timer("repart_time_ns"):
            batch, idx = self._partition_ids(batch, part)
            if batch.num_rows == 0:
                return
            order, bounds = partition_permutation(idx, n_out)
        # no `take` here: the per-partition row gathers run on the pool
        # threads at slab-flush time (writer.append_rows), so the compute
        # thread never pays a row copy
        for p in range(n_out):
            lo, hi = int(bounds[p]), int(bounds[p + 1])
            if hi > lo:
                writer.append_rows(p, batch, order[lo:hi])

    def _partition_ids(self, batch: pa.RecordBatch, part: Partitioning):
        """(payload batch, partition id per row): pop the device-computed
        pid column when the input stage attached one (install_shuffle_hint),
        else run the host/native partitioner."""
        import numpy as np

        from ..exec.operators import SHUFFLE_PID_COLUMN

        ncols = batch.num_columns
        if (
            self._hint_installed
            and ncols
            and batch.schema.field(ncols - 1).name == SHUFFLE_PID_COLUMN
        ):
            idx = np.asarray(batch.column(ncols - 1)).astype(np.int64)
            self.metrics.add("device_pid_batches", 1)
            return batch.select(range(ncols - 1)), idx
        return batch, partition_indices(batch, list(part.exprs), part.n)

    def _stats_from_sinks(self, sinks: list) -> list[ShuffleWritePartition]:
        from ..serde.scheduler_types import ShuffleWritePartition

        out = []
        for p, s in enumerate(sinks):
            self.metrics.add("output_rows", s.num_rows)
            out.append(
                ShuffleWritePartition(
                    p, s.path, s.num_batches, s.num_rows, s.wire_bytes,
                    replica_path=s.replica_path,
                )
            )
        return out

    def _hash_split_into_sinks(
        self, batch, part: Partitioning, sinks: list, to_mem: bool,
        stage_dir: str, in_part: int,
    ) -> None:
        """Pre-pipelining hash split (the reference hot loop,
        shuffle_writer.rs:201-285): argsort permutation + one synchronous
        uncoalesced sink write per split run.  Kept as the measured A/B
        baseline behind ``ballista.shuffle.write_pipelined=false``."""
        import numpy as np

        n_out = part.n
        with self.metrics.timer("repart_time_ns"):
            idx = partition_indices(batch, list(part.exprs), n_out)
            order = np.argsort(idx, kind="stable")
            sorted_idx = idx[order]
            shuffled = batch.take(pa.array(order))
            bounds = np.searchsorted(sorted_idx, np.arange(n_out + 1))
        with self.metrics.timer("write_time_ns"):
            for p in range(n_out):
                lo, hi = int(bounds[p]), int(bounds[p + 1])
                if hi <= lo:
                    continue
                if sinks[p] is None:
                    sinks[p] = self._sink(
                        to_mem, stage_dir, p, in_part, batch.schema, False
                    )
                sinks[p].write(shuffled.slice(lo, hi - lo))

    def _close_sinks(
        self, sinks: list, to_mem: bool, stage_dir: str, in_part: int,
        in_schema: pa.Schema,
    ) -> list[ShuffleWritePartition]:
        """Close every partition sink (creating empty ones so readers need
        no existence probe) and assemble the write stats."""
        from ..serde.scheduler_types import ShuffleWritePartition

        out = []
        replicate = self._replicate_hook()
        with self.metrics.timer("write_time_ns"):
            for p in range(len(sinks)):
                s = sinks[p]
                if s is None:
                    s = self._sink(
                        to_mem, stage_dir, p, in_part, in_schema, False
                    )
                nbytes = s.close()
                if replicate is not None:
                    replicate(s)
                self.metrics.add("output_rows", s.num_rows)
                out.append(
                    ShuffleWritePartition(
                        p, s.path, s.num_batches, s.num_rows, nbytes,
                        replica_path=s.replica_path,
                    )
                )
        return out

    def _exchanged_write(
        self, input_partition: int, ctx: TaskContext, stage_dir: str
    ) -> list[ShuffleWritePartition]:
        """Persist already-exchanged (out_partition, batch) pairs from a
        MeshRepartitionExec stage body — the write half of the ICI
        shuffle.  No hash-split work here, but the batches still ride the
        slab-buffered async pool (coalescing + off-thread serialization
        + compression)."""
        assert input_partition == 0, "mesh-exchanged stages are single-task"
        from .writer import AsyncShuffleWriter

        to_mem = self._store_kind(self._policy(None)) == "mem"
        if not self._policy(None).pipelined:
            # the A/B baseline flag pins the pre-pipelining behavior on
            # EVERY write shape, this one included
            sinks: list = [None] * self.shuffle_output_partitioning.n
            for out_p, batch in self.input.execute_exchanged(ctx):
                ctx.check_cancelled()
                with self.metrics.timer("write_time_ns"):
                    if sinks[out_p] is None:
                        sinks[out_p] = self._sink(
                            to_mem, stage_dir, out_p, 0, batch.schema, False
                        )
                    sinks[out_p].write(batch)
            return self._close_sinks(
                sinks, to_mem, stage_dir, 0, self.input.schema
            )
        writer = AsyncShuffleWriter(
            self.shuffle_output_partitioning.n,
            self._sink_factory(to_mem, stage_dir, 0, self.input.schema),
            self._policy(None),
            self.metrics,
            cancel_event=ctx.cancel_event,
            replicate_fn=self._replicate_hook(),
        )
        try:
            for out_p, batch in self.input.execute_exchanged(ctx):
                ctx.check_cancelled()
                writer.append(out_p, batch)
            sinks = writer.finish()
        except BaseException:
            writer.abort()
            raise
        return self._stats_from_sinks(sinks)

    def _fallback_hash_write(
        self, ctx: TaskContext, stage_dir: str, part: Partitioning
    ) -> list[ShuffleWritePartition]:
        """Exchange fallback: run the hash-split over EVERY inner
        partition inside this one task (still correct, no collective).

        Sinks follow the EXPLICIT config only — the mesh-input heuristic
        of _store_kind must not apply here, or a shuffle that fell back
        precisely because it exceeded the row ceiling would be buffered
        whole in executor memory anyway."""
        to_mem = self._policy(None).store == "mem"
        inner = self.input.children()[0]

        if self._policy(None).pipelined:

            def batches():
                for in_p in range(inner.output_partitioning().n):
                    for batch in inner.execute(in_p, ctx):
                        ctx.check_cancelled()
                        yield batch

            return self._pipelined_hash_write(
                batches(), part, ctx, stage_dir, to_mem, 0,
                schema=inner.schema,
            )
        sinks: list = [None] * part.n
        for in_p in range(inner.output_partitioning().n):
            for batch in inner.execute(in_p, ctx):
                ctx.check_cancelled()
                self._hash_split_into_sinks(
                    batch, part, sinks, to_mem, stage_dir, 0
                )
        return self._close_sinks(sinks, to_mem, stage_dir, 0, inner.schema)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        stats = self.execute_shuffle_write(partition, ctx)
        yield pa.RecordBatch.from_arrays(
            [
                pa.array([s.partition_id for s in stats], pa.int64()),
                pa.array([s.path for s in stats], pa.string()),
                pa.array([s.num_batches for s in stats], pa.int64()),
                pa.array([s.num_rows for s in stats], pa.int64()),
                pa.array([s.num_bytes for s in stats], pa.int64()),
            ],
            schema=WRITE_STATS_SCHEMA,
        )

    def __str__(self) -> str:
        p = self.shuffle_output_partitioning
        desc = f"hash({p.n})" if p is not None else "none"
        return f"ShuffleWriterExec: job={self.job_id} stage={self.stage_id} partitioning={desc}"


def apply_read_selections(
    selections: list[list[tuple[int, int, int]]],
    source_lists: list[list],
) -> list[list]:
    """Materialize AQE read selections against per-source-partition
    fragment lists.

    Each reduce TASK is a list of ``(source_partition, chunk_i, chunk_k)``
    triples: the task reads chunk ``i`` of ``k`` index-contiguous slices
    of that source partition's fragment list.  ``(p, 0, 1)`` reads the
    whole partition; a coalesced task lists several whole partitions; a
    skew-split task reads one chunk of one partition.  Chunks are derived
    from the CURRENT fragment count, so any k chunks are always an exact
    disjoint cover — a producer re-run (same map-task count, possibly
    different paths) re-resolves to the same coverage without the
    scheduler persisting fragment indices."""
    out: list[list] = []
    for sel in selections:
        frags: list = []
        for p, i, k in sel:
            src = source_lists[p]
            n = len(src)
            lo, hi = (i * n) // k, ((i + 1) * n) // k
            frags.extend(src[lo:hi])
        out.append(frags)
    return out


class ShuffleReaderExec(ExecutionPlan):
    """Reads shuffle partitions written by upstream ShuffleWriter tasks.

    ``partition[p]`` lists every map-side location contributing to output
    partition ``p`` (reference: shuffle_reader.rs:44-130).

    ``selections``/``source_partition_count`` record the AQE rewrite
    (partition coalescing / skew splitting) this reader was resolved
    with, so an executor-loss rollback reconstructs the REWRITTEN
    placeholder — a rolled-back consumer re-resolves with the same
    adaptive plan, not the original static one.

    ``tail=True`` (streaming pipelined execution, ISSUE 15): the reader
    was resolved BEFORE its producer stage completed — ``partition``
    carries no static locations; execution tails the scheduler's
    shuffle-location feed for this stage (``shuffle/delta_store.py``)
    until the feed reports complete, streaming each committed map
    fragment the moment it lands.
    """

    def __init__(
        self,
        stage_id: int,
        schema: pa.Schema,
        partition: list[list[PartitionLocation]],
        selections: Optional[list[list[tuple[int, int, int]]]] = None,
        source_partition_count: Optional[int] = None,
        tail: bool = False,
    ):
        super().__init__()
        self.stage_id = stage_id
        self._schema = schema
        self.partition = partition
        self.selections = selections
        self.source_partition_count = source_partition_count
        self.tail = tail

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(len(self.partition))

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        """Stream the merged batches of every map-side location.

        EVERY read routes through :class:`ShuffleFetcher` — with
        ``fetch_concurrency=1`` (or a single location) it runs one worker
        that walks locations in order, so "sequential" keeps the same
        retry/backoff, streaming memory profile, cancel wake-up and
        shutdown-abort registration as the pipelined path instead of
        being a second, less robust code path."""
        from ..obs import trace
        from .fetcher import FetchPolicy, ShuffleFetcher

        if self.tail:
            yield from self._execute_tail(partition, ctx)
            return
        locations = self.partition[partition]
        if not locations:
            return
        policy = FetchPolicy.from_config(ctx.config)
        # manual (stack-free) span: this is a generator — a context-pushing
        # span would stay "current" on the consuming thread between yields
        sp = trace.manual_span(
            "shuffle.fetch",
            stage=self.stage_id,
            partition=partition,
            locations=len(locations),
        )
        try:
            fetcher = ShuffleFetcher(
                locations,
                policy,
                self.metrics,
                cancel_event=ctx.cancel_event,
                owner=ctx.work_dir,
                trace_parent=sp.ctx,
            )
            rows = 0
            for b in fetcher:
                ctx.check_cancelled()
                rows += b.num_rows
                self.metrics.add("output_rows", b.num_rows)
                yield b
            sp.set_attr("rows", rows)
        finally:
            sp.finish()

    def _execute_tail(
        self, partition: int, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        """Pipelined read: stream the producer's growing location set
        from the delta feed (committed winners only) until it completes.
        The feed is keyed by the TASK's job id — a tailing reader never
        travels outside a distributed task."""
        from ..obs import trace
        from .fetcher import FetchPolicy, TailingShuffleFetcher

        policy = FetchPolicy.from_config(ctx.config)
        sp = trace.manual_span(
            "shuffle.fetch",
            stage=self.stage_id,
            partition=partition,
            tail=True,
        )
        try:
            fetcher = TailingShuffleFetcher(
                ctx.job_id,
                self.stage_id,
                partition,
                policy,
                self.metrics,
                cancel_event=ctx.cancel_event,
                owner=ctx.work_dir,
                trace_parent=sp.ctx,
            )
            rows = 0
            for b in fetcher:
                ctx.check_cancelled()
                rows += b.num_rows
                self.metrics.add("output_rows", b.num_rows)
                yield b
            sp.set_attr("rows", rows)
        finally:
            sp.finish()

    def with_new_children(self, children):
        assert not children
        return self

    def __str__(self) -> str:
        if self.tail:
            return (
                f"ShuffleReaderExec: stage={self.stage_id} "
                f"partitions={len(self.partition)} tail=true"
            )
        n_loc = sum(len(p) for p in self.partition)
        aqe = (
            f" aqe_source_partitions={self.source_partition_count}"
            if self.selections is not None
            else ""
        )
        return (
            f"ShuffleReaderExec: stage={self.stage_id} "
            f"partitions={len(self.partition)} locations={n_loc}{aqe}"
        )


class UnresolvedShuffleExec(ExecutionPlan):
    """Placeholder for a dependency on stage ``stage_id`` that has not been
    computed yet (reference: unresolved_shuffle.rs:33-110).

    ``output_partition_count`` is always the SOURCE reduce-partition
    count the producer stage writes.  ``selections`` (optional, set by
    the AQE policy engine in ``scheduler/adaptive.py``) remaps those
    source partitions onto a different reduce-task layout — coalesced
    groups of tiny partitions and/or fragment-chunk splits of skewed
    ones; when set, this node resolves to ``len(selections)`` tasks
    instead of one per source partition."""

    def __init__(
        self,
        stage_id: int,
        schema: pa.Schema,
        input_partition_count: int,
        output_partition_count: int,
        selections: Optional[list[list[tuple[int, int, int]]]] = None,
    ):
        super().__init__()
        self.stage_id = stage_id
        self._schema = schema
        self.input_partition_count = input_partition_count
        self.output_partition_count = output_partition_count
        self.selections = selections

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    @property
    def reduce_task_count(self) -> int:
        """Reduce tasks this placeholder resolves to (selections-aware)."""
        if self.selections is not None:
            return len(self.selections)
        return self.output_partition_count

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(self.reduce_task_count)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        raise ExecutionError(
            "UnresolvedShuffleExec cannot execute; it must be replaced with a "
            "ShuffleReaderExec once the producing stage completes"
        )

    def with_new_children(self, children):
        assert not children
        return self

    def __str__(self) -> str:
        if self.selections is not None:
            return (
                f"UnresolvedShuffleExec: stage={self.stage_id} "
                f"aqe_tasks={len(self.selections)}/{self.output_partition_count}"
            )
        return f"UnresolvedShuffleExec: stage={self.stage_id}"
