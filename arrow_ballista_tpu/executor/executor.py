"""Executor core object: runs shuffle-write tasks, tracks abort handles.

Counterpart of the reference's ``executor/src/executor.rs:44-179``: holds
registration metadata, the local ``work_dir`` and concurrency budget;
``execute_task`` decodes the stage plan, rebuilds the ShuffleWriterExec
against the local work_dir (`:137-161` new_shuffle_writer), wraps execution
with a cancellation handle keyed by PartitionId (`:97-134` abortable), and
maps the outcome to a protobuf TaskStatus (``executor/src/lib.rs``
as_task_status).  Panics/exceptions become Failed statuses like the
reference's catch_unwind (``execution_loop.rs:120-130``).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from ..config import BallistaConfig
from ..exec.operators import TaskContext
from ..proto import pb
from ..scheduler.execution_stage import TaskInfo
from ..scheduler.task_status import collect_plan_metrics, task_info_to_proto
from ..serde import BallistaCodec, partitioning_from_proto
from ..serde.scheduler_types import ExecutorMetadata, PartitionId
from ..shuffle.execution_plans import ShuffleWriterExec

log = logging.getLogger(__name__)


class LoggingMetricsCollector:
    """Prints the per-partition stage plan with metrics (reference:
    executor/src/metrics/mod.rs:28-60)."""

    def record_stage(
        self, job_id: str, stage_id: int, partition: int, plan, metrics
    ) -> None:
        log.info(
            "=== [%s/%s/%s] stage completed: %s metrics=%s ===",
            job_id,
            stage_id,
            partition,
            plan,
            metrics,
        )


class Executor:
    def __init__(
        self,
        metadata: ExecutorMetadata,
        work_dir: str,
        concurrent_tasks: int = 4,
        metrics_collector: Optional[LoggingMetricsCollector] = None,
    ):
        self.metadata = metadata
        self.work_dir = work_dir
        self.concurrent_tasks = concurrent_tasks
        self.metrics_collector = metrics_collector or LoggingMetricsCollector()
        self._abort_handles: Dict[PartitionId, threading.Event] = {}
        self._abort_lock = threading.Lock()

    @property
    def id(self) -> str:
        return self.metadata.id

    # ---------------------------------------------------------------- run
    def execute_task(self, task: pb.TaskDefinition) -> pb.TaskStatus:
        """Run one shuffle-write task to completion; never raises — any
        error becomes a Failed TaskStatus."""
        pid = PartitionId.from_proto(task.task_id)
        cancel_event = threading.Event()
        with self._abort_lock:
            self._abort_handles[pid] = cancel_event
        try:
            plan = BallistaCodec.decode_physical(task.plan, self.work_dir)
            config = BallistaConfig(dict(task.props))
            writer = self._new_shuffle_writer(pid, plan, task, config)
            ctx = TaskContext(
                session_id=task.session_id or "default",
                config=config,
                work_dir=self.work_dir,
                job_id=pid.job_id,
                stage_id=pid.stage_id,
                cancel_event=cancel_event,
            )
            partitions = writer.execute_shuffle_write(pid.partition_id, ctx)
            metrics = collect_plan_metrics(writer)
            self.metrics_collector.record_stage(
                pid.job_id, pid.stage_id, pid.partition_id, writer, metrics
            )
            info = TaskInfo(
                pid,
                "completed",
                executor_id=self.id,
                partitions=partitions,
                metrics=metrics,
            )
        except Exception as e:  # noqa: BLE001 - every failure must report
            log.warning("task %s failed: %s", pid, e, exc_info=True)
            info = TaskInfo(pid, "failed", error=f"{type(e).__name__}: {e}")
        finally:
            with self._abort_lock:
                self._abort_handles.pop(pid, None)
        return task_info_to_proto(info)

    def _new_shuffle_writer(
        self, pid: PartitionId, plan, task: pb.TaskDefinition, config: BallistaConfig
    ) -> ShuffleWriterExec:
        """Rebuild the stage root against the local work_dir (reference:
        executor.rs:137-161), re-applying the TPU acceleration pass to the
        stage subplan under this task's session config — acceleration is an
        executor-local physical-optimizer rule, so plans travel
        unaccelerated."""
        from ..ops.stage_compiler import maybe_accelerate

        partitioning = None
        if task.has_output_partitioning:
            partitioning = partitioning_from_proto(task.output_partitioning)
        if isinstance(plan, ShuffleWriterExec):
            inner = plan.input
            partitioning = partitioning or plan.shuffle_output_partitioning
        else:
            inner = plan
        inner = maybe_accelerate(inner, config)
        return ShuffleWriterExec(
            pid.job_id, pid.stage_id, inner, self.work_dir, partitioning
        )

    # --------------------------------------------------------------- abort
    def cancel_task(self, pid: PartitionId) -> bool:
        with self._abort_lock:
            ev = self._abort_handles.get(pid)
        if ev is None:
            return False
        ev.set()
        return True

    def active_task_count(self) -> int:
        with self._abort_lock:
            return len(self._abort_handles)

    def cancel_all(self) -> int:
        with self._abort_lock:
            handles = list(self._abort_handles.values())
        for ev in handles:
            ev.set()
        return len(handles)
