"""Executor core object: runs shuffle-write tasks, tracks abort handles.

Counterpart of the reference's ``executor/src/executor.rs:44-179``: holds
registration metadata, the local ``work_dir`` and concurrency budget;
``execute_task`` decodes the stage plan, rebuilds the ShuffleWriterExec
against the local work_dir (`:137-161` new_shuffle_writer), wraps execution
with a cancellation handle keyed by PartitionId (`:97-134` abortable), and
maps the outcome to a protobuf TaskStatus (``executor/src/lib.rs``
as_task_status).  Panics/exceptions become Failed statuses like the
reference's catch_unwind (``execution_loop.rs:120-130``).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional

from ..config import BallistaConfig
from ..exec.operators import TaskContext
from ..obs import trace
from ..obs.recorder import get_recorder
from ..proto import pb
from ..scheduler.execution_stage import TaskInfo
from ..scheduler.task_status import collect_plan_metrics, task_info_to_proto
from ..serde import BallistaCodec, partitioning_from_proto
from ..serde.scheduler_types import ExecutorMetadata, PartitionId
from ..shuffle.execution_plans import ShuffleWriterExec

log = logging.getLogger(__name__)


def _sum_metric(metrics, key: str) -> int:
    """Total one named counter across the per-operator metric sets (used
    to lift shuffle ``fetch_retries`` into TaskStatus for the scheduler)."""
    return sum(int(values.get(key, 0)) for _, values in metrics)


def _has_tailing_reader(msg) -> bool:
    """Reflection walk over a plan proto: does any ShuffleReaderExecNode
    carry ``tail=True`` (pipelined execution)?  Generic over node shapes
    so new operators never need to register here."""
    if isinstance(msg, pb.ShuffleReaderExecNode):
        return bool(msg.tail)
    for fd, value in msg.ListFields():
        if fd.type != fd.TYPE_MESSAGE:
            continue
        # singular sub-message vs repeated container, told apart by the
        # message surface itself (fd.label is deprecated); map fields
        # iterate KEYS (scalars), which the hasattr guard skips
        children = [value] if hasattr(value, "ListFields") else value
        if any(
            hasattr(v, "ListFields") and _has_tailing_reader(v)
            for v in children
        ):
            return True
    return False


class LoggingMetricsCollector:
    """Prints the per-partition stage plan with metrics (reference:
    executor/src/metrics/mod.rs:28-60)."""

    def record_stage(
        self, job_id: str, stage_id: int, partition: int, plan, metrics
    ) -> None:
        log.info(
            "=== [%s/%s/%s] stage completed: %s metrics=%s ===",
            job_id,
            stage_id,
            partition,
            plan,
            metrics,
        )


class _ProcessWorker:
    """One persistent task-runner subprocess (see ``task_runner.py``)."""

    def __init__(
        self,
        executor_id: str,
        work_dir: str,
        plugin_dir: str = "",
        host: str = "",
    ):
        import os
        import subprocess
        import sys

        args = [
            sys.executable, "-m", "arrow_ballista_tpu.executor.task_runner",
            "--executor-id", executor_id, "--work-dir", work_dir,
        ]
        if host:
            # the worker inherits the parent's advertised host so its
            # local-transport identity matches (shuffle/transport.py)
            args += ["--host", host]
        if plugin_dir:
            args += ["--plugin-dir", plugin_dir]
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        self._proc = subprocess.Popen(
            args, env=env,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        )

    def alive(self) -> bool:
        return self._proc.poll() is None

    def run(self, task_bytes: bytes) -> Optional[bytes]:
        """Execute one task; returns TaskStatus bytes or None if the
        worker died mid-task (killed by cancel, or crashed)."""
        import struct

        try:
            self._proc.stdin.write(struct.pack(">I", len(task_bytes)))
            self._proc.stdin.write(task_bytes)
            self._proc.stdin.flush()
            hdr = self._proc.stdout.read(4)
            if len(hdr) < 4:
                return None
            n = struct.unpack(">I", hdr)[0]
            out = b""
            while len(out) < n:
                chunk = self._proc.stdout.read(n - len(out))
                if not chunk:
                    return None
                out += chunk
            return out
        except (BrokenPipeError, ValueError, OSError):
            return None

    def kill(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()

    def close(self) -> None:
        """Ask for a clean exit; kill if it doesn't comply."""
        import struct

        try:
            self._proc.stdin.write(struct.pack(">I", 0))
            self._proc.stdin.flush()
            self._proc.wait(timeout=3)
        except Exception:
            self.kill()


class _WorkerAbort:
    """Duck-types threading.Event.set() for the abort-handle table: a
    cancelled process-isolated task dies by worker kill.  ``cancelled``
    records that the kill was deliberate — the scheduler must see
    Cancelled (fatal, no retry), not a transient worker crash."""

    def __init__(self, worker: _ProcessWorker):
        self._worker = worker
        self.cancelled = False

    def set(self) -> None:
        self.cancelled = True
        self._worker.kill()


class Executor:
    def __init__(
        self,
        metadata: ExecutorMetadata,
        work_dir: str,
        concurrent_tasks: int = 4,
        metrics_collector: Optional[LoggingMetricsCollector] = None,
        task_isolation: str = "thread",
        plugin_dir: str = "",
    ):
        self.metadata = metadata
        self.work_dir = work_dir
        self.concurrent_tasks = concurrent_tasks
        # local-transport identity (shuffle/transport.py): fetches of
        # partitions served by THIS executor — or any executor advertising
        # the same host — go zero-copy through the filesystem instead of
        # Flight.  Registered here so every executor shape (push, pull,
        # standalone, process-isolated task runner) participates.
        from ..shuffle import transport

        transport.register_local_executor(metadata.id, metadata.host)
        self.metrics_collector = metrics_collector or LoggingMetricsCollector()
        self.task_isolation = task_isolation
        self.plugin_dir = plugin_dir
        # pid -> {attempt: handle}: two attempts of one partition can
        # coexist on this executor (a deadline-reaped task re-dispatched
        # here while the wedged copy still runs), so the table must not
        # let the re-dispatch clobber the old handle — or the old task's
        # cleanup pop the new task's handle
        self._abort_handles: Dict[PartitionId, Dict[int, threading.Event]] = {}
        self._abort_lock = threading.Lock()
        self._idle_workers: List[_ProcessWorker] = []
        self._worker_lock = threading.Lock()

    @property
    def id(self) -> str:
        return self.metadata.id

    # ---------------------------------------------------------------- run
    def execute_task(self, task: pb.TaskDefinition) -> pb.TaskStatus:
        """Run one shuffle-write task to completion; never raises — any
        error becomes a Failed TaskStatus."""
        if self.task_isolation == "process" and self._worker_eligible(task):
            return self._execute_in_worker(task)
        from ..testing.faults import fault_point

        # observability ratchets on with the first traced task and the
        # task's trace context (minted at the scheduler) adopts on this
        # thread so every child span stitches under the job's trace
        trace.enable_from_props(task.props, process=f"executor:{self.id}")
        self._note_external_root(task)
        pid = PartitionId.from_proto(task.task_id)
        cancel_event = threading.Event()
        with self._abort_lock:
            self._abort_handles.setdefault(pid, {})[task.attempt] = cancel_event
        try:
            with trace.activate(task.trace_id, task.parent_span_id), trace.span(
                "task.execute",
                job=pid.job_id,
                stage=pid.stage_id,
                partition=pid.partition_id,
                attempt=task.attempt,
                executor=self.id,
                speculative=bool(task.speculative),
            ):
                fault_point(
                    "executor.execute_task",
                    executor_id=self.id,
                    job_id=pid.job_id,
                    stage_id=pid.stage_id,
                    partition_id=pid.partition_id,
                    attempt=task.attempt,
                )
                # delay-friendly point (faults action="delay"): manufactures
                # deterministic stragglers/wedged tasks for the speculation
                # and deadline-reaper tests; cancel_event cuts the sleep
                # short so CancelTasks still aborts a "wedged" task promptly
                fault_point(
                    "task.run",
                    executor_id=self.id,
                    job_id=pid.job_id,
                    stage_id=pid.stage_id,
                    partition_id=pid.partition_id,
                    attempt=task.attempt,
                    speculative=bool(task.speculative),
                    cancel_event=cancel_event,
                )
                with trace.span("task.prepare"):
                    plan = BallistaCodec.decode_physical(task.plan, self.work_dir)
                    config = BallistaConfig(dict(task.props))
                    writer = self._new_shuffle_writer(pid, plan, task, config)
                ctx = TaskContext(
                    session_id=task.session_id or "default",
                    config=config,
                    work_dir=self.work_dir,
                    job_id=pid.job_id,
                    stage_id=pid.stage_id,
                    cancel_event=cancel_event,
                )
                with trace.span("shuffle.write") as wspan:
                    partitions = writer.execute_shuffle_write(
                        pid.partition_id, ctx
                    )
                    wspan.set_attr(
                        "bytes", sum(p.num_bytes for p in partitions)
                    )
                    wspan.set_attr("partitions", len(partitions))
                    wspan.set_attr(
                        "compression", config.shuffle_compression
                    )
                    wvals = writer.metrics.to_dict()
                    for k in (
                        "bytes_written_raw",
                        "bytes_written_wire",
                        "slab_flushes",
                        "write_queue_full_ns",
                        "device_pid_batches",
                    ):
                        if k in wvals:
                            wspan.set_attr(k, wvals[k])
                metrics = collect_plan_metrics(writer)
                self.metrics_collector.record_stage(
                    pid.job_id, pid.stage_id, pid.partition_id, writer, metrics
                )
                info = TaskInfo(
                    pid,
                    "completed",
                    executor_id=self.id,
                    partitions=partitions,
                    metrics=metrics,
                    attempt=task.attempt,
                    fetch_retries=_sum_metric(metrics, "fetch_retries"),
                    speculative=bool(task.speculative),
                )
        except Exception as e:  # noqa: BLE001 - every failure must report
            log.warning("task %s failed: %s", pid, e, exc_info=True)
            info = TaskInfo(
                pid,
                "failed",
                executor_id=self.id,
                error=f"{type(e).__name__}: {e}",
                attempt=task.attempt,
                speculative=bool(task.speculative),
            )
        finally:
            self._drop_abort_handle(pid, task.attempt)
        if trace.is_enabled():
            # piggyback every span finished in this process (this task's
            # and any stragglers) onto the status report
            info.spans = get_recorder().drain()
        return task_info_to_proto(info)

    @staticmethod
    def _note_external_root(task: pb.TaskDefinition) -> None:
        """Remember the session's external shuffle root process-wide: the
        drain-time replica upload needs it after the last task finished,
        when no session config is in scope."""
        from ..config import SHUFFLE_EXTERNAL_PATH

        ext = task.props.get(SHUFFLE_EXTERNAL_PATH, "")
        if ext:
            from ..shuffle import store as shuffle_store

            shuffle_store.note_external_root(ext)

    def _new_shuffle_writer(
        self, pid: PartitionId, plan, task: pb.TaskDefinition, config: BallistaConfig
    ) -> ShuffleWriterExec:
        """Rebuild the stage root against the local work_dir (reference:
        executor.rs:137-161), re-applying the TPU acceleration pass to the
        stage subplan under this task's session config — acceleration is an
        executor-local physical-optimizer rule, so plans travel
        unaccelerated."""
        from ..ops.stage_compiler import maybe_accelerate

        partitioning = None
        if task.has_output_partitioning:
            partitioning = partitioning_from_proto(task.output_partitioning)
        if isinstance(plan, ShuffleWriterExec):
            inner = plan.input
            partitioning = partitioning or plan.shuffle_output_partitioning
        else:
            inner = plan
        inner = maybe_accelerate(inner, config)
        return ShuffleWriterExec(
            pid.job_id, pid.stage_id, inner, self.work_dir, partitioning
        )

    # ---------------------------------------------------- process isolation
    def _worker_eligible(self, task: pb.TaskDefinition) -> bool:
        """Process isolation runs tasks whose outputs OUTLIVE the worker:
        file shuffle (shared work_dir) and memory shuffle (the worker
        SPOOLS mem:// partitions to the shared work_dir and this process
        absorbs them into its store on completion).  Device stages need
        this process's XLA client and keep the thread path on a real
        accelerator — the measured residual risk
        (tests/test_executor_isolation.py device-stage latency test).
        Pipelined TAILING tasks also keep the thread path: they stream
        the scheduler's shuffle-location feed through THIS process's
        delta-store mirror, which a task-runner subprocess (no scheduler
        stub, no push notifications) cannot reach.  The plan walk is
        gated on the session's pipelined knob (which the scheduler
        stamps into the props whenever it could have produced a tailing
        plan), so the default-off dispatch path never pays a second
        plan parse."""
        if task.props.get("ballista.shuffle.pipelined", "").lower() in (
            "true", "1", "yes",
        ):
            try:
                if _has_tailing_reader(
                    pb.PhysicalPlanNode.FromString(task.plan)
                ):
                    return False
            except Exception:  # noqa: BLE001 - undecodable: fail in-thread
                return False
        props = dict(task.props)
        if props.get("ballista.tpu.enable", "true").lower() in (
            "true", "1", "yes",
        ):
            import jax

            # CPU platform: "device" stages are host jit — safe in a
            # worker.  A real accelerator belongs to THIS process only.
            if jax.default_backend() != "cpu":
                return False
        return True

    def _execute_in_worker(self, task: pb.TaskDefinition) -> pb.TaskStatus:
        """Run the task in a pooled task-runner subprocess (reference
        DedicatedExecutor property: plan execution cannot starve Flight
        serving / CancelTasks / heartbeats in this process)."""
        pid = PartitionId.from_proto(task.task_id)
        # the worker records its own spans (they ride back inside the
        # TaskStatus bytes); the parent still ratchets obs on so ITS
        # heartbeat piggyback and Flight-serving spans flow too
        trace.enable_from_props(task.props, process=f"executor:{self.id}")
        self._note_external_root(task)
        with self._worker_lock:
            worker = (
                self._idle_workers.pop() if self._idle_workers else None
            )
        if worker is None or not worker.alive():
            worker = _ProcessWorker(
                self.id, self.work_dir, self.plugin_dir,
                host=self.metadata.host,
            )
        abort = _WorkerAbort(worker)
        with self._abort_lock:
            self._abort_handles.setdefault(pid, {})[task.attempt] = abort
        try:
            out = worker.run(task.SerializeToString())
        finally:
            self._drop_abort_handle(pid, task.attempt)
        if out is None:
            worker.kill()
            # a deliberate cancel is fatal (no retry); an unexplained
            # worker death is a transient infrastructure failure
            error = (
                "Cancelled: task cancelled (worker killed)"
                if abort.cancelled
                else "ExecutionError: task worker terminated (crashed)"
            )
            info = TaskInfo(
                pid, "failed",
                executor_id=self.id,
                error=error,
                attempt=task.attempt,
                speculative=bool(task.speculative),
            )
            return task_info_to_proto(info)
        with self._worker_lock:
            self._idle_workers.append(worker)
        status = pb.TaskStatus()
        status.ParseFromString(out)
        self._absorb_spooled(status)
        return status

    def _absorb_spooled(self, status: pb.TaskStatus) -> None:
        """Move a worker's spooled mem:// partitions into THIS process's
        memory store (the Flight service serves from here)."""
        if status.WhichOneof("status") != "completed":
            return
        from ..shuffle import memory_store

        spool = os.path.join(self.work_dir, ".memspool")
        for part in status.completed.partitions:
            if part.path.startswith(memory_store.SCHEME):
                if not memory_store.absorb_spooled(spool, part.path):
                    log.warning(
                        "spooled memory partition missing: %s", part.path
                    )

    def shutdown_workers(self) -> None:
        # worker-pool teardown ONLY — full executor teardown is close(),
        # which also drops the local-transport identity.  A caller that
        # stops here leaves the identity registered; later fetches then
        # warn and fall back to Flight per miss instead of going zero-copy
        # (self-healing, but noisy — prefer close()).
        with self._worker_lock:
            workers, self._idle_workers = self._idle_workers, []
        for w in workers:
            w.close()

    def close(self) -> None:
        """Full teardown: drop this executor's local-transport identity
        (a later fetch in this process must not treat its dead work_dir
        as servable) and stop the worker pool."""
        from ..shuffle import transport

        transport.unregister_local_executor(self.metadata.id)
        self.shutdown_workers()

    # --------------------------------------------------------------- abort
    def _drop_abort_handle(self, pid: PartitionId, attempt: int) -> None:
        with self._abort_lock:
            per = self._abort_handles.get(pid)
            if per is not None:
                per.pop(attempt, None)
                if not per:
                    self._abort_handles.pop(pid, None)

    def cancel_task(self, pid: PartitionId) -> bool:
        """Abort the OLDEST live attempt of ``pid`` — CancelTasks is
        pid-addressed and always targets a superseded copy (losing
        duplicate, reaped straggler, cancelled job), so when two attempts
        coexist here the newer one must survive the cancel."""
        with self._abort_lock:
            per = self._abort_handles.get(pid)
            ev = per[min(per)] if per else None
        if ev is None:
            return False
        ev.set()
        return True

    def active_task_count(self) -> int:
        with self._abort_lock:
            return sum(len(per) for per in self._abort_handles.values())

    def cancel_all(self) -> int:
        with self._abort_lock:
            handles = [
                ev for per in self._abort_handles.values()
                for ev in per.values()
            ]
        for ev in handles:
            ev.set()
        return len(handles)
