"""Process-isolated task worker (the DedicatedExecutor slot).

Counterpart of the reference's ``executor/src/cpu_bound_executor.rs:37-131``:
CPU-bound plan execution must not be able to starve the executor's service
plane — Flight shuffle serving, CancelTasks, heartbeats.  The reference
isolates with a second prioritized tokio runtime; a Python executor
isolates with a second PROCESS: the worker executes the (protobuf) task
plan against the shared ``work_dir`` and the parent's GIL never runs plan
code, so a pure-Python UDF pegging every worker cannot slow a downstream
stage's shuffle fetch.

Protocol (stdin/stdout, length-prefixed): the parent writes
``[u32 BE len][TaskDefinition]``; the worker replies
``[u32 BE len][TaskStatus]``.  ``len == 0`` → clean exit; stdin EOF (the
parent died) → exit.  The worker pins the CPU platform before anything
touches jax — device stages belong to the PARENT process (XLA client
state is per-process), which keeps the in-thread path for them; the
executor only routes memory-shuffle-free tasks here.
"""

from __future__ import annotations

import struct
import sys


def _read_exact(f, n: int):
    buf = b""
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(
        prog="arrow_ballista_tpu.executor.task_runner"
    )
    parser.add_argument("--executor-id", required=True)
    parser.add_argument("--work-dir", required=True)
    parser.add_argument("--plugin-dir", default="")
    # the parent executor's advertised host: the worker shares its
    # filesystem, so it inherits the local-transport identity
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args()

    # never the device: a second process must not try to claim the chip
    # (the env var alone loses to a session-level platform pin)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..proto import pb
    from ..serde.scheduler_types import ExecutorMetadata, ExecutorSpecification
    from ..shuffle import memory_store
    from ..udf import load_udf_plugins
    from .executor import Executor

    # mem:// puts in this process spool to the shared work_dir; the
    # parent absorbs them into its store when the task completes
    import os

    memory_store.set_spool_dir(os.path.join(args.work_dir, ".memspool"))

    if args.plugin_dir:
        load_udf_plugins(args.plugin_dir)
    metadata = ExecutorMetadata(
        args.executor_id, args.host, 0, 0, ExecutorSpecification(1)
    )
    ex = Executor(metadata, args.work_dir, concurrent_tasks=1)

    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    while True:
        hdr = _read_exact(stdin, 4)
        if hdr is None:
            return  # parent died or closed us
        n = struct.unpack(">I", hdr)[0]
        if n == 0:
            return  # clean shutdown
        payload = _read_exact(stdin, n)
        if payload is None:
            return
        task = pb.TaskDefinition()
        task.ParseFromString(payload)
        # worker-crash injection (BALLISTA_FAULTS is inherited through the
        # environment): "exit" hard-kills this process mid-task, "raise"
        # propagates out of main() — either way the parent sees EOF and
        # reports a transient "task worker terminated" failure
        from ..testing.faults import fault_point

        fault_point(
            "executor.task_runner",
            executor_id=args.executor_id,
            attempt=task.attempt,
        )
        status = ex.execute_task(task)  # never raises
        out = status.SerializeToString()
        stdout.write(struct.pack(">I", len(out)))
        stdout.write(out)
        stdout.flush()


if __name__ == "__main__":
    main()
