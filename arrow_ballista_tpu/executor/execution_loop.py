"""Pull-mode executor poll loop.

Counterpart of the reference's ``executor/src/execution_loop.rs:46-255``:
loop { PollWork(metadata, can_accept_task, drained statuses) }; a returned
TaskDefinition decrements the local slot counter and runs on a worker
thread; finished statuses queue up and piggyback on the next poll; idle
polls sleep 100ms (`:114`).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

import grpc

from ..proto import pb
from ..proto.rpc import SchedulerGrpcStub
from .executor import Executor

log = logging.getLogger(__name__)

IDLE_POLL_INTERVAL_S = 0.1  # reference: execution_loop.rs:114


class PollLoop:
    def __init__(
        self,
        executor: Executor,
        scheduler: SchedulerGrpcStub,
        poll_interval_s: float = IDLE_POLL_INTERVAL_S,
    ):
        self.executor = executor
        self.scheduler = scheduler
        self.poll_interval_s = poll_interval_s
        # pipelined execution (ISSUE 15): pull-mode tailing fetches read
        # the scheduler's shuffle-location feed by polling
        # GetShuffleLocationDelta through this loop's stub
        from ..shuffle import delta_store

        delta_store.configure_scheduler(lambda: self.scheduler)
        self._statuses: "queue.Queue[pb.TaskStatus]" = queue.Queue()
        self._free_count = executor.concurrent_tasks
        self._count_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "PollLoop":
        # bootstrap poll: register metadata synchronously (can_accept_task
        # False so no task is handed out before the loop thread exists).
        # Without it the first real poll races anything that looks the
        # executor up right after start() — decommission, REST state, tests.
        try:
            self.scheduler.PollWork(
                pb.PollWorkParams(
                    metadata=self._registration(),
                    can_accept_task=False,
                    task_status=[],
                ),
                timeout=20,
            )
        except grpc.RpcError as e:
            # scheduler unreachable at start is tolerated in pull mode —
            # the loop below keeps retrying
            log.debug("bootstrap PollWork failed (%s); loop will retry", e.code())
        self._thread = threading.Thread(
            target=self._run, name=f"poll-loop-{self.executor.id}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        # abort THIS executor's in-flight shuffle fetch pipelines (scoped
        # by work_dir): a fetch worker blocked on a dead peer would
        # otherwise pin its task thread past shutdown
        from ..shuffle.fetcher import shutdown_active_fetchers

        shutdown_active_fetchers(owner=self.executor.work_dir)
        if self._thread is not None:
            self._thread.join(timeout)

    def _registration(self) -> pb.ExecutorRegistration:
        return pb.ExecutorRegistration(
            id=self.executor.metadata.id,
            host=self.executor.metadata.host,
            has_host=bool(self.executor.metadata.host),
            flight_port=self.executor.metadata.flight_port,
            grpc_port=self.executor.metadata.grpc_port,
            specification=self.executor.metadata.specification.to_proto(),
        )

    # ---------------------------------------------------------------- loop
    def _run(self) -> None:
        registration = self._registration()
        while not self._stop.is_set():
            statuses = self._drain_statuses()
            with self._count_lock:
                can_accept = self._free_count > 0
            try:
                result: pb.PollWorkResult = self.scheduler.PollWork(
                    pb.PollWorkParams(
                        metadata=registration,
                        can_accept_task=can_accept,
                        task_status=statuses,
                    ),
                    timeout=20,
                )
            except grpc.RpcError as e:
                # scheduler unreachable: requeue statuses and retry
                for s in statuses:
                    self._statuses.put(s)
                log.debug("PollWork failed (%s); retrying", e.code())
                if self._stop.wait(self.poll_interval_s):
                    break
                continue

            if result.has_task:
                self._spawn(result.task)
                continue  # poll again immediately while work may remain
            if self._stop.wait(self.poll_interval_s):
                break

    def _drain_statuses(self) -> list:
        out = []
        while True:
            try:
                out.append(self._statuses.get_nowait())
            except queue.Empty:
                return out

    def _spawn(self, task: pb.TaskDefinition) -> None:
        with self._count_lock:
            self._free_count -= 1
        t = threading.Thread(
            target=self._run_task, args=(task,), name="task-runner", daemon=True
        )
        t.start()

    def _run_task(self, task: pb.TaskDefinition) -> None:
        try:
            status = self.executor.execute_task(task)
        finally:
            with self._count_lock:
                self._free_count += 1
        self._statuses.put(status)
