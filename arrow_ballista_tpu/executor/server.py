"""Push-mode executor server.

Counterpart of the reference's ``executor/src/executor_server.rs``: starts
an ExecutorGrpc server, registers with the scheduler (`:162-178`), runs a
Heartbeater (60s, `:401-431`) and a TaskRunnerPool — a task-runner loop
draining the LaunchTask channel onto worker threads (`:538-592`) and a
status-reporter loop batching TaskStatus per curator scheduler
(`:446-536`).  RPC handlers: LaunchTask / StopExecutor / CancelTasks
(`:595-662`).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Dict, List, Optional

import grpc

from ..proto import pb
from ..proto.rpc import (
    SchedulerGrpcStub,
    add_executor_servicer,
    make_channel,
    make_server,
)
from ..serde.scheduler_types import PartitionId
from .executor import Executor

log = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_S = 60.0  # reference: executor_server.rs:421


class ExecutorGrpcService:
    """The three ExecutorGrpc RPC handlers (reference: `:595-662`)."""

    def __init__(self, server: "ExecutorServer"):
        self.server = server

    def LaunchTask(self, request: pb.LaunchTaskParams, context) -> pb.LaunchTaskResult:
        for task in request.tasks:
            self.server.enqueue_task(task, request.scheduler_id)
        return pb.LaunchTaskResult(success=True)

    def StopExecutor(
        self, request: pb.StopExecutorParams, context
    ) -> pb.StopExecutorResult:
        log.info(
            "StopExecutor received (force=%s, drain=%s): %s",
            request.force, request.drain, request.reason,
        )
        if request.drain:
            # graceful decommission: drain on a detached thread — finish
            # running tasks inside the budget, upload un-replicated
            # shuffle partitions, report ExecutorStopped, then exit
            threading.Thread(
                target=self.server.drain,
                args=(request.reason, request.drain_timeout_seconds),
                name="executor-drain",
                daemon=True,
            ).start()
            return pb.StopExecutorResult()
        if request.force:
            self.server.executor.cancel_all()
        self.server.trigger_shutdown(request.reason)
        return pb.StopExecutorResult()

    def CancelTasks(
        self, request: pb.CancelTasksParams, context
    ) -> pb.CancelTasksResult:
        ok = True
        for p in request.partition_ids:
            pid = PartitionId.from_proto(p)
            if not self.server.executor.cancel_task(pid):
                ok = False
        return pb.CancelTasksResult(cancelled=ok)

    def UpdateShuffleLocations(
        self, request: pb.UpdateShuffleLocationsParams, context
    ) -> pb.UpdateShuffleLocationsResult:
        """Streaming pipelined execution (ISSUE 15): fresh map-output
        location deltas for feeds this executor's tailing consumer tasks
        are streaming; merged into the process-wide mirror."""
        from ..shuffle import delta_store

        for d in request.deltas:
            delta_store.apply_delta_proto(d)
        return pb.UpdateShuffleLocationsResult(success=True)


class Heartbeater:
    """Periodic HeartBeatFromExecutor (reference: `:401-431`).

    ``telemetry`` (an ``obs.telemetry.TelemetrySampler``) piggybacks a
    resource snapshot on every beat.  Unlike the span payload — which
    requeues when the RPC fails, so traces keep no gaps — a telemetry
    snapshot is latest-wins: a lost beat is simply superseded by the
    fresh sample taken for the next one."""

    def __init__(
        self,
        executor_id: str,
        scheduler: SchedulerGrpcStub,
        interval_s: float = HEARTBEAT_INTERVAL_S,
        telemetry=None,
        on_reregister: Optional[Callable[[], None]] = None,
    ):
        self.executor_id = executor_id
        self.scheduler = scheduler
        self.interval_s = interval_s
        self.telemetry = telemetry
        self.on_reregister = on_reregister
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Heartbeater":
        self._send()  # immediate first beat so liveness starts now
        self._thread = threading.Thread(
            target=self._run, name="heartbeater", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._send()

    def _send(self) -> None:
        from ..obs import trace
        from ..obs.recorder import get_recorder
        from ..testing.faults import FaultInjected, fault_point

        drained = None
        try:
            fault_point("executor.heartbeat", executor_id=self.executor_id)
            status = pb.ExecutorStatus()
            status.active = ""
            params = pb.HeartBeatParams(
                executor_id=self.executor_id, status=status
            )
            if self.telemetry is not None:
                snap = self.telemetry.sample()
                if snap is not None:
                    import json as _json

                    params.telemetry_json = _json.dumps(snap).encode()
            if trace.is_enabled():
                # spans finished between task reports (Flight serving,
                # cache activity) ride the heartbeat to the trace store
                drained = get_recorder().drain()
                if drained:
                    import json as _json

                    params.spans_json = _json.dumps(drained).encode()
            result = self.scheduler.HeartBeatFromExecutor(params, timeout=10)
            if getattr(result, "reregister", False) and self.on_reregister:
                # the scheduler restarted and lost our metadata (memory
                # backend) while this process survived — re-register so
                # slots/endpoints rebuild instead of heartbeating into
                # a registry that can never dispatch to us
                log.info("scheduler requested re-registration; re-registering")
                try:
                    self.on_reregister()
                except Exception:  # noqa: BLE001 - next beat retries
                    log.warning("re-registration failed", exc_info=True)
        except FaultInjected as e:
            # injected dropped beat: skip this interval, next one retries
            log.warning("heartbeat suppressed by fault injection: %s", e)
        except grpc.RpcError as e:
            # the beat (and its span payload) never arrived: give the
            # spans back so the next beat re-ships them instead of
            # leaving silent trace gaps exactly when the system limps
            if drained:
                get_recorder().requeue(drained)
            log.warning("heartbeat failed: %s", e.code())


class ExecutorServer:
    """Owns the gRPC server + task runner pool + status reporter."""

    def __init__(
        self,
        executor: Executor,
        scheduler_host: str,
        scheduler_port: int,
        heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S,
        on_shutdown: Optional[Callable[[str], None]] = None,
        bind_host: str = "0.0.0.0",
        telemetry_enabled: bool = True,
    ):
        from ..obs.telemetry import TelemetrySampler

        self.bind_host = bind_host
        self.executor = executor
        self.scheduler = SchedulerGrpcStub(
            make_channel(scheduler_host, scheduler_port)
        )
        self._scheduler_stubs: Dict[str, SchedulerGrpcStub] = {
            f"{scheduler_host}:{scheduler_port}": self.scheduler
        }
        # pipelined execution: tailing fetches poll the scheduler's
        # shuffle-location feed when a push notification hasn't arrived
        # yet (catch-up for the startup race and lost pushes)
        from ..shuffle import delta_store

        delta_store.configure_scheduler(lambda: self.scheduler)
        # the telemetry piggyback is the one obs piece on by default: the
        # sampler is O(1) per beat (the work-dir disk walk is throttled)
        self.telemetry = TelemetrySampler(
            work_dir=executor.work_dir,
            slots_total=executor.concurrent_tasks,
            active_tasks_fn=executor.active_task_count,
            enabled=telemetry_enabled,
        )
        self.heartbeater = Heartbeater(
            executor.id, self.scheduler, heartbeat_interval_s,
            telemetry=self.telemetry, on_reregister=self._register,
        )
        self._tasks: "queue.Queue" = queue.Queue()
        self._statuses: "queue.Queue" = queue.Queue()
        self._draining = False
        self._drain_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._grpc_server: Optional[grpc.Server] = None
        self.grpc_port: int = executor.metadata.grpc_port
        self.on_shutdown = on_shutdown

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "ExecutorServer":
        # 1. gRPC server first so the scheduler can push immediately
        self._grpc_server = make_server()
        add_executor_servicer(self._grpc_server, ExecutorGrpcService(self))
        # bind locally on all interfaces; metadata.host is the ADVERTISE
        # address (may be a DNS name that is not a local interface)
        bound = self._grpc_server.add_insecure_port(
            f"{self.bind_host}:{self.grpc_port}"
        )
        if self.grpc_port == 0:
            self.grpc_port = bound
            meta = self.executor.metadata
            object.__setattr__(meta, "grpc_port", bound)
        self._grpc_server.start()

        # 2. register with the scheduler (reference: `:162-178`)
        self._register()

        # 3. heartbeats + worker pool + status reporter
        self.heartbeater.start()
        for i in range(self.executor.concurrent_tasks):
            t = threading.Thread(
                target=self._task_runner, name=f"task-runner-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        reporter = threading.Thread(
            target=self._status_reporter, name="status-reporter", daemon=True
        )
        reporter.start()
        self._threads.append(reporter)
        return self

    def _register(self) -> None:
        """Send RegisterExecutor — on startup and again whenever a
        heartbeat answer carries ``reregister`` (a restarted scheduler
        adopted this surviving process but lost its metadata).  In push
        mode registration also re-mints the slot reservations."""
        meta = self.executor.metadata
        registration = pb.ExecutorRegistration(
            id=meta.id,
            host=meta.host,
            has_host=bool(meta.host),
            flight_port=meta.flight_port,
            grpc_port=self.grpc_port,
            specification=meta.specification.to_proto(),
        )
        result = self.scheduler.RegisterExecutor(
            pb.RegisterExecutorParams(metadata=registration), timeout=20
        )
        if not result.success:
            raise RuntimeError("scheduler refused executor registration")

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self.heartbeater.stop()
        # abort this executor's in-flight shuffle fetch pipelines (the
        # push-mode analogue of PollLoop.stop's cleanup)
        from ..shuffle.fetcher import shutdown_active_fetchers

        shutdown_active_fetchers(owner=self.executor.work_dir)
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1)

    def trigger_shutdown(self, reason: str) -> None:
        if self.on_shutdown is not None:
            # shutdown must not run on the gRPC handler thread
            threading.Thread(
                target=self.on_shutdown, args=(reason,), daemon=True
            ).start()

    # ---------------------------------------------------------------- drain
    def drain(self, reason: str, timeout_s: float = 0.0) -> int:
        """Graceful decommission (ISSUE 6): finish running tasks within
        ``timeout_s`` (the scheduler has already stopped sending new
        ones), cancel-and-hand-off whatever outlives the budget, flush
        reported statuses, upload every un-replicated shuffle partition
        to the external store, report ExecutorStopped, then shut down.
        Returns the number of partitions uploaded."""
        import time as _time

        from ..shuffle import store as shuffle_store

        with self._drain_lock:
            # concurrent drain RPCs (operator REST + scheduler, or a gRPC
            # retry) must collapse to ONE drain cycle
            if self._draining:
                return 0
            self._draining = True
        timeout = timeout_s if timeout_s > 0 else 30.0
        deadline = _time.monotonic() + timeout
        log.info("draining executor %s (budget %.0fs)", self.executor.id, timeout)
        while (
            _time.monotonic() < deadline
            and (self.executor.active_task_count() > 0 or not self._tasks.empty())
        ):
            _time.sleep(0.05)
        if self.executor.active_task_count() > 0:
            # past the budget: cancel the stragglers — the scheduler's
            # draining-handoff guard re-queues them budget-free
            n = self.executor.cancel_all()
            log.warning(
                "drain budget exhausted with %d task(s) running; cancelled",
                n,
            )
            grace = _time.monotonic() + 5.0
            while _time.monotonic() < grace and self.executor.active_task_count() > 0:
                _time.sleep(0.05)
        # let the status reporter flush: a completed status that never
        # reaches the scheduler before ExecutorStopped would be dropped
        # by the dead-executor guard and strand its partition
        flush_deadline = _time.monotonic() + 5.0
        while _time.monotonic() < flush_deadline and not self._statuses.empty():
            _time.sleep(0.05)
        _time.sleep(0.25)  # in-flight UpdateTaskStatus RPC tail
        # upload whatever has no external copy yet, then flush the async
        # replicator so nothing queued is lost with this process
        uploaded, failed = shuffle_store.drain_upload(
            self.executor.work_dir, shuffle_store.noted_external_root()
        )
        shuffle_store.replicator().flush(timeout=30.0)
        if failed:
            log.warning("drain: %d upload(s) failed (degraded)", len(failed))
        log.info(
            "drain complete: %d partition(s) uploaded; reporting stopped",
            uploaded,
        )
        try:
            self.scheduler.ExecutorStopped(
                pb.ExecutorStoppedParams(
                    executor_id=self.executor.id,
                    reason=f"drained: {reason} ({uploaded} partition(s) uploaded)",
                ),
                timeout=10,
            )
        except grpc.RpcError as e:
            log.warning("ExecutorStopped after drain failed: %s", e.code())
        self.trigger_shutdown(f"drained: {reason}")
        return uploaded

    # ------------------------------------------------------------- running
    def enqueue_task(self, task: pb.TaskDefinition, scheduler_id: str) -> None:
        self._tasks.put((task, scheduler_id))

    def _task_runner(self) -> None:
        while not self._stop.is_set():
            try:
                task, scheduler_id = self._tasks.get(timeout=0.2)
            except queue.Empty:
                continue
            status = self.executor.execute_task(task)
            self._statuses.put((scheduler_id, status))

    def _status_reporter(self) -> None:
        """Batch statuses per curator scheduler (reference: `:446-536`)."""
        while not self._stop.is_set():
            try:
                scheduler_id, status = self._statuses.get(timeout=0.2)
            except queue.Empty:
                continue
            batch: Dict[str, List[pb.TaskStatus]] = {scheduler_id: [status]}
            while True:
                try:
                    sid, s = self._statuses.get_nowait()
                    batch.setdefault(sid, []).append(s)
                except queue.Empty:
                    break
            for sid, statuses in batch.items():
                stub = self._stub_for(sid)
                try:
                    stub.UpdateTaskStatus(
                        pb.UpdateTaskStatusParams(
                            executor_id=self.executor.id, task_status=statuses
                        ),
                        timeout=20,
                    )
                except grpc.RpcError as e:
                    log.warning(
                        "UpdateTaskStatus to %s failed (%s); retrying", sid, e.code()
                    )
                    for s in statuses:
                        self._statuses.put((sid, s))
                    # back off so a dead scheduler doesn't spin this thread
                    self._stop.wait(0.5)

    def _stub_for(self, scheduler_id: str) -> SchedulerGrpcStub:
        """Curator scheduler ids are host:port strings; fall back to the
        registration scheduler (reference: `:222-245` multi-scheduler cache)."""
        stub = self._scheduler_stubs.get(scheduler_id)
        if stub is not None:
            return stub
        if ":" in scheduler_id:
            host, _, port = scheduler_id.rpartition(":")
            try:
                stub = SchedulerGrpcStub(make_channel(host, int(port)))
                self._scheduler_stubs[scheduler_id] = stub
                return stub
            except Exception:  # noqa: BLE001
                pass
        return self.scheduler
