"""Executor process binary: ``python -m arrow_ballista_tpu.executor``.

Counterpart of the reference's ``executor/src/main.rs:74-301`` +
``executor_config_spec.toml:27-121``: scheduler host/port, bind/external
host, Flight port (default 50051) and gRPC port (50052), work_dir,
concurrent_tasks (default 4), scheduling policy, and the shuffle-data
janitor (delete job dirs older than the TTL every cleanup interval;
reference ``main.rs:186-214,320-474``).  Graceful shutdown notifies the
scheduler via ExecutorStopped (``main.rs:252-299``).
"""

from __future__ import annotations

import argparse
import logging
import os
import shutil
import signal
import threading
import time
import uuid

CONFIG_KEYS = {
    "executor_id": (
        str, "",
        "pre-assigned executor id (default: random).  Launch controllers "
        "(the autoscaler's ExecutorProvider) set this so their handle and "
        "the registration correlate",
    ),
    "scheduler_host": (str, "localhost", "scheduler hostname"),
    "scheduler_port": (int, 50050, "scheduler gRPC port"),
    "bind_host": (str, "0.0.0.0", "local bind address"),
    "external_host": (str, "", "address advertised to the scheduler"),
    "bind_port": (int, 50051, "Arrow Flight (shuffle) port"),
    "bind_grpc_port": (int, 50052, "executor gRPC port (push mode)"),
    "work_dir": (str, "", "shuffle data dir (default: tmp)"),
    "concurrent_tasks": (int, 4, "task slots"),
    "task_scheduling_policy": (str, "pull-staged", "pull-staged | push-staged"),
    "task_isolation": (
        str, "process",
        "process | thread: 'process' (default) runs shuffle tasks — file "
        "AND memory data plane (mem:// partitions spool through the "
        "shared work_dir and the executor absorbs them) — in pooled "
        "worker subprocesses so plan execution (e.g. a GIL-pegging UDF) "
        "cannot starve Flight serving/CancelTasks/heartbeats (reference "
        "DedicatedExecutor); device stages stay in-process on a real "
        "accelerator (the XLA client is per-process)",
    ),
    "plugin_dir": (str, "", "directory of UDF plugin .py modules"),
    "job_data_clean_up_interval_seconds": (int, 0, "janitor period (0=off)"),
    "job_data_ttl_seconds": (int, 604800, "delete job dirs older than this"),
    "heartbeat_sidecar": (int, 1, "process-isolated liveness backstop (0=off)"),
    "heartbeat_interval_seconds": (
        float, 0.0,
        "push-mode heartbeat cadence (0 = built-in default); autoscaled "
        "executors beat faster so liveness tracks launches",
    ),
    "telemetry_enabled": (int, 1, "piggyback a resource snapshot (CPU%, RSS, shuffle disk, queue occupancy, slots) on every heartbeat; 0 disables (push mode only)"),
    "log_level_setting": (str, "INFO", "log filter"),
    "log_dir": (str, "", "write logs to a file here instead of stdout"),
    "log_file_name_prefix": (str, "executor", "log file prefix"),
}


def load_config(argv=None) -> dict:
    cfg = {k: v[1] for k, v in CONFIG_KEYS.items()}
    ap = argparse.ArgumentParser("ballista-tpu executor")
    ap.add_argument("--config-file", default=None, help="TOML config file")
    for k, (typ, default, hlp) in CONFIG_KEYS.items():
        ap.add_argument(f"--{k.replace('_', '-')}", type=typ, default=None, help=hlp)
    args = ap.parse_args(argv)
    if args.config_file:
        import tomllib

        with open(args.config_file, "rb") as f:
            for k, v in tomllib.load(f).items():
                k = k.replace("-", "_")
                if k in cfg:
                    cfg[k] = CONFIG_KEYS[k][0](v)
    for k in CONFIG_KEYS:
        env = os.environ.get(f"BALLISTA_EXECUTOR_{k.upper()}")
        if env is not None:
            cfg[k] = CONFIG_KEYS[k][0](env)
    for k in CONFIG_KEYS:
        v = getattr(args, k, None)
        if v is not None:
            cfg[k] = v
    return cfg


class ShuffleJanitor(threading.Thread):
    """Periodic shuffle-data GC (reference: executor/src/main.rs:320-474):
    removes ``work_dir/<job>`` trees whose newest file is older than the
    TTL; a full sweep runs on shutdown."""

    def __init__(self, work_dir: str, interval_s: float, ttl_s: float):
        super().__init__(name="shuffle-janitor", daemon=True)
        self.work_dir = work_dir
        self.interval_s = interval_s
        self.ttl_s = ttl_s
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sweep(self.ttl_s)

    def stop(self, final_sweep: bool = False) -> None:
        self._stop.set()
        if final_sweep:
            self.sweep(0)

    def sweep(self, ttl_s: float) -> None:
        from ..shuffle import memory_store

        memory_store.sweep(ttl_s)
        now = time.time()
        try:
            entries = os.listdir(self.work_dir)
        except OSError:
            return
        for job in entries:
            path = os.path.join(self.work_dir, job)
            if job == ".memspool" and os.path.isdir(path):
                # orphaned worker spool files (a failed/cancelled task's
                # mem:// partitions were never absorbed): age per file
                for f in os.listdir(path):
                    fp = os.path.join(path, f)
                    try:
                        if now - os.path.getmtime(fp) > ttl_s:
                            os.unlink(fp)
                    except OSError:
                        pass
                continue
            if not os.path.isdir(path):
                continue
            newest = 0.0
            for root, _dirs, files in os.walk(path):
                for f in files:
                    try:
                        newest = max(newest, os.path.getmtime(os.path.join(root, f)))
                    except OSError:
                        pass
            if newest == 0.0:
                # no files yet (a task may have just created the dir) —
                # age by the directory's own mtime, not the epoch
                try:
                    newest = os.path.getmtime(path)
                except OSError:
                    continue
            if now - newest > ttl_s:
                logging.getLogger("ballista.executor").info(
                    "janitor: removing job dir %s", path
                )
                shutil.rmtree(path, ignore_errors=True)


def main(argv=None) -> None:
    from ..utils import apply_jax_platform_env

    apply_jax_platform_env()
    cfg = load_config(argv)
    from ..scheduler.__main__ import init_logging

    init_logging(cfg)
    log = logging.getLogger("ballista.executor")

    import tempfile

    from ..config import TaskSchedulingPolicy
    from ..flight.server import FlightServerHandle
    from ..proto import pb
    from ..proto.rpc import SchedulerGrpcStub, make_channel
    from ..serde.scheduler_types import ExecutorMetadata, ExecutorSpecification
    from .execution_loop import PollLoop
    from .executor import Executor
    from .server import ExecutorServer

    work_dir = cfg["work_dir"] or tempfile.mkdtemp(prefix="ballista-executor-")
    os.makedirs(work_dir, exist_ok=True)

    # populate the process-global UDF registry BEFORE any task arrives —
    # plans reference UDFs by name only (reference: executors load .so
    # plugins from plugin_dir at startup)
    if cfg["plugin_dir"]:
        from ..udf import load_udf_plugins

        n = load_udf_plugins(cfg["plugin_dir"])
        log.info("loaded %d UDF plugin(s) from %s", n, cfg["plugin_dir"])
    external = cfg["external_host"] or cfg["bind_host"]
    if external == "0.0.0.0":
        external = "127.0.0.1"

    flight = FlightServerHandle(
        work_dir, host=cfg["bind_host"], port=cfg["bind_port"]
    ).start()
    policy = (
        TaskSchedulingPolicy.PUSH_STAGED
        if cfg["task_scheduling_policy"] == "push-staged"
        else TaskSchedulingPolicy.PULL_STAGED
    )
    metadata = ExecutorMetadata(
        id=cfg["executor_id"] or uuid.uuid4().hex[:12],
        host=external,
        flight_port=flight.port,
        grpc_port=cfg["bind_grpc_port"] if policy == TaskSchedulingPolicy.PUSH_STAGED else 0,
        specification=ExecutorSpecification(task_slots=cfg["concurrent_tasks"]),
    )
    executor = Executor(
        metadata, work_dir, cfg["concurrent_tasks"],
        task_isolation=cfg["task_isolation"], plugin_dir=cfg["plugin_dir"],
    )
    log.info(
        "executor %s starting: flight :%d, policy=%s, work_dir=%s",
        executor.id, flight.port, policy.value, work_dir,
    )

    janitor = None
    if cfg["job_data_clean_up_interval_seconds"] > 0:
        janitor = ShuffleJanitor(
            work_dir,
            cfg["job_data_clean_up_interval_seconds"],
            cfg["job_data_ttl_seconds"],
        )
        janitor.start()

    stop = {"flag": False}

    def on_signal(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    stub = SchedulerGrpcStub(
        make_channel(cfg["scheduler_host"], cfg["scheduler_port"])
    )
    sidecar = None
    if cfg["heartbeat_sidecar"]:
        # liveness survives anything the main process's GIL is doing (the
        # TPU-side answer to the reference's DedicatedExecutor isolation)
        from .isolation import HeartbeatSidecar

        sidecar = HeartbeatSidecar(
            executor.id, cfg["scheduler_host"], cfg["scheduler_port"]
        ).start()
    server = None
    loop = None
    if policy == TaskSchedulingPolicy.PUSH_STAGED:
        server_kwargs = {}
        if cfg["heartbeat_interval_seconds"] > 0:
            server_kwargs["heartbeat_interval_s"] = cfg[
                "heartbeat_interval_seconds"
            ]
        server = ExecutorServer(
            executor,
            cfg["scheduler_host"],
            cfg["scheduler_port"],
            on_shutdown=lambda reason: stop.update(flag=True),
            bind_host=cfg["bind_host"],
            telemetry_enabled=bool(cfg["telemetry_enabled"]),
            **server_kwargs,
        ).start()
    else:
        loop = PollLoop(executor, stub).start()

    try:
        while not stop["flag"]:
            time.sleep(0.5)
    finally:
        log.info("executor %s shutting down", executor.id)
        try:
            stub.ExecutorStopped(
                pb.ExecutorStoppedParams(
                    executor_id=executor.id, reason="shutdown"
                ),
                timeout=5,
            )
        except Exception:
            pass
        if sidecar is not None:
            sidecar.stop()
        if loop is not None:
            loop.stop()
        if server is not None:
            server.stop()
        if janitor is not None:
            janitor.stop(final_sweep=True)
        executor.close()
        flight.shutdown()


if __name__ == "__main__":
    main()
