"""In-process executor for standalone mode.

Counterpart of the reference's ``executor/src/standalone.rs:39-97``: spins
up a Flight server on a random port, a temp work dir, and either the
pull-mode poll loop or the push-mode executor server, all inside the
current process.
"""

from __future__ import annotations

import logging
import tempfile
import uuid
from typing import Optional

from ..config import TaskSchedulingPolicy
from ..flight.server import FlightServerHandle
from ..proto.rpc import SchedulerGrpcStub, make_channel
from ..serde.scheduler_types import ExecutorMetadata, ExecutorSpecification
from .execution_loop import PollLoop
from .executor import Executor
from .server import ExecutorServer

log = logging.getLogger(__name__)


class StandaloneExecutor:
    """Handle owning the in-proc executor's threads + resources."""

    def __init__(
        self,
        executor: Executor,
        flight: FlightServerHandle,
        poll_loop: Optional[PollLoop] = None,
        server: Optional[ExecutorServer] = None,
    ):
        self.executor = executor
        self.flight = flight
        self.poll_loop = poll_loop
        self.server = server

    @property
    def id(self) -> str:
        return self.executor.id

    def shutdown(self) -> None:
        if self.poll_loop is not None:
            self.poll_loop.stop()
        if self.server is not None:
            self.server.stop()
        self.executor.close()
        self.flight.shutdown()


def new_standalone_executor(
    scheduler_host: str,
    scheduler_port: int,
    concurrent_tasks: int = 4,
    work_dir: Optional[str] = None,
    policy: TaskSchedulingPolicy = TaskSchedulingPolicy.PULL_STAGED,
    poll_interval_s: float = 0.02,
    heartbeat_interval_s: float = 5.0,
    task_isolation: str = "thread",
    plugin_dir: str = "",
) -> StandaloneExecutor:
    """Start an in-proc executor registered with the given scheduler.

    Poll/heartbeat intervals default much tighter than production (100ms /
    60s) because standalone mode exists for tests and local runs.
    """
    work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-executor-")
    flight = FlightServerHandle(work_dir, host="127.0.0.1", port=0).start()
    metadata = ExecutorMetadata(
        id=uuid.uuid4().hex[:12],
        host="127.0.0.1",
        flight_port=flight.port,
        grpc_port=0,
        specification=ExecutorSpecification(task_slots=concurrent_tasks),
    )
    executor = Executor(
        metadata, work_dir, concurrent_tasks,
        task_isolation=task_isolation, plugin_dir=plugin_dir,
    )

    if policy == TaskSchedulingPolicy.PUSH_STAGED:
        server = ExecutorServer(
            executor,
            scheduler_host,
            scheduler_port,
            heartbeat_interval_s=heartbeat_interval_s,
        ).start()
        log.info(
            "standalone executor %s up (push mode, grpc :%d, flight :%d)",
            executor.id,
            server.grpc_port,
            flight.port,
        )
        handle = StandaloneExecutor(executor, flight, server=server)
        # a drained (or stopped) executor must stop SERVING too — wire
        # the server's shutdown hook to the whole handle so decommission
        # takes the Flight endpoint down exactly like a real process exit
        server.on_shutdown = lambda reason: handle.shutdown()
        return handle

    stub = SchedulerGrpcStub(make_channel(scheduler_host, scheduler_port))
    loop = PollLoop(executor, stub, poll_interval_s).start()
    log.info(
        "standalone executor %s up (pull mode, flight :%d)",
        executor.id,
        flight.port,
    )
    return StandaloneExecutor(executor, flight, poll_loop=loop)
