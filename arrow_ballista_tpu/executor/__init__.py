from .executor import Executor, LoggingMetricsCollector
from .standalone import StandaloneExecutor, new_standalone_executor

__all__ = [
    "Executor",
    "LoggingMetricsCollector",
    "StandaloneExecutor",
    "new_standalone_executor",
]
