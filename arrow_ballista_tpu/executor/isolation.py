"""Task/liveness isolation (counterpart of the reference's
DedicatedExecutor, ``executor/src/cpu_bound_executor.rs:37-131``).

The reference moves CPU-bound plan execution onto a separate prioritized
tokio runtime so it cannot starve heartbeat/RPC I/O.  A TPU executor
inverts that: the DEVICE handle must live in the main process (XLA client
state is per-process), so the liveness I/O is what gets its own OS
process — a :class:`HeartbeatSidecar` child that keeps
``HeartBeatFromExecutor`` flowing no matter what the parent's GIL is
doing (a pure-Python UDF pegging every task thread, a long native call
that forgot to release the GIL, a stop-the-world pause).

The in-process threaded Heartbeater stays as the primary (it carries
executor status); the sidecar is the liveness backstop.  It exits on its
own when the parent process dies, so it can never keep a dead executor
looking alive: the scheduler's 60s liveness window starts from the last
beat, exactly as for the reference's 60s heartbeats.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time
from typing import Optional

log = logging.getLogger(__name__)


class HeartbeatSidecar:
    """Child process beating on behalf of an executor."""

    def __init__(
        self,
        executor_id: str,
        scheduler_host: str,
        scheduler_port: int,
        interval_s: float = 15.0,
    ):
        self.executor_id = executor_id
        self._proc: Optional[subprocess.Popen] = None
        self._args = [
            sys.executable,
            "-m",
            "arrow_ballista_tpu.executor.isolation",
            "--executor-id",
            executor_id,
            "--scheduler",
            f"{scheduler_host}:{scheduler_port}",
            "--interval",
            str(interval_s),
            "--parent-pid",
            str(os.getpid()),
        ]

    def start(self) -> "HeartbeatSidecar":
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        # the sidecar must never initialize a device backend
        env["JAX_PLATFORMS"] = "cpu"
        self._proc = subprocess.Popen(
            self._args,
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return self

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc else None

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def stop(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:  # pragma: no cover
                self._proc.kill()


def _parent_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def main() -> None:
    """Sidecar entry: beat until stopped or the parent dies."""
    import argparse

    import grpc

    parser = argparse.ArgumentParser(
        prog="arrow_ballista_tpu.executor.isolation"
    )
    parser.add_argument("--executor-id", required=True)
    parser.add_argument("--scheduler", required=True, help="host:port")
    parser.add_argument("--interval", type=float, default=15.0)
    parser.add_argument("--parent-pid", type=int, required=True)
    args = parser.parse_args()

    from ..proto import pb
    from ..proto.rpc import SchedulerGrpcStub, make_channel

    host, _, port = args.scheduler.partition(":")
    stub = SchedulerGrpcStub(make_channel(host, int(port)))
    while _parent_alive(args.parent_pid):
        try:
            status = pb.ExecutorStatus()
            status.active = ""
            stub.HeartBeatFromExecutor(
                pb.HeartBeatParams(executor_id=args.executor_id, status=status),
                timeout=10,
            )
        except grpc.RpcError:
            pass  # scheduler restarting: keep trying while the parent lives
        # short sleep slices so parent death is noticed within ~1s
        deadline = time.monotonic() + args.interval
        while time.monotonic() < deadline:
            if not _parent_alive(args.parent_pid):
                return
            time.sleep(min(1.0, max(0.05, deadline - time.monotonic())))


if __name__ == "__main__":
    main()
