"""Wire protocol package.

``ballista.proto`` is the single protocol definition (counterpart of the
reference's ``core/proto/ballista.proto``); generated code is committed
under ``gen/`` and regenerated automatically when the .proto is newer and
``protoc`` is available.
"""

from __future__ import annotations

import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_PROTO = os.path.join(_HERE, "ballista.proto")
_GEN = os.path.join(_HERE, "gen")
_PB2 = os.path.join(_GEN, "ballista_pb2.py")


def _maybe_regen(proto: str, pb2: str) -> None:
    if not os.path.exists(proto):
        return
    if os.path.exists(pb2) and os.path.getmtime(pb2) >= os.path.getmtime(proto):
        return
    try:
        subprocess.run(
            ["protoc", f"--python_out={_GEN}", f"-I{_HERE}", proto],
            check=True,
            capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        if not os.path.exists(pb2):
            raise


_maybe_regen(_PROTO, _PB2)
_maybe_regen(
    os.path.join(_HERE, "keda.proto"), os.path.join(_GEN, "keda_pb2.py")
)

if _GEN not in sys.path:
    sys.path.insert(0, _GEN)

import ballista_pb2 as pb  # noqa: E402
import keda_pb2 as keda_pb  # noqa: E402

__all__ = ["pb", "keda_pb"]
