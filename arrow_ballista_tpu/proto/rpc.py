"""Hand-written gRPC bindings for the two services.

The environment has grpcio but not grpcio-tools, so the client stubs and
server registration helpers the protoc grpc plugin would emit are written
by hand here.  Service/method paths follow proto conventions
(``/ballista_tpu.SchedulerGrpc/PollWork`` etc.), so the wire format is
exactly what generated stubs would produce.

Reference service definitions: ``core/proto/ballista.proto:852-882``
(SchedulerGrpc 9 RPCs, ExecutorGrpc 3 RPCs).
"""

from __future__ import annotations

import threading

import grpc

from . import pb

_SCHEDULER_METHODS = {
    "PollWork": (pb.PollWorkParams, pb.PollWorkResult),
    "RegisterExecutor": (pb.RegisterExecutorParams, pb.RegisterExecutorResult),
    "HeartBeatFromExecutor": (pb.HeartBeatParams, pb.HeartBeatResult),
    "UpdateTaskStatus": (pb.UpdateTaskStatusParams, pb.UpdateTaskStatusResult),
    "GetFileMetadata": (pb.GetFileMetadataParams, pb.GetFileMetadataResult),
    "ExecuteQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "GetJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
    "ExecutorStopped": (pb.ExecutorStoppedParams, pb.ExecutorStoppedResult),
    "CancelJob": (pb.CancelJobParams, pb.CancelJobResult),
    # graceful decommission (ISSUE 6): same message shapes as
    # ExecutorStopped — executor_id + reason in, empty ack out
    "DecommissionExecutor": (pb.ExecutorStoppedParams, pb.ExecutorStoppedResult),
    # streaming pipelined execution (ISSUE 15): pull-mode executors poll
    # the scheduler's shuffle-location feed for their tailing tasks
    "GetShuffleLocationDelta": (
        pb.ShuffleLocationDeltaParams, pb.ShuffleLocationDelta,
    ),
}

_EXECUTOR_METHODS = {
    "LaunchTask": (pb.LaunchTaskParams, pb.LaunchTaskResult),
    "StopExecutor": (pb.StopExecutorParams, pb.StopExecutorResult),
    "CancelTasks": (pb.CancelTasksParams, pb.CancelTasksResult),
    # streaming pipelined execution (ISSUE 15): push-mode feed deltas
    "UpdateShuffleLocations": (
        pb.UpdateShuffleLocationsParams, pb.UpdateShuffleLocationsResult,
    ),
}

_KV_METHODS = {
    "Get": (pb.KvGetParams, pb.KvGetResult),
    "GetFromPrefix": (pb.KvScanParams, pb.KvScanResult),
    "Scan": (pb.KvScanParams, pb.KvScanResult),
    "Put": (pb.KvPutParams, pb.KvPutResult),
    "PutTxn": (pb.KvTxnParams, pb.KvTxnResult),
    "Mv": (pb.KvMvParams, pb.KvMvResult),
    "Delete": (pb.KvDeleteParams, pb.KvDeleteResult),
    "Lock": (pb.KvLockParams, pb.KvLockResult),
    "Unlock": (pb.KvUnlockParams, pb.KvUnlockResult),
}
# server-streaming: handled separately from the unary table
_KV_STREAM_METHODS = {
    "Watch": (pb.KvWatchParams, pb.KvWatchEvent),
}

# Tuned channel options (reference: core/src/utils.rs:318-345 keepalive /
# nodelay / 20s connect timeout).
GRPC_OPTIONS = [
    ("grpc.keepalive_time_ms", 10_000),
    ("grpc.keepalive_timeout_ms", 20_000),
    ("grpc.keepalive_permit_without_calls", 1),
    ("grpc.http2.max_pings_without_data", 0),
    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
    ("grpc.max_send_message_length", 256 * 1024 * 1024),
]


class _Stub:
    """Builds a unary-unary callable per method on a channel."""

    def __init__(self, channel: grpc.Channel, service: str, methods: dict):
        for name, (req_t, resp_t) in methods.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/ballista_tpu.{service}/{name}",
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )


class SchedulerGrpcStub(_Stub):
    def __init__(self, channel: grpc.Channel):
        super().__init__(channel, "SchedulerGrpc", _SCHEDULER_METHODS)


class ExecutorGrpcStub(_Stub):
    def __init__(self, channel: grpc.Channel):
        super().__init__(channel, "ExecutorGrpc", _EXECUTOR_METHODS)


def _generic_handler(service: str, methods: dict, servicer) -> grpc.GenericRpcHandler:
    handlers = {}
    for name, (req_t, resp_t) in methods.items():
        fn = getattr(servicer, name, None)
        if fn is None:
            continue
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(f"ballista_tpu.{service}", handlers)


def add_scheduler_servicer(server: grpc.Server, servicer) -> None:
    server.add_generic_rpc_handlers(
        (_generic_handler("SchedulerGrpc", _SCHEDULER_METHODS, servicer),)
    )


def add_executor_servicer(server: grpc.Server, servicer) -> None:
    server.add_generic_rpc_handlers(
        (_generic_handler("ExecutorGrpc", _EXECUTOR_METHODS, servicer),)
    )


class KvStoreGrpcStub(_Stub):
    def __init__(self, channel: grpc.Channel):
        super().__init__(channel, "KvStoreGrpc", _KV_METHODS)
        for name, (req_t, resp_t) in _KV_STREAM_METHODS.items():
            setattr(
                self,
                name,
                channel.unary_stream(
                    f"/ballista_tpu.KvStoreGrpc/{name}",
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )


def add_kvstore_servicer(server: grpc.Server, servicer) -> None:
    handlers = {}
    for name, (req_t, resp_t) in _KV_METHODS.items():
        fn = getattr(servicer, name, None)
        if fn is None:
            continue
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    for name, (req_t, resp_t) in _KV_STREAM_METHODS.items():
        fn = getattr(servicer, name, None)
        if fn is None:
            continue
        handlers[name] = grpc.unary_stream_rpc_method_handler(
            fn,
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                "ballista_tpu.KvStoreGrpc", handlers
            ),
        )
    )


def make_channel(host: str, port: int) -> grpc.Channel:
    return grpc.insecure_channel(f"{host}:{port}", options=GRPC_OPTIONS)


# Process-wide executor-stub pool: every scheduler-side control-plane call
# to an executor (LaunchTask, CancelTasks, StopExecutor) reuses one cached
# channel per host:port instead of paying a fresh gRPC channel handshake
# per fan-out (the pre-existing GrpcLauncher cache, generalized).
_executor_stubs: dict = {}
_executor_stubs_lock = threading.Lock()


def executor_stub(host: str, port: int) -> ExecutorGrpcStub:
    key = f"{host}:{port}"
    with _executor_stubs_lock:
        stub = _executor_stubs.get(key)
        if stub is None:
            stub = ExecutorGrpcStub(make_channel(host, port))
            _executor_stubs[key] = stub
        return stub


def make_server(executor_workers: int = 16) -> grpc.Server:
    from concurrent.futures import ThreadPoolExecutor

    return grpc.server(
        ThreadPoolExecutor(max_workers=executor_workers), options=GRPC_OPTIONS
    )
