"""Arrow Flight client for fetching shuffle partitions.

Counterpart of the reference's ``BallistaClient``
(``core/src/client.rs:51-179``): connects to an executor's Flight port and
issues a DoGet whose ticket is a protobuf ``FetchPartitionTicket``; the
response stream is the partition's record batches.
"""

from __future__ import annotations

import threading
from typing import Iterator

import pyarrow as pa
import pyarrow.flight as flight

from ..errors import ExecutionError
from ..proto import pb


class BallistaClient:
    """Per-(host,port) cached Flight connections (the reference caches
    clients similarly in executor_manager.rs:219-246)."""

    _cache: dict[tuple[str, int], "BallistaClient"] = {}
    _lock = threading.Lock()

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._client = flight.FlightClient(f"grpc://{host}:{port}")

    @classmethod
    def get(cls, host: str, port: int) -> "BallistaClient":
        key = (host, port)
        with cls._lock:
            c = cls._cache.get(key)
            if c is None:
                c = cls(host, port)
                cls._cache[key] = c
            return c

    @classmethod
    def clear_cache(cls) -> None:
        with cls._lock:
            for c in cls._cache.values():
                try:
                    c._client.close()
                except Exception:
                    pass
            cls._cache.clear()

    @classmethod
    def invalidate(
        cls, host: str, port: int, instance: "BallistaClient" = None
    ) -> None:
        """Drop the cached connection for one endpoint.

        Called on every FlightError so a retry reconnects instead of
        reusing a dead channel.  With ``instance`` given, the entry is
        only dropped while it still IS that instance — a worker erroring
        on an old dead channel must not evict the healthy replacement a
        faster worker already cached.  The old object is NOT closed here:
        concurrent fetch workers may still be streaming healthy
        partitions over it (closing would burn their retry budgets on a
        self-inflicted teardown); it drains and is collected when the
        last holder drops it.
        """
        with cls._lock:
            c = cls._cache.get((host, port))
            if c is not None and (instance is None or c is instance):
                del cls._cache[(host, port)]

    def _do_get(self, ticket: flight.Ticket, headers: list = None):
        """The one DoGet call site: positional options only when headers
        ride along, so test/client doubles with a plain ``do_get(ticket)``
        signature keep working untraced."""
        if headers:
            return self._client.do_get(
                ticket, flight.FlightCallOptions(headers=headers)
            )
        return self._client.do_get(ticket)

    def _fetch_error(self, what: str, e: BaseException) -> ExecutionError:
        """Invalidate this cached connection and wrap the Flight error so
        a retry reconnects instead of reusing a dead channel."""
        type(self).invalidate(self.host, self.port, self)
        return ExecutionError(
            f"flight fetch of {what} from {self.host}:{self.port} "
            f"failed: {e}"
        )

    def fetch_partition(
        self,
        job_id: str,
        stage_id: int,
        partition_id: int,
        path: str,
        headers: list = None,
    ) -> Iterator[pa.RecordBatch]:
        _schema, batches = self.fetch_partition_with_schema(
            job_id, stage_id, partition_id, path, headers=headers
        )
        return batches

    def fetch_partition_with_schema(
        self,
        job_id: str,
        stage_id: int,
        partition_id: int,
        path: str,
        headers: list = None,
    ) -> tuple[pa.Schema, Iterator[pa.RecordBatch]]:
        """Returns the partition schema up front (available even when the
        partition holds zero batches) plus a lazy batch stream.

        ``headers`` (list of (bytes, bytes) pairs) ride the DoGet as gRPC
        metadata — the trace-context hop for stitched shuffle traces."""
        what = f"{job_id}/{stage_id}/{partition_id}"
        ticket_proto = pb.FetchPartitionTicket(
            job_id=job_id,
            stage_id=stage_id,
            partition_id=partition_id,
            path=path,
        )
        ticket = flight.Ticket(ticket_proto.SerializeToString())
        try:
            reader = self._do_get(ticket, headers)
            schema = reader.schema
        except flight.FlightError as e:
            raise self._fetch_error(what, e) from e

        def gen() -> Iterator[pa.RecordBatch]:
            try:
                for chunk in reader:
                    yield chunk.data
            except flight.FlightError as e:
                raise self._fetch_error(what, e) from e

        return schema, gen()

    def fetch_partitions(
        self,
        job_id: str,
        stage_id: int,
        parts: list,
        headers: list = None,
    ) -> tuple[pa.Schema, Iterator[tuple[int, pa.RecordBatch]]]:
        """One DoGet streaming SEVERAL partitions of one stage
        (``parts`` = [(partition_id, path), ...]): the batched
        cross-host fetch leg — N per-partition round trips collapse into
        one multi-partition stream the server interleaves from its
        mmap-backed readers.

        Yields ``(index, batch)`` where ``index`` is the position in
        ``parts`` the batch belongs to (carried per batch as Flight
        ``app_metadata``), so the caller tracks per-partition delivery
        for mid-stream resume.  Serving order is deterministic: ticket
        path order, IPC batch order within each partition."""
        what = f"{job_id}/{stage_id}/[{len(parts)} partitions]"
        ticket_proto = pb.FetchPartitionTicket(
            job_id=job_id,
            stage_id=stage_id,
            partition_id=parts[0][0] if parts else 0,
            path="",
            paths=[p for _, p in parts],
        )
        ticket = flight.Ticket(ticket_proto.SerializeToString())
        try:
            reader = self._do_get(ticket, headers)
            schema = reader.schema
        except flight.FlightError as e:
            raise self._fetch_error(what, e) from e

        def gen() -> Iterator[tuple[int, pa.RecordBatch]]:
            from ..errors import BatchedFetchProtocolError

            try:
                for chunk in reader:
                    meta = chunk.app_metadata
                    if meta is None:
                        raise BatchedFetchProtocolError(
                            f"flight fetch of {what}: server sent a batch "
                            "without a partition index (mixed-version "
                            "cluster?)"
                        )
                    try:
                        idx = int(bytes(meta))
                    except ValueError as e:
                        # malformed tag is just as deterministic as a
                        # missing one: same skip-the-retry-budget verdict
                        raise BatchedFetchProtocolError(
                            f"flight fetch of {what}: unparsable partition "
                            f"index tag {bytes(meta)!r}"
                        ) from e
                    yield idx, chunk.data
            except flight.FlightError as e:
                raise self._fetch_error(what, e) from e

        return schema, gen()
