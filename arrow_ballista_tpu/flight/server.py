"""Executor-side Arrow Flight service serving shuffle partitions.

Counterpart of the reference's ``executor/src/flight_service.rs``: DoGet
only — the ticket is a protobuf ``FetchPartitionTicket`` whose ``path``
points at an Arrow IPC file under this executor's work_dir; the file is
streamed schema-first then batch-by-batch.  All other Flight methods are
unimplemented, exactly like the reference.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import pyarrow as pa
import pyarrow.flight as flight

from ..obs import trace
from ..proto import pb


class _TraceMiddleware(flight.ServerMiddleware):
    """Carries the caller's trace context for the duration of one call."""

    def __init__(self, trace_id: str, parent_span_id: str):
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id


def _header_value(headers, key: str) -> str:
    vals = headers.get(key) or headers.get(key.encode(), ())
    for v in vals:
        return v.decode() if isinstance(v, bytes) else v
    return ""


class _TraceMiddlewareFactory(flight.ServerMiddlewareFactory):
    def start_call(self, info, headers):
        tid = _header_value(headers, trace.TRACE_HEADER.decode())
        if not tid:
            return None
        return _TraceMiddleware(
            tid, _header_value(headers, trace.PARENT_HEADER.decode())
        )


class ShuffleFlightService(flight.FlightServerBase):
    def __init__(self, work_dir: str, host: str = "0.0.0.0", port: int = 0):
        location = f"grpc://{host}:{port}"
        super().__init__(
            location, middleware={"trace": _TraceMiddlewareFactory()}
        )
        self.work_dir = os.path.abspath(work_dir)

    @staticmethod
    def _trace_ctx(context) -> tuple:
        """(trace_id, parent_span_id) from call metadata, or ("", "")."""
        try:
            mw = context.get_middleware("trace")
        except Exception:  # noqa: BLE001 - tracing never fails a fetch
            mw = None
        if mw is None:
            return "", ""
        return mw.trace_id, mw.parent_span_id

    @staticmethod
    def _traced_stream(batches, trace_id: str, parent: str, path: str):
        """Wrap a batch stream so the serving window is one span in the
        CALLER's trace (closed when the stream drains or breaks).
        Items may be bare RecordBatches or ``(batch, app_metadata)``
        tuples (the multi-partition stream tags each batch with its
        partition index)."""
        t0_unix, t0_mono = time.time_ns(), time.monotonic_ns()
        nbytes = 0
        error = ""
        try:
            for b in batches:
                data = b[0] if isinstance(b, tuple) else b
                nbytes += int(getattr(data, "nbytes", 0) or 0)
                yield b
        except BaseException as e:
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            attrs = {"path": path, "bytes": nbytes}
            if error:
                attrs["error"] = error
            trace.record_raw(
                "flight.do_get",
                trace_id,
                trace.new_id(),
                parent or trace_id,
                t0_unix,
                time.monotonic_ns() - t0_mono,
                **attrs,
            )

    # ------------------------------------------------------------ sources
    def _open_file_reader(self, raw_path: str):
        """(mmap source, IPC file reader) for one on-disk partition —
        path-validated against the work dir, memory-mapped so served
        batches are zero-copy views of the page cache (Zerrow property:
        the Arrow data plane never copies on the serving side); OSFile
        fallback for filesystems without mmap."""
        path = os.path.abspath(raw_path)
        # only serve files inside the work dir (the ticket's path originates
        # from this executor's own shuffle-write stats, but never trust it)
        if not path.startswith(self.work_dir + os.sep):
            raise flight.FlightServerError(f"path {path!r} outside work dir")
        if not os.path.exists(path):
            raise flight.FlightServerError(f"no such partition file {path!r}")
        try:
            source = pa.memory_map(path, "rb")
        except Exception:
            source = pa.OSFile(path, "rb")
        try:
            reader = pa.ipc.open_file(source)
        except Exception as e:
            # truncated/corrupt partition file: close the handle before
            # raising, or every reduce-side retry leaks an mmap/fd here
            source.close()
            raise flight.FlightServerError(
                f"unreadable partition file {path!r}: {e}"
            )
        return source, reader

    @staticmethod
    def _mem_buffer(path: str):
        """The already-serialized IPC stream buffer of one memory-store
        partition: the slab writer's bytes go to the wire as zero-copy
        views, never re-materialized as a batch list first."""
        from ..shuffle import memory_store

        buf = memory_store.get_buffer(path)
        if buf is None:
            raise flight.FlightServerError(
                f"no such memory partition {path!r}"
            )
        return buf

    def _source_schema(self, path: str) -> pa.Schema:
        from ..shuffle import memory_store

        if path.startswith(memory_store.SCHEME):
            with pa.ipc.open_stream(self._mem_buffer(path)) as r:
                return r.schema
        source, reader = self._open_file_reader(path)
        try:
            return reader.schema
        finally:
            source.close()

    def _iter_source(self, path: str):
        """Lazily stream one partition's batches (mem buffer or mmap)."""
        from ..shuffle import memory_store

        if path.startswith(memory_store.SCHEME):
            with pa.ipc.open_stream(self._mem_buffer(path)) as r:
                yield from r
            return
        source, reader = self._open_file_reader(path)
        try:
            for i in range(reader.num_record_batches):
                yield reader.get_batch(i)
        finally:
            source.close()

    # -------------------------------------------------------------- serve
    def do_get(self, context, ticket: flight.Ticket):
        msg = pb.FetchPartitionTicket()
        try:
            msg.ParseFromString(ticket.ticket)
        except Exception as e:
            raise flight.FlightServerError(f"invalid ticket: {e}")
        trace_id, parent = self._trace_ctx(context)
        if msg.paths:
            return self._do_get_multi(list(msg.paths), trace_id, parent)
        from ..shuffle import memory_store

        if msg.path.startswith(memory_store.SCHEME):
            buf = self._mem_buffer(msg.path)
            with pa.ipc.open_stream(buf) as r:
                schema = r.schema

            def mem_gen():
                # reopen lazily: batches are zero-copy views of the
                # stored buffer, emitted straight onto the wire
                with pa.ipc.open_stream(buf) as reader:
                    yield from reader

            stream = mem_gen()
            if trace_id and trace.is_enabled():
                stream = self._traced_stream(
                    stream, trace_id, parent, msg.path
                )
            return flight.GeneratorStream(schema, stream)
        source, reader = self._open_file_reader(msg.path)

        def gen():
            try:
                for i in range(reader.num_record_batches):
                    yield reader.get_batch(i)
            finally:
                source.close()

        stream = gen()
        if trace_id and trace.is_enabled():
            stream = self._traced_stream(stream, trace_id, parent, msg.path)
        return flight.GeneratorStream(reader.schema, stream)

    def _do_get_multi(self, paths, trace_id: str, parent: str):
        """Multi-partition ticket (``FetchPartitionTicket.paths``): ONE
        stream interleaving every requested partition in ticket order,
        each batch tagged with its partition index as ``app_metadata``
        so the client tracks per-partition delivery for mid-stream
        resume.  Replaces N per-partition DoGet round trips per
        (stage, host) pair."""
        if not paths:
            raise flight.FlightServerError("empty multi-partition ticket")
        # schema up front (from the first partition — one stage, one
        # schema) so zero-batch partitions still stream cleanly
        schema = self._source_schema(paths[0])

        def gen():
            for i, path in enumerate(paths):
                tag = str(i).encode()
                for batch in self._iter_source(path):
                    yield batch, tag

        stream = gen()
        if trace_id and trace.is_enabled():
            stream = self._traced_stream(
                stream, trace_id, parent, f"[{len(paths)} partitions]"
            )
        return flight.GeneratorStream(schema, stream)


class FlightServerHandle:
    """Owns a running Flight service on its own thread."""

    def __init__(self, work_dir: str, host: str = "0.0.0.0", port: int = 0):
        self.service = ShuffleFlightService(work_dir, host, port)
        self.port = self.service.port  # resolved if port was 0
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FlightServerHandle":
        self._thread = threading.Thread(
            target=self.service.serve, name="flight-server", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        try:
            self.service.shutdown()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
