from .client import BallistaClient
from .server import FlightServerHandle, ShuffleFlightService

__all__ = ["BallistaClient", "FlightServerHandle", "ShuffleFlightService"]
