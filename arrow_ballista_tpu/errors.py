"""Unified error hierarchy for the framework.

Counterpart of the reference's ``BallistaError`` enum
(``ballista/rust/core/src/error.rs:35-51`` in /root/reference), redesigned as a
Python exception tree instead of a Rust enum.
"""

from __future__ import annotations


class BallistaError(Exception):
    """Base class for all framework errors."""


class PlanError(BallistaError):
    """Logical/physical planning failed."""


class SqlError(PlanError):
    """SQL parse or analysis error."""


class NotImplementedYet(BallistaError):
    """Feature recognized but not supported yet."""


class ExecutionError(BallistaError):
    """Runtime failure while executing an operator."""


class SerdeError(BallistaError):
    """Plan (de)serialization failure."""


class SchedulerError(BallistaError):
    """Scheduler-side state machine failure."""


class ConfigError(BallistaError):
    """Invalid configuration value."""


class Cancelled(BallistaError):
    """Task was cancelled."""


class InternalError(BallistaError):
    """Invariant violation — a bug in the framework."""
