"""Unified error hierarchy for the framework.

Counterpart of the reference's ``BallistaError`` enum
(``ballista/rust/core/src/error.rs:35-51`` in /root/reference), redesigned as a
Python exception tree instead of a Rust enum.
"""

from __future__ import annotations


class BallistaError(Exception):
    """Base class for all framework errors."""


class PlanError(BallistaError):
    """Logical/physical planning failed."""


class SqlError(PlanError):
    """SQL parse or analysis error."""


class NotImplementedYet(BallistaError):
    """Feature recognized but not supported yet."""


class ExecutionError(BallistaError):
    """Runtime failure while executing an operator."""


class SerdeError(BallistaError):
    """Plan (de)serialization failure."""


class BatchedFetchProtocolError(ExecutionError):
    """The multi-partition shuffle stream broke the batched-fetch
    protocol (partition index out of range, batch without an index tag —
    e.g. a mixed-version server ignoring ``FetchPartitionTicket.paths``).
    Deterministic: retrying the same stream cannot succeed, so the
    fetcher degrades straight to per-location DoGets instead of burning
    the retry/backoff budget first."""


class ShuffleFetchFailed(ExecutionError):
    """A shuffle reader exhausted its per-location fetch retries: the map
    output it needs is gone (wiped work_dir, evicted memory partition,
    dead serving process).  Carries the producer coordinates so the
    scheduler can re-run just the lost partitions instead of burning the
    consumer's attempt budget — the message embeds them in a stable
    ``stage=N partition=M executor=E`` form that survives the
    string-only TaskStatus wire format
    (``scheduler/failure.py parse_shuffle_fetch_failure``)."""

    def __init__(
        self,
        stage_id: int,
        map_partition: int,
        executor_id: str,
        detail: str = "",
    ):
        self.stage_id = stage_id
        self.map_partition = map_partition
        self.executor_id = executor_id
        msg = (
            "shuffle fetch exhausted retries for map output "
            f"stage={stage_id} partition={map_partition} "
            f"executor={executor_id or '<unknown>'}"
        )
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class SchedulerError(BallistaError):
    """Scheduler-side state machine failure."""


class ConfigError(BallistaError):
    """Invalid configuration value."""


class Cancelled(BallistaError):
    """Task was cancelled."""


class InternalError(BallistaError):
    """Invariant violation — a bug in the framework."""
