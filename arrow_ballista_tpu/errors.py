"""Unified error hierarchy for the framework.

Counterpart of the reference's ``BallistaError`` enum
(``ballista/rust/core/src/error.rs:35-51`` in /root/reference), redesigned as a
Python exception tree instead of a Rust enum.
"""

from __future__ import annotations

from typing import Optional


class BallistaError(Exception):
    """Base class for all framework errors."""


class PlanError(BallistaError):
    """Logical/physical planning failed."""


class SqlError(PlanError):
    """SQL parse or analysis error."""


class NotImplementedYet(BallistaError):
    """Feature recognized but not supported yet."""


class ExecutionError(BallistaError):
    """Runtime failure while executing an operator."""


class SerdeError(BallistaError):
    """Plan (de)serialization failure."""


class BatchedFetchProtocolError(ExecutionError):
    """The multi-partition shuffle stream broke the batched-fetch
    protocol (partition index out of range, batch without an index tag —
    e.g. a mixed-version server ignoring ``FetchPartitionTicket.paths``).
    Deterministic: retrying the same stream cannot succeed, so the
    fetcher degrades straight to per-location DoGets instead of burning
    the retry/backoff budget first."""


class ShuffleFetchFailed(ExecutionError):
    """A shuffle reader exhausted its per-location fetch retries: the map
    output it needs is gone (wiped work_dir, evicted memory partition,
    dead serving process).  Carries the producer coordinates so the
    scheduler can re-run just the lost partitions instead of burning the
    consumer's attempt budget — the message embeds them in a stable
    ``stage=N partition=M executor=E`` form that survives the
    string-only TaskStatus wire format
    (``scheduler/failure.py parse_shuffle_fetch_failure``)."""

    def __init__(
        self,
        stage_id: int,
        map_partition: int,
        executor_id: str,
        detail: str = "",
    ):
        self.stage_id = stage_id
        self.map_partition = map_partition
        self.executor_id = executor_id
        msg = (
            "shuffle fetch exhausted retries for map output "
            f"stage={stage_id} partition={map_partition} "
            f"executor={executor_id or '<unknown>'}"
        )
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class SchedulerError(BallistaError):
    """Scheduler-side state machine failure."""


class ClusterSaturated(SchedulerError):
    """Admission-control backpressure: the cluster is saturated and this
    job was shed instead of queued (queue full, displaced by
    ``shed_policy=oldest``, or queued past ``max_queue_wait_seconds``).
    RETRYABLE by design — nothing about the job itself is wrong, and the
    running set was never touched.  The message keeps a stable
    ``ClusterSaturated:`` prefix with ``key=value`` coordinates so
    clients and benches can recognize sheds across the string-only
    status wire."""

    def __init__(
        self,
        reason: str,
        pool: str = "",
        queued: int = 0,
        policy: str = "",
        queue_wait_s: Optional[float] = None,
    ):
        self.pool = pool
        self.queued = queued
        self.policy = policy
        self.queue_wait_s = queue_wait_s
        parts = [f"pool={pool or '<none>'}", f"queued={queued}"]
        if policy:
            parts.append(f"policy={policy}")
        if queue_wait_s is not None:
            parts.append(f"queue_wait_s={queue_wait_s:.3f}")
        super().__init__(
            f"ClusterSaturated: {reason} ({' '.join(parts)}); "
            "backpressure — safe to retry later"
        )


class ConfigError(BallistaError):
    """Invalid configuration value."""


class Cancelled(BallistaError):
    """Task was cancelled."""


class InternalError(BallistaError):
    """Invariant violation — a bug in the framework."""
