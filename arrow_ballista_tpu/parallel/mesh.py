"""Multi-chip execution: device mesh, ICI collectives, sharded stages.

The reference scales with one task per partition over executors connected
by gRPC/Flight (SURVEY.md §2.5).  On a TPU pod slice, partitions that live
on the same mesh become SHARDS: a stage runs as ONE ``shard_map``-ped
program over the mesh's data axis, and the cross-partition exchange that
Ballista does via disk+Flight becomes an XLA collective over ICI —
``psum`` for partial-aggregate reduction, ``all_to_all`` for hash
repartition.  Cross-host/cross-pod exchange stays on the Arrow Flight data
plane (flight/, shuffle/).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


# ------------------------------------------------------- distributed agg
def make_distributed_agg_step(
    kernel: Callable,
    specs,
    mesh: Mesh,
    capacity: int,
    mode: Optional[str] = None,
):
    """Wrap a fused partial-agg kernel so it runs sharded over the mesh.

    Inputs (seg, valid, *leaf arrays) are sharded on the row axis; each
    device reduces its shard to [capacity] states, then the states reduce
    across the mesh over ICI (psum / pmin / pmax per aggregate) — the
    TPU-native replacement for the reference's map-side shuffle write +
    reduce-side Flight fetch when all shards share a mesh.

    Returns a jitted fn producing fully-reduced (replicated) states.
    """
    from jax import shard_map

    from ..ops import kernels as K

    # the mode must match the one the kernel was BUILT under (pinned by
    # the owning TpuStageExec); the global is only a fallback
    mode = mode or K.precision_mode()

    def reduce_states(states):
        # per-field collective chosen by the kernel's state layout
        # (state_fields): psum for additive fields — including the x32
        # double-float lo term, whose psum error is second-order — and
        # pmin/pmax for extrema
        out = []
        i = 0
        for spec in specs:
            for role in K.state_fields(spec, mode):
                if role == "min":
                    out.append(jax.lax.pmin(states[i], DATA_AXIS))
                elif role == "max":
                    out.append(jax.lax.pmax(states[i], DATA_AXIS))
                else:
                    out.append(jax.lax.psum(states[i], DATA_AXIS))
                i += 1
        out.append(jax.lax.psum(states[-1], DATA_AXIS))  # presence
        return tuple(out)

    def sharded_step(seg, valid, *arrays):
        local = kernel(seg, valid, *arrays)
        return reduce_states(local)

    # built once: a per-call jit would retrace and recompile every batch
    fn = jax.jit(
        shard_map(
            sharded_step,
            mesh=mesh,
            in_specs=P(DATA_AXIS),  # prefix spec: every arg row-sharded
            out_specs=P(),  # replicated after the cross-chip reduction
            check_vma=False,
        )
    )
    return fn


# ------------------------------------------------- on-device repartition
def ici_all_to_all_repartition(mesh: Mesh, capacity: int):
    """Build a sharded hash-repartition exchange over ICI.

    Each device holds rows plus a destination-device id per row.  Rows
    route to their destination with a single ``all_to_all`` on a
    [n_dev, capacity] staging buffer (capacity-padded, mask-carrying — the
    static-shape answer to Ballista's variable-size shuffle files).

    Returns fn(values f64[rows], dest i32[rows], valid bool[rows]) →
    (recv_values f64[n_dev*capacity], recv_valid bool[n_dev*capacity],
    n_dropped i32 scalar).  Each device ends holding every row whose
    dest == its index.  ``n_dropped`` is the GLOBAL count of valid rows
    that exceeded a (source, destination) bucket's capacity and were not
    delivered — callers MUST check it and re-run with a larger capacity
    (or fall back to the Flight shuffle) when it is non-zero; silent loss
    would corrupt downstream aggregates.
    """
    from jax import shard_map

    n_dev = mesh.devices.size

    def local_exchange(values, dest, valid):
        # values/dest/valid: this device's shard [rows_local]
        rows = values.shape[0]
        # invalid rows sort to a sentinel destination past every real one,
        # so each real destination's run contains only valid rows and the
        # within-run index is dense
        dest_m = jnp.where(valid, dest, n_dev)
        order = jnp.argsort(dest_m, stable=True)
        values_s = values[order]
        dest_s = dest_m[order]
        # per-destination staging buffer [n_dev, capacity]
        counts = jax.ops.segment_sum(
            jnp.ones(rows, jnp.int32), dest_s, num_segments=n_dev + 1
        )[:n_dev]
        offsets = jnp.cumsum(counts) - counts  # start of each dest run
        safe_dest = jnp.minimum(dest_s, n_dev - 1)
        idx_within = jnp.arange(rows, dtype=jnp.int32) - offsets[safe_dest]
        ok = (
            (dest_s < n_dev) & (idx_within >= 0) & (idx_within < capacity)
        )
        # valid rows that overflowed their bucket: surfaced to the caller
        overflow = (dest_s < n_dev) & (idx_within >= capacity)
        n_dropped = jax.lax.psum(
            jnp.sum(overflow.astype(jnp.int32)), DATA_AXIS
        )
        # rows that don't belong (sentinel dest / over capacity) scatter
        # into a spill column that is sliced away — they can never clobber
        # a real slot
        slot = jnp.where(ok, idx_within, capacity)
        stage_vals = jnp.zeros((n_dev, capacity + 1), values.dtype)
        stage_valid = jnp.zeros((n_dev, capacity + 1), jnp.bool_)
        stage_vals = stage_vals.at[safe_dest, slot].set(values_s, mode="drop")
        stage_valid = stage_valid.at[safe_dest, slot].set(ok, mode="drop")
        stage_vals = stage_vals[:, :capacity]
        stage_valid = stage_valid[:, :capacity]
        # the collective: swap staging rows so device d receives every
        # other device's bucket d — Ballista's shuffle in one ICI op
        recv_vals = jax.lax.all_to_all(
            stage_vals, DATA_AXIS, split_axis=0, concat_axis=0, tiled=False
        )
        recv_valid = jax.lax.all_to_all(
            stage_valid, DATA_AXIS, split_axis=0, concat_axis=0, tiled=False
        )
        return recv_vals.reshape(-1), recv_valid.reshape(-1), n_dropped

    fn = shard_map(
        local_exchange,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def shard_batch(
    mesh: Mesh, arrays: Sequence[np.ndarray]
) -> list[jax.Array]:
    """Place host arrays onto the mesh sharded along the row axis."""
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    out = []
    for a in arrays:
        n_dev = mesh.devices.size
        n = len(a)
        padded = ((n + n_dev - 1) // n_dev) * n_dev
        if padded != n:
            pad = np.zeros(padded - n, dtype=a.dtype)
            a = np.concatenate([a, pad])
        out.append(jax.device_put(a, sharding))
    return out
