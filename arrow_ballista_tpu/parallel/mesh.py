"""Multi-chip execution: device mesh, ICI collectives, sharded stages.

The reference scales with one task per partition over executors connected
by gRPC/Flight (SURVEY.md §2.5).  On a TPU pod slice, partitions that live
on the same mesh become SHARDS: a stage runs as ONE ``shard_map``-ped
program over the mesh's data axis, and the cross-partition exchange that
Ballista does via disk+Flight becomes an XLA collective over ICI —
``psum`` for partial-aggregate reduction, ``all_to_all`` for hash
repartition.  Cross-host/cross-pod exchange stays on the Arrow Flight data
plane (flight/, shuffle/).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


# ------------------------------------------------------- distributed agg
def make_distributed_agg_step(
    kernel: Callable,
    specs,
    mesh: Mesh,
    capacity: int,
    mode: Optional[str] = None,
):
    """Wrap a fused partial-agg kernel so it runs sharded over the mesh.

    Inputs (seg, valid, *leaf arrays) are sharded on the row axis; each
    device reduces its shard to [capacity] states, then the states reduce
    across the mesh over ICI (psum / pmin / pmax per aggregate) — the
    TPU-native replacement for the reference's map-side shuffle write +
    reduce-side Flight fetch when all shards share a mesh.

    Returns a jitted fn producing fully-reduced (replicated) states.
    """
    from jax import shard_map

    from ..ops import kernels as K

    # the mode must match the one the kernel was BUILT under (pinned by
    # the owning TpuStageExec); the global is only a fallback
    mode = mode or K.precision_mode()

    def reduce_states(states):
        # per-field collective chosen by the kernel's state layout
        # (state_fields): psum for additive fields — including the x32
        # double-float lo term, whose psum error is second-order — and
        # pmin/pmax for extrema
        out = []
        i = 0
        for spec in specs:
            fields = K.state_fields(spec, mode)
            if spec.ord_pair and spec.func in ("min", "max"):
                # lexicographic 64-bit extremum over ICI: reduce hi, then
                # reduce lo among chips tied at the extremal hi (ties
                # carry the identity so they drop out)
                red = (
                    jax.lax.pmin if spec.func == "min" else jax.lax.pmax
                )
                info = jnp.iinfo(states[i].dtype)
                ident = info.max if spec.func == "min" else info.min
                g_hi = red(states[i], DATA_AXIS)
                lo_cand = jnp.where(states[i] == g_hi, states[i + 1], ident)
                g_lo = red(lo_cand, DATA_AXIS)
                out.extend(
                    [g_hi, g_lo, jax.lax.psum(states[i + 2], DATA_AXIS)]
                )
                i += 3
                continue
            for role in fields:
                if role == "min":
                    out.append(jax.lax.pmin(states[i], DATA_AXIS))
                elif role == "max":
                    out.append(jax.lax.pmax(states[i], DATA_AXIS))
                else:
                    out.append(jax.lax.psum(states[i], DATA_AXIS))
                i += 1
        out.append(jax.lax.psum(states[-1], DATA_AXIS))  # presence
        return tuple(out)

    def sharded_step(seg, valid, *arrays):
        local = kernel(seg, valid, *arrays)
        return reduce_states(local)

    # built once: a per-call jit would retrace and recompile every batch
    fn = jax.jit(
        shard_map(
            sharded_step,
            mesh=mesh,
            in_specs=P(DATA_AXIS),  # prefix spec: every arg row-sharded
            out_specs=P(),  # replicated after the cross-chip reduction
            check_vma=False,
        )
    )
    return fn


# ------------------------------------------------- on-device repartition
def ici_batch_exchange(mesh: Mesh, n_cols: int, capacity: int):
    """Multi-column hash-repartition exchange over ICI.

    Generalizes :func:`ici_all_to_all_repartition` (single f64 column) to a
    typed multi-column payload (VERDICT.md round-1 item 4): the routing —
    stable sort by destination, per-destination staging slots, overflow
    accounting — is computed ONCE from (dest, valid), then every column
    scatters into its own [n_dev, capacity] staging buffer and rides its
    own ``all_to_all``.  Columns may be any device dtype (f32/f64, i32,
    bool, dictionary codes); validity masks travel as ordinary bool
    columns.

    Returns ``fn(dest i32[rows], valid bool[rows], *cols) →
    (*recv_cols [n_dev*capacity], recv_valid bool[n_dev*capacity],
    n_dropped i32)``.  ``n_dropped`` is the global count of valid rows that
    overflowed a (source, destination) bucket — callers MUST re-run with a
    larger capacity (or fall back to the Flight shuffle) when non-zero.
    """
    from jax import shard_map

    n_dev = mesh.devices.size

    def local_exchange(dest, valid, *cols):
        rows = dest.shape[0]
        dest_m = jnp.where(valid, dest, n_dev)
        order = jnp.argsort(dest_m, stable=True)
        dest_s = dest_m[order]
        counts = jax.ops.segment_sum(
            jnp.ones(rows, jnp.int32), dest_s, num_segments=n_dev + 1
        )[:n_dev]
        offsets = jnp.cumsum(counts) - counts
        safe_dest = jnp.minimum(dest_s, n_dev - 1)
        idx_within = jnp.arange(rows, dtype=jnp.int32) - offsets[safe_dest]
        ok = (dest_s < n_dev) & (idx_within >= 0) & (idx_within < capacity)
        overflow = (dest_s < n_dev) & (idx_within >= capacity)
        n_dropped = jax.lax.psum(
            jnp.sum(overflow.astype(jnp.int32)), DATA_AXIS
        )
        slot = jnp.where(ok, idx_within, capacity)

        def route(c, fill_ok=False):
            cs = (ok if fill_ok else c[order])
            stage = jnp.zeros((n_dev, capacity + 1), cs.dtype)
            stage = stage.at[safe_dest, slot].set(cs, mode="drop")
            stage = stage[:, :capacity]
            return jax.lax.all_to_all(
                stage, DATA_AXIS, split_axis=0, concat_axis=0, tiled=False
            ).reshape(-1)

        recv_cols = tuple(route(c) for c in cols)
        recv_valid = route(None, fill_ok=True)
        return recv_cols + (recv_valid, n_dropped)

    fn = shard_map(
        local_exchange,
        mesh=mesh,
        in_specs=(P(DATA_AXIS),) * (2 + n_cols),
        out_specs=(P(DATA_AXIS),) * (n_cols + 1) + (P(),),
        check_vma=False,
    )
    return jax.jit(fn)


class BatchExchanger:
    """Schema-aware host bridge around :func:`ici_batch_exchange`.

    Turns RecordBatches into device columns (value + validity per field;
    strings as shared dictionary codes; i64 as exact lo/hi i32 pairs when
    the device dtype mode is x32), runs the on-mesh exchange, and
    reassembles per-destination RecordBatches.
    """

    def __init__(self, mesh: Mesh, schema, capacity: int, share_from=None):
        import pyarrow as pa

        from ..ops import kernels as K
        from ..ops.bridge import DictEncoder

        self.mesh = mesh
        self.schema = schema
        self.capacity = capacity
        if share_from is not None:
            # capacity retry: the layout/encoders (and any columns already
            # produced by to_columns) are schema-properties, capacity only
            # parameterizes the jitted exchange — share them
            self._x32 = share_from._x32
            self.layout = share_from.layout
            self.encoders = share_from.encoders
            self.n_cols = share_from.n_cols
            self._fn = ici_batch_exchange(mesh, self.n_cols, capacity)
            return
        self._x32 = K.precision_mode() == "x32"
        # per-field device layout: "num" (one array), "dict" (codes),
        # "i64pair" (lo/hi split — exchange-exact without device i64)
        self.layout: list[tuple] = []
        self.encoders: dict[int, DictEncoder] = {}
        for i, f in enumerate(schema):
            t = f.type
            if pa.types.is_string(t) or pa.types.is_large_string(t):
                self.encoders[i] = DictEncoder()
                self.layout.append(("dict", i))
            elif self._x32 and (
                pa.types.is_int64(t)
                or pa.types.is_uint64(t)
                or pa.types.is_date64(t)
                or pa.types.is_timestamp(t)
                # f64 bitcasts through the pair path too: the exchange is
                # pure data movement, so values must survive EXACTLY even
                # though the device has no f64 (narrowing to f32 would
                # silently corrupt pass-through repartition payloads)
                or pa.types.is_float64(t)
            ):
                self.layout.append(("i64pair", i))
            else:
                self.layout.append(("num", i))
        self.n_cols = sum(
            2 if kind == "i64pair" else 1 for kind, _ in self.layout
        ) + len(self.layout)  # +1 validity per field
        self._fn = ici_batch_exchange(mesh, self.n_cols, capacity)

    # ------------------------------------------------------------- host →
    def to_columns(self, batch) -> list[np.ndarray]:
        """Flatten one RecordBatch into the exchange's column list."""
        import pyarrow.compute as pc

        from ..ops.bridge import arrow_to_numpy

        cols: list[np.ndarray] = []
        for kind, i in self.layout:
            arr = batch.column(i)
            if kind == "dict":
                codes = self.encoders[i].encode(arr)
                validity = (
                    np.asarray(pc.is_valid(arr))
                    if arr.null_count
                    else np.ones(len(arr), bool)
                )
                cols.append(codes)
            else:
                values, validity = arrow_to_numpy(
                    arr.combine_chunks() if hasattr(arr, "combine_chunks") else arr
                )
                if validity is None:
                    validity = np.ones(len(values), bool)
                if kind == "i64pair":
                    v = (
                        values.view(np.int64)  # f64: exact bitcast
                        if values.dtype == np.float64
                        else values.astype(np.int64)
                    )
                    cols.append((v & 0xFFFFFFFF).astype(np.uint32).view(np.int32))
                    cols.append((v >> 32).astype(np.int32))
                else:
                    if self._x32 and values.dtype == np.float64:
                        values = values.astype(np.float32)
                    cols.append(values)
            cols.append(validity)
        return cols

    # ------------------------------------------------------------ exchange
    def exchange(self, dest: np.ndarray, valid: np.ndarray, cols):
        """Run the sharded exchange; returns (recv_cols, recv_valid,
        n_dropped) as host arrays."""
        sharded = shard_batch(self.mesh, [dest, valid] + list(cols))
        out = self._fn(*sharded)
        host = [np.asarray(o) for o in out[:-1]]
        return host[:-1], host[-1], int(np.asarray(out[-1]))

    # ------------------------------------------------------------- → host
    def to_batches(self, recv_cols, recv_valid) -> list:
        """Reassemble one RecordBatch per destination device."""
        import pyarrow as pa

        n_dev = self.mesh.devices.size
        per_dev = len(recv_valid) // n_dev
        out = []
        for d in range(n_dev):
            sl = slice(d * per_dev, (d + 1) * per_dev)
            mask = recv_valid[sl]
            arrays = []
            ci = 0
            for kind, i in self.layout:
                f = self.schema.field(i)
                if kind == "i64pair":
                    lo = recv_cols[ci][sl][mask].view(np.uint32).astype(np.int64)
                    hi = recv_cols[ci + 1][sl][mask].astype(np.int64)
                    values = (hi << 32) | lo
                    ci += 2
                else:
                    values = recv_cols[ci][sl][mask]
                    ci += 1
                validity = recv_cols[ci][sl][mask]
                ci += 1
                if kind == "dict":
                    # vectorized decode: the repartition path pushes up to
                    # mesh.exchange_max_rows rows through here
                    arrays.append(
                        self.encoders[i].decode(values, f.type, mask=~validity)
                    )
                else:
                    arrays.append(
                        pa.array(
                            _cast_back(values, f.type),
                            f.type,
                            mask=~validity,
                        )
                    )
            out.append(pa.RecordBatch.from_arrays(arrays, schema=self.schema))
        return out


def _cast_back(values: np.ndarray, t) -> np.ndarray:
    import pyarrow as pa

    if pa.types.is_date32(t):
        return values.astype("datetime64[D]")
    if pa.types.is_date64(t):
        return values.astype("int64").view("datetime64[ms]")
    if pa.types.is_timestamp(t):
        return values.astype("int64").view(f"datetime64[{t.unit}]")
    if pa.types.is_float64(t) and values.dtype == np.int64:
        return values.view(np.float64)  # inverse of the exact pair bitcast
    if pa.types.is_floating(t) and values.dtype == np.float32:
        return values.astype(np.float64)
    return values


def ici_all_to_all_repartition(mesh: Mesh, capacity: int):
    """Build a sharded hash-repartition exchange over ICI.

    Each device holds rows plus a destination-device id per row.  Rows
    route to their destination with a single ``all_to_all`` on a
    [n_dev, capacity] staging buffer (capacity-padded, mask-carrying — the
    static-shape answer to Ballista's variable-size shuffle files).

    Returns fn(values f64[rows], dest i32[rows], valid bool[rows]) →
    (recv_values f64[n_dev*capacity], recv_valid bool[n_dev*capacity],
    n_dropped i32 scalar).  Each device ends holding every row whose
    dest == its index.  ``n_dropped`` is the GLOBAL count of valid rows
    that exceeded a (source, destination) bucket's capacity and were not
    delivered — callers MUST check it and re-run with a larger capacity
    (or fall back to the Flight shuffle) when it is non-zero; silent loss
    would corrupt downstream aggregates.
    """
    from jax import shard_map

    n_dev = mesh.devices.size

    def local_exchange(values, dest, valid):
        # values/dest/valid: this device's shard [rows_local]
        rows = values.shape[0]
        # invalid rows sort to a sentinel destination past every real one,
        # so each real destination's run contains only valid rows and the
        # within-run index is dense
        dest_m = jnp.where(valid, dest, n_dev)
        order = jnp.argsort(dest_m, stable=True)
        values_s = values[order]
        dest_s = dest_m[order]
        # per-destination staging buffer [n_dev, capacity]
        counts = jax.ops.segment_sum(
            jnp.ones(rows, jnp.int32), dest_s, num_segments=n_dev + 1
        )[:n_dev]
        offsets = jnp.cumsum(counts) - counts  # start of each dest run
        safe_dest = jnp.minimum(dest_s, n_dev - 1)
        idx_within = jnp.arange(rows, dtype=jnp.int32) - offsets[safe_dest]
        ok = (
            (dest_s < n_dev) & (idx_within >= 0) & (idx_within < capacity)
        )
        # valid rows that overflowed their bucket: surfaced to the caller
        overflow = (dest_s < n_dev) & (idx_within >= capacity)
        n_dropped = jax.lax.psum(
            jnp.sum(overflow.astype(jnp.int32)), DATA_AXIS
        )
        # rows that don't belong (sentinel dest / over capacity) scatter
        # into a spill column that is sliced away — they can never clobber
        # a real slot
        slot = jnp.where(ok, idx_within, capacity)
        stage_vals = jnp.zeros((n_dev, capacity + 1), values.dtype)
        stage_valid = jnp.zeros((n_dev, capacity + 1), jnp.bool_)
        stage_vals = stage_vals.at[safe_dest, slot].set(values_s, mode="drop")
        stage_valid = stage_valid.at[safe_dest, slot].set(ok, mode="drop")
        stage_vals = stage_vals[:, :capacity]
        stage_valid = stage_valid[:, :capacity]
        # the collective: swap staging rows so device d receives every
        # other device's bucket d — Ballista's shuffle in one ICI op
        recv_vals = jax.lax.all_to_all(
            stage_vals, DATA_AXIS, split_axis=0, concat_axis=0, tiled=False
        )
        recv_valid = jax.lax.all_to_all(
            stage_valid, DATA_AXIS, split_axis=0, concat_axis=0, tiled=False
        )
        return recv_vals.reshape(-1), recv_valid.reshape(-1), n_dropped

    fn = shard_map(
        local_exchange,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def assemble_shards(
    mesh: Mesh, per_dev_chunks: list, n_cols: int
) -> list[jax.Array]:
    """Device-resident chunks → global row-sharded arrays, no host concat.

    ``per_dev_chunks[d]`` is a list of chunks already placed on device d,
    each chunk a list of ``n_cols`` equal-length 1-D arrays (the streaming
    upload path: partitions transfer as they are scanned).  Shards must
    share one length, so each device concatenates ITS chunks and pads to
    the longest device — on device, in shard-size pieces — then the padded
    per-device arrays stitch into one sharded array per column via
    ``make_array_from_single_device_arrays``.  Pad rows are zeros, which
    the kernels' validity column (False-padded) masks out.
    """
    devices = list(mesh.devices.flatten())
    assert len(per_dev_chunks) == len(devices)
    lens = [
        sum(int(ch[0].shape[0]) for ch in chunks) for chunks in per_dev_chunks
    ]
    L = max(max(lens), 1)
    protos = [
        next(ch[c] for chunks in per_dev_chunks for ch in chunks)
        for c in range(n_cols)
    ]
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    out = []
    for c in range(n_cols):
        per_dev = []
        for d, chunks in enumerate(per_dev_chunks):
            pieces = [ch[c] for ch in chunks]
            if not pieces:
                a = jax.device_put(
                    np.zeros(L, dtype=protos[c].dtype), devices[d]
                )
            else:
                a = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
                pad = L - int(a.shape[0])
                if pad:
                    a = jnp.pad(a, (0, pad))
                a = jax.device_put(a, devices[d])
            per_dev.append(a)
        out.append(
            jax.make_array_from_single_device_arrays(
                (L * len(devices),), sharding, per_dev
            )
        )
    return out


def shard_batch(
    mesh: Mesh, arrays: Sequence[np.ndarray]
) -> list[jax.Array]:
    """Place host arrays onto the mesh sharded along the row axis."""
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    out = []
    for a in arrays:
        n_dev = mesh.devices.size
        n = len(a)
        padded = ((n + n_dev - 1) // n_dev) * n_dev
        if padded != n:
            pad = np.zeros(padded - n, dtype=a.dtype)
            a = np.concatenate([a, pad])
        out.append(jax.device_put(a, sharding))
    return out
