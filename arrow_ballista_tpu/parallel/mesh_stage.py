"""Mesh gang stages: whole-stage SPMD execution over the device mesh.

This is the engine integration of :mod:`.mesh` (VERDICT.md round-1 item 3):
the reference routes EVERY cross-stage exchange through the disk+Flight
shuffle (``shuffle_writer.rs:142-292`` → ``flight_service.rs:80-118``); on
a TPU host, partitions of a mesh-resident stage are SHARDS, and the
partial-aggregate exchange collapses into ``psum``/``pmin``/``pmax`` over
ICI inside one jit-compiled ``shard_map`` program.

Mechanically: the distributed planner wraps an eligible stage subtree
(filter→project→partial-aggregate, the same shapes ``maybe_accelerate``
fuses) in a :class:`MeshGangExec` whose output partitioning is 1 — so the
scheduler naturally creates ONE task for the stage, and the executor that
receives it runs every input partition as a shard of a single mesh
program.  Nothing else in the graph/task machinery changes: recovery,
retries and stats see an ordinary one-task stage.  The reduced
[capacity]-sized states are the only thing that leaves the device.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from ..exec.operators import ExecutionPlan, Partitioning, TaskContext

# jitted shard_map step per (kernel signature, n_devices): reused across
# plan instances exactly like stage_compiler._KERNEL_CACHE
_MESH_STEP_CACHE: dict = {}


def gang_eligible(plan: ExecutionPlan) -> bool:
    """Structural check (no kernel build, no device touch — safe on the
    scheduler): does this stage subtree fuse into a partial-aggregate
    kernel whose states reduce with mesh collectives?"""
    from ..exec.aggregates import PARTIAL, HashAggregateExec
    from ..ops.stage_compiler import _flatten

    if not isinstance(plan, HashAggregateExec) or plan.mode != PARTIAL:
        return False
    if any(
        a.func == "count_distinct" or a.func.startswith("udaf:")
        for a in plan.aggs
    ):
        return False
    return _flatten(plan) is not None


class MeshGangExec(ExecutionPlan):
    """Runs a whole stage as one shard_map program over the mesh.

    Output partitioning is always 1: the scheduler sees a one-task stage.
    Execution accelerates the subtree (``maybe_accelerate``) and, when it
    fused, shards ALL input partitions over the mesh's data axis, reduces
    the per-device states over ICI and materializes the combined partial
    result.  Any fusion/capacity failure falls back to executing the input
    partitions sequentially inside the same task — still correct, just
    without the collective.
    """

    def __init__(self, input: ExecutionPlan, n_devices: int = 0):
        super().__init__()
        self.input = input
        self.n_devices = n_devices

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        return MeshGangExec(children[0], self.n_devices)

    def __str__(self) -> str:
        n = self.n_devices or "auto"
        return f"MeshGangExec: devices={n}"

    # ------------------------------------------------------------ execute
    def execute(
        self, partition: int, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        assert partition == 0, "gang stages are single-task"
        from ..ops.stage_compiler import TpuStageExec, maybe_accelerate

        from ..errors import ExecutionError
        from ..ops.stage_compiler import _CapacityExceeded

        inner = self.input
        if not isinstance(inner, TpuStageExec):
            inner = maybe_accelerate(inner, ctx.config)
        if isinstance(inner, TpuStageExec) and ctx.config.tpu_enable:
            try:
                # fully materialized before yielding: a capacity fallback
                # must never follow already-emitted rows with a re-run
                batches = list(self._execute_mesh(inner, ctx))
                yield from batches
                return
            except (_CapacityExceeded, ExecutionError):
                # group capacity overflow or a type that slipped past
                # plan-time lowering: re-run sequentially (Cancelled and
                # real bugs propagate — they are not fusion failures)
                self.metrics.add("mesh_fallback", 1)
        yield from self._execute_sequential(inner, ctx)

    def _execute_sequential(
        self, inner: ExecutionPlan, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        for p in range(self.input.output_partitioning().n):
            yield from inner.execute(p, ctx)

    def _execute_mesh(self, tpu, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        """All input partitions → one sharded fused kernel + ICI reduce."""
        import jax

        from ..ops import kernels as K
        from ..ops.bridge import DictEncoder
        from . import mesh as M

        fused = tpu.fused
        n_dev = self.n_devices or ctx.config.mesh_devices or len(jax.devices())
        n_dev = max(1, min(n_dev, len(jax.devices())))

        key_encoders = [DictEncoder() for _ in fused.group_exprs]
        tuple_gids: dict = {}
        gid_tuples: list = []
        segs: list[np.ndarray] = []
        leaf_arrays: dict[str, list[np.ndarray]] = {
            nm: [] for nm in tpu._flat_names
        }
        n_rows = 0
        n_parts = fused.source.output_partitioning().n
        with self.metrics.timer("mesh_stage_time_ns"):
            for p in range(n_parts):
                for batch in fused.source.execute(p, ctx):
                    ctx.check_cancelled()
                    if batch.num_rows == 0:
                        continue
                    n = batch.num_rows
                    if fused.group_exprs:
                        with self.metrics.timer("key_encode_time_ns"):
                            seg = tpu._encode_groups(
                                batch, key_encoders, tuple_gids, gid_tuples
                            )
                    else:
                        seg = np.zeros(n, dtype=np.int32)
                    segs.append(seg)
                    with self.metrics.timer("bridge_time_ns"):
                        env = K.build_env(batch, tpu.leaves, n)
                    for nm in tpu._flat_names:
                        leaf_arrays[nm].append(env[nm])
                    n_rows += n

            if n_rows == 0:
                yield from tpu._materialize(
                    None, key_encoders, gid_tuples, 0, ctx, 0
                )
                return

            seg = np.concatenate(segs)
            valid = np.ones(n_rows, dtype=bool)
            args = [
                np.concatenate(leaf_arrays[nm]) for nm in tpu._flat_names
            ]

            # same 4x capacity bucketing as the sequential device path —
            # segment ids beyond the table would be dropped silently
            cap = tpu.capacity
            while cap < len(gid_tuples):
                cap *= 4
            cap = min(cap, tpu.max_capacity)
            if cap > tpu.capacity:
                self.metrics.add("capacity_growths", 1)

            step_key = (tpu._sig, n_dev, cap)
            step = _MESH_STEP_CACHE.get(step_key)
            if step is None:
                mesh = M.make_mesh(n_dev)
                raw_kernel, _ = tpu._kernel_for(cap)
                step = M.make_distributed_agg_step(
                    raw_kernel, tpu.specs, mesh, cap, tpu._mode
                )
                _MESH_STEP_CACHE[step_key] = step
            with self.metrics.timer("device_time_ns"):
                mesh = M.make_mesh(n_dev)
                sharded = M.shard_batch(mesh, [seg, valid] + args)
                out = step(*sharded)
                out = [o.block_until_ready() for o in out]
        self.metrics.add("mesh_rows_in", n_rows)
        self.metrics.add("mesh_devices", n_dev)
        yield from tpu._materialize(
            tuple(out), key_encoders, gid_tuples, n_rows, ctx, 0
        )


def maybe_mesh(plan: ExecutionPlan, config) -> ExecutionPlan:
    """Physical-optimizer rule for the LOCAL engine (SessionContext): run
    an accelerated partial-aggregate under Repartition/Coalesce as one
    mesh gang so the local path exercises the same collectives as the
    distributed gang stages."""
    from ..exec.operators import CoalescePartitionsExec, RepartitionExec
    from ..ops.stage_compiler import TpuStageExec

    if not (config.mesh_enable and config.tpu_enable):
        return plan
    kids = plan.children()
    if kids:
        plan = plan.with_new_children([maybe_mesh(c, config) for c in kids])
    if isinstance(plan, (RepartitionExec, CoalescePartitionsExec)):
        child = plan.children()[0]
        if (
            isinstance(child, TpuStageExec)
            and child.fused.mode == "partial"
            and child.fused.source.output_partitioning().n > 1
        ):
            return plan.with_new_children(
                [MeshGangExec(child, config.mesh_devices)]
            )
    return plan
