"""Mesh gang stages: whole-stage SPMD execution over the device mesh.

This is the engine integration of :mod:`.mesh` (VERDICT.md round-1 item 3):
the reference routes EVERY cross-stage exchange through the disk+Flight
shuffle (``shuffle_writer.rs:142-292`` → ``flight_service.rs:80-118``); on
a TPU host, partitions of a mesh-resident stage are SHARDS, and the
partial-aggregate exchange collapses into ``psum``/``pmin``/``pmax`` over
ICI inside one jit-compiled ``shard_map`` program.

Mechanically: the distributed planner wraps an eligible stage subtree
(filter→project→partial-aggregate, the same shapes ``maybe_accelerate``
fuses) in a :class:`MeshGangExec` whose output partitioning is 1 — so the
scheduler naturally creates ONE task for the stage, and the executor that
receives it runs every input partition as a shard of a single mesh
program.  Nothing else in the graph/task machinery changes: recovery,
retries and stats see an ordinary one-task stage.  The reduced
[capacity]-sized states are the only thing that leaves the device.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from ..exec.operators import ExecutionPlan, Partitioning, TaskContext

# jitted shard_map step per (kernel signature, n_devices): reused across
# plan instances exactly like stage_compiler._KERNEL_CACHE
_MESH_STEP_CACHE: dict = {}


class _MeshKeyedRoute(Exception):
    """Control flow: the gang's first batch showed groups ~ rows — run
    the KEYED reduction per shard (every device concurrently) and merge
    the [distinct]-sized results on host, instead of abandoning the
    mesh for the sequential fallback."""

    def __init__(self, n_dev: int):
        super().__init__("mesh keyed high-cardinality")
        self.n_dev = n_dev


def gang_eligible(plan: ExecutionPlan) -> bool:
    """Structural check (no kernel build, no device touch — safe on the
    scheduler): does this stage subtree fuse into a partial-aggregate
    kernel whose states reduce with mesh collectives?"""
    from ..exec.aggregates import PARTIAL, HashAggregateExec
    from ..ops.stage_compiler import _flatten

    if not isinstance(plan, HashAggregateExec) or plan.mode != PARTIAL:
        return False
    if any(
        a.func == "count_distinct" or a.func.startswith("udaf:")
        for a in plan.aggs
    ):
        return False
    fused = _flatten(plan)
    # device-join stages run sequentially for now: the gang path would
    # need the build side replicated across shards
    return fused is not None and fused.join is None


class MeshGangExec(ExecutionPlan):
    """Runs a whole stage as one shard_map program over the mesh.

    Output partitioning is always 1: the scheduler sees a one-task stage.
    Execution accelerates the subtree (``maybe_accelerate``) and, when it
    fused, shards ALL input partitions over the mesh's data axis, reduces
    the per-device states over ICI and materializes the combined partial
    result.  Any fusion/capacity failure falls back to executing the input
    partitions sequentially inside the same task — still correct, just
    without the collective.
    """

    def __init__(self, input: ExecutionPlan, n_devices: int = 0):
        super().__init__()
        self.input = input
        self.n_devices = n_devices

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        return MeshGangExec(children[0], self.n_devices)

    def __str__(self) -> str:
        n = self.n_devices or "auto"
        return f"MeshGangExec: devices={n}"

    # ------------------------------------------------------------ execute
    def execute(
        self, partition: int, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        assert partition == 0, "gang stages are single-task"
        from ..ops.stage_compiler import TpuStageExec, maybe_accelerate

        from ..errors import ExecutionError
        from ..ops.stage_compiler import _CapacityExceeded, _JaxRuntimeError

        inner = self.input
        if not isinstance(inner, TpuStageExec):
            inner = maybe_accelerate(inner, ctx.config)
        if (
            isinstance(inner, TpuStageExec)
            and ctx.config.tpu_enable
            and inner.fused.join is None
        ):
            try:
                # fully materialized before yielding: a capacity fallback
                # must never follow already-emitted rows with a re-run
                batches = list(self._execute_mesh(inner, ctx))
                yield from batches
                return
            except _MeshKeyedRoute as route:
                try:
                    batches = list(
                        self._execute_mesh_keyed(inner, ctx, route.n_dev)
                    )
                    yield from batches
                    return
                except (_CapacityExceeded, ExecutionError, _JaxRuntimeError):
                    self.metrics.add("mesh_fallback", 1)
            except (_CapacityExceeded, ExecutionError, _JaxRuntimeError):
                # group capacity overflow, a type that slipped past
                # plan-time lowering, or a DEVICE/COMPILE failure
                # (BENCH_SUITE_r05 h2o: the gang's shard_map compile got
                # its tpu_compile_helper SIGKILLed and the uncaught
                # JaxRuntimeError killed the whole query — a gang stage
                # must degrade to the sequential path, never crash): re-run
                # sequentially.  Only jax's runtime error is caught
                # (blanket RuntimeError would hide real bugs); Cancelled
                # is a BallistaError sibling and still propagates.
                self.metrics.add("mesh_fallback", 1)
        yield from self._execute_sequential(inner, ctx)

    def _execute_sequential(
        self, inner: ExecutionPlan, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        for p in range(self.input.output_partitioning().n):
            yield from inner.execute(p, ctx)

    def _execute_mesh(self, tpu, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        """All input partitions → one sharded fused kernel + ICI reduce."""
        import jax

        from ..ops import kernels as K
        from . import mesh as M

        fused = tpu.fused
        n_dev = self.n_devices or ctx.config.mesh_devices or len(jax.devices())
        n_dev = max(1, min(n_dev, len(jax.devices())))

        from ..ops.groups import GroupTable

        from ..ops.bridge import make_key_encoder

        key_encoders = [
            make_key_encoder(tpu._schema.field(i).type)
            for i in range(len(fused.group_exprs))
        ]
        group_table = GroupTable(len(fused.group_exprs))
        n_rows = 0
        n_parts = fused.source.output_partitioning().n
        # Partitions ARE the shards: each partition's arrays transfer to
        # its device (round-robin) as soon as the partition is scanned, so
        # peak host memory is ONE partition and source I/O overlaps device
        # transfer (round-2 weakness #6: the old path np.concatenate'd the
        # whole stage input on host first).  Column order per device chunk:
        # [seg, valid, *flat_names].
        names = ["__seg", "__valid"] + list(tpu._flat_names)
        n_dev_chunks: list[list[list]] = []  # [device][chunk][column]
        with self.metrics.timer("mesh_stage_time_ns"):
            import jax as _jax

            mesh = M.make_mesh(n_dev)
            devices = list(mesh.devices.flatten())
            n_dev_chunks = [[] for _ in devices]
            for p in range(n_parts):
                for batch in fused.source.execute(p, ctx):
                    ctx.check_cancelled()
                    if batch.num_rows == 0:
                        continue
                    n = batch.num_rows
                    if fused.group_exprs:
                        with self.metrics.timer("key_encode_time_ns"):
                            seg = tpu._encode_groups(
                                batch, key_encoders, group_table
                            )
                        if n_rows == 0:
                            from ..ops.stage_compiler import (
                                _highcard_detect,
                                keyed_route_wanted,
                            )

                            if _highcard_detect(group_table.n_groups, n):
                                if keyed_route_wanted(tpu.config):
                                    # groups ~ rows: per-shard KEYED
                                    # reduction keeps the whole mesh busy
                                    raise _MeshKeyedRoute(n_dev)
                                if tpu.config.tpu_highcard_mode != "gid":
                                    # cpu platform / highcard_mode=cpu:
                                    # the sequential fallback routes each
                                    # partition to the C++ hash aggregate
                                    # (the measured winner off-
                                    # accelerator); 'gid' pins the gid-
                                    # table gang path (capacity must fit)
                                    from ..errors import ExecutionError

                                    raise ExecutionError(
                                        "high-cardinality gang stage"
                                    )
                    else:
                        seg = np.zeros(n, dtype=np.int32)
                    with self.metrics.timer("bridge_time_ns"):
                        env = K.build_env(batch, tpu.leaves, n)
                        cols = [seg, np.ones(n, dtype=bool)] + [
                            env[nm] for nm in tpu._flat_names
                        ]
                        dev = devices[p % n_dev]
                        n_dev_chunks[p % n_dev].append(
                            [_jax.device_put(c, dev) for c in cols]
                        )
                    n_rows += n
                    # host copies die with `env`/`cols` at next iteration

            if n_rows == 0:
                yield from tpu._materialize(
                    None, key_encoders, group_table, 0, ctx, 0
                )
                return

            # same 4x capacity bucketing as the sequential device path —
            # segment ids beyond the table would be dropped silently
            cap = tpu.capacity
            while cap < group_table.n_groups:
                cap *= 4
            cap = min(cap, tpu.max_capacity)
            if cap > tpu.capacity:
                self.metrics.add("capacity_growths", 1)

            step_key = (tpu._sig, n_dev, cap) + K.algo_cache_token()
            step = _MESH_STEP_CACHE.get(step_key)
            if step is None:
                raw_kernel, _ = tpu._kernel_for(cap)
                step = M.make_distributed_agg_step(
                    raw_kernel, tpu.specs, mesh, cap, tpu._mode
                )
                _MESH_STEP_CACHE[step_key] = step
            with self.metrics.timer("device_time_ns"):
                sharded = M.assemble_shards(mesh, n_dev_chunks, len(names))
                out = step(*sharded)
                # packed fetch = the only reliable sync on the tunnel TPU
                # (block_until_ready is a no-op there); one roundtrip,
                # sliced to the assigned groups (pow2 bucket)
                host_states = tpu._fetch_states(
                    tuple(out),
                    group_table.n_groups if tpu.fused.group_exprs else None,
                )
        self.metrics.add("mesh_rows_in", n_rows)
        self.metrics.add("mesh_devices", n_dev)
        yield from tpu._materialize(
            host_states, key_encoders, group_table, n_rows, ctx, 0
        )


    def _execute_mesh_keyed(
        self, tpu, ctx: TaskContext, n_dev: int
    ) -> Iterator[pa.RecordBatch]:
        """High-cardinality gang: per-shard KEYED reduction on every
        device CONCURRENTLY (async dispatch of the single-chip keyed
        kernels — sort by raw key codes, gids from key-change
        boundaries), then a [distinct]-sized vectorized host merge by
        key.  The O(rows) sort/scan work stays on the shards; only the
        per-shard (unique keys, states) cross to host.  An ICI
        tree-merge is the future optimization; the host merge is already
        orders of magnitude below row scale."""
        import jax
        import jax.numpy as jnp

        from ..errors import ExecutionError
        from ..ops import kernels as K
        from ..ops.bridge import make_key_encoder
        from ..ops.stage_compiler import _CapacityExceeded, _KeyedGroups
        from . import mesh as M

        fused = tpu.fused
        holder, _raw, prep = tpu._keyed_prep()
        key_encoders = [
            make_key_encoder(tpu._schema.field(pos).type)
            for pos, (kind, _s) in enumerate(tpu._group_plan)
            if kind == "enc"
        ]
        n_keys = tpu._n_encoded_groups
        mesh = M.make_mesh(n_dev)
        devices = list(mesh.devices.flatten())
        per_dev_buf: list[list] = [[] for _ in devices]
        n_rows = 0
        with self.metrics.timer("mesh_stage_time_ns"):
            n_parts = fused.source.output_partitioning().n
            for p in range(n_parts):
                for batch in fused.source.execute(p, ctx):
                    ctx.check_cancelled()
                    n = batch.num_rows
                    if n == 0:
                        continue
                    with self.metrics.timer("key_encode_time_ns"):
                        codes = tpu._encode_codes(batch, key_encoders)
                    if tpu._mode == "x32":
                        for c in codes:
                            if len(c) and (
                                c.min() < -(1 << 31)
                                or c.max() >= (1 << 31)
                            ):
                                raise ExecutionError(
                                    "gang keys exceed i32"
                                )
                    n_pad = K.bucket_rows(n)
                    keys = tuple(
                        K._pad(K.coerce_host_values(c), n_pad)
                        for c in codes
                    )
                    valid = np.zeros(n_pad, dtype=bool)
                    valid[:n] = True
                    with self.metrics.timer("bridge_time_ns"):
                        # trivial-validity substitution is skipped here:
                        # the gang pins arrays to explicit mesh devices,
                        # and a default-device iota mask would break that
                        # placement
                        args, _ = tpu._kernel_args(batch, n, n_pad, None)
                    dev = devices[p % n_dev]
                    with self.metrics.timer("device_time_ns"):
                        keys_d = tuple(
                            jax.device_put(k, dev) for k in keys
                        )
                        valid_d = jax.device_put(valid, dev)
                        args_d = [jax.device_put(a, dev) for a in args]
                        per_dev_buf[p % n_dev].append(
                            prep(keys_d, valid_d, *args_d)
                        )
                    n_rows += n

            if n_rows == 0:
                yield from tpu._materialize(
                    None, key_encoders, _KeyedGroups([], 0), 0, ctx, 0
                )
                return

            with self.metrics.timer("device_time_ns"):
                # per-device concat + phase-1 sort (dispatches overlap
                # across devices; only the scalar fetches serialize)
                sort_out: list = []
                for buf in per_dev_buf:
                    if not buf:
                        sort_out.append(None)
                        continue
                    parts = list(zip(*buf))
                    if len(buf) == 1:
                        fields = [q[0] for q in parts]
                    else:
                        fields = [jnp.concatenate(q) for q in parts]
                    total = int(fields[0].shape[0])
                    n2 = K.bucket_rows(total)
                    if n2 != total:
                        fields = [
                            jnp.pad(f, (0, n2 - total)) for f in fields
                        ]
                    mask = fields[0]
                    keys_f = fields[1:1 + n_keys]
                    flat = fields[1 + n_keys:]
                    out = K.keyed_sort_kernel(n_keys)(mask, *keys_f)
                    sort_out.append((out, flat))
                counts = [
                    int(np.asarray(so[0][-1])) if so is not None else 0
                    for so in sort_out
                ]
                if max(counts, default=0) > tpu.max_capacity:
                    raise _CapacityExceeded()
                cap = max(64, 1 << (max(max(counts), 1) - 1).bit_length())
                fetches = []
                for so, ng in zip(sort_out, counts):
                    if so is None:
                        continue
                    out, flat = so
                    s2, perm, sk = out[0], out[1], out[2:-1]
                    finish = K.keyed_finish_kernel(
                        holder["kinds"], holder["plan"], tpu.specs,
                        n_keys, cap, tpu._mode,
                    )
                    fetches.append(
                        (finish(s2, perm, tuple(sk), tuple(flat)), ng)
                    )
                per_dev = []
                for packed, ng in fetches:
                    host = np.asarray(packed)
                    states, kc = K.unpack_keyed_host(
                        tpu.specs, host, tpu._mode, n_keys
                    )
                    per_dev.append((states, kc, ng))
            merged_states, merged_keys, n_groups = K.merge_keyed_host(
                tpu.specs, tpu._mode, per_dev
            )
        self.metrics.add("mesh_rows_in", n_rows)
        self.metrics.add("mesh_devices", n_dev)
        self.metrics.add("mesh_keyed", 1)
        yield from tpu._materialize(
            merged_states, key_encoders,
            _KeyedGroups(merged_keys, n_groups), n_rows, ctx, 0,
        )


class MeshExchangeError(Exception):
    """Exchange-specific failure (capacity ceiling, untransferable column):
    the owning writer falls back to the classic hash-split.  Deliberately
    NOT an ExecutionError so inner-plan execution errors propagate to the
    normal stage-retry machinery instead of being silently re-run."""


def exchange_supported(schema: pa.Schema) -> bool:
    """Can every field of this schema cross the ICI batch exchange?
    (numeric/bool/date/timestamp directly, strings as dictionary codes,
    i64 as lo/hi pairs — mesh.BatchExchanger's layout rules)."""
    from ..ops.bridge import _is_device_friendly

    for f in schema:
        t = f.type
        if not (
            pa.types.is_string(t)
            or pa.types.is_large_string(t)
            or _is_device_friendly(t)
        ):
            return False
    return True


class MeshRepartitionExec(ExecutionPlan):
    """Gang-form hash repartition: the stage's shuffle IS an ICI collective.

    The reference hash-splits every batch per input partition and writes
    n_in x n_out shuffle files (``shuffle_writer.rs:201-285``); when the
    stage's partitions are mesh-resident, this node runs ONE task that
    shards every input partition over the mesh, routes rows to their
    destination output partition with a single ``all_to_all``
    (:class:`..parallel.mesh.BatchExchanger`), and hands the owning
    :class:`ShuffleWriterExec` already-partitioned output batches — zero
    hash-split files, one memory write per output partition.

    ``output_partitioning()`` is 1 so the scheduler sees an ordinary
    one-task stage (same trick as :class:`MeshGangExec`); recovery and
    stats machinery are untouched.  Capacity follows the documented
    n_dropped contract: computed exactly from the shard layout, doubled
    and retried if the exchange still reports drops, ExecutionError (→
    writer fallback) past the ceiling.
    """

    _CAP_CEILING = 1 << 24
    # process-wide observability: completed exchanges / writer fallbacks
    # (executor-side metrics are not reachable from cluster tests)
    exchanges_completed = 0

    def __init__(
        self, input: ExecutionPlan, partitioning: Partitioning,
        n_devices: int = 0,
    ):
        super().__init__()
        assert partitioning.kind == "hash"
        self.input = input
        self.partitioning = partitioning
        self.n_devices = n_devices

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        return MeshRepartitionExec(
            children[0], self.partitioning, self.n_devices
        )

    def __str__(self) -> str:
        return (
            f"MeshRepartitionExec: hash({self.partitioning.n}) "
            f"devices={self.n_devices or 'auto'}"
        )

    def execute(
        self, partition: int, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        # direct execution (no writer): repartition does not change row
        # content, so pass every input partition through unchanged
        for p in range(self.input.output_partitioning().n):
            yield from self.input.execute(p, ctx)

    # -------------------------------------------------------- exchanged
    def execute_exchanged(
        self, ctx: TaskContext
    ) -> Iterator[tuple[int, pa.RecordBatch]]:
        """Yield (output_partition, batch) pairs after the mesh exchange."""
        import jax

        from ..errors import ExecutionError
        from ..shuffle.execution_plans import partition_indices
        from . import mesh as M

        n_out = self.partitioning.n
        exprs = list(self.partitioning.exprs)
        n_dev = self.n_devices or ctx.config.mesh_devices or len(jax.devices())
        n_dev = max(1, min(n_dev, len(jax.devices())))

        # the exchange buffers the stage input in host memory (~2x resident
        # plus device staging): a row ceiling keeps huge shuffles on the
        # streaming hash-split path instead of OOMing this task
        max_rows = ctx.config.mesh_exchange_max_rows
        with self.metrics.timer("mesh_stage_time_ns"):
            batches: list[pa.RecordBatch] = []
            dest_parts: list[np.ndarray] = []
            rows_seen = 0
            for p in range(self.input.output_partitioning().n):
                for b in self.input.execute(p, ctx):
                    ctx.check_cancelled()
                    if b.num_rows == 0:
                        continue
                    rows_seen += b.num_rows
                    if rows_seen > max_rows:
                        raise MeshExchangeError(
                            f"stage exceeds mesh.exchange_max_rows "
                            f"({rows_seen} > {max_rows})"
                        )
                    with self.metrics.timer("repart_time_ns"):
                        idx = partition_indices(b, exprs, n_out)
                    batches.append(b)
                    dest_parts.append(idx.astype(np.int32))
            if not batches:
                return

            # destination column rides the exchange so one device can
            # carry several output partitions (n_out != n_dev)
            ext_schema = pa.schema(
                list(self.input.schema) + [pa.field("__part", pa.int32())]
            )
            ext_batches = [
                pa.RecordBatch.from_arrays(
                    list(b.columns) + [pa.array(d)], schema=ext_schema
                )
                for b, d in zip(batches, dest_parts)
            ]
            dest_dev = np.concatenate(dest_parts) % n_dev
            dest_dev = dest_dev.astype(np.int32)
            total = len(dest_dev)
            valid = np.ones(total, dtype=bool)

            # exact per-(source shard, destination) bucket need from the
            # known contiguous shard layout (shard_batch pads evenly)
            per_shard = -(-total // n_dev)
            shard_id = np.arange(total, dtype=np.int64) // per_shard
            need = int(
                np.bincount(
                    shard_id * n_dev + dest_dev, minlength=n_dev * n_dev
                ).max()
            )
            cap = 1 << max(need - 1, 0).bit_length()

            mesh = M.make_mesh(n_dev)
            try:
                base_ex = None
                cols = None
                while True:
                    ex = M.BatchExchanger(
                        mesh, ext_schema, cap, share_from=base_ex
                    )
                    if cols is None:  # encoding is capacity-independent
                        base_ex = ex
                        cols_per_batch = [
                            ex.to_columns(b) for b in ext_batches
                        ]
                        cols = [
                            np.concatenate(parts)
                            for parts in zip(*cols_per_batch)
                        ]
                    with self.metrics.timer("device_time_ns"):
                        recv_cols, recv_valid, n_dropped = ex.exchange(
                            dest_dev, valid, cols
                        )
                    if n_dropped == 0:
                        break
                    cap *= 2  # grow-or-fallback contract (mesh.py docstring)
                    if cap > self._CAP_CEILING:
                        raise MeshExchangeError(
                            "mesh exchange capacity ceiling exceeded"
                        )
                    self.metrics.add("capacity_growths", 1)
            except ExecutionError as e:
                # column didn't cross the bridge (dtype slipped past the
                # plan-time check): an exchange failure, not a plan failure
                raise MeshExchangeError(str(e)) from e

            self.metrics.add("mesh_exchange_rows", total)
            self.metrics.add("mesh_devices", n_dev)
            MeshRepartitionExec.exchanges_completed += 1

            part_col = len(ext_schema) - 1
            for recv in ex.to_batches(recv_cols, recv_valid):
                if recv.num_rows == 0:
                    continue
                parts = np.asarray(recv.column(part_col))
                core = recv.select(range(part_col))
                order = np.argsort(parts, kind="stable")
                sorted_parts = parts[order]
                shuffled = core.take(pa.array(order))
                bounds = np.searchsorted(
                    sorted_parts, np.arange(n_out + 1)
                )
                for out_p in range(n_out):
                    lo, hi = int(bounds[out_p]), int(bounds[out_p + 1])
                    if hi > lo:
                        yield out_p, shuffled.slice(lo, hi - lo)


def maybe_mesh(plan: ExecutionPlan, config) -> ExecutionPlan:
    """Physical-optimizer rule for the LOCAL engine (SessionContext): run
    an accelerated partial-aggregate under Repartition/Coalesce as one
    mesh gang so the local path exercises the same collectives as the
    distributed gang stages."""
    from ..exec.operators import CoalescePartitionsExec, RepartitionExec
    from ..ops.stage_compiler import TpuStageExec

    if not (config.mesh_enable and config.tpu_enable):
        return plan
    kids = plan.children()
    if kids:
        plan = plan.with_new_children([maybe_mesh(c, config) for c in kids])
    if isinstance(plan, (RepartitionExec, CoalescePartitionsExec)):
        child = plan.children()[0]
        if (
            isinstance(child, TpuStageExec)
            and child.fused.mode == "partial"
            and child.fused.source.output_partitioning().n > 1
        ):
            return plan.with_new_children(
                [MeshGangExec(child, config.mesh_devices)]
            )
    return plan
