"""Whole-stage fusion planner: enumerate the fusion boundaries of a
``_FusedStage`` subplan instead of hardcoding them.

The SystemML move (PAPERS.md "On Optimizing Operator Fusion Plans for
Large-Scale ML") applied to this executor: a map stage's operator chain
(scan → filter… → project → join → partial-agg → shuffle-pid) is walked
once and partitioned into SEGMENTS.  Everything inside one segment
compiles into one traced function and executes as ONE jitted dispatch —
filter masks, projected columns and agg state flow as jax arrays,
intermediates never leave the device (PAPERS.md "Data Path Fusion in
GPU for Analytical Query Processing").  A cut is forced exactly where
fusion is impossible or unprofitable:

* **non-traceable op** — an operator with no jax lowering (a string-key
  shuffle-pid derivation, a host UDF) becomes its own single-op segment
  and runs on the existing per-operator path;
* **pipeline breaker** — an operator that must consume its whole input
  before producing output (join build, the keyed sort-based agg) cuts
  BEFORE itself: upstream ops still fuse, the breaker starts a fresh
  segment;
* **capacity** — segments wider than ``fusion_max_ops`` (a measured
  ``ops/routing_table.json`` entry, not a code constant) are split so
  the unrolled XLA program stays clear of the compile cliff.

The planner is pure bookkeeping — no jax, no device.  ``TpuStageExec``
maps the plan onto its retained-entry single-dispatch runner: a plan
whose compute ops all land in segment 0 executes as one
``fused_dispatches`` call (with the shuffle pid column derived inside
the same trace when the pid op fused too), anything else degrades
segment-by-segment to per-operator dispatch.  Invariant (property-
tested): the segments partition the op list exactly once, in order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Tuple

__all__ = [
    "FusionOp",
    "FusionPlan",
    "plan_segments",
    "stage_ops",
]


@dataclass(frozen=True)
class FusionOp:
    """One operator of the stage subplan, as the planner sees it."""

    kind: str  # scan | filter | project | join | partial_agg | agg | shuffle_pid
    traceable: bool = True
    pipeline_breaker: bool = False
    label: str = ""


@dataclass(frozen=True)
class FusionPlan:
    """Ordered segments partitioning the op list, plus why each cut
    happened (``cuts`` holds ``(op_index, reason)`` — the boundary sits
    immediately before that op index)."""

    segments: Tuple[Tuple[FusionOp, ...], ...]
    cuts: Tuple[Tuple[int, str], ...] = field(default_factory=tuple)

    @property
    def ops(self) -> Tuple[FusionOp, ...]:
        return tuple(op for seg in self.segments for op in seg)

    @property
    def max_segment_ops(self) -> int:
        return max((len(s) for s in self.segments), default=0)

    def compute_fused(self) -> bool:
        """True when every COMPUTE op (everything but shuffle_pid) lives
        in segment 0 — the shape the single-dispatch runner can take."""
        if not self.segments:
            return False
        for seg in self.segments[1:]:
            for op in seg:
                if op.kind != "shuffle_pid":
                    return False
        return True

    def pid_fused(self) -> bool:
        """True when the shuffle-pid op fused into segment 0 (compute +
        partition-id derivation in ONE dispatch)."""
        return any(
            op.kind == "shuffle_pid" for op in (self.segments[0] if self.segments else ())
        )


def plan_segments(ops: Iterable[FusionOp], max_ops: int) -> FusionPlan:
    """Partition ``ops`` into fused segments under the cut rules above.

    The result's segments always concatenate back to ``ops`` exactly —
    no op is dropped, duplicated or reordered; degradation happens by
    making segments smaller, never by changing the plan's meaning."""
    ops = list(ops)
    max_ops = max(1, int(max_ops))
    segments: List[Tuple[FusionOp, ...]] = []
    cuts: List[Tuple[int, str]] = []
    cur: List[FusionOp] = []
    for i, op in enumerate(ops):
        if not op.traceable:
            # no lowering: isolate it so neighbours still fuse
            if cur:
                segments.append(tuple(cur))
                cur = []
            cuts.append((i, "non_traceable"))
            segments.append((op,))
            continue
        if op.pipeline_breaker and cur:
            segments.append(tuple(cur))
            cur = []
            cuts.append((i, "pipeline_breaker"))
        if len(cur) >= max_ops:
            segments.append(tuple(cur))
            cur = []
            cuts.append((i, "capacity"))
        cur.append(op)
    if cur:
        segments.append(tuple(cur))
    return FusionPlan(tuple(segments), tuple(cuts))


def _is_col(e) -> bool:
    from ..exec import expressions as pe

    return isinstance(e, pe.Col)


def stage_ops(stage) -> List[FusionOp]:
    """The op descriptors of one compiled ``TpuStageExec`` subplan.

    Everything a TpuStageExec compiled is traceable by construction
    (``K.NotLowerable`` already routed unsupported expressions to the
    CPU operator path before this planner runs) — the exceptions the
    descriptors record are the join build and the keyed sort-based agg
    (pipeline breakers: they consume the whole stream before emitting)
    and a shuffle-pid derivation whose keys the device hash can't take
    (string keys, non-column exprs, too many partitions: non-traceable,
    runs as its own host-prepped dispatch after materialize)."""
    fused = stage.fused
    ops: List[FusionOp] = [
        FusionOp("scan", label=type(fused.source).__name__)
    ]
    for _ in fused.filters:
        ops.append(FusionOp("filter"))
    # a projection op exists when any agg arg / group key is a computed
    # expression rather than a bare column reference
    computed = any(
        not _is_col(g) for g, _name in fused.group_exprs
    ) or any(
        a.arg is not None and not _is_col(a.arg) for a in fused.aggs
    )
    if computed:
        ops.append(FusionOp("project"))
    if fused.join is not None:
        ops.append(FusionOp("join", pipeline_breaker=True))
    ops.append(
        FusionOp(
            "partial_agg",
            pipeline_breaker=bool(getattr(stage, "_needs_keyed", False)),
            label="keyed" if getattr(stage, "_needs_keyed", False) else "",
        )
    )
    if getattr(stage, "_shuffle_hint", None) is not None:
        ops.append(
            FusionOp(
                "shuffle_pid",
                traceable=stage._fused_pid_spec() is not None,
            )
        )
    return ops
