"""TPU compute plane: fused relational kernels over JAX/XLA.

f64 is enabled globally: TPC-H aggregates sum ~1e10-magnitude values over
millions of rows, beyond f32 precision; XLA emulates f64 on TPU at a cost
the (tiny) aggregate FLOP count absorbs easily — the stage bottleneck is
host→HBM transfer, not VPU math.
"""

import jax

jax.config.update("jax_enable_x64", True)
