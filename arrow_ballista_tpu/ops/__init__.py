"""TPU compute plane: fused relational kernels over JAX/XLA.

Dtype policy (``kernels.precision_mode``): the CPU platform runs f64/i64
kernels ("x64" — exact vs pyarrow oracles); TPU runs native f32/i32
("x32") with double-float compensated sums, since v5e has no f64/i64 ALUs.
``jax_enable_x64`` is enabled globally so the x64 mode can exist at all;
x32-mode kernels pin every dtype explicitly and never materialize a 64-bit
device array, so the flag is harmless on TPU.
"""

import jax

jax.config.update("jax_enable_x64", True)
