"""Device window-function kernel (sort + segmented scans + gathers).

TPU-first lowering of :class:`~..exec.window.WindowExec` — a capability
the reference lacks entirely (its distributed planner raises
NotImplemented for WindowAggExec, ``scheduler/src/planner.rs:81-170``):

* ONE multi-key integer ``lax.sort`` orders rows by (pad flag, PARTITION
  BY codes, per-ORDER-BY null flag + order-preserving integer encoding);
  the host pre-encodes every key into integers whose signed order equals
  the SQL order (``window_compiler._order_encode``), so the device sort
  is exact for any numeric/date/dict key in BOTH dtype modes;
* partition / peer boundaries fall out of key-change flags; ranking
  functions are arithmetic over boundary indices; running (default
  RANGE) aggregates are ONE segmented inclusive ``associative_scan``
  with reset-at-boundary (df32-compensated sums in x32, the same 2Sum
  discipline as the aggregate kernels); value functions are clamped
  gathers;
* results return to INPUT row order via an inverse-permutation GATHER
  (scatter serializes on TPU; ``sort_key_val(perm, iota)`` gives the
  inverse as a second sort), and one packed fetch moves every output
  column in a single tunnel roundtrip.

Spec encoding (static per kernel): tuples
  ("row_number",) | ("rank",) | ("dense_rank",) | ("ntile", k)
  | ("agg", fn, arg_slot, pair)        # fn in sum|count|avg|min|max, RANGE
  | ("aggf", fn, arg_slot, a, b, pair) # ROWS frame [i+a, i+b]; None=UNBOUNDED
  | ("val", fn, arg_slot, offset)      # fn in lag|lead|first_value|last_value
arg slots index the (value, validity) array pairs passed after the keys;
``pair`` marks slots whose value is an exact f32 (hi, lo) tuple — x32
integer sum/avg args ride the aggregate path's column_pair discipline so
values above 2^24 don't lose low bits at an f32 cast.
ROWS-framed sums are two gathers on a compensated prefix (global prefix:
both frame bounds live in one segment, so earlier segments subtract out).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import kernels as K

_WINDOW_KERNEL_CACHE: dict = {}


def _seg_first(flag, idx):
    """Per sorted row: index of its segment's first row (cummax trick)."""
    return jax.lax.cummax(jnp.where(flag, idx, 0))


def _seg_last(flag, n):
    """Per sorted row: index of its segment's LAST row.  A row is last
    when the next row starts a new segment (or is the final row); in the
    flipped array those become segment firsts."""
    last_marker = jnp.concatenate(
        [flag[1:], jnp.ones((1,), jnp.bool_)]
    )
    fm = jnp.flip(last_marker)
    fidx = jnp.arange(n, dtype=jnp.int32)
    ffirst = jax.lax.cummax(jnp.where(fm, fidx, 0))
    return (n - 1) - jnp.flip(ffirst)


def _change_flag(keys: list):
    """flag[i] = row i differs from row i-1 on ANY key (row 0 starts)."""
    diff = keys[0][1:] != keys[0][:-1]
    for k in keys[1:]:
        diff = jnp.logical_or(diff, k[1:] != k[:-1])
    return jnp.concatenate([jnp.ones((1,), jnp.bool_), diff])


def _seg_scan(flag, elems: list, kinds: list):
    """Segmented inclusive scan resetting at ``flag``.

    kinds per element: "df32" (the element is an (hi, lo) pair summed
    with 2Sum compensation), "sum" (plain add), "min", "max".  Returns
    per-row scanned values in the same structure.
    """
    flat = [flag]
    layout = []
    for kind, e in zip(kinds, elems):
        if kind == "df32":
            layout.append((kind, len(flat)))
            flat.extend(e)
        else:
            layout.append((kind, len(flat)))
            flat.append(e)
    flat_kinds = ["flag"]
    for kind, _ in layout:
        flat_kinds.extend(
            ["df32_hi", "df32_lo"] if kind == "df32" else [kind]
        )

    def combine(a, b):
        fb = b[0]
        out = [jnp.logical_or(a[0], fb)]
        i = 1
        while i < len(flat_kinds):
            kind = flat_kinds[i]
            if kind == "df32_hi":
                s, e = K._two_sum(a[i], b[i])
                hi, lo2 = K._two_sum(s, a[i + 1] + b[i + 1] + e)
                out.append(jnp.where(fb, b[i], hi))
                out.append(jnp.where(fb, b[i + 1], lo2))
                i += 2
                continue
            if kind == "sum":
                merged = a[i] + b[i]
            elif kind == "min":
                merged = jnp.minimum(a[i], b[i])
            else:  # max
                merged = jnp.maximum(a[i], b[i])
            out.append(jnp.where(fb, b[i], merged))
            i += 1
        return tuple(out)

    scanned = jax.lax.associative_scan(combine, tuple(flat))
    outs = []
    for kind, slot in layout:
        if kind == "df32":
            outs.append((scanned[slot], scanned[slot + 1]))
        else:
            outs.append(scanned[slot])
    return outs


def _range_extremum(v, lo, hi, fn, ident, n, max_len):
    """Per-row extremum over [lo_i, hi_i] via a SPARSE TABLE (doubling):
    level k holds the extremum of the size-2^k window starting at each
    row, built with log-depth shifted minimum/maximum folds; the query
    is two gathers (the classic overlapping-windows RMQ decomposition).
    A monotonic deque is inherently sequential — this is the
    gather-friendly device form.  Segment safety: both query windows lie
    inside [lo, hi], which callers clip to the row's segment, so levels
    may freely span segment boundaries without contaminating results.
    ``max_len`` bounds the table depth: finite frames need only
    ceil(log2(frame_len)) levels."""
    ext = jnp.minimum if fn == "min" else jnp.maximum
    levels = [v]
    depth = max(1, int(max_len - 1).bit_length())
    cur = v
    for k in range(1, depth + 1):
        s = 1 << (k - 1)
        if s < n:
            shifted = jnp.concatenate(
                [cur[s:], jnp.full((s,), ident, cur.dtype)]
            )
        else:
            shifted = jnp.full((n,), ident, cur.dtype)
        cur = ext(cur, shifted)
        levels.append(cur)
    table = jnp.stack(levels)  # [depth+1, n]
    length = jnp.maximum(hi - lo + 1, 1)
    kq = jnp.zeros_like(length)
    for k in range(1, depth + 1):
        kq = kq + (length >= (1 << k)).astype(length.dtype)
    size = jnp.left_shift(jnp.ones_like(kq), kq)
    aidx = jnp.clip(lo, 0, n - 1)
    bidx = jnp.clip(hi - size + 1, 0, n - 1)
    flat = table.reshape(-1)
    return ext(flat[kq * n + aidx], flat[kq * n + bidx])


def make_window_kernel(
    specs: tuple,
    n_part_keys: int,
    n_order_keys: int,
    n_args: int,
    mode: str,
):
    """Jitted ``fn(part_keys, order_keys, args) -> packed``.

    ``part_keys``/``order_keys`` are tuples of integer key arrays (the
    pad flag is part_keys[0]); ``args`` is a tuple of (value, validity)
    pairs, where a pair-slot's value is itself an (hi, lo) f32 tuple.
    ``packed`` is an [n_out_rows, n] integer array in INPUT row
    order — float rows bitcast exactly like the aggregate packed fetch.
    Per-spec output layout (host side must mirror):
      ranking/ntile → 1 int row
      agg count     → 1 int row
      agg sum/avg   → x32: hi, lo, cnt  | x64: val, cnt
      agg min/max   → val, cnt
      aggf count(*)/count → 1 int row
      aggf sum/avg  → x32: P_hi@hi, P_lo@hi, P_hi@lo-1, P_lo@lo-1, cnt
                      | x64: P@hi, P@lo-1, cnt   (segment-reset prefixes)
      val fns       → val (arg dtype), ok flag
    """
    cache_key = (specs, n_part_keys, n_order_keys, n_args, mode,
                 jax.default_backend())
    fn = _WINDOW_KERNEL_CACHE.get(cache_key)
    if fn is not None:
        return fn

    fdt = jnp.float64 if mode == "x64" else jnp.float32
    idt = jnp.int64 if mode == "x64" else jnp.int32

    def kernel(part_keys, order_keys, args):
        n = part_keys[0].shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        all_keys = tuple(part_keys) + tuple(order_keys)
        packed = K.packed_multikey_sort(all_keys, iota)
        if packed is not None:
            # pairwise-u64-packed operands: ~half the bytes per bitonic
            # pass (the r05 chip capture's window sort never returned;
            # see kernels.packed_multikey_sort)
            perm, s_all = packed
            s_part = s_all[: len(part_keys)]
        else:
            sorted_ = jax.lax.sort(
                all_keys + (iota,), num_keys=len(all_keys)
            )
            perm = sorted_[-1]
            s_part = sorted_[: len(part_keys)]
            s_all = sorted_[:-1]
        # inverse permutation as a SORT (gather-friendly), not a scatter
        _, inv = jax.lax.sort_key_val(perm, iota)

        idx = jnp.arange(n, dtype=jnp.int32)
        seg_flag = _change_flag(list(s_part))
        peer_flag = _change_flag(list(s_all))
        seg_first = _seg_first(seg_flag, idx)
        peer_last = _seg_last(peer_flag, n)

        s_args = []
        for a in args:
            v, m_ = a
            if isinstance(v, tuple):  # pair slot: (hi, lo) f32 arrays
                s_args.append(((v[0][perm], v[1][perm]), m_[perm]))
            else:
                s_args.append((v[perm], m_[perm]))

        rows: list = []  # (array, is_int) in sorted order pre-inverse

        def emit(arr, is_int):
            rows.append((arr, is_int))

        # lazily-computed shared quantities
        shared: dict = {}

        def get(name):
            if name in shared:
                return shared[name]
            if name == "seg_last":
                v = _seg_last(seg_flag, n)
            elif name == "peer_first":
                v = _seg_first(peer_flag, idx)
            elif name == "peers_cum":
                v = jnp.cumsum(peer_flag.astype(jnp.int32))
            else:
                raise KeyError(name)
            shared[name] = v
            return v

        for spec in specs:
            kind = spec[0]
            if kind == "row_number":
                emit(idx - seg_first + 1, True)
                continue
            if kind == "rank":
                emit(get("peer_first") - seg_first + 1, True)
                continue
            if kind == "dense_rank":
                pc_ = get("peers_cum")
                emit(pc_ - pc_[seg_first] + 1, True)
                continue
            if kind == "ntile":
                k = spec[1]
                seg_last = get("seg_last")
                sizes = seg_last - seg_first + 1
                pos = idx - seg_first
                q, r = sizes // k, sizes % k
                big = r * (q + 1)
                in_big = pos < big
                bucket_big = pos // (q + 1) + 1
                bucket_small = r + (pos - big) // jnp.maximum(q, 1) + 1
                emit(jnp.where(in_big, bucket_big, bucket_small), True)
                continue
            if kind == "agg":
                _, fn_name, slot, is_pair = spec
                if fn_name == "count" and slot is None:
                    # count(*): rows from segment start through last peer
                    cnt = idx - seg_first + 1
                    emit(cnt[peer_last], True)
                    continue
                val, avalid = s_args[slot]
                m = avalid
                cnt_run = _seg_scan(
                    seg_flag, [m.astype(jnp.int32)], ["sum"]
                )[0]
                if fn_name == "count":
                    emit(cnt_run[peer_last], True)
                    continue
                if fn_name in ("sum", "avg"):
                    if mode == "x32":
                        if is_pair:
                            h = jnp.where(m, val[0], 0.0)
                            l = jnp.where(m, val[1], 0.0)
                        else:
                            h = jnp.where(m, val.astype(jnp.float32), 0.0)
                            l = jnp.zeros_like(h)
                        (hi, lo), = _seg_scan(
                            seg_flag, [(h, l)], ["df32"]
                        )
                        emit(hi[peer_last], False)
                        emit(lo[peer_last], False)
                    else:
                        v = jnp.where(m, val.astype(fdt), 0.0)
                        s, = _seg_scan(seg_flag, [v], ["sum"])
                        emit(s[peer_last], False)
                    emit(cnt_run[peer_last], True)
                    continue
                # min / max (numeric; identity = +/- inf in float domain,
                # int idents for exact-int operands)
                if jnp.issubdtype(val.dtype, jnp.integer):
                    info = jnp.iinfo(idt)
                    ident = info.max if fn_name == "min" else info.min
                    v = jnp.where(m, val.astype(idt), ident)
                    is_int = True
                else:
                    ident = jnp.inf if fn_name == "min" else -jnp.inf
                    v = jnp.where(m, val.astype(fdt), ident)
                    is_int = False
                s, = _seg_scan(seg_flag, [v], [fn_name])
                emit(s[peer_last], is_int)
                emit(cnt_run[peer_last], True)
                continue
            if kind == "aggf":
                _, fn_name, slot, fstart, fend, is_pair = spec
                seg_last = get("seg_last")
                lo = (
                    seg_first
                    if fstart is None
                    else jnp.maximum(seg_first, idx + fstart)
                )
                hi = (
                    seg_last
                    if fend is None
                    else jnp.minimum(seg_last, idx + fend)
                )
                empty = hi < lo
                if slot is None:  # count(*)
                    emit(jnp.where(empty, 0, hi - lo + 1), True)
                    continue
                val, avalid = s_args[slot]
                # SEGMENT-RESET prefixes: a global prefix would make the
                # P[hi]-P[lo-1] cancellation scale with the whole-batch
                # magnitude (measured 1e-3 relative on mixed-magnitude
                # partitions); resetting at seg_flag keeps it at frame
                # scale.  lo == seg_first reads 0, not a neighbor's tail.
                hi_g = jnp.clip(hi, 0, n - 1)
                lom1_g = jnp.clip(lo - 1, 0, n - 1)
                lo_open = lo > seg_first  # P[lo-1] is inside the segment
                cp, = _seg_scan(
                    seg_flag, [avalid.astype(jnp.int32)], ["sum"]
                )
                cnt = jnp.where(
                    empty,
                    0,
                    cp[hi_g] - jnp.where(lo_open, cp[lom1_g], 0),
                )
                if fn_name == "count":
                    emit(cnt, True)
                    continue
                if fn_name in ("min", "max"):
                    if jnp.issubdtype(val.dtype, jnp.integer):
                        info = jnp.iinfo(idt)
                        ident = info.max if fn_name == "min" else info.min
                        vv = jnp.where(avalid, val.astype(idt), ident)
                        out_int = True
                    else:
                        ident = jnp.inf if fn_name == "min" else -jnp.inf
                        vv = jnp.where(avalid, val.astype(fdt), ident)
                        out_int = False
                    # finite frames bound the sparse table's depth
                    max_len = (
                        fend - fstart + 1
                        if fstart is not None and fend is not None
                        else n
                    )
                    res = _range_extremum(
                        vv, lo, hi, fn_name, ident, n, max_len
                    )
                    emit(jnp.where(empty, ident, res), out_int)
                    emit(cnt, True)
                    continue
                if mode == "x32":
                    if is_pair:
                        vh = jnp.where(avalid, val[0], 0.0)
                        vl = jnp.where(avalid, val[1], 0.0)
                    else:
                        vh = jnp.where(avalid, val.astype(fdt), 0.0)
                        vl = jnp.zeros_like(vh)
                    (ph, pl), = _seg_scan(
                        seg_flag, [(vh, vl)], ["df32"]
                    )
                    emit(ph[hi_g], False)
                    emit(pl[hi_g], False)
                    emit(
                        jnp.where(lo_open, ph[lom1_g], 0.0), False
                    )
                    emit(
                        jnp.where(lo_open, pl[lom1_g], 0.0), False
                    )
                else:
                    vm = jnp.where(avalid, val.astype(fdt), 0.0)
                    p, = _seg_scan(seg_flag, [vm], ["sum"])
                    emit(p[hi_g], False)
                    emit(jnp.where(lo_open, p[lom1_g], 0.0), False)
                emit(cnt, True)
                continue
            if kind == "val":
                _, fn_name, slot, offset = spec
                val, avalid = s_args[slot]
                seg_last = get("seg_last")
                if fn_name == "first_value":
                    src = seg_first
                    ok = jnp.ones(n, jnp.bool_)
                elif fn_name == "last_value":
                    src = peer_last
                    ok = jnp.ones(n, jnp.bool_)
                elif fn_name == "lag":
                    src = idx - offset
                    ok = jnp.logical_and(src >= seg_first, src <= seg_last)
                else:  # lead
                    src = idx + offset
                    ok = jnp.logical_and(src <= seg_last, src >= seg_first)
                src = jnp.clip(src, 0, n - 1)
                emit(val[src], jnp.issubdtype(val.dtype, jnp.integer))
                emit(
                    jnp.logical_and(ok, avalid[src]).astype(jnp.int32),
                    True,
                )
                continue
            raise AssertionError(f"window spec {spec}")

        packed_rows = []
        for arr, is_int in rows:
            a = arr[inv]  # back to INPUT row order
            if is_int:
                packed_rows.append(a.astype(idt))
            else:
                packed_rows.append(
                    jax.lax.bitcast_convert_type(a.astype(fdt), idt)
                )
        return jnp.stack(packed_rows, axis=0)

    fn = jax.jit(kernel)
    _WINDOW_KERNEL_CACHE[cache_key] = fn
    return fn
