"""JAX lowering of physical expressions + fused segment-aggregate kernels.

This is the TPU replacement for the reference's per-stage DataFusion
operator pipeline (the hot loop at ``shuffle_writer.rs:214-256`` /
``executor.rs:97-134``): instead of streaming 8K-row batches through
interpreted operators, the eligible stage subtree (filter → project →
partial aggregate) compiles ONCE to a fused XLA kernel and each large
batch is a single device invocation.

TPU-first design rules (see /opt/skills/guides/pallas_guide.md):
* static shapes only — rows are padded to power-of-two buckets, filters are
  boolean masks (multiply, never compact);
* group-by is ``segment_sum`` over host-assigned dense group ids with a
  fixed segment capacity — no device-side hash table, no dynamic growth;
* nulls ride as separate validity masks and fold into the row mask;
* strings never reach the device — host dictionary codes stand in.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..errors import ExecutionError
from ..exec import expressions as pe
from .bridge import arrow_to_numpy

# A lowered node evaluates to (value, validity-or-None) in a leaf env.
JaxClosure = Callable[[dict], tuple[jnp.ndarray, Optional[jnp.ndarray]]]


class NotLowerable(Exception):
    """Subtree cannot run on device (string compute, unsupported fn)."""


@dataclass
class LeafSpec:
    """One host-supplied input array of the fused kernel.

    Kinds: "column" (value + validity), "cpu_expr" (host-evaluated value +
    validity), "column_validity" (validity ONLY — count(col) never needs
    the values, so wide i64 key columns don't cross the bridge at all),
    "column_pair" (i64 as an exact f32 (hi, lo) pair in x32 mode — hi/lo
    and validity; 48-bit exact, so big-key sums survive the i32-less
    device), "column_ord_pair" (f64 as an ORDER-preserving (hi, lo) i32
    pair — lexicographic comparisons equal f64 comparisons, so x32
    min/max over f64 columns is bit-EXACT, the q2 decorrelated-equality
    requirement).
    """

    name: str
    kind: str  # "column" | "cpu_expr" | "column_validity" | "column_pair"
    col_index: int = -1
    cpu_expr: Optional[pe.PhysicalExpr] = None


@dataclass
class CompiledExpr:
    closure: JaxClosure
    leaves: dict[str, LeafSpec] = field(default_factory=dict)


# ------------------------------------------------------------- precision
# TPU v5e has no native f64/i64 ALUs (VERDICT.md round-1 weakness #4): the
# device dtype policy is a MODE, not a constant.
#   "x64" — f64/i64 kernels (CPU platform: exact, matches pyarrow oracles)
#   "x32" — f32/i32 kernels (TPU platform: native dtypes; sums recover
#           ~48-bit effective precision via the double-float compensated
#           segment sum below, so TPC-H aggregates still match oracles
#           at 1e-6)
_PRECISION: dict = {"mode": None}


def set_precision(mode: Optional[str]) -> None:
    """Force the kernel dtype mode ("x64" | "x32") or None to re-resolve."""
    if mode not in (None, "x64", "x32"):
        raise ValueError(f"precision mode {mode!r}")
    _PRECISION["mode"] = mode


def precision_mode() -> str:
    """Resolve the dtype mode, defaulting by platform (CPU→x64, else x32)."""
    if _PRECISION["mode"] is None:
        import jax

        _PRECISION["mode"] = (
            "x64" if jax.default_backend() == "cpu" else "x32"
        )
    return _PRECISION["mode"]


def value_dtype():
    return jnp.float64 if precision_mode() == "x64" else jnp.float32


def index_dtype():
    return jnp.int64 if precision_mode() == "x64" else jnp.int32


def _F():
    return value_dtype()


def _I():
    return index_dtype()


def _pa_to_jnp_dtype(t: pa.DataType):
    if pa.types.is_floating(t) or pa.types.is_decimal(t):
        return _F()
    if pa.types.is_boolean(t):
        return jnp.bool_
    return _I()


class JaxExprCompiler:
    """Lower PhysicalExpr trees to jax closures over a shared leaf env.

    Any subtree that cannot lower (LIKE, string functions, …) but whose
    OUTPUT is device-friendly becomes a ``cpu_expr`` leaf: the engine
    evaluates it with pyarrow per batch and ships the resulting
    numeric/bool array to the device alongside the raw columns.
    """

    def __init__(self, schema: pa.Schema):
        self.schema = schema
        self.leaves: dict[str, LeafSpec] = {}

    def compile(self, expr: pe.PhysicalExpr) -> CompiledExpr:
        closure = self._lower_or_leaf(expr)
        return CompiledExpr(closure, self.leaves)

    # ------------------------------------------------------------ helpers
    def _leaf_column(self, e: pe.Col) -> JaxClosure:
        t = self.schema.field(e.index).type
        # keep in sync with bridge._is_device_friendly — anything accepted
        # here must actually cross the bridge at runtime
        if not (
            pa.types.is_integer(t)
            or pa.types.is_floating(t)
            or pa.types.is_boolean(t)
            or pa.types.is_date(t)
            or pa.types.is_timestamp(t)
        ):
            raise NotLowerable(f"column {e.colname}: type {t}")
        if precision_mode() == "x32" and (
            pa.types.is_timestamp(t) or pa.types.is_date64(t)
        ):
            # ns/ms epoch values overflow i32; keep these on the CPU path
            raise NotLowerable(f"column {e.colname}: {t} needs i64 (x32 mode)")
        name = f"col_{e.index}"
        self.leaves[name] = LeafSpec(name, "column", col_index=e.index)
        vname = f"{name}__valid"

        def run(env: dict):
            return env[name], env[vname]

        return run

    def validity_only(self, e: pe.Col) -> JaxClosure:
        """Leaf that ships ONLY the validity mask of a column (count(col):
        the values are never read, so i32-unrepresentable columns still
        count on device)."""
        name = f"col_{e.index}__validonly"
        self.leaves[name] = LeafSpec(name, "column_validity", col_index=e.index)
        vname = f"{name}__valid"

        def run(env: dict):
            return None, env[vname]

        return run

    def pair_column(self, e: pe.Col) -> JaxClosure:
        """i64 column as an exact f32 (hi, lo) pair (x32 mode): the value
        half of the closure result is a (hi, lo) TUPLE consumed only by
        pair-aware aggregate kernels (KernelAggSpec.pair)."""
        name = f"col_{e.index}__pair"
        self.leaves[name] = LeafSpec(name, "column_pair", col_index=e.index)
        vname = f"{name}__valid"

        def run(env: dict):
            return (env[f"{name}__hi"], env[f"{name}__lo"]), env[vname]

        return run

    def ord_pair_column(self, e: pe.Col) -> JaxClosure:
        """f64 column as an order-preserving (hi, lo) i32 pair (x32
        mode): consumed only by ord_pair min/max kernels, where
        lexicographic integer comparison IS f64 comparison."""
        name = f"col_{e.index}__ordpair"
        self.leaves[name] = LeafSpec(
            name, "column_ord_pair", col_index=e.index
        )
        vname = f"{name}__valid"

        def run(env: dict):
            return (env[f"{name}__ohi"], env[f"{name}__olo"]), env[vname]

        return run

    def _cpu_leaf(self, e: pe.PhysicalExpr) -> JaxClosure:
        out_t = _infer_pa_type(e, self.schema)
        if not (
            pa.types.is_boolean(out_t)
            or pa.types.is_integer(out_t)
            or pa.types.is_floating(out_t)
            or pa.types.is_date(out_t)
        ):
            raise NotLowerable(f"cpu-leaf output type {out_t} for {e}")
        name = f"cpu_{len(self.leaves)}"
        self.leaves[name] = LeafSpec(name, "cpu_expr", cpu_expr=e)
        vname = f"{name}__valid"

        def run(env: dict):
            return env[name], env[vname]

        return run

    def _lower_or_leaf(self, e: pe.PhysicalExpr) -> JaxClosure:
        try:
            return self._lower(e)
        except NotLowerable:
            return self._cpu_leaf(e)

    # ------------------------------------------------------------ lowering
    def _lower(self, e: pe.PhysicalExpr) -> JaxClosure:
        if isinstance(e, pe.Col):
            return self._leaf_column(e)

        if isinstance(e, pe.Lit):
            v = e.value
            if v is None:
                raise NotLowerable("null literal")
            if isinstance(v, bool):
                const = jnp.asarray(v)
            elif isinstance(v, int):
                if precision_mode() == "x32" and not (
                    -(2**31) <= v < 2**31
                ):
                    raise NotLowerable(f"int literal {v} exceeds i32")
                const = jnp.asarray(v, _I())
            elif isinstance(v, float):
                const = jnp.asarray(v, _F())
            else:
                import datetime

                if isinstance(v, datetime.date):
                    const = jnp.asarray(
                        (v - datetime.date(1970, 1, 1)).days, _I()
                    )
                else:
                    raise NotLowerable(f"literal {v!r}")
            return lambda env: (const, None)

        if isinstance(e, pe.Binary):
            op = e.op
            if op in ("AND", "OR"):
                lf, rf = self._lower_or_leaf(e.left), self._lower_or_leaf(e.right)

                def run_bool(env, lf=lf, rf=rf, op=op):
                    lv, lval = lf(env)
                    rv, rval = rf(env)
                    # Kleene: null treated as False for filter masks, which
                    # matches WHERE semantics (null predicate drops the row)
                    lv = lv if lval is None else jnp.logical_and(lv, lval)
                    rv = rv if rval is None else jnp.logical_and(rv, rval)
                    if op == "AND":
                        return jnp.logical_and(lv, rv), None
                    return jnp.logical_or(lv, rv), None

                return run_bool
            lf, rf = self._lower(e.left), self._lower(e.right)
            fns = {
                "=": jnp.equal, "<>": jnp.not_equal, "<": jnp.less,
                "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal,
                "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
            }
            if op in fns:
                f = fns[op]

                def run_bin(env, lf=lf, rf=rf, f=f):
                    lv, lval = lf(env)
                    rv, rval = rf(env)
                    lv, rv = _numeric_align(lv, rv)
                    return f(lv, rv), _merge_valid(lval, rval)

                return run_bin
            if op == "/":

                def run_div(env, lf=lf, rf=rf):
                    lv, lval = lf(env)
                    rv, rval = rf(env)
                    if (
                        jnp.issubdtype(lv.dtype, jnp.integer)
                        and jnp.issubdtype(rv.dtype, jnp.integer)
                    ):
                        # SQL / Arrow integer division truncates toward zero
                        # (pc.divide on ints); lax.div matches, floor_divide
                        # and float division do not
                        import jax.lax as lax

                        rv_safe = jnp.where(rv == 0, 1, rv)
                        return lax.div(lv, rv_safe), _merge_valid(lval, rval)
                    return (
                        lv.astype(_F()) / rv.astype(_F()),
                        _merge_valid(lval, rval),
                    )

                return run_div
            if op == "%":

                def run_mod(env, lf=lf, rf=rf):
                    lv, lval = lf(env)
                    rv, rval = rf(env)
                    return jnp.mod(lv, rv), _merge_valid(lval, rval)

                return run_mod
            raise NotLowerable(f"binary op {op}")

        if isinstance(e, pe.Not):
            f = self._lower_or_leaf(e.expr)

            def run_not(env, f=f):
                v, val = f(env)
                v = v if val is None else jnp.logical_and(v, val)
                return jnp.logical_not(v), None

            return run_not

        if isinstance(e, pe.Negative):
            f = self._lower(e.expr)

            def run_neg(env, f=f):
                v, val = f(env)
                return -v, val

            return run_neg

        if isinstance(e, pe.IsNull):
            f = self._lower_or_leaf(e.expr)
            negated = e.negated

            def run_isnull(env, f=f, negated=negated):
                _, val = f(env)
                if val is None:
                    out = jnp.zeros((), jnp.bool_)
                    return (jnp.logical_not(out) if negated else out), None
                return (val if negated else jnp.logical_not(val)), None

            return run_isnull

        if isinstance(e, pe.InList):
            f = self._lower(e.expr)
            items = e.items
            if not all(isinstance(i, (int, float)) or _is_date(i) for i in items):
                raise NotLowerable("IN list with non-numeric items")
            # integer membership must compare in int64: casting an int64 id
            # to f64 loses precision above 2^53 and admits adjacent values
            all_int = all(
                isinstance(i, int) and not isinstance(i, bool) for i in items
            )
            if (
                all_int
                and precision_mode() == "x32"
                and any(not (-(2**31) <= i < 2**31) for i in items)
            ):
                raise NotLowerable("IN list item exceeds i32")
            consts = (
                jnp.asarray(list(items), _I())
                if all_int
                else jnp.asarray([_to_num(i) for i in items], _F())
            )
            negated = e.negated

            def run_in(env, f=f, consts=consts, negated=negated, all_int=all_int):
                v, val = f(env)
                if all_int and jnp.issubdtype(v.dtype, jnp.integer):
                    lhs = v.astype(_I())
                    rhs = consts
                else:
                    lhs = v.astype(_F())
                    rhs = consts.astype(_F())
                m = jnp.any(jnp.equal(lhs[:, None], rhs[None, :]), axis=1)
                if negated:
                    m = jnp.logical_not(m)
                return m, val

            return run_in

        if isinstance(e, pe.Case):
            whens = [
                (self._lower_or_leaf(w), self._lower(t)) for w, t in e.whens
            ]
            else_f = self._lower(e.else_expr) if e.else_expr is not None else None
            out_dtype = _pa_to_jnp_dtype(e.out_type)

            def run_case(env, whens=whens, else_f=else_f, out_dtype=out_dtype):
                # per-row branch selection: both the value AND the validity
                # follow the selected branch (SQL CASE); a no-ELSE CASE is
                # NULL on rows no WHEN matches
                if else_f is not None:
                    acc, ev = else_f(env)
                    acc = acc.astype(out_dtype)
                    acc_val = jnp.asarray(True) if ev is None else ev
                else:
                    acc = jnp.zeros((), out_dtype)
                    acc_val = jnp.asarray(False)
                for wf, tf in reversed(whens):
                    c, cval = wf(env)
                    c = c if cval is None else jnp.logical_and(c, cval)
                    t, tval = tf(env)
                    acc = jnp.where(c, t.astype(out_dtype), acc)
                    tv = jnp.asarray(True) if tval is None else tval
                    acc_val = jnp.where(c, tv, acc_val)
                return acc, acc_val

            return run_case

        if isinstance(e, pe.Cast):
            f = self._lower(e.expr)
            dt = _pa_to_jnp_dtype(e.to_type)

            def run_cast(env, f=f, dt=dt):
                v, val = f(env)
                return v.astype(dt), val

            return run_cast

        if isinstance(e, pe.ScalarFn):
            mapping = {
                "abs": jnp.abs, "sqrt": jnp.sqrt, "exp": jnp.exp, "ln": jnp.log,
                "log10": lambda x: jnp.log10(x), "log2": jnp.log2,
                "ceil": jnp.ceil, "floor": jnp.floor, "sin": jnp.sin,
                "cos": jnp.cos, "tan": jnp.tan, "signum": jnp.sign,
            }
            if e.fname in mapping and len(e.args) == 1:
                f = self._lower(e.args[0])
                fn = mapping[e.fname]

                def run_fn(env, f=f, fn=fn):
                    v, val = f(env)
                    return fn(v.astype(_F())), val

                return run_fn
            if e.fname == "power" and len(e.args) == 2:
                a = self._lower(e.args[0])
                b = self._lower(e.args[1])

                def run_pow(env, a=a, b=b):
                    av, aval = a(env)
                    bv, bval = b(env)
                    return jnp.power(av.astype(_F()), bv.astype(_F())), _merge_valid(aval, bval)

                return run_pow
            if e.fname == "round":
                f = self._lower(e.args[0])

                def run_round(env, f=f):
                    v, val = f(env)
                    return jnp.round(v.astype(_F())), val

                return run_round
            raise NotLowerable(f"scalar fn {e.fname}")

        raise NotLowerable(f"node {type(e).__name__}")


def _merge_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return jnp.logical_and(a, b)


def _numeric_align(lv, rv):
    if lv.dtype == jnp.bool_ or rv.dtype == jnp.bool_:
        return lv, rv
    if jnp.issubdtype(lv.dtype, jnp.floating) or jnp.issubdtype(
        rv.dtype, jnp.floating
    ):
        return lv.astype(_F()), rv.astype(_F())
    return lv.astype(_I()), rv.astype(_I())


def _is_date(v) -> bool:
    import datetime

    return isinstance(v, datetime.date)


def _to_num(v):
    import datetime

    if isinstance(v, datetime.date):
        return float((v - datetime.date(1970, 1, 1)).days)
    return float(v)


def _infer_pa_type(e: pe.PhysicalExpr, schema: pa.Schema) -> pa.DataType:
    empty = pa.RecordBatch.from_arrays(
        [pa.nulls(0, f.type) for f in schema], schema=schema
    )
    v = e.evaluate(empty)
    return v.type


# ---------------------------------------------------------------- env build
def build_env(
    batch: pa.RecordBatch, leaves: dict[str, LeafSpec], n_padded: int,
    trivial_valid: Optional[set] = None,
) -> dict[str, np.ndarray]:
    """Evaluate/extract all leaf arrays for one batch, padded to n_padded.

    Every leaf ALWAYS ships a validity companion (all-true when the batch
    has no nulls) so the fused kernel's positional signature is identical
    across batches — nulls appearing mid-stream must not trigger an XLA
    recompile.  Names of companions that are trivially the row tail mask
    (all-true over live rows, False over padding) are added to
    ``trivial_valid`` when given: the executor substitutes ONE shared
    device-built iota mask for them instead of shipping n_padded host
    bytes per leaf over the tunnel.
    """
    import pyarrow.compute as pc

    env: dict[str, np.ndarray] = {}
    for name, spec in leaves.items():
        if spec.kind == "join_col":
            continue  # gathered on device by the join wrapper
        if spec.kind == "cpu_expr":
            arr = spec.cpu_expr.evaluate(batch)
            if isinstance(arr, pa.Scalar):
                arr = pa.array([arr.as_py()] * batch.num_rows, arr.type)
        else:
            arr = batch.column(spec.col_index)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if spec.kind == "column_validity":
            # count(col): ONLY the validity mask crosses — the values are
            # never read, so any column type (strings, decimals, wide
            # i64) counts on device
            if arr.null_count:
                validity = np.asarray(pc.is_valid(arr))
            else:
                validity = np.ones(len(arr), dtype=bool)
                if trivial_valid is not None:
                    trivial_valid.add(f"{name}__valid")
            env[f"{name}__valid"] = _pad(validity, n_padded)
            continue
        values, validity = arrow_to_numpy(arr)
        if validity is None:
            validity = np.ones(len(values), dtype=bool)
            if trivial_valid is not None:
                trivial_valid.add(f"{name}__valid")
        env[f"{name}__valid"] = _pad(validity, n_padded)
        if spec.kind == "column_pair":
            v = values.astype(np.float64)
            if (
                values.dtype.kind in "iu"
                and len(v)
                and np.abs(v).max() >= float(1 << 48)
            ):
                # integer pairs must be EXACT: beyond 48 bits the split
                # loses low bits.  Float pairs are exact at any magnitude
                # (hi carries the exponent) up to f32 range.
                raise ExecutionError(
                    "int64 column exceeds 48-bit pair range in x32 mode"
                )
            if (
                values.dtype.kind == "f"
                and len(v)
                and np.abs(v).max() >= 3e38
            ):
                raise ExecutionError("f64 column exceeds f32 range")
            hi = v.astype(np.float32)
            env[f"{name}__hi"] = _pad(hi, n_padded)
            env[f"{name}__lo"] = _pad(
                (v - hi.astype(np.float64)).astype(np.float32), n_padded
            )
            continue
        if spec.kind == "column_ord_pair":
            from .bridge import split_u64_i32, to_u64_order

            # always encode the f64 VALUE (ints cast exactly below 2^53):
            # consumers decode through order_decode_f64
            ohi, olo = split_u64_i32(to_u64_order(values.astype(np.float64)))
            env[f"{name}__ohi"] = _pad(ohi, n_padded)
            env[f"{name}__olo"] = _pad(olo, n_padded)
            continue
        env[name] = _pad(coerce_host_values(values), n_padded)
    return env


def coerce_host_values(values: np.ndarray) -> np.ndarray:
    """Narrow host arrays to the device dtype mode before transfer.

    x32 mode ships f32/i32 (native TPU dtypes, half the host→HBM bytes).
    64-bit integers that cannot narrow losslessly raise ExecutionError,
    which the stage executor turns into a CPU fallback for the partition.
    """
    if precision_mode() != "x32":
        return values
    if values.dtype == np.float64:
        return values.astype(np.float32)
    if values.dtype in (np.dtype(np.int64), np.dtype(np.uint64)):
        if len(values) and (
            values.max(initial=0) > np.iinfo(np.int32).max
            or values.min(initial=0) < np.iinfo(np.int32).min
        ):
            raise ExecutionError("int64 column exceeds i32 range in x32 mode")
        return values.astype(np.int32)
    return values


def flat_arg_names(leaves: dict[str, LeafSpec]) -> list[str]:
    """Positional arg order of the fused kernel, per leaf kind."""
    out = []
    for n, spec in leaves.items():
        if spec.kind == "column_validity":
            out.append(f"{n}__valid")
        elif spec.kind == "column_pair":
            out.extend([f"{n}__hi", f"{n}__lo", f"{n}__valid"])
        elif spec.kind == "column_ord_pair":
            out.extend([f"{n}__ohi", f"{n}__olo", f"{n}__valid"])
        else:
            out.extend([n, f"{n}__valid"])
    return out


def make_join_kernel(
    inner_fn, flat_names: list[str], join_slots: dict[str, int],
    n_build: int, dense: bool = False,
):
    """Wrap a fused aggregate kernel with an on-device PK-FK probe join.

    ``join_slots`` maps flat arg NAMES that come from the build side to
    their index in the build-column arrays.  The wrapped signature is::

        fn(seg, valid, *probe_args, pkey, pkey_valid,
           bkeys, *bvals, *bvalids)               # sorted-probe form
        fn(seg, valid, *probe_args, pkey, pkey_valid,
           table, kmin, *bvals, *bvalids)         # dense form

    where ``probe_args`` are the per-batch arrays for NON-join flat names
    (in order) and ``pkey`` is this batch's probe join key.  Sorted form:
    build arrays are [m]-sized, SORTED by key (unique keys), probed by
    searchsorted + gather.  Dense form (key span fits the slot cap):
    ``table`` is a [span] array holding row_index+1 at slot key-kmin
    (0 = no such key), probed with ONE gather — searchsorted's log2(m)
    sequential gather passes dominated device time on the chip
    (BENCH_SUITE_r05 starjoin row).  Either way non-matching probe rows
    fold into the global row mask (inner join), so shapes stay static
    and the joined relation is never materialized.
    """
    n_probe = sum(1 for n in flat_names if n not in join_slots)

    def fn(seg_ids, valid, *args):
        probe_args = args[:n_probe]
        if dense:
            pkey, pkey_valid, tbl, kmin = args[n_probe:n_probe + 4]
            bvals = args[n_probe + 4:n_probe + 4 + n_build]
            bvalids = args[n_probe + 4 + n_build:]
            span = tbl.shape[0]
            # i64 probe arithmetic: i32 pkey - i32 kmin can overflow
            rel = pkey.astype(jnp.int64) - kmin.astype(jnp.int64)
            inb = jnp.logical_and(rel >= 0, rel < span)
            slot = tbl[jnp.clip(rel, 0, span - 1).astype(jnp.int32)]
            match = jnp.logical_and(
                jnp.logical_and(inb, slot > 0), pkey_valid
            )
            idx = jnp.maximum(slot - 1, 0).astype(jnp.int32)
        else:
            pkey, pkey_valid, bkeys = args[n_probe:n_probe + 3]
            bvals = args[n_probe + 3:n_probe + 3 + n_build]
            bvalids = args[n_probe + 3 + n_build:]
            m = bkeys.shape[0]
            idx = jnp.clip(
                jnp.searchsorted(bkeys, pkey), 0, max(m - 1, 0)
            ).astype(jnp.int32)
            match = jnp.logical_and(bkeys[idx] == pkey, pkey_valid)
        full = []
        it = iter(probe_args)
        for name in flat_names:
            j = join_slots.get(name)
            if j is None:
                full.append(next(it))
            elif name.endswith("__valid"):
                full.append(jnp.logical_and(bvalids[j][idx], match))
            else:
                full.append(bvals[j][idx])
        return inner_fn(seg_ids, jnp.logical_and(valid, match), *full)

    return fn


def _pad(x: np.ndarray, n: int) -> np.ndarray:
    if len(x) == n:
        return x
    out = np.zeros(n, dtype=x.dtype)
    out[: len(x)] = x
    return out


def bucket_rows(n: int, floor: int = 1024) -> int:
    """Power-of-two bucketing caps distinct XLA shapes at ~log2(max rows)."""
    return max(floor, 1 << math.ceil(math.log2(max(n, 1))))


# ------------------------------------------------------------- fused kernel
@dataclass(frozen=True)
class KernelAggSpec:
    func: str  # sum | count | avg | min | max | count_star
    has_arg: bool
    # x32 only: the arg closure yields an exact f32 (hi, lo) pair for an
    # i64 column; the kernel sums both halves and recombines error-free
    pair: bool = False
    # min/max over integer/date args stay in INTEGER dtype end-to-end —
    # casting to f32 rounds above 2^24, and a min/max that comes back
    # sub-ulp wrong breaks decorrelated equality predicates (q2)
    int_minmax: bool = False
    # x32 only: min/max over an f64 COLUMN rides an order-preserving
    # (hi, lo) i32 pair — lexicographic integer min/max IS f64 min/max,
    # so the extremum is bit-exact without f64 device dtypes
    ord_pair: bool = False


def state_fields(spec: KernelAggSpec, mode: str) -> tuple[str, ...]:
    """Per-aggregate kernel-state layout: field roles in output order.

    Roles drive merging: "add" → +, "min"/"max" → elementwise extremum.
    In x32 mode sums carry a double-float (hi, lo) pair so f32 device math
    retains ~48 effective mantissa bits; host materialization adds the pair
    in f64.
    """
    if spec.func in ("count", "count_star"):
        return ("add",)
    if spec.func in ("sum", "avg"):
        return ("add", "add", "add") if mode == "x32" else ("add", "add")
    if spec.func == "min":
        if spec.ord_pair:
            return ("omin_hi", "omin_lo", "add")
        return ("min", "add")
    if spec.func == "max":
        if spec.ord_pair:
            return ("omax_hi", "omax_lo", "add")
        return ("max", "add")
    raise ExecutionError(f"kernel agg {spec.func}")


def _two_sum(a, b):
    """Knuth 2Sum: s = fl(a+b) plus the EXACT rounding error e (no FMA)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def _two_product_f32(a, b):
    """Dekker two-product: p = fl(a*b) plus the EXACT rounding error e
    (Veltkamp split; no FMA assumed — XLA contracting into FMA only
    makes the error term more accurate)."""
    p = a * b
    c = jnp.asarray(4097.0, jnp.float32)  # 2^12 + 1 splits f32 mantissas
    ac = a * c
    a_hi = ac - (ac - a)
    a_lo = a - a_hi
    bc = b * c
    b_hi = bc - (bc - b)
    b_lo = b - b_hi
    e = ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo
    return p, e


def square_pair_closure(pair_closure: JaxClosure) -> JaxClosure:
    """x² as a double-float pair from a double-float x (variance family,
    x32): x = hi+lo exactly, so x² = hi² + 2·hi·lo + lo² — hi² splits
    error-free via Dekker, the cross/low terms fold into the error word
    (their own rounding sits at ~2^-48 of x²)."""

    def run(env: dict):
        (hi, lo), valid = pair_closure(env)
        p, e = _two_product_f32(hi, hi)
        e = e + jnp.asarray(2.0, jnp.float32) * hi * lo + lo * lo
        return (p, e), valid

    return run


def square_closure(closure: JaxClosure) -> JaxClosure:
    """x² in the value dtype (variance family, x64 mode)."""

    def run(env: dict):
        v, valid = closure(env)
        v = v.astype(_F())
        return v * v, valid

    return run


def _lex_merge(a_hi, a_lo, b_hi, b_lo, is_min: bool):
    """Lexicographic (hi, lo) extremum merge — the order-pair encoding of
    f64 makes this identical to an f64 min/max."""
    if is_min:
        better_b = jnp.logical_or(
            b_hi < a_hi, jnp.logical_and(b_hi == a_hi, b_lo < a_lo)
        )
    else:
        better_b = jnp.logical_or(
            b_hi > a_hi, jnp.logical_and(b_hi == a_hi, b_lo > a_lo)
        )
    return jnp.where(better_b, b_hi, a_hi), jnp.where(better_b, b_lo, a_lo)


# ------------------------------------------------------- algorithm choice
# The segment reduction has two device strategies:
#   "matmul"  — blocked one-hot einsum on the MXU.  TPU scatter serializes
#               (measured: the round-2 q1 kernel spent ~2.4s in blocked
#               scatter-adds); a [block, cap] one-hot matmul with
#               precision=HIGHEST runs the same reduction as dense MXU
#               work.  FLOPs scale with capacity, so it applies while
#               capacity <= _MATMUL_MAX_CAP.
#   "scatter" — jax.ops.segment_sum.  Exact choice on CPU (XLA:CPU lowers
#               scatter to a tight loop) and the fallback for very high
#               cardinality on TPU.
# Tests force a strategy via set_agg_algorithm to exercise the matmul path
# on the CPU-mesh CI host.
_AGG_ALGO: dict = {"force": None}
# matmul FLOP bounds come from the generated routing table
# (ops/routing.py: dev/analyze_grid.py --emit over KERNELBENCH grids;
# builtin defaults 8192 / 2^36 are the pre-table chip-measured values).
# A non-None module value overrides the table (tests).
_MATMUL_MAX_CAP: Optional[int] = None
_MATMUL_MAX_ELEMS: Optional[int] = None


def _matmul_max_cap() -> int:
    if _MATMUL_MAX_CAP is not None:
        return _MATMUL_MAX_CAP
    from . import routing

    return routing.value("matmul_max_cap")


def _matmul_max_elems() -> int:
    if _MATMUL_MAX_ELEMS is not None:
        return _MATMUL_MAX_ELEMS
    from . import routing

    return routing.value("matmul_max_elems")
# Per-block MXU accumulation error grows ~sqrt(block)*eps relative to the
# block sum; 16K-row blocks measured 9e-8 relative error on q1-scale data
# (6M rows), an order inside the 1e-6 oracle tolerance.
_MATMUL_BLOCK = 1 << 14


def set_agg_algorithm(algo: Optional[str]) -> None:
    """Force the device segment-reduction strategy (tests) or None=auto."""
    if algo not in (None, "matmul", "scatter", "sort"):
        raise ValueError(f"agg algorithm {algo!r}")
    _AGG_ALGO["force"] = algo


def segment_algo(capacity: int, n_rows: Optional[int] = None) -> str:
    """Strategy for one kernel trace (n_rows static at trace time).

    TPU: matmul (MXU one-hot einsum) while rows x capacity stays inside
    the FLOP bound, else sort (one sort + segmented scan — scatter would
    cost ~n/45M seconds PER aggregate column).  CPU: scatter (XLA:CPU
    lowers it to a tight loop; sorting only adds work).
    """
    if _AGG_ALGO["force"] is not None:
        return _AGG_ALGO["force"]
    if jax.default_backend() == "cpu":
        return "scatter"
    if capacity > _matmul_max_cap():
        return "sort"
    if n_rows is not None and n_rows * capacity > _matmul_max_elems():
        return "sort"
    return "matmul"


def algo_cache_token() -> tuple:
    """Part of any compiled-kernel cache key: the strategy inputs that are
    NOT visible in the kernel signature (forced algorithm, backend,
    routing-table matmul bounds — tests swap tables mid-process)."""
    return (
        _AGG_ALGO["force"],
        jax.default_backend(),
        _matmul_max_cap(),
        _matmul_max_elems(),
    )


def _blocked_onehot_agg(V, seg_ids, capacity, n_sum_cols):
    """Segment-reduce all aggregate columns in ONE one-hot einsum.

    V: [n, S+C] f32 — S masked value columns then C 0/1 count columns.
    Returns (hi [cap, S], lo [cap, S], counts [cap, C] int).

    Rows reshape into [nb, block] blocks; a single batched einsum
    ``onehot[nb, block, cap] x V[nb, block, S+C] -> partials[nb, cap, S+C]``
    puts the whole reduction on the MXU (precision=HIGHEST keeps f32
    products exact — default bf16 inputs measured 5.5e-6 relative error,
    30x past the oracle tolerance).  Value partials then combine across
    blocks in a pairwise 2Sum tree for a double-float (hi, lo) total;
    count partials are exact integers (block <= 2^22 < 2^24) and sum
    exactly in i32/i64.
    """
    n = V.shape[0]
    block = _MATMUL_BLOCK
    nb = max(1, -(-n // block))
    nb = 1 << (nb - 1).bit_length()  # pow2 block count for the pair tree
    n2 = nb * block
    if n2 != n:
        V = jnp.pad(V, ((0, n2 - n), (0, 0)))
        seg_ids = jnp.pad(seg_ids, (0, n2 - n))
    oh = jax.nn.one_hot(
        seg_ids.reshape(nb, block), capacity, dtype=jnp.float32
    )
    partials = jnp.einsum(
        "abc,abk->ack",
        oh,
        V.reshape(nb, block, V.shape[1]),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [nb, cap, S+C]
    counts = partials[:, :, n_sum_cols:].astype(_I()).sum(axis=0)
    hi = partials[:, :, :n_sum_cols]
    lo = jnp.zeros_like(hi)
    while hi.shape[0] > 1:  # unrolled at trace: static shapes, log depth
        s, e = _two_sum(hi[0::2], hi[1::2])
        hi, lo = s, lo[0::2] + lo[1::2] + e
    return hi[0], lo[0], counts


def _segment_sum_df32(v, seg_ids, capacity, block_cap: int = 4096):
    """Double-float compensated segment sum for f32 device math.

    f32 scatter-add over millions of rows accumulates ~sqrt(n)·eps ≈ 1e-4
    relative error — two orders past the 1e-6 oracle tolerance.  Instead:

    * rows split into 512-row blocks; per-block f32 scatter partials see at
      most 512 sequential adds per segment (≲ sqrt(512)·eps ≈ 1.4e-6 of
      one block's contribution, and per-block errors are independent so
      they shrink by another sqrt(n_blocks) in the total);
    * block partials combine in a pairwise double-float TREE — each level
      a vectorized 2Sum whose error term is captured EXACTLY into the lo
      word — giving a (hi, lo) pair with ~48-bit effective mantissa.

    Everything is vectorized (vmapped scatter + log2(n/block) tree levels);
    there is no O(n) scan, so device utilization stays high.  Rows pad up
    to a power-of-two block count (zeros aggregate into segment 0 with
    weight 0), so any row count works — mesh shards are NOT pow2-bucketed.

    Block sizing: relative error ≈ block·eps/sqrt(n) (per-block scatter
    error, independent across blocks), so block grows with n — keeping the
    [n/block, capacity] partial buffer small — while staying well inside
    the 1e-6 oracle tolerance at every scale.
    """
    n = v.shape[0]
    if jax.default_backend() == "cpu":
        block = int(max(256, min(block_cap, n // 64)))
    elif capacity <= (1 << 16):
        # TPU scatter cost grows with block COUNT (each vmapped block is
        # its own serialized scatter), but compensation quality shrinks as
        # blocks grow: nb <= 64 bounds the vmap cost while worst-case
        # skew (a whole segment inside one 8K block) stays ~5e-6 — this
        # path only runs at capacity > 8192, where typical rows/segment
        # per block are far smaller
        block = int(max(8192, -(-n // 64)))
    else:
        # very high cardinality: the [nb, capacity] partial buffer is the
        # constraint (64 x 2M x 4B = 512MB per column) — nb <= 8 keeps it
        # ~64MB; rows/segment are tiny here, so precision holds
        block = int(max(1 << 16, -(-n // 8)))
    nb = -(-n // block)
    nb = 1 << (nb - 1).bit_length()  # pow2 block count for the pair tree
    n2 = nb * block
    if n2 != n:
        v = jnp.pad(v, (0, n2 - n))
        seg_ids = jnp.pad(seg_ids, (0, n2 - n))
    vb = v.reshape(nb, block)
    sb = seg_ids.reshape(nb, block)
    hi = jax.vmap(
        lambda vv, ss: jax.ops.segment_sum(vv, ss, num_segments=capacity)
    )(vb, sb)
    lo = jnp.zeros_like(hi)
    while hi.shape[0] > 1:  # unrolled at trace: static shapes, log depth
        s, e = _two_sum(hi[0::2], hi[1::2])
        hi, lo = s, lo[0::2] + lo[1::2] + e
    return hi[0], lo[0]


def _sorted_segment_agg(seg_key, capacity: int, kinds: list, cols: list):
    """Sort-based segmented reduction: the TPU-native high-cardinality path.

    TPU scatter serializes (one element per cycle-ish), so at capacity
    beyond the matmul bound the scatter path costs ~rows/45M seconds PER
    COLUMN.  Sorting rows by group id once and running one segmented
    ``lax.associative_scan`` over ALL columns costs one XLA sort plus a
    handful of HBM passes, independent of capacity, amortized across every
    aggregate in the stage — and segment boundaries come from
    ``searchsorted`` (exact row counts, no reduction at all).

    seg_key: [n] i32 group ids with base-mask-failing rows set to
    ``capacity`` (they sort to the end, past every extracted boundary).
    kinds: per logical column, one of
      "df32" — double-float compensated sum; col is an (hi, lo) pair of
               f32 arrays (normalize leaves via ``_two_sum`` first).
               Errors stay RELATIVE TO THE SEGMENT (the scan resets at
               boundaries), unlike global-prefix schemes.
      "f64"  — plain f64 sum (x64 mode)
      "i32"  — exact integer count sum
      ("min", ident) / ("max", ident) — extremum (any dtype; masked rows
               AND empty segments carry the identity, matching the
               scatter path so cross-batch state merges stay correct)
    cols: matching arrays, gathered through the sort permutation here.

    Returns (per-kind segment totals [capacity], presence counts
    [capacity]); empty segments yield 0 for sums/counts and the identity
    for min/max.
    """
    n = seg_key.shape[0]
    if n < (1 << 31):
        # one u64 operand instead of (key, iota): seg_key is
        # non-negative and <= capacity (< 2^22 at the ceiling), so
        # key<<31|iota fits 53 bits and unsigned order == (key, iota)
        # lex order.  Measured (KERNELBENCH sort_operands): the
        # single-operand sort runs ~4.6x faster than the two-operand
        # form at equal rows.
        packed = (
            seg_key.astype(jnp.uint64) << jnp.uint64(31)
        ) | jnp.arange(n, dtype=jnp.uint64)
        (sp,) = jax.lax.sort((packed,), num_keys=1)
        s2 = (sp >> jnp.uint64(31)).astype(jnp.int32)
        perm = (sp & jnp.uint64(0x7FFFFFFF)).astype(jnp.int32)
    else:  # pragma: no cover - >2^31 rows per batch never happens
        s2, perm = jax.lax.sort_key_val(
            seg_key, jnp.arange(n, dtype=jnp.int32)
        )
    outs, presence, _ = _scan_segments(s2, perm, capacity, kinds, cols)
    return outs, presence


def _scan_segments(s2, perm, capacity: int, kinds: list, cols: list):
    """Segmented reduction over PRE-SORTED segment ids.

    ``s2``: [n] non-decreasing segment ids; rows excluded from every
    segment carry a sentinel >= capacity and sit at the end.  ``perm`` is
    the permutation that sorted the original rows into ``s2`` order;
    ``cols`` are in ORIGINAL row order and are gathered through ``perm``
    here.  Shared by :func:`_sorted_segment_agg` (which sorts host gids)
    and the keyed path (which sorts raw key codes and derives gids from
    key-change boundaries on device).  Returns (outs, presence, bounds).
    """
    n = s2.shape[0]
    flag = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), s2[1:] != s2[:-1]]
    )

    elems = [flag]
    slots = []  # per logical col: (kind, ident, slot index or (slot, slot))
    for kind, col in zip(kinds, cols):
        ident = None
        if isinstance(kind, tuple):
            kind, ident = kind
        if kind in ("df32", "omin", "omax"):
            hi, lo = col
            slots.append((kind, ident, (len(elems), len(elems) + 1)))
            elems.append(hi[perm])
            elems.append(lo[perm])
        else:
            slots.append((kind, ident, len(elems)))
            elems.append(col[perm])

    flat_kinds = ["flag"]
    for kind, _, _ in slots:
        if kind == "df32":
            flat_kinds.extend(["df32_hi", "df32_lo"])
        elif kind in ("omin", "omax"):
            flat_kinds.extend([f"{kind}_hi", f"{kind}_lo"])
        else:
            flat_kinds.append(kind)

    def combine(a, b):
        fa, fb = a[0], b[0]
        out = [jnp.logical_or(fa, fb)]
        i = 1
        while i < len(flat_kinds):
            kind = flat_kinds[i]
            if kind == "df32_hi":
                s, e = _two_sum(a[i], b[i])
                hi, lo2 = _two_sum(s, a[i + 1] + b[i + 1] + e)
                out.append(jnp.where(fb, b[i], hi))
                out.append(jnp.where(fb, b[i + 1], lo2))
                i += 2
                continue
            if kind in ("omin_hi", "omax_hi"):
                hi, lo = _lex_merge(
                    a[i], a[i + 1], b[i], b[i + 1], kind == "omin_hi"
                )
                out.append(jnp.where(fb, b[i], hi))
                out.append(jnp.where(fb, b[i + 1], lo))
                i += 2
                continue
            if kind in ("f64", "i32"):
                merged = a[i] + b[i]
            elif kind == "min":
                merged = jnp.minimum(a[i], b[i])
            else:  # max
                merged = jnp.maximum(a[i], b[i])
            out.append(jnp.where(fb, b[i], merged))
            i += 1
        return tuple(out)

    scanned = jax.lax.associative_scan(combine, tuple(elems))

    bounds = jnp.searchsorted(
        s2, jnp.arange(capacity + 1, dtype=jnp.int32), side="left"
    )
    presence = jnp.diff(bounds)
    last = jnp.clip(bounds[1:] - 1, 0, max(n - 1, 0))
    occupied = presence > 0

    outs = []
    for kind, ident, slot in slots:
        if kind == "df32":
            hi = jnp.where(occupied, scanned[slot[0]][last], 0.0)
            lo = jnp.where(occupied, scanned[slot[1]][last], 0.0)
            outs.append((hi, lo))
        elif kind in ("omin", "omax"):
            hi_s = scanned[slot[0]][last]
            lo_s = scanned[slot[1]][last]
            empty = jnp.asarray(ident, hi_s.dtype)
            outs.append(
                (
                    jnp.where(occupied, hi_s, empty),
                    jnp.where(occupied, lo_s, empty),
                )
            )
        else:
            v = scanned[slot][last]
            empty = (
                jnp.zeros((), v.dtype)
                if ident is None
                else jnp.asarray(ident, v.dtype)
            )
            outs.append(jnp.where(occupied, v, empty))
    return outs, presence, bounds


def make_partial_agg_kernel(
    filter_closure: Optional[JaxClosure],
    arg_closures: list[Optional[JaxClosure]],
    specs: list[KernelAggSpec],
    capacity: int,
    flat_names: list[str],
    force_sort: bool = False,
):
    """Build the fused filter→project→segment-aggregate device function.

    Returns ``fn(seg_ids, valid, *leaf_arrays) -> (states..., presence)``
    where every output is a [capacity] array.  Per-agg state layout is
    :func:`state_fields` — x64: sum/avg → (sum, n), x32: (sum_hi, sum_lo,
    n) double-float; min/max → (value, n); count/count_star → (n,).
    ``presence`` counts mask-passing rows per group: groups whose presence
    is 0 are dropped on host (their rows were all filtered out).

    Strategy (:func:`segment_algo`): on TPU at moderate capacity every
    sum/count reduces in ONE blocked one-hot einsum on the MXU (scatter
    serializes on TPU); min/max stay on ``segment_min/max``.  On CPU (and
    very high cardinality) everything stays scatter-based.
    """
    mode = precision_mode()

    def fn(seg_ids, valid, *arrays):
        env = dict(zip(flat_names, arrays))
        mask = valid
        if filter_closure is not None:
            pred, pvalid = filter_closure(env)
            if pvalid is not None:
                pred = jnp.logical_and(pred, pvalid)
            mask = jnp.logical_and(mask, pred)
        maskf = mask

        # strategy is static per trace: jit re-traces per row-count shape,
        # so the rows x capacity bound sees the actual batch size.
        # force_sort (variance family, x32): the scatter/matmul pair sums
        # compensate only across BLOCKS — in-block f32 rounding leaves
        # ~eps32·sqrt(block) relative error, which the Σx²−(Σx)²/n
        # cancellation amplifies by the conditioning number.  The sorted
        # scan 2Sums at EVERY combine (~2^-45 relative), keeping raw
        # moments usable.
        if force_sort and mode == "x32":
            algo = "sort"
        else:
            algo = segment_algo(capacity, int(seg_ids.shape[0]))
        if algo == "matmul" and mode == "x32":
            return _fn_matmul(env, seg_ids, maskf)
        if algo == "sort":
            return _fn_sorted(env, seg_ids, maskf)

        outs = []
        for spec, closure in zip(specs, arg_closures):
            if spec.func == "count_star":
                outs.append(
                    jax.ops.segment_sum(
                        maskf.astype(_I()), seg_ids, num_segments=capacity
                    )
                )
                continue
            val, avalid = closure(env)
            m = maskf if avalid is None else jnp.logical_and(maskf, avalid)
            n = jax.ops.segment_sum(m.astype(_I()), seg_ids, num_segments=capacity)
            if spec.func == "count":
                outs.append(n)
                continue
            if spec.func in ("sum", "avg"):
                if spec.pair:  # x32 i64 pair: sum halves, recombine exactly
                    vhi, vlo = val
                    z = jnp.zeros((), jnp.float32)
                    a_hi, a_lo = _segment_sum_df32(
                        jnp.where(m, vhi, z), seg_ids, capacity
                    )
                    b_hi, b_lo = _segment_sum_df32(
                        jnp.where(m, vlo, z), seg_ids, capacity
                    )
                    s, e = _two_sum(a_hi, b_hi)
                    outs.append(s)
                    outs.append(a_lo + b_lo + e)
                    outs.append(n)
                    continue
                v = jnp.where(m, val.astype(_F()), jnp.zeros((), _F()))
                if mode == "x32":
                    hi, lo = _segment_sum_df32(v, seg_ids, capacity)
                    outs.append(hi)
                    outs.append(lo)
                else:
                    outs.append(
                        jax.ops.segment_sum(v, seg_ids, num_segments=capacity)
                    )
                outs.append(n)
                continue
            if spec.func in ("min", "max") and spec.ord_pair:
                outs.extend(
                    _ord_segment_extremum(spec, val, m, seg_ids, capacity)
                )
                outs.append(n)
                continue
            if spec.func in ("min", "max"):
                v, ident = _minmax_operand(spec, val)
                red = (
                    jax.ops.segment_min
                    if spec.func == "min"
                    else jax.ops.segment_max
                )
                outs.append(
                    red(jnp.where(m, v, ident), seg_ids, num_segments=capacity)
                )
                outs.append(n)
                continue
            raise ExecutionError(f"kernel agg {spec.func}")
        presence = jax.ops.segment_sum(
            maskf.astype(_I()), seg_ids, num_segments=capacity
        )
        return tuple(outs) + (presence,)

    def _fn_sorted(env, seg_ids, maskf):
        """High-cardinality path: one sort, one segmented scan, no scatter.

        Base-mask-failing rows get the sentinel key ``capacity`` and sort
        past every boundary; presence comes free from the boundary counts.
        Per-argument validity folds into the columns (0 / identity), and
        count columns dedupe by validity like the matmul path.
        """
        key = jnp.where(maskf, seg_ids, jnp.asarray(capacity, seg_ids.dtype))
        kinds, cols, plan = _build_scan_plan(
            env, maskf, specs, arg_closures, mode
        )
        totals, presence = _sorted_segment_agg(key, capacity, kinds, cols)
        return tuple(_emit_scan_outs(plan, totals, presence)) + (presence,)

    def _fn_matmul(env, seg_ids, maskf):
        """x32 MXU path: one einsum reduces all sums AND all counts.

        Value columns are masked f32; count columns are 0/1 masks carried
        as f32 (per-block partials are exact integers, combined in i32).
        Count columns dedupe by mask identity — aggregates over the same
        argument validity share one column.
        """
        sum_cols: list = []  # masked f32 value columns
        cnt_cols: list = []  # f32 0/1 mask columns (deduped)
        # dedupe count columns by the VALIDITY tracer: leaf closures return
        # the shared env[...__valid] object, so sum(x)/avg(x)/count(x) over
        # the same column share one mask column (the base-mask sentinel
        # covers count_star and all-valid args)
        cnt_index: dict = {}

        def cnt_col(m, avalid=None):
            key = "base" if avalid is None else id(avalid)
            j = cnt_index.get(key)
            if j is None:
                j = len(cnt_cols)
                cnt_index[key] = j
                cnt_cols.append(m.astype(jnp.float32))
            return j

        plan: list = []  # per spec: ("sumlike"|"count", indices...) emit plan
        minmax: list = []  # (out_slot_builder) computed via segment_min/max
        for spec, closure in zip(specs, arg_closures):
            if spec.func == "count_star":
                plan.append(("count", cnt_col(maskf)))
                continue
            val, avalid = closure(env)
            m = maskf if avalid is None else jnp.logical_and(maskf, avalid)
            nj = cnt_col(m, avalid)
            if spec.func == "count":
                plan.append(("count", nj))
            elif spec.func in ("sum", "avg") and spec.pair:
                vhi, vlo = val
                z = jnp.zeros((), jnp.float32)
                sj1 = len(sum_cols)
                sum_cols.append(jnp.where(m, vhi, z))
                sj2 = len(sum_cols)
                sum_cols.append(jnp.where(m, vlo, z))
                plan.append(("sumpair", sj1, sj2, nj))
            elif spec.func in ("sum", "avg"):
                sj = len(sum_cols)
                sum_cols.append(
                    jnp.where(m, val.astype(jnp.float32), jnp.zeros((), jnp.float32))
                )
                plan.append(("sum", sj, nj))
            elif spec.func in ("min", "max") and spec.ord_pair:
                plan.append(("ominmax", len(minmax), nj))
                minmax.append(
                    _ord_segment_extremum(spec, val, m, seg_ids, capacity)
                )
            elif spec.func in ("min", "max"):
                v, ident = _minmax_operand(spec, val)
                red = (
                    jax.ops.segment_min
                    if spec.func == "min"
                    else jax.ops.segment_max
                )
                plan.append(("minmax", len(minmax), nj))
                minmax.append(
                    red(jnp.where(m, v, ident), seg_ids, num_segments=capacity)
                )
            else:
                raise ExecutionError(f"kernel agg {spec.func}")
        presence_j = cnt_col(maskf)

        V = jnp.stack(sum_cols + cnt_cols, axis=1)
        hi, lo, counts = _blocked_onehot_agg(
            V, seg_ids, capacity, len(sum_cols)
        )
        outs = []
        for entry in plan:
            if entry[0] == "count":
                outs.append(counts[:, entry[1]])
            elif entry[0] == "sumpair":
                s, e = _two_sum(hi[:, entry[1]], hi[:, entry[2]])
                outs.append(s)
                outs.append(lo[:, entry[1]] + lo[:, entry[2]] + e)
                outs.append(counts[:, entry[3]])
            elif entry[0] == "sum":
                outs.append(hi[:, entry[1]])
                outs.append(lo[:, entry[1]])
                outs.append(counts[:, entry[2]])
            elif entry[0] == "ominmax":
                ohi, olo = minmax[entry[1]]
                outs.append(ohi)
                outs.append(olo)
                outs.append(counts[:, entry[2]])
            else:  # minmax
                outs.append(minmax[entry[1]])
                outs.append(counts[:, entry[2]])
        return tuple(outs) + (counts[:, presence_j],)

    return fn


def _build_scan_plan(env, maskf, specs, arg_closures, mode):
    """Column/plan construction shared by the sort-based reductions.

    Evaluates every aggregate argument closure against ``env``, folds the
    base mask + per-argument validity into masked SCAN-FORM columns, and
    returns ``(kinds, cols, plan)``:

    * ``kinds``/``cols`` — per logical column, the scan element kind and
      array(s) as documented on :func:`_sorted_segment_agg` (min/max
      identities are PYTHON scalars so kinds stays hashable for kernel
      cache keys);
    * ``plan`` — per aggregate spec, the static emission recipe consumed
      by :func:`_emit_scan_outs`.

    Count columns dedupe by argument-validity identity (like the matmul
    path); a ``None`` count index means "use presence" (base mask).
    """
    kinds: list = []
    cols: list = []
    cnt_index: dict = {}  # validity id -> logical col index (None=base)

    def cnt_col(m, avalid=None):
        if avalid is None:
            return None  # base-mask count == presence (boundary diff)
        k = id(avalid)
        j = cnt_index.get(k)
        if j is None:
            j = len(kinds)
            cnt_index[k] = j
            kinds.append("i32")
            cols.append(m.astype(_I()))
        return j

    plan: list = []
    for spec, closure in zip(specs, arg_closures):
        if spec.func == "count_star":
            plan.append(("count", None))
            continue
        val, avalid = closure(env)
        m = maskf if avalid is None else jnp.logical_and(maskf, avalid)
        nj = cnt_col(m, avalid)
        if spec.func == "count":
            plan.append(("count", nj))
            continue
        if spec.func in ("sum", "avg"):
            if mode == "x32":
                if spec.pair:
                    vhi, vlo = val
                    z = jnp.zeros((), jnp.float32)
                    h, l = _two_sum(
                        jnp.where(m, vhi, z), jnp.where(m, vlo, z)
                    )
                else:
                    h = jnp.where(
                        m, val.astype(jnp.float32), jnp.zeros((), jnp.float32)
                    )
                    l = jnp.zeros_like(h)
                plan.append(("sum32", len(kinds), nj))
                kinds.append("df32")
                cols.append((h, l))
            else:
                v = jnp.where(m, val.astype(_F()), jnp.zeros((), _F()))
                plan.append(("sum64", len(kinds), nj))
                kinds.append("f64")
                cols.append(v)
            continue
        if spec.func in ("min", "max") and spec.ord_pair:
            vhi, vlo = val
            info = jnp.iinfo(jnp.int32)
            ident = int(info.max if spec.func == "min" else info.min)
            plan.append(("ominmax", len(kinds), nj))
            kinds.append((f"o{spec.func}", ident))
            cols.append(
                (jnp.where(m, vhi, ident), jnp.where(m, vlo, ident))
            )
            continue
        if spec.func in ("min", "max"):
            v, ident = _minmax_operand(spec, val)
            # identity as a PYTHON scalar: kinds must stay hashable for
            # kernel cache keys, and tracers have no .item() under jit
            if spec.int_minmax:
                info = jnp.iinfo(_I())
                ident_py = int(
                    info.max if spec.func == "min" else info.min
                )
            else:
                ident_py = float("inf" if spec.func == "min" else "-inf")
            plan.append(("minmax", len(kinds), nj))
            kinds.append((spec.func, ident_py))
            cols.append(jnp.where(m, v, ident))
            continue
        raise ExecutionError(f"kernel agg {spec.func}")
    return kinds, cols, plan


def _emit_scan_outs(plan, totals, presence) -> list:
    """Expand scan totals into the kernel's per-spec state-field order."""
    outs: list = []
    for entry in plan:
        if entry[0] == "count":
            outs.append(presence if entry[1] is None else totals[entry[1]])
        elif entry[0] in ("sum32", "ominmax"):
            hi, lo = totals[entry[1]]
            outs.append(hi)
            outs.append(lo)
            outs.append(presence if entry[2] is None else totals[entry[2]])
        else:  # sum64 / minmax
            outs.append(totals[entry[1]])
            outs.append(presence if entry[2] is None else totals[entry[2]])
    return outs


# --------------------------------------------------------- keyed aggregate
# Device-KEYED aggregation: the host never assigns group ids at all.  Raw
# per-key dictionary/identity CODES ship to the device; one multi-key
# ``lax.sort`` orders the rows, group ids fall out of key-change
# boundaries (cumsum of change flags), and the packed fetch returns the
# unique key codes alongside the states.  This replaces the host
# hash-probe/factorize encode (``ops/groups.py``) on the high-cardinality
# path — 44% of q3 SF10 wall in BENCH_SUITE_r03 — with one astype per key
# per batch.  Counterpart of the reference's per-batch hash repartition
# loop (``shuffle_writer.rs:214-256``), redesigned sort-first for a
# scatter-hostile device.


def make_keyed_prep_kernel(
    filter_closure: Optional[JaxClosure],
    arg_closures: list[Optional[JaxClosure]],
    specs: list[KernelAggSpec],
    flat_names: list[str],
    holder: dict,
    extra_names: tuple = (),
    key_kinds: Optional[tuple] = None,
):
    """Per-batch half of the keyed aggregation.

    ``fn(keys, valid, *leaf_arrays) -> (mask, *keys, *flat_cols,
    *extras)``: runs the fused filter (and, wrapped in
    :func:`make_join_kernel`, the device join) and emits masked
    scan-form columns that BUFFER in HBM until the final sort.  ``keys``
    is a tuple of per-key code arrays and passes through untouched (it
    rides the ``seg_ids`` slot so the join wrapper composes unchanged);
    with ``key_kinds`` set, each entry is instead the operand tuple
    :func:`device_encode_keys` expects and the group-code derivation
    runs INSIDE this dispatch — the raw key column crosses the bridge
    once and the host never encodes at all.
    ``extra_names`` are env arrays buffered RAW for post-sort passes
    (device median / count_distinct / corr).  ``holder`` captures the
    static ``kinds``/``plan`` during the first trace for the finish
    kernel.
    """
    mode = precision_mode()

    def fn(keys, valid, *arrays):
        if key_kinds is not None:
            keys = device_encode_keys(key_kinds, keys)
        env = dict(zip(flat_names, arrays))
        mask = valid
        if filter_closure is not None:
            pred, pvalid = filter_closure(env)
            if pvalid is not None:
                pred = jnp.logical_and(pred, pvalid)
            mask = jnp.logical_and(mask, pred)
        kinds, cols, plan = _build_scan_plan(
            env, mask, specs, arg_closures, mode
        )
        holder["kinds"] = tuple(kinds)
        holder["plan"] = tuple(plan)
        flat: list = []
        for kind, col in zip(kinds, cols):
            if _is_pair_kind(kind):
                flat.extend(col)
            else:
                flat.append(col)
        extras = tuple(env[nm] for nm in extra_names)
        return (mask,) + tuple(keys) + tuple(flat) + extras

    return fn


def _is_pair_kind(kind) -> bool:
    """Scan-plan kinds whose column is an (hi, lo) ARRAY PAIR: df32
    compensated sums and order-pair extrema.  Pair columns must flatten
    into two buffer slots (the multi-batch path concatenates and pads
    per slot) and re-pair inside the finish kernel."""
    return kind == "df32" or (
        isinstance(kind, tuple) and kind[0] in ("omin", "omax")
    )


_KEYED_MEDIAN_CACHE: dict = {}


def keyed_median_kernel(n_keys: int, capacity: int):
    """Per-group sorted-argument pass: exact median AND distinct count
    (cached per key count/capacity).

    ``fn(mask, keys, vhi, vlo, vvalid) -> packed [6, capacity]``: ONE
    multi-key sort by (masked-last, *group keys, arg-null-last, value
    order-pair) places each group's valid values ascending; group
    boundaries come from a doubled segment id (gid*2 + null_flag) so the
    VALID-value count per group needs no scatter; the two middle values
    gather per group (decode/average on host) and distinct values count
    as run-starts via one cumsum.  Output rows: hi@lo_idx, lo@lo_idx,
    hi@hi_idx, lo@hi_idx, valid_count, distinct_count.
    """
    key = (n_keys, capacity)
    fn = _KEYED_MEDIAN_CACHE.get(key)
    if fn is not None:
        return fn

    def median_fn(mask, keys, vhi, vlo, vvalid):
        n = mask.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        inv = jnp.logical_not(mask).astype(jnp.int32)
        argnull = jnp.logical_not(vvalid).astype(jnp.int32)
        # vlo MUST be a sort key too: values whose hi words collide
        # (within ~1.2e-7 relative) otherwise stay unordered, gathering
        # the wrong middle element and overcounting distinct run-starts
        kfields = (inv,) + tuple(keys) + (argnull, vhi, vlo)
        packed = packed_multikey_sort(kfields, iota)
        if packed is not None:
            _, skeys = packed
        else:
            sorted_ = jax.lax.sort(
                kfields + (iota,), num_keys=4 + n_keys
            )
            skeys = sorted_[:-1]
        sinv = skeys[0]
        sk = skeys[1:1 + n_keys]
        snull = skeys[1 + n_keys]
        shi = skeys[2 + n_keys]
        slo = skeys[3 + n_keys]
        valid = sinv == 0
        diff = sk[0][1:] != sk[0][:-1]
        for k in sk[1:]:
            diff = jnp.logical_or(diff, k[1:] != k[:-1])
        first = jnp.concatenate([jnp.ones((1,), jnp.bool_), diff])
        flag = jnp.logical_and(first, valid)
        gid = jnp.cumsum(flag.astype(jnp.int32)) - 1
        # doubled id: even slot = valid-arg rows, odd = null-arg rows;
        # masked rows park past every boundary
        big = jnp.asarray(np.iinfo(np.int32).max, jnp.int32)
        s2 = jnp.where(valid, gid * 2 + snull, big)
        bounds = jnp.searchsorted(
            s2, jnp.arange(2 * capacity + 1, dtype=jnp.int32), side="left"
        )
        start = bounds[0::2][:capacity]
        end_valid = bounds[1::2]
        cnt = end_valid - start
        lo_idx = jnp.clip(start + (cnt - 1) // 2, 0, max(n - 1, 0))
        hi_idx = jnp.clip(start + cnt // 2, 0, max(n - 1, 0))
        # distinct count: value-run starts among each group's valid rows
        vdiff = jnp.logical_or(shi[1:] != shi[:-1], slo[1:] != slo[:-1])
        runfirst = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), jnp.logical_or(diff, vdiff)]
        )
        dflag = jnp.logical_and(
            jnp.logical_and(runfirst, valid), snull == 0
        )
        cum0 = jnp.concatenate(
            [
                jnp.zeros((1,), jnp.int32),
                jnp.cumsum(dflag.astype(jnp.int32)),
            ]
        )
        distinct = cum0[end_valid] - cum0[start]
        idt = jnp.int32 if precision_mode() == "x32" else jnp.int64
        rows = [
            shi[lo_idx].astype(idt),
            slo[lo_idx].astype(idt),
            shi[hi_idx].astype(idt),
            slo[hi_idx].astype(idt),
            cnt.astype(idt),
            distinct.astype(idt),
        ]
        return jnp.stack(rows, axis=0)

    fn = jax.jit(median_fn)
    _KEYED_MEDIAN_CACHE[key] = fn
    return fn


_KEYED_SORT_CACHE: dict = {}


def packed_multikey_sort(keys: tuple, iota):
    """Lexicographic multi-key sort with PAIRWISE-u64-PACKED operands.

    ``keys`` are i32 arrays (most-significant first); ``iota`` is the i32
    row index riding as the final tiebreaker.  Each u64 word carries two
    sign-biased 32-bit fields, so unsigned u64 lex order over
    ceil((k+1)/2) words equals i32 tuple order over k+1 operands —
    halving (or better) the bytes every bitonic pass moves.  Measured
    (KERNELBENCH sort_operands): u64x1 sorts ~4.6x faster than i32x2 and
    ~9x faster than i32x5 at equal rows.

    Returns ``(perm, sorted_keys)`` or None when a key isn't i32 (x64
    identity codes) — callers keep the plain operand form then.
    """
    import jax

    n = iota.shape[0]
    if n >= (1 << 31) or any(k.dtype != jnp.int32 for k in keys):
        return None
    fields = [
        # bias in SIGNED i64 first (no uint wraparound subtleties), then
        # reinterpret: result is always in [0, 2^32)
        (k.astype(jnp.int64) + jnp.int64(1 << 31)).astype(jnp.uint64)
        for k in keys
    ]
    fields.append(iota.astype(jnp.uint64))  # non-negative: bias-free
    if len(fields) % 2:
        # a constant low half never affects order
        fields.append(jnp.zeros((), jnp.uint64))
    words = []
    for j in range(0, len(fields), 2):
        hi, lo = fields[j], fields[j + 1]
        words.append((hi << jnp.uint64(32)) | (lo & jnp.uint64(0xFFFFFFFF)))
    sorted_words = jax.lax.sort(tuple(words), num_keys=len(words))
    out_fields = []
    for w in sorted_words:
        out_fields.append((w >> jnp.uint64(32)).astype(jnp.int64))
        out_fields.append((w & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64))
    sorted_keys = tuple(
        (f - jnp.int64(1 << 31)).astype(jnp.int32)
        for f in out_fields[: len(keys)]
    )
    perm = out_fields[len(keys)].astype(jnp.int32)
    return perm, sorted_keys


# ------------------------------------------------------ device key encode
# Device twin of the host group-key encoders (bridge.make_key_encoder /
# device_key_encoder): the raw key column crosses the bridge ONCE as
# (values, validity) and the jitted kernel derives the group code
# bit-identically to the host encoder, so the keyed route pays no host
# encode at all — the same host/device bit-identity pattern
# make_partition_id_kernel proved for shuffle partition ids.  Kinds:
#   "code"  — host-encoded codes pass through (dict/string handoff)
#   "ident" — int/date32 identity codes: value + 1, null -> 0
#             (bridge.IdentityKeyEncoder), computed in the shipped
#             integer dtype (i32 when the host precheck narrowed)
#   "bool"  — null -> 0, False -> 1, True -> 2 (bridge.BoolKeyEncoder)
#   "f32"/"f64" — the RAW bit pattern as a signed integer, null -> a
#             reserved NaN pattern (bridge.FloatKeyEncoder).  Pure
#             bit-pattern grouping matches the CPU hash aggregate
#             exactly (dictionary_encode distinguishes -0.0 from +0.0
#             and NaN payloads from each other — measured, and the
#             oracle identity contract follows IT, not IEEE equality);
#             a host precheck falls back when data contains the one
#             reserved payload
FLOAT32_NULL_BITS = 0xFFC00001 - (1 << 32)  # as signed i32
FLOAT64_NULL_BITS = 0xFFF8000000000001 - (1 << 64)  # as signed i64


def device_encode_key(kind: str, vals, valid):
    """Traceable group-code derivation for ONE key column (see the kind
    table above).  ``vals``/``valid`` are the padded device arrays; pad
    rows carry valid=False and encode to the null code — they are masked
    out of every segment downstream, so their code value never matters.
    """
    if kind == "ident":
        one = jnp.asarray(1, vals.dtype)
        zero = jnp.zeros((), vals.dtype)
        return jnp.where(valid, vals + one, zero)
    if kind == "bool":
        v = vals.astype(jnp.int32) + jnp.int32(1)
        return jnp.where(valid, v, jnp.zeros((), jnp.int32))
    if kind in ("f32", "f64"):
        idt = jnp.int32 if kind == "f32" else jnp.int64
        null = jnp.asarray(
            FLOAT32_NULL_BITS if kind == "f32" else FLOAT64_NULL_BITS,
            idt,
        )
        bits = jax.lax.bitcast_convert_type(vals, idt)
        return jnp.where(valid, bits, null)
    raise ExecutionError(f"device key-encode kind {kind}")


def device_encode_keys(kinds: tuple, keys: tuple) -> tuple:
    """Per-key codes from mixed operands: ``keys[k]`` is ``(codes,)`` for
    kind "code" (host dictionary handoff) or ``(values, validity)`` for
    a device-encoded kind."""
    out = []
    for kind, ops in zip(kinds, keys):
        if kind == "code":
            out.append(ops[0])
        else:
            out.append(device_encode_key(kind, *ops))
    return tuple(out)


_KEY_ENCODE_CACHE: dict = {}


def make_key_encode_kernel(kinds: tuple):
    """Jitted standalone ``fn(keys) -> code arrays`` (parity tests; the
    production path traces :func:`device_encode_keys` INSIDE the fused
    keyed prep kernel so encode shares the batch's single dispatch)."""
    fn = _KEY_ENCODE_CACHE.get(kinds)
    if fn is None:
        fn = jax.jit(lambda keys: device_encode_keys(kinds, keys))
        _KEY_ENCODE_CACHE[kinds] = fn
    return fn


def keyed_sort_body(n_keys: int):
    """Traceable phase-1 body (see :func:`keyed_sort_kernel`): returned
    uncompiled so the fused keyed runner can inline encode→sort into one
    jitted dispatch."""
    return _keyed_sort_fn(n_keys)


def keyed_sort_kernel(n_keys: int):
    """Phase 1 of the keyed aggregation (cached per key count).

    ``fn(mask, *keys) -> (s2, perm, *sorted_keys, n_groups)``: one
    multi-key sort with the inverted mask as the MAJOR key (masked rows
    sink past every boundary), then group ids from key-change boundaries.
    ``s2`` is non-decreasing with masked rows at INT32_MAX, exactly the
    contract :func:`_scan_segments` wants; ``n_groups`` is the only value
    the host fetches before building the capacity-sized finish kernel.
    """
    fn = _KEYED_SORT_CACHE.get(n_keys)
    if fn is not None:
        return fn
    fn = jax.jit(_keyed_sort_fn(n_keys))
    _KEYED_SORT_CACHE[n_keys] = fn
    return fn


def _keyed_sort_fn(n_keys: int):
    def sort_fn(mask, *keys):
        n = mask.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        inv = jnp.logical_not(mask).astype(jnp.int32)
        if n_keys == 1 and keys[0].dtype == jnp.int32 and n < (1 << 31):
            # Single-OPERAND packed sort (trace-time specialization —
            # dtype and shape are static): bit 63 carries the inverted
            # mask (masked rows sink), bits 62..31 the sign-biased key,
            # bits 30..0 the row index, so ONE uint64 array rides the
            # bitonic passes instead of three i32 operands.  Measured
            # (KERNELBENCH sort_operands family): the u64x1 form sorts
            # ~4.6x faster than i32x2 and ~9x faster than i32x5 at 1e5
            # rows on the CPU backend — and every sort-based device
            # path was the r05 chip capture's loss center.
            biased = (
                keys[0].astype(jnp.int64) + jnp.int64(1 << 31)
            ).astype(jnp.uint64)
            packed = (
                (inv.astype(jnp.uint64) << jnp.uint64(63))
                | (biased << jnp.uint64(31))
                | iota.astype(jnp.uint64)
            )
            (sp,) = jax.lax.sort((packed,), num_keys=1)
            perm = (sp & jnp.uint64(0x7FFFFFFF)).astype(jnp.int32)
            k0 = (
                ((sp >> jnp.uint64(31)) & jnp.uint64(0xFFFFFFFF)).astype(
                    jnp.int64
                )
                - jnp.int64(1 << 31)
            ).astype(jnp.int32)
            valid = (sp >> jnp.uint64(63)) == jnp.uint64(0)
            sk = (k0,)
        else:
            packed2 = packed_multikey_sort((inv,) + tuple(keys), iota)
            if packed2 is not None:
                # multi-key form: pairwise-u64 words (see
                # packed_multikey_sort) — 2 words vs 3-5 operands
                perm, skeys = packed2
                sk = skeys[1:]
                valid = skeys[0] == 0
            else:
                sorted_ = jax.lax.sort(
                    (inv, *keys, iota), num_keys=1 + n_keys
                )
                sk = sorted_[1:1 + n_keys]
                perm = sorted_[-1]
                valid = sorted_[0] == 0
        diff = sk[0][1:] != sk[0][:-1]
        for k in sk[1:]:
            diff = jnp.logical_or(diff, k[1:] != k[:-1])
        first = jnp.concatenate([jnp.ones((1,), jnp.bool_), diff])
        flag = jnp.logical_and(first, valid)
        gid = jnp.cumsum(flag.astype(jnp.int32)) - 1
        sentinel = jnp.asarray(np.iinfo(np.int32).max, jnp.int32)
        s2 = jnp.where(valid, gid, sentinel)
        n_groups = jnp.sum(flag.astype(jnp.int32))
        return (s2, perm) + tuple(sk) + (n_groups,)

    return sort_fn


_KEYED_FINISH_CACHE: dict = {}


def keyed_finish_kernel(
    kinds: tuple,
    plan: tuple,
    specs: list[KernelAggSpec],
    n_keys: int,
    capacity: int,
    mode: str,
):
    """Phase 2: gather + segmented scan + key extraction + pack, one jit.

    ``fn(s2, perm, sk, flat_cols) -> packed [n_state_fields + 1 + n_keys,
    capacity]`` integer array (floats bitcast like
    :func:`pack_for_fetch`): per-spec state fields, presence, then the
    unique key CODES gathered at each segment's first sorted row — so one
    tunnel roundtrip returns both the states and the group keys.
    """
    cache_key = (kinds, plan, tuple(specs), n_keys, capacity, mode)
    fn = _KEYED_FINISH_CACHE.get(cache_key)
    if fn is not None:
        return fn
    flags = [f for spec in specs for f in state_is_int(spec, mode)] + [True]

    def finish_fn(s2, perm, sk, flat):
        cols: list = []
        i = 0
        for kind in kinds:
            if _is_pair_kind(kind):
                cols.append((flat[i], flat[i + 1]))
                i += 2
            else:
                cols.append(flat[i])
                i += 1
        totals, presence, bounds = _scan_segments(
            s2, perm, capacity, list(kinds), cols
        )
        outs = _emit_scan_outs(list(plan), totals, presence) + [presence]
        n = s2.shape[0]
        starts = jnp.clip(bounds[:-1], 0, max(n - 1, 0))
        occupied = presence > 0
        fdt = jnp.float64 if mode == "x64" else jnp.float32
        idt = jnp.int64 if mode == "x64" else jnp.int32
        rows = [
            a.astype(idt)
            if is_int
            else jax.lax.bitcast_convert_type(a.astype(fdt), idt)
            for a, is_int in zip(outs, flags)
        ]
        for k in sk:
            rows.append(
                jnp.where(occupied, k[starts], jnp.zeros((), k.dtype)).astype(
                    idt
                )
            )
        return jnp.stack(rows, axis=0)

    fn = jax.jit(finish_fn)
    _KEYED_FINISH_CACHE[cache_key] = fn
    return fn


_KEYED_CORR_CACHE: dict = {}


def keyed_corr_kernel(capacity: int, mode: str):
    """Per-group Pearson correlation moments, PER-GROUP centered.

    Reuses the keyed path's phase-1 sort (``s2``/``perm``): pass 1 scans
    per-group Σx, Σy, n over pairwise-valid rows (null or NaN in either
    argument drops the row from every sum, pandas semantics); the
    per-group means gather back to rows; pass 2 scans the CENTERED
    products Σx'y', Σx'², Σy'².  Centering by each group's own mean is
    strictly stronger conditioning than the CPU operator's global-mean
    centering — the center constant need not be exact, it only has to
    kill the magnitude.

    x32: ``fn(s2, perm, xhi, xlo, xvalid, yhi, ylo, yvalid)``; x64:
    ``fn(s2, perm, x, xvalid, y, yvalid)``.  Returns packed integer rows
    [Σxy(hi,lo) Σxx(hi,lo) Σyy(hi,lo) n] (x32) / [Σxy Σxx Σyy n] (x64);
    the host finalizes Σxy/√(Σxx·Σyy).
    """
    key = (capacity, mode)
    fn = _KEYED_CORR_CACHE.get(key)
    if fn is not None:
        return fn

    if mode == "x32":

        def corr_fn(s2, perm, xhi, xlo, xvalid, yhi, ylo, yvalid):
            m = jnp.logical_and(xvalid, yvalid)
            m = jnp.logical_and(m, jnp.logical_not(jnp.isnan(xhi)))
            m = jnp.logical_and(m, jnp.logical_not(jnp.isnan(yhi)))
            z = jnp.zeros((), jnp.float32)
            kinds1 = ["df32", "df32", "i32"]
            cols1 = [
                (jnp.where(m, xhi, z), jnp.where(m, xlo, z)),
                (jnp.where(m, yhi, z), jnp.where(m, ylo, z)),
                m.astype(jnp.int32),
            ]
            (sx, sy, n_pair), _pres, _b = _scan_segments(
                s2, perm, capacity, kinds1, cols1
            )
            nf = jnp.maximum(n_pair, 1).astype(jnp.float32)
            mx = (sx[0] + sx[1]) / nf
            my = (sy[0] + sy[1]) / nf
            gid = jnp.clip(s2, 0, capacity - 1)
            # centered values in sorted-row order: gather means per row
            mxr = mx[gid]
            myr = my[gid]
            # perm-gathered (sorted) argument rows
            xs_hi, xs_lo = xhi[perm], xlo[perm]
            ys_hi, ys_lo = yhi[perm], ylo[perm]
            ms = m[perm]
            xc = (xs_hi - mxr) + xs_lo
            yc = (ys_hi - myr) + ys_lo
            kinds2 = ["df32", "df32", "df32"]
            zero = jnp.zeros_like(xc)
            cols2 = [
                (jnp.where(ms, xc * yc, z), zero),
                (jnp.where(ms, xc * xc, z), zero),
                (jnp.where(ms, yc * yc, z), zero),
            ]
            # cols are already in SORTED order: identity perm for pass 2
            iota = jnp.arange(s2.shape[0], dtype=jnp.int32)
            (sxy, sxx, syy), _p2, _b2 = _scan_segments(
                s2, iota, capacity, kinds2, cols2
            )
            idt = jnp.int32
            rows = [
                jax.lax.bitcast_convert_type(sxy[0], idt),
                jax.lax.bitcast_convert_type(sxy[1], idt),
                jax.lax.bitcast_convert_type(sxx[0], idt),
                jax.lax.bitcast_convert_type(sxx[1], idt),
                jax.lax.bitcast_convert_type(syy[0], idt),
                jax.lax.bitcast_convert_type(syy[1], idt),
                n_pair.astype(idt),
            ]
            return jnp.stack(rows, axis=0)

    else:

        def corr_fn(s2, perm, x, xvalid, y, yvalid):
            m = jnp.logical_and(xvalid, yvalid)
            m = jnp.logical_and(m, jnp.logical_not(jnp.isnan(x)))
            m = jnp.logical_and(m, jnp.logical_not(jnp.isnan(y)))
            z = jnp.zeros((), jnp.float64)
            kinds1 = ["f64", "f64", "i32"]
            cols1 = [
                jnp.where(m, x, z),
                jnp.where(m, y, z),
                m.astype(jnp.int64),
            ]
            (sx, sy, n_pair), _pres, _b = _scan_segments(
                s2, perm, capacity, kinds1, cols1
            )
            nf = jnp.maximum(n_pair, 1).astype(jnp.float64)
            mx = sx / nf
            my = sy / nf
            gid = jnp.clip(s2, 0, capacity - 1)
            xs, ys, ms = x[perm], y[perm], m[perm]
            xc = xs - mx[gid]
            yc = ys - my[gid]
            iota = jnp.arange(s2.shape[0], dtype=jnp.int32)
            (sxy, sxx, syy), _p2, _b2 = _scan_segments(
                s2, iota, capacity, ["f64", "f64", "f64"],
                [
                    jnp.where(ms, xc * yc, z),
                    jnp.where(ms, xc * xc, z),
                    jnp.where(ms, yc * yc, z),
                ],
            )
            idt = jnp.int64
            rows = [
                jax.lax.bitcast_convert_type(sxy, idt),
                jax.lax.bitcast_convert_type(sxx, idt),
                jax.lax.bitcast_convert_type(syy, idt),
                n_pair.astype(idt),
            ]
            return jnp.stack(rows, axis=0)

    fn = jax.jit(corr_fn)
    _KEYED_CORR_CACHE[key] = fn
    return fn


def merge_keyed_host(
    specs: list[KernelAggSpec],
    mode: str,
    per_dev: list,
) -> tuple[list[np.ndarray], list[np.ndarray], int]:
    """Merge per-shard keyed results BY KEY on host (numpy, vectorized).

    ``per_dev``: list of (states, key_cols, n_groups) as returned by
    :func:`unpack_keyed_host` (+ group count).  The merge is
    [total distinct]-sized — the O(rows) work stayed on the shards; an
    ICI tree-merge is a future optimization.  Returns (merged states
    incl. trailing presence, merged key code arrays, n_groups).
    """
    live = [(s, k, n) for s, k, n in per_dev if n > 0]
    if not live:
        empty = [np.zeros(0, dtype=np.int64) for _ in per_dev[0][0]]
        return empty, [np.zeros(0, np.int64) for _ in per_dev[0][1]], 0
    n_keys = len(live[0][1])
    keys = [
        np.concatenate([k[j][:n] for _s, k, n in live])
        for j in range(n_keys)
    ]
    states = [
        np.concatenate([s[i][:n] for s, _k, n in live])
        for i in range(len(live[0][0]))
    ]
    order = np.lexsort(tuple(reversed(keys)))
    keys = [k[order] for k in keys]
    states = [s[order] for s in states]
    n_rows = len(keys[0])
    newflag = np.ones(n_rows, dtype=bool)
    for k in keys:
        nf = np.empty(n_rows, dtype=bool)
        nf[0] = True
        nf[1:] = k[1:] != k[:-1]
        if k is keys[0]:
            newflag = nf
        else:
            newflag |= nf
    starts = np.flatnonzero(newflag)
    out_keys = [k[starts] for k in keys]

    def _reduceat(a, how):
        if how == "sum":
            return np.add.reduceat(a.astype(np.float64), starts)
        if how == "isum":
            return np.add.reduceat(a.astype(np.int64), starts)
        if how == "min":
            return np.minimum.reduceat(a, starts)
        return np.maximum.reduceat(a, starts)

    def _lex_reduceat(hi, lo, how):
        # lexicographic (hi, lo) i32 extremum via ONE biased u64 key —
        # bridge.join_u64 owns the bias/pack convention (and its
        # docstring owns the i64-wrap warning)
        from .bridge import join_u64

        m = _reduceat(join_u64(hi, lo), how)
        return (
            (m >> np.uint64(32)).astype(np.int64) - (1 << 31),
            (m & np.uint64(0xFFFFFFFF)).astype(np.int64) - (1 << 31),
        )

    out: list[np.ndarray] = []
    i = 0
    for spec in specs:
        if spec.func in ("sum", "avg") and mode == "x32":
            # recombine the pair in f64; compensation already happened
            # on-device — the per-group cross-shard sum is tiny
            v = states[i].astype(np.float64) + states[i + 1].astype(
                np.float64
            )
            out.append(_reduceat(v, "sum"))
            out.append(np.zeros(len(starts)))  # lo absorbed into hi
            out.append(_reduceat(states[i + 2], "isum"))
            i += 3
            continue
        if spec.ord_pair and spec.func in ("min", "max"):
            hi, lo = _lex_reduceat(
                states[i], states[i + 1], spec.func
            )
            out.extend([hi, lo, _reduceat(states[i + 2], "isum")])
            i += 3
            continue
        for role in state_fields(spec, mode):
            if role == "min":
                out.append(_reduceat(states[i], "min"))
            elif role == "max":
                out.append(_reduceat(states[i], "max"))
            else:  # additive
                is_int = states[i].dtype.kind in "iu"
                out.append(
                    _reduceat(states[i], "isum" if is_int else "sum")
                )
            i += 1
    out.append(_reduceat(states[-1], "isum"))  # presence
    return out, out_keys, len(starts)


def unpack_keyed_host(
    specs: list[KernelAggSpec], packed: np.ndarray, mode: str, n_keys: int
) -> tuple[list[np.ndarray], list[np.ndarray]]:
    """Host inverse of :func:`keyed_finish_kernel`'s pack: (state arrays
    incl. trailing presence, per-key unique code arrays as int64)."""
    flags = [f for spec in specs for f in state_is_int(spec, mode)] + [True]
    fdt = np.float64 if mode == "x64" else np.float32
    states = [
        row if is_int else row.view(fdt)
        for row, is_int in zip(packed[: len(flags)], flags)
    ]
    keys = [
        packed[len(flags) + k].astype(np.int64) for k in range(n_keys)
    ]
    return states, keys


def _ord_segment_extremum(spec, val, m, seg_ids, capacity):
    """Exact segment extremum over an order-pair operand: reduce hi, then
    reduce lo among the rows tied at the extremal hi (two segment
    reductions = one lexicographic 64-bit extremum)."""
    vhi, vlo = val
    info = jnp.iinfo(jnp.int32)
    if spec.func == "min":
        red, ident = jax.ops.segment_min, info.max
    else:
        red, ident = jax.ops.segment_max, info.min
    hi_m = jnp.where(m, vhi, ident)
    seg_hi = red(hi_m, seg_ids, num_segments=capacity)
    tie = jnp.logical_and(m, hi_m == seg_hi[seg_ids])
    lo_m = jnp.where(tie, vlo, ident)
    seg_lo = red(lo_m, seg_ids, num_segments=capacity)
    return [seg_hi, seg_lo]


def _minmax_operand(spec: KernelAggSpec, val):
    """(operand, identity) for a min/max reduction, dtype-preserving for
    the integer path (exactness) and float for the rest."""
    if spec.int_minmax:
        v = val.astype(_I())
        info = jnp.iinfo(_I())
        ident = jnp.asarray(
            info.max if spec.func == "min" else info.min, _I()
        )
        return v, ident
    v = val.astype(_F())
    ident = jnp.asarray(
        jnp.inf if spec.func == "min" else -jnp.inf, _F()
    )
    return v, ident


def _pad_ident(role: str, dtype):
    """Growth-padding identity per state field, dtype-aware (integer
    min/max states must not pad with float inf)."""
    if role in ("min", "omin_hi", "omin_lo"):
        return (
            jnp.iinfo(dtype).max
            if jnp.issubdtype(dtype, jnp.integer)
            else jnp.inf
        )
    if role in ("max", "omax_hi", "omax_lo"):
        return (
            jnp.iinfo(dtype).min
            if jnp.issubdtype(dtype, jnp.integer)
            else -jnp.inf
        )
    return 0


def pad_states(
    specs: list[KernelAggSpec],
    acc: Optional[tuple],
    new_cap: int,
    mode: str,
):
    """Grow accumulated [old_cap] states to [new_cap] (adaptive segment
    capacity): additive fields pad with 0, extrema with their identity.
    Existing group ids stay valid — the host encoder assigns them
    monotonically."""
    if acc is None:
        return None
    out = []
    i = 0
    old_cap = acc[0].shape[0]
    grow = new_cap - old_cap
    for spec in specs:
        for role in state_fields(spec, mode):
            ident = _pad_ident(role, acc[i].dtype)
            out.append(
                jnp.pad(acc[i], (0, grow), constant_values=ident)
            )
            i += 1
    out.append(jnp.pad(acc[-1], (0, grow)))  # presence
    return tuple(out)


def state_is_int(spec: KernelAggSpec, mode: str) -> tuple[bool, ...]:
    """Which state fields are integer (counts) vs float, in layout order."""
    if spec.func in ("count", "count_star"):
        return (True,)
    if spec.func in ("sum", "avg"):
        return (False, False, True) if mode == "x32" else (False, True)
    if spec.ord_pair:
        return (True, True, True)  # (hi, lo, n) — all integer
    return (spec.int_minmax, True)  # min/max: (value, n)


# Packed-fetch plumbing: on the tunnel-attached TPU only FETCHES block
# (block_until_ready is unreliable), and every fetch pays a ~35ms
# roundtrip.  Packing the whole state tuple into ONE array makes
# materialization a single roundtrip instead of one per state field.
# The pack travels in the INTEGER domain (floats bitcast to i32/i64):
# int→float bitcasts produce denormal bit patterns that the TPU flushes
# to zero during multi-row relayout — measured: a [2, 1] stack of
# bitcast counts came back all-zero — while integer copies are exact.
_PACK_CACHE: dict = {}


def pack_states(
    specs: list[KernelAggSpec], states: tuple, mode: str,
    keep: Optional[int] = None,
):
    """Traceable body of :func:`pack_for_fetch`: stack every state field
    (floats bitcast to the integer domain) into one [n_fields, keep]
    array.  Usable inside a larger jit (the fused single-dispatch runner
    packs in the same trace as the kernels) or via the jitted wrapper."""
    cap = states[0].shape[-1]
    if keep is None or keep > cap:
        keep = cap
    flags = [
        f for spec in specs for f in state_is_int(spec, mode)
    ] + [True]  # presence
    fdt = jnp.float64 if mode == "x64" else jnp.float32
    idt = jnp.int64 if mode == "x64" else jnp.int32
    rows = [
        a[:keep].astype(idt)
        if is_int
        else jax.lax.bitcast_convert_type(a[:keep].astype(fdt), idt)
        for a, is_int in zip(states, flags)
    ]
    return jnp.stack(rows, axis=0)


def pack_for_fetch(
    specs: list[KernelAggSpec], acc: tuple, mode: str,
    keep: Optional[int] = None,
):
    """Device-side: concat all state fields into one [n_fields, keep] array.

    ``keep`` (static per trace; callers bucket it to a power of two so
    retraces stay bounded) slices the fetch to the slots that hold real
    groups — capacity grows in 4x steps, so fetching all of it moves up
    to 4x more bytes than the group table ever assigned, and tunnel fetch
    bandwidth is the scarce resource at high cardinality."""
    cap = acc[0].shape[-1]
    if keep is None or keep > cap:
        keep = cap
    key = (tuple(specs), mode, cap, keep)
    fn = _PACK_CACHE.get(key)
    if fn is None:
        fn = jax.jit(
            lambda states: pack_states(specs, states, mode, keep)
        )
        _PACK_CACHE[key] = fn
    return fn(acc)


def unpack_host(
    specs: list[KernelAggSpec], packed: np.ndarray, mode: str
) -> list[np.ndarray]:
    """Host-side inverse of :func:`pack_for_fetch` (numpy, no device)."""
    flags = [f for spec in specs for f in state_is_int(spec, mode)] + [True]
    fdt = np.float64 if mode == "x64" else np.float32
    out = []
    for row, is_int in zip(packed, flags):
        out.append(row if is_int else row.view(fdt))
    return out


def combine_states(
    specs: list[KernelAggSpec],
    acc: Optional[tuple],
    new: tuple,
    mode: Optional[str] = None,
) -> tuple:
    """Merge per-batch kernel outputs (device-side, cheap elementwise).

    In x32 mode sum/avg states are double-float (hi, lo) pairs merged with
    an error-free 2Sum so cross-batch accumulation keeps ~f64 precision.
    ``mode`` must be the mode the kernel was BUILT under (the owning
    TpuStageExec pins it); the global is only a fallback.
    """
    if acc is None:
        return new
    mode = mode or precision_mode()
    out = []
    i = 0
    for spec in specs:
        fields = state_fields(spec, mode)
        if spec.func in ("sum", "avg") and mode == "x32":
            s, e = _two_sum(acc[i], new[i])
            out.append(s)
            out.append(acc[i + 1] + new[i + 1] + e)
            out.append(acc[i + 2] + new[i + 2])
            i += 3
            continue
        if spec.ord_pair and spec.func in ("min", "max"):
            hi, lo = _lex_merge(
                acc[i], acc[i + 1], new[i], new[i + 1],
                spec.func == "min",
            )
            out.append(hi)
            out.append(lo)
            out.append(acc[i + 2] + new[i + 2])
            i += 3
            continue
        for role in fields:
            if role == "min":
                out.append(jnp.minimum(acc[i], new[i]))
            elif role == "max":
                out.append(jnp.maximum(acc[i], new[i]))
            else:
                out.append(acc[i] + new[i])
            i += 1
    out.append(acc[-1] + new[-1])  # presence
    return tuple(out)


# --------------------------------------------------- shuffle hash partition
# Device twin of exec.operators.hash_partition_indices: the SAME 64-bit
# multiply/xorshift/combine hash, built from uint32 limb arithmetic so it
# runs in x32 mode on accelerators without native 64-bit ALUs.  Map and
# reduce sides of a join must co-partition, so assignments have to match
# the host/native partitioner bit-for-bit (property-tested in
# tests/test_shuffle_writer.py).

_HASH_MUL = (0x9E3779B9, 0x7F4A7C15)  # (hi, lo) of the host multiplier
_NULL_HASH = (0xA5A5A5A5, 0xDEADBEEF)  # (hi, lo) of the host null hash
# the n <= 2^16 gate keeps every intermediate of the final 64-bit mod
# inside uint32: (n-1)^2 + (n-1) < 2^32
PID_MAX_PARTITIONS = 1 << 16


def _mul64_limbs(ahi, alo, bhi, blo):
    """Low 64 bits of a 64x64 product over (hi, lo) uint32 limbs —
    16-bit half-products so nothing needs a widening multiply."""
    mask16 = jnp.uint32(0xFFFF)
    a0, a1 = alo & mask16, alo >> 16
    b0, b1 = blo & mask16, blo >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    mid = (p00 >> 16) + (p01 & mask16) + (p10 & mask16)
    lo = (mid << 16) | (p00 & mask16)
    hi = a1 * b1 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    hi = hi + alo * bhi + ahi * blo  # uint32 wrap == mod 2^32
    return hi, lo


def _add64_limbs(ahi, alo, bhi, blo):
    lo = alo + blo
    carry = (lo < alo).astype(jnp.uint32)
    return ahi + bhi + carry, lo


_PID_KERNEL_CACHE: dict = {}


def partition_id_hash(args, n_out: int):
    """Traceable body of the partition-id kernel: flattened
    ``(hi, lo, is_null)`` limb triples -> int32 partition ids.

    Per column: ``hv = (x * 0x9E3779B97F4A7C15) mod 2^64``,
    ``hv ^= hv >> 32`` (both limbs uint32: the xorshift is one limb
    xor), nulls replaced by the host's constant; columns combine as
    ``h = h * 31 + hv``; the result is ``h mod n_out`` with the 64-bit
    mod folded through ``2^32 mod n``.  Usable inside a larger jit (the
    whole-stage fused runner derives the shuffle pid column in the same
    trace as the agg kernels) or via the jitted wrapper below.
    """
    n_cols = len(args) // 3
    mul_hi = jnp.uint32(_HASH_MUL[0])
    mul_lo = jnp.uint32(_HASH_MUL[1])
    null_hi = jnp.uint32(_NULL_HASH[0])
    null_lo = jnp.uint32(_NULL_HASH[1])
    m = jnp.uint32(n_out)
    pow32_mod = jnp.uint32((1 << 32) % n_out)
    hhi = jnp.zeros_like(args[0])
    hlo = jnp.zeros_like(args[0])
    for c in range(n_cols):
        vhi, vlo, is_null = args[3 * c : 3 * c + 3]
        phi, plo = _mul64_limbs(vhi, vlo, mul_hi, mul_lo)
        plo = plo ^ phi  # hv ^= hv >> 32
        phi = jnp.where(is_null, null_hi, phi)
        plo = jnp.where(is_null, null_lo, plo)
        thi, tlo = _mul64_limbs(hhi, hlo, jnp.uint32(0), jnp.uint32(31))
        hhi, hlo = _add64_limbs(thi, tlo, phi, plo)
    return (((hhi % m) * pow32_mod + (hlo % m)) % m).astype(jnp.int32)


def make_partition_id_kernel(n_cols: int, n_out: int):
    """Jitted ``(hi, lo, is_null) x n_cols -> int32 partition ids``
    (see :func:`partition_id_hash` for the hash definition)."""
    key = (n_cols, n_out)
    cached = _PID_KERNEL_CACHE.get(key)
    if cached is not None:
        return cached

    def kernel(*args):
        return partition_id_hash(args, n_out)

    cached = jax.jit(kernel)
    _PID_KERNEL_CACHE[key] = cached
    return cached


def _pid_limbs(v: pa.Array) -> Optional[tuple]:
    """(hi, lo, is_null) uint32/bool limb arrays for one key column —
    the exact value prep of hash_partition_indices, or None when the
    column type has no device hash (strings hash FNV over bytes on
    host)."""
    import pyarrow.compute as pc

    t = v.type
    if not (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_boolean(t)
        or pa.types.is_date(t)
        or pa.types.is_timestamp(t)
    ):
        return None
    is_null = (
        np.asarray(pc.is_null(v))
        if v.null_count
        else np.zeros(len(v), dtype=bool)
    )
    if pa.types.is_date32(t):
        v = v.cast(pa.int32())
    elif pa.types.is_date64(t) or pa.types.is_timestamp(t):
        v = v.cast(pa.int64())
    elif pa.types.is_boolean(t):
        v = v.cast(pa.int8())
    if v.null_count:
        v = v.fill_null(0)
    x = np.asarray(v)
    if x.dtype.kind == "f":
        x = (
            x.view(np.uint64)
            if x.dtype == np.float64
            else x.astype(np.float64).view(np.uint64)
        )
    else:
        x = x.astype(np.int64).view(np.uint64)
    hi = (x >> np.uint64(32)).astype(np.uint32)
    lo = (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo, is_null


def pid_limb_args(columns, pad_to: int) -> Optional[list]:
    """Flattened ``(hi, lo, is_null)`` limb arrays, padded to ``pad_to``,
    for a list of arrow key columns — or None when any column has no
    device hash.  Host prep for :func:`partition_id_hash` inside a
    larger trace (the whole-stage fused runner derives the shuffle pid
    lane in the same dispatch as the agg kernels)."""
    args: list = []
    for col in columns:
        limbs = _pid_limbs(col)
        if limbs is None:
            return None
        for a in limbs:
            args.append(_pad(a, pad_to))
    return args or None


def device_partition_ids(
    batch: pa.RecordBatch, exprs, n: int
) -> Optional[np.ndarray]:
    """Partition ids for ``batch`` through the jitted device hash, or
    None when a key isn't device-hashable (non-column expression, string
    key, n past PID_MAX_PARTITIONS) — the caller falls back to the host
    partitioner.  Rows pad to power-of-two buckets so distinct XLA
    shapes stay logarithmic in batch size."""
    if n <= 0 or n > PID_MAX_PARTITIONS or batch.num_rows == 0:
        return None
    flat = []
    for e in exprs:
        if not isinstance(e, pe.Col) or not (0 <= e.index < batch.num_columns):
            return None
        limbs = _pid_limbs(batch.column(e.index))
        if limbs is None:
            return None
        flat.append(limbs)
    if not flat:
        return None
    n_rows = batch.num_rows
    bucket = bucket_rows(n_rows, floor=256)
    args = []
    for hi, lo, is_null in flat:
        args.append(_pad(hi, bucket))
        args.append(_pad(lo, bucket))
        args.append(_pad(is_null, bucket))
    kernel = make_partition_id_kernel(len(flat), n)
    out = np.asarray(kernel(*args))[:n_rows]
    return out.astype(np.int64)
