"""TPU stage compiler (placeholder wired from SessionContext; real
implementation lands with ops/kernels.py)."""


def maybe_accelerate(plan, config):
    return plan
