"""TPU stage compiler: swap eligible subtrees for fused XLA kernels.

This is the north-star component (BASELINE.json): the counterpart of a
DataFusion ``PhysicalOptimizerRule`` + extension ``ExecutionPlan`` that
intercepts eligible Filter→Project→HashAggregate subplans inside the stage
runner.  ``maybe_accelerate`` walks a physical plan and replaces each
eligible ``HashAggregateExec`` (plus its filter/projection chain) with a
:class:`TpuStageExec`; everything else stays on the CPU operator path, so
the TPU path is a pure operator-level plugin gated by session config
(``ballista.tpu.enable``) — the same role the reference's extension-codec
hook plays for third-party operators (``core/src/serde/mod.rs:82-95``).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from ..config import BallistaConfig
from ..errors import ExecutionError
from ..exec import expressions as pe
from ..exec.aggregates import PARTIAL, SINGLE, AggSpec, HashAggregateExec
from ..exec.operators import (
    ExecutionPlan,
    FilterExec,
    Partitioning,
    ProjectionExec,
    TaskContext,
)
from ..exec.planner import RenameSchemaExec
from . import kernels as K

try:  # jax is already imported by ops/__init__; .errors adds no backend init
    from jax.errors import JaxRuntimeError as _JaxRuntimeError
except Exception:  # pragma: no cover - ancient jax
    class _JaxRuntimeError(RuntimeError):
        pass


class _CapacityExceeded(Exception):
    pass


class _JoinIneligible(Exception):
    """The device join cannot run for THIS data (non-unique or
    i32-unrepresentable build keys): re-run with the join on CPU and only
    the aggregate on device (the pre-fold round-2 shape)."""


class _SmallInput(Exception):
    """Control flow: the source peek found fewer rows than tpu.min_rows;
    carries the already-buffered batches so the CPU path needn't re-scan."""

    def __init__(self, batches: list):
        super().__init__(f"{sum(b.num_rows for b in batches)} rows")
        self.batches = batches


class _HighCardinality(Exception):
    """Control flow: the first batch showed groups ~ rows and
    ``highcard_mode=cpu`` pins the C++ hash aggregate — the stage hands
    back to the CPU path, replaying the consumed batch and chaining the
    still-live source iterator (no re-scan)."""

    def __init__(self, batches: list, tail):
        super().__init__("high-cardinality aggregate")
        self.batches = batches
        self.tail = tail


class _KeyedRoute(Exception):
    """Control flow: the first batch showed groups ~ rows — route the
    stage to the device-KEYED aggregation (raw key codes sort on device,
    group ids from key-change boundaries; no host hash encode).  Carries
    the consumed batch (with its already-computed key codes) and the
    still-live source iterator."""

    def __init__(self, batches: list, tail, key_encoders, ra):
        super().__init__("keyed high-cardinality aggregate")
        self.batches = batches  # [(RecordBatch, code_arrays)]
        self.tail = tail
        self.key_encoders = key_encoders
        self.ra = ra


class _TrackingIter:
    """Iterator wrapper recording whether any item was actually yielded —
    lets the keyed fallback replay buffered batches + chain the tail when
    the failure happened before the live source was touched."""

    def __init__(self, it):
        self._it = iter(it)
        self.consumed = False

    def __iter__(self):
        return self

    def __next__(self):
        item = next(self._it)
        self.consumed = True
        return item


class _KeyedGroups:
    """GroupTable-shaped view over DEVICE-assigned groups: the fetched
    unique key codes (gid order = key-sorted order) satisfy the
    ``n_groups`` / ``codes_for`` surface ``_materialize`` reads."""

    def __init__(self, key_codes: list, n_groups: int):
        self._codes = key_codes
        self.n_groups = n_groups

    def codes_for(self, gids: np.ndarray, key: int) -> np.ndarray:
        return self._codes[key][gids]


# High-cardinality routing: below either bound the gid-table device path
# wins outright (measured on chip, BENCH_r05_dev.json q1 SF10: 35-40x).
# Above both, 'auto' routes to the C++ hash aggregate on EVERY platform
# (join-free shapes) or stays on the gid table (fused joins, which pay
# the join either way).  The measurements behind that:
#   - chip (BENCH_SUITE_r05.json): q3 SF10 keyed = 0.036x — ~130s/iter
#     of stream-wide device sort vs the hash aggregate's 14s; the r03
#     gid/hash route ran the same query at 1.13x;
#   - CPU platform (KERNELBENCH smoke, 1e5 rows: scatter 166M rows/s vs
#     keyed sort 2.6M; h2o G1_1e6 A/B: q10 9.9s keyed vs 2.4s hash).
# 'cpu' pins the hash handoff explicitly; 'device' pins the keyed path
# (tests, chip A/B, and the r05 packed-sort rework whose chip numbers
# are still pending — KERNELBENCH sort_operands will say whether the
# 4.6-9x single-operand speedup moves the routing again).
# The detector bounds load from the generated routing table
# (ops/routing.py; regenerate via dev/analyze_grid.py --emit).  A
# non-None module value overrides the table (tests pin tiny detector
# bounds to route small fixtures keyed).
_HIGHCARD_MIN_GROUPS: Optional[int] = None
_HIGHCARD_RATIO: Optional[float] = None


def _highcard_min_groups() -> int:
    if _HIGHCARD_MIN_GROUPS is not None:
        return _HIGHCARD_MIN_GROUPS
    from . import routing

    return routing.value("highcard_min_groups")


def _highcard_ratio() -> float:
    if _HIGHCARD_RATIO is not None:
        return _HIGHCARD_RATIO
    from . import routing

    return routing.value("highcard_ratio")


# Whole-stage fusion bounds (ballista.tpu.whole_stage_fusion) load from
# the same measured table; non-None module values override (tests).
_FUSION_MAX_OPS: Optional[int] = None
_FUSION_MIN_ROWS: Optional[int] = None


def _fusion_max_ops() -> int:
    if _FUSION_MAX_OPS is not None:
        return _FUSION_MAX_OPS
    from . import routing

    return routing.value("fusion_max_ops")


def _fusion_min_rows() -> int:
    if _FUSION_MIN_ROWS is not None:
        return _FUSION_MIN_ROWS
    from . import routing

    return routing.value("fusion_min_rows")
# Build-key spans up to this many slots use the dense direct-probe join
# table ([span] i32 = 256 MiB HBM at the cap) instead of searchsorted's
# log2(m) sequential gather passes (BENCH_SUITE_r05 starjoin row).
_DENSE_JOIN_SPAN_CAP = 1 << 26
# The fused single-dispatch runner unrolls one kernel body per retained
# batch; past this many entries the per-batch dispatch loop runs instead
# (an unbounded unroll compiles an XLA program linear in batch count —
# a compile cliff at the default 8k batch size).
_FUSED_MAX_ENTRIES = 32


def _keep_bucket(n_groups: int) -> int:
    """Pow2 bucket of assigned-group slots a packed fetch moves (shared
    by the streamed and fused fetch paths so their trace keys agree)."""
    return 1 << max(6, (max(n_groups, 1) - 1).bit_length())


def keyed_route_wanted(config) -> bool:
    """Does groups~rows route to the device-KEYED path in this config
    on this platform?  (See the routing comment above.)

    MEASURED r05 revision: the first chip capture of the keyed path
    (BENCH_SUITE_r05 q3 SF10) ran 0.036x CPU — the stream-wide
    multi-operand device sort is the cost center, and the same query's
    gid/hash route measured 1.13x in r03.  No captured shape has the
    keyed sort winning on real silicon, so ``auto`` now routes
    groups~rows to the gid table (fused joins) or the C++ hash handoff
    on EVERY platform; the keyed path is an explicit
    ``highcard_mode=device`` pin (and remains mandatory for median/corr
    stages, which need the device sort anyway)."""
    mode = config.tpu_highcard_mode
    if mode == "cpu":
        return False
    if mode == "device":
        return True
    from . import routing

    # 'auto' follows the measured routing table: True only on platforms
    # whose KERNELBENCH grid shows the keyed reduction winning the
    # high-cardinality cells (dev/analyze_grid.py --emit)
    return bool(routing.value("keyed_route_auto"))


def _highcard_detect(n_groups: int, n_rows: int) -> bool:
    """Raw groups~rows detector (first data batch), mode-independent."""
    return (
        n_groups > _highcard_min_groups()
        and n_groups > _highcard_ratio() * n_rows
    )


class _ReadAhead:
    """Bounded background prefetch of source batches.

    Device stages alternate host-side work (scan/decode, key encode) with
    device dispatch; pulling the NEXT batch on a daemon thread overlaps
    the source's IO (pyarrow readers release the GIL in C++) with the
    current batch's device work.  The iterator is transparent: batches
    arrive in order, source exceptions re-raise at the consumer, and
    fallback replay (``_HighCardinality.tail``) can keep consuming it —
    queued batches are still inside and will be yielded.

    ``close()`` stops the pump before a fallback re-runs the stage on
    CPU — otherwise the abandoned thread would keep consuming the old
    source concurrently with the re-run's fresh iterator (a double-read
    of e.g. a Flight stream) and then block on the bounded queue forever.
    Residual race: a pump already blocked INSIDE the source's read when
    ``close()`` lands cannot be interrupted and may consume ONE more item
    before it sees the flag (the item is dropped, never yielded); the
    double-read window is mitigated to that single in-flight read, not
    eliminated.
    """

    _DONE = object()

    def __init__(self, it, depth: int):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._closed = False
        self._exhausted = False

        def pump():
            try:
                for item in it:
                    if self._closed:
                        return  # drop: a fallback re-run owns the source
                    self._q.put(item)
                    if self._closed:
                        return
            except BaseException as e:  # re-raised on the consumer side
                self._q.put(e)
                return
            self._q.put(self._DONE)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, BaseException):
            # generator semantics: a terminal exception surfaces once,
            # then the iterator stays exhausted
            self._exhausted = True
            raise item
        return item

    def close(self, deadline_s: float = 1.0) -> None:
        """Stop the pump: drain the queue until the thread exits (freeing
        queue slots unblocks a pump stuck in put; the loop re-checks the
        flag after each put).  Bounded wait: a pump blocked inside the
        SOURCE's read (e.g. a stalled Flight stream) cannot be
        interrupted — after the deadline the daemon thread is abandoned
        (it dies with the source or the process) rather than hanging the
        caller's CPU fallback."""
        import queue
        import time

        self._closed = True
        self._exhausted = True
        give_up = time.monotonic() + deadline_s
        while self._thread.is_alive() and time.monotonic() < give_up:
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(0.05)


def _shuffle_coalesce_rows(config) -> int:
    """Resolved host-coalescing target for shuffle-fed device stages:
    ``ballista.shuffle.coalesce_rows`` (0 → follow ``ballista.batch.size``,
    negative → disabled)."""
    n = config.shuffle_coalesce_rows
    if n < 0:
        return 0
    return n or config.batch_size


def _reads_shuffle(plan) -> bool:
    """Does this stage source pull from a shuffle reader (whose batches
    arrive as per-map-task fragments worth coalescing)?"""
    from ..shuffle.execution_plans import ShuffleReaderExec

    if isinstance(plan, ShuffleReaderExec):
        return True
    return any(_reads_shuffle(c) for c in plan.children())


@contextlib.contextmanager
def _closing_on_error(ra: Optional[_ReadAhead]):
    """Stop the prefetch pump when the device stage aborts into a CPU
    re-run (_CapacityExceeded / ExecutionError): the re-run opens a
    FRESH source iterator, so the old pump must not keep reading the
    abandoned one.  _HighCardinality / _KeyedRoute pass through untouched
    — their replay paths keep consuming this same iterator."""
    try:
        yield
    except (_HighCardinality, _KeyedRoute):
        raise
    except BaseException:
        if ra is not None:
            ra.close()
        raise


class _BufferedExec(ExecutionPlan):
    """In-memory stand-in for a stage source whose batches were already
    pulled by a peek (optionally chaining the still-live remainder)."""

    def __init__(self, template: ExecutionPlan, batches: list, tail=None):
        super().__init__()
        self._template = template
        self._batches = batches
        self._tail = tail

    @property
    def schema(self) -> pa.Schema:
        return self._template.schema

    def output_partitioning(self) -> Partitioning:
        return self._template.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return []

    def with_new_children(self, children):
        return self

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        yield from self._batches
        if self._tail is not None:
            yield from self._tail


# Compiled-kernel cache: plans are rebuilt per query, but the fused kernel
# is a pure function of the stage's structural signature — reuse the jitted
# callable (and with it XLA's compilation cache) across plan instances.
_KERNEL_CACHE: dict[tuple, object] = {}


# ----------------------------------------------------------- substitution
def _subst(e: pe.PhysicalExpr, mapping: list[pe.PhysicalExpr]) -> pe.PhysicalExpr:
    """Rewrite ``e`` (defined over an intermediate projection schema) onto
    the stage source schema by inlining the producing expressions."""
    if isinstance(e, pe.Col):
        return mapping[e.index]
    if isinstance(e, pe.Binary):
        return pe.Binary(_subst(e.left, mapping), e.op, _subst(e.right, mapping))
    if isinstance(e, pe.Not):
        return pe.Not(_subst(e.expr, mapping))
    if isinstance(e, pe.Negative):
        return pe.Negative(_subst(e.expr, mapping))
    if isinstance(e, pe.IsNull):
        return pe.IsNull(_subst(e.expr, mapping), e.negated)
    if isinstance(e, pe.InList):
        return pe.InList(_subst(e.expr, mapping), e.items, e.negated)
    if isinstance(e, pe.Like):
        return pe.Like(_subst(e.expr, mapping), e.pattern, e.negated)
    if isinstance(e, pe.Case):
        return pe.Case(
            tuple((_subst(w, mapping), _subst(t, mapping)) for w, t in e.whens),
            _subst(e.else_expr, mapping) if e.else_expr is not None else None,
            e.out_type,
        )
    if isinstance(e, pe.Cast):
        return pe.Cast(_subst(e.expr, mapping), e.to_type)
    if isinstance(e, pe.ScalarFn):
        return pe.ScalarFn(
            e.fname, tuple(_subst(a, mapping) for a in e.args), e.out_type
        )
    if isinstance(e, (pe.Lit, pe.IntervalLit)):
        return e
    raise ExecutionError(f"cannot substitute through {type(e).__name__}")


@dataclasses.dataclass
class DeviceJoinSpec:
    """A PK-FK join folded INTO the fused device stage (SURVEY §7 hard
    part: hash join on device).

    Scope: inner single-key equi-join with UNIQUE build keys (every TPC-H
    join).  The build side (smaller input) collects once on host, sorts by
    key and ships [m]-sized arrays; each probe batch joins ON DEVICE with
    a searchsorted + gather — static shapes, no dynamic output: the match
    mask simply folds into the stage's row mask, so the joined rows feed
    the fused aggregate without EVER materializing the join.
    """

    build: ExecutionPlan  # collected on host, must have unique keys
    probe_key: pe.PhysicalExpr  # over the probe (source) schema
    build_key_index: int  # plain column of the build schema
    build_cols: list[int]  # build columns the stage reads, virtual order
    # (group-only build columns resolve on HOST at materialize time; only
    # the ones the kernel reads ship to the device — see _join_slots)


@dataclasses.dataclass
class _FusedStage:
    """The flattened eligible subtree, rewritten onto the source schema."""

    source: ExecutionPlan
    filters: list[pe.PhysicalExpr]
    group_exprs: list[tuple[pe.PhysicalExpr, str]]
    aggs: list[AggSpec]
    mode: str
    join: Optional[DeviceJoinSpec] = None


def _flatten(
    agg: HashAggregateExec, fold_join: bool = True
) -> Optional[_FusedStage]:
    chain: list[ExecutionPlan] = []
    node = agg.input
    while isinstance(node, (FilterExec, ProjectionExec, RenameSchemaExec)):
        chain.append(node)
        node = node.children()[0]
    source = node
    mapping: list[pe.PhysicalExpr] = [
        pe.Col(i, f.name) for i, f in enumerate(source.schema)
    ]
    filters: list[pe.PhysicalExpr] = []
    try:
        for op in reversed(chain):
            if isinstance(op, RenameSchemaExec):
                continue
            if isinstance(op, FilterExec):
                filters.append(_subst(op.predicate, mapping))
            else:
                mapping = [_subst(e, mapping) for e, _ in op.exprs]
        group_exprs = [(_subst(g, mapping), name) for g, name in agg.group_exprs]
        aggs = [
            dataclasses.replace(
                a,
                arg=_subst(a.arg, mapping) if a.arg is not None else None,
                arg2=_subst(a.arg2, mapping) if a.arg2 is not None else None,
            )
            for a in agg.aggs
        ]
    except ExecutionError:
        return None
    fused = _FusedStage(source, filters, group_exprs, aggs, agg.mode)
    if fold_join:
        return _maybe_fold_join(fused) or fused
    return fused


def _cols_used(e: pe.PhysicalExpr, out: set) -> None:
    if isinstance(e, pe.Col):
        out.add(e.index)
    for name in ("left", "right", "expr", "else_expr"):
        sub = getattr(e, name, None)
        if sub is not None:
            _cols_used(sub, out)
    for name in ("args",):
        for sub in getattr(e, name, ()) or ():
            _cols_used(sub, out)
    if isinstance(e, pe.Case):
        for w, t in e.whens:
            _cols_used(w, out)
            _cols_used(t, out)


def _shift_cols(e: pe.PhysicalExpr, remap: dict) -> pe.PhysicalExpr:
    """Rewrite column indexes through ``remap`` (join schema → probe +
    virtual build columns)."""
    mapping = [None] * (max(remap) + 1 if remap else 0)
    for i, j in remap.items():
        mapping[i] = pe.Col(j, f"c{j}")
    return _subst(e, mapping)


def _maybe_fold_join(fused: _FusedStage) -> Optional[_FusedStage]:
    """Fold an eligible HashJoinExec source into a DeviceJoinSpec."""
    from ..exec.joins import HashJoinExec

    join = fused.source
    if not isinstance(join, HashJoinExec):
        return None
    if (
        join.join_type != "inner"
        or len(join.on) != 1
        or join.filter is not None
    ):
        return None
    lkey, rkey = join.on[0]
    if not isinstance(lkey, pe.Col):
        return None  # build key must be a plain column (sortable table)
    probe = join.right
    left_n = len(join.left.schema)
    probe_n = len(probe.schema)

    def _int_key(t) -> bool:
        return pa.types.is_integer(t) or pa.types.is_date32(t)

    # float keys would truncate through the int64 key path and match rows
    # SQL equality never joins: integer/date keys only
    if not _int_key(join.left.schema.field(lkey.index).type):
        return None
    try:
        if not _int_key(K._infer_pa_type(rkey, probe.schema)):
            return None
    except Exception:
        return None

    # which join-schema columns does the stage actually read?
    used: set = set()
    for f in fused.filters:
        _cols_used(f, used)
    for g, _ in fused.group_exprs:
        _cols_used(g, used)
    for a in fused.aggs:
        if a.arg is not None:
            _cols_used(a.arg, used)
        if a.arg2 is not None:
            _cols_used(a.arg2, used)

    build_cols: list[int] = []
    remap: dict = {}
    for i in sorted(used):
        if i >= left_n:
            remap[i] = i - left_n  # probe side, shifted onto probe schema
        else:
            if i not in build_cols:
                build_cols.append(i)
            remap[i] = probe_n + build_cols.index(i)

    # group keys on the build side must be PLAIN build columns AND the
    # probe join key must itself be a group key, so materialize can
    # resolve them (unique build keys => functional dependency)
    probe_key = rkey
    group_has_build = False
    key_in_groups = False
    for g, _name in fused.group_exprs:
        gused: set = set()
        _cols_used(g, gused)
        if any(i < left_n for i in gused):
            if not (isinstance(g, pe.Col) and g.index < left_n):
                return None
            group_has_build = True
        elif (
            isinstance(g, pe.Col)
            and g.index >= left_n
            and isinstance(probe_key, pe.Col)
            and g.index - left_n == probe_key.index
        ):
            key_in_groups = True
    if group_has_build and not key_in_groups:
        return None

    try:
        filters = [_shift_cols(f, remap) for f in fused.filters]
        group_exprs = [
            (_shift_cols(g, remap), name) for g, name in fused.group_exprs
        ]
        aggs = [
            dataclasses.replace(
                a,
                arg=_shift_cols(a.arg, remap) if a.arg is not None else None,
                arg2=(
                    _shift_cols(a.arg2, remap)
                    if a.arg2 is not None
                    else None
                ),
            )
            for a in fused.aggs
        ]
    except ExecutionError:
        return None

    return _FusedStage(
        probe,
        filters,
        group_exprs,
        aggs,
        fused.mode,
        join=DeviceJoinSpec(
            join.left, probe_key, lkey.index, build_cols
        ),
    )


class TpuStageExec(ExecutionPlan):
    """Fused scan→filter→project→aggregate stage on device.

    Replaces the interpreted per-batch operator chain (the reference's hot
    loop, ``shuffle_writer.rs:214-256``) with one jit-compiled XLA kernel
    invoked once per batch; partial states accumulate on device and only
    [num_groups]-sized results return to host.  Runtime group-capacity
    overflow falls back to re-executing the original CPU subtree.
    """

    def __init__(
        self, original: HashAggregateExec, fused: _FusedStage, config: BallistaConfig
    ):
        super().__init__()
        self.original = original
        self.fused = fused
        self.config = config
        self._schema = original.schema

        # device-join stages compile over a VIRTUAL schema: the probe
        # schema plus one appended field per referenced build column
        probe_schema = fused.source.schema
        if fused.join is not None:
            virtual = list(probe_schema) + [
                fused.join.build.schema.field(i) for i in fused.join.build_cols
            ]
            compile_schema = pa.schema(virtual)
        else:
            compile_schema = probe_schema
        self._probe_ncols = len(probe_schema)

        compiler = K.JaxExprCompiler(compile_schema)
        filter_closure = None
        if fused.filters:
            pred = fused.filters[0]
            for f in fused.filters[1:]:
                pred = pe.Binary(pred, "AND", f)
            filter_closure = compiler._lower_or_leaf(pred)
        x32 = K.precision_mode() == "x32"
        # two passes: count(col) resolves AFTER the other aggregates so it
        # can reuse a column leaf's validity that is shipping anyway,
        # instead of adding a duplicate mask leaf
        pending: list = [None] * len(fused.aggs)
        count_cols: list[tuple[int, pe.Col]] = []
        for idx, a in enumerate(fused.aggs):
            if a.arg is None:
                if a.func not in ("count", "count_star"):
                    raise K.NotLowerable(a.func)
                pending[idx] = (K.KernelAggSpec("count_star", False), None)
                continue
            if a.func == "median":
                # exact device median: the keyed path sorts each group's
                # values (order-pair encoded) and gathers the two middle
                # rows — no host percentile pass.  Needs the keyed
                # buffering, so the stage is FORCED onto that route.
                if fused.mode == PARTIAL:
                    raise K.NotLowerable("median is single-stage")
                if not fused.group_exprs:
                    raise K.NotLowerable("global median stays on CPU")
                if not isinstance(a.arg, pe.Col):
                    raise K.NotLowerable("median over expression")
                at = compile_schema.field(a.arg.index).type
                if not (
                    pa.types.is_floating(at) or pa.types.is_integer(at)
                ):
                    raise K.NotLowerable(f"median over {at}")
                compiler.ord_pair_column(a.arg)  # ships the encoded pair
                pending[idx] = ("median", a.arg.index)
                continue
            if a.func == "count_distinct":
                # per-group distinct count rides the same sorted-argument
                # pass as median: run-starts among each group's sorted
                # valid values, one cumsum (q16's count(distinct
                # ps_suppkey) shape)
                if fused.mode == PARTIAL:
                    raise K.NotLowerable("count_distinct is single-stage")
                if not fused.group_exprs:
                    raise K.NotLowerable("global count_distinct on CPU")
                if not isinstance(a.arg, pe.Col):
                    raise K.NotLowerable("count_distinct over expression")
                at = compile_schema.field(a.arg.index).type
                if not (
                    pa.types.is_floating(at)
                    or pa.types.is_integer(at)
                    or pa.types.is_date(at)
                ):
                    raise K.NotLowerable(f"count_distinct over {at}")
                compiler.ord_pair_column(a.arg)
                pending[idx] = ("cdist", a.arg.index)
                continue
            if a.func == "corr":
                # Pearson r on the keyed path, PER-GROUP centered (the
                # CPU operator centers by the global mean; per-group is
                # strictly better conditioned).  Null/NaN in either
                # argument drops the row pairwise (pandas semantics).
                if fused.mode == PARTIAL:
                    raise K.NotLowerable("corr is single-stage")
                if not fused.group_exprs:
                    raise K.NotLowerable("global corr stays on CPU")
                for e in (a.arg, a.arg2):
                    if not isinstance(e, pe.Col):
                        raise K.NotLowerable("corr over expression")
                    at = compile_schema.field(e.index).type
                    if not (
                        pa.types.is_floating(at) or pa.types.is_integer(at)
                    ):
                        raise K.NotLowerable(f"corr over {at}")
                if x32:
                    compiler.pair_column(a.arg)
                    compiler.pair_column(a.arg2)
                else:
                    compiler._leaf_column(a.arg)
                    compiler._leaf_column(a.arg2)
                pending[idx] = ("corr", a.arg.index, a.arg2.index)
                continue
            if a.func in ("stddev", "stddev_pop", "var", "var_pop"):
                # variance family lowers as compensated Σx + Σx² (+ the
                # sum's own count): x32 ships x as an exact double-float
                # pair and squares it error-free via Dekker two-product,
                # so the host-side cancellation (Σx² − (Σx)²/n) starts
                # from ~48-bit-exact moments; a conditioning guard at
                # materialize falls back to CPU when even that is not
                # enough (κ = Σx²/(n·var) past 1e8)
                if fused.mode == PARTIAL:
                    raise K.NotLowerable("variance family is single-stage")
                if a.arg is None:
                    raise K.NotLowerable(a.func)
                ddof = 0 if a.func.endswith("_pop") else 1
                use_sqrt = a.func.startswith("stddev")
                if x32:
                    if not isinstance(a.arg, pe.Col):
                        raise K.NotLowerable("x32 variance over expression")
                    at = compile_schema.field(a.arg.index).type
                    if not (
                        pa.types.is_floating(at) or pa.types.is_integer(at)
                    ):
                        raise K.NotLowerable(f"variance over {at}")
                    pairc = compiler.pair_column(a.arg)
                    parts = [
                        (K.KernelAggSpec("sum", True, pair=True), pairc),
                        (
                            K.KernelAggSpec("sum", True, pair=True),
                            K.square_pair_closure(pairc),
                        ),
                    ]
                else:
                    c = compiler._lower(a.arg)
                    parts = [
                        (K.KernelAggSpec("sum", True), c),
                        (K.KernelAggSpec("sum", True), K.square_closure(c)),
                    ]
                pending[idx] = ("var", ddof, use_sqrt, parts)
                continue
            if a.func not in ("count", "sum", "avg", "min", "max"):
                # count_distinct, udaf:*, anything unknown: reject at PLAN
                # time so no partition pays a failed device trace
                raise K.NotLowerable(a.func)
            if a.func == "count" and isinstance(a.arg, pe.Col):
                count_cols.append((idx, a.arg))
                continue
            t = (
                compile_schema.field(a.arg.index).type
                if isinstance(a.arg, pe.Col)
                else None
            )
            if a.func in ("min", "max"):
                if t is None:
                    try:
                        t = K._infer_pa_type(a.arg, compile_schema)
                    except Exception:
                        t = None
                int_mm = t is not None and (
                    pa.types.is_integer(t) or pa.types.is_date32(t)
                )
                if x32 and not int_mm and not (
                    t is not None and pa.types.is_float32(t)
                ):
                    # f64 min/max must not come back f32-rounded: a
                    # sub-ulp wrong extremum breaks decorrelated equality
                    # (q2's ps_supplycost = (select min(...))).  Plain f64
                    # COLUMNS ride an order-preserving (hi, lo) i32 pair —
                    # lexicographic integer extremum IS the f64 extremum,
                    # bit-exact; computed f64 expressions (already
                    # f32-rounded on device) stay on CPU
                    if isinstance(a.arg, pe.Col) and t is not None and (
                        pa.types.is_float64(t)
                    ):
                        pending[idx] = (
                            K.KernelAggSpec(a.func, True, ord_pair=True),
                            compiler.ord_pair_column(a.arg),
                        )
                        continue
                    raise K.NotLowerable("x32 min/max over f64 expression")
                pending[idx] = (
                    K.KernelAggSpec(a.func, True, int_minmax=int_mm),
                    compiler._lower(a.arg),
                )
                continue
            if (
                x32
                and a.func == "avg"
                and t is not None
                and (pa.types.is_int64(t) or pa.types.is_uint64(t))
            ):
                # avg(i64) rides as an f32 (hi, lo) pair: each VALUE is
                # 48-bit exact, the float average is good to ~1e-7 — no
                # i32 narrowing cliff.  sum(i64) keeps the CPU fallback
                # past i32 range: its INT output must be bit-exact, and
                # block-level f32 partials round at 2^24-scale totals.
                pending[idx] = (
                    K.KernelAggSpec(a.func, True, pair=True),
                    compiler.pair_column(a.arg),
                )
                continue
            pending[idx] = (
                K.KernelAggSpec(a.func, True), compiler._lower(a.arg)
            )
        for idx, colarg in count_cols:
            # count(col) needs only the validity mask — wide i64 / string
            # columns never ship values (round-2 x32 cliff); reuse an
            # existing leaf's validity when the column ships anyway
            existing = None
            for cand in (f"col_{colarg.index}", f"col_{colarg.index}__pair"):
                if cand in compiler.leaves:
                    existing = f"{cand}__valid"
                    break
            if existing is not None:
                closure = (lambda vn: lambda env: (None, env[vn]))(existing)
            else:
                closure = compiler.validity_only(colarg)
            pending[idx] = (K.KernelAggSpec("count", True), closure)
        # flatten per-OUTPUT entries into kernel specs + an emission plan
        # (the variance family expands one output into two kernel sums)
        specs: list[K.KernelAggSpec] = []
        arg_closures: list[Optional[K.JaxClosure]] = []
        emit: list[tuple] = []
        self._median_cols: list[int] = []
        self._corr_cols: list[int] = []
        self._corr_pairs: list[tuple] = []
        for entry in pending:
            if isinstance(entry, tuple) and entry[0] == "var":
                _, ddof, use_sqrt, parts = entry
                emit.append(
                    ("var", len(specs), len(specs) + 1, ddof, use_sqrt)
                )
                for s, c in parts:
                    specs.append(s)
                    arg_closures.append(c)
            elif isinstance(entry, tuple) and entry[0] in ("median", "cdist"):
                ci = entry[1]
                if ci in self._median_cols:
                    slot = self._median_cols.index(ci)
                else:
                    slot = len(self._median_cols)
                    self._median_cols.append(ci)
                emit.append((entry[0], slot))
            elif isinstance(entry, tuple) and entry[0] == "corr":
                slots = []
                for ci in (entry[1], entry[2]):
                    if ci in self._corr_cols:
                        slots.append(self._corr_cols.index(ci))
                    else:
                        slots.append(len(self._corr_cols))
                        self._corr_cols.append(ci)
                # r is symmetric: canonicalize so corr(x,y) and
                # corr(y,x) share one device pass
                pair = tuple(sorted(slots))
                if pair in self._corr_pairs:
                    pslot = self._corr_pairs.index(pair)
                else:
                    pslot = len(self._corr_pairs)
                    self._corr_pairs.append(pair)
                emit.append(("corr", pslot))
            else:
                s, c = entry
                emit.append(("plain", len(specs)))
                specs.append(s)
                arg_closures.append(c)
        self._emit = emit
        # median/count_distinct/corr require the keyed path's buffers
        self._needs_keyed = bool(self._median_cols) or bool(
            self._corr_pairs
        )
        self.leaves = compiler.leaves
        self.specs = specs
        self.capacity = config.tpu_segment_capacity if fused.group_exprs else 1
        self.max_capacity = (
            config.tpu_max_capacity if fused.group_exprs else 1
        )
        self.keyed_buffer_bytes = config.tpu_keyed_buffer_mb << 20
        self._filter_closure = filter_closure
        self._arg_closures = arg_closures

        # device-join plumbing: leaves over virtual (build-side) columns
        # are gathered ON DEVICE by the join wrapper, never read from the
        # probe batch; pair/validity-only kinds and host-evaluated exprs
        # cannot reference the build side
        self._join_slots: dict[str, int] = {}
        if fused.join is not None:
            for name, spec in self.leaves.items():
                if spec.kind == "cpu_expr":
                    used: set = set()
                    _cols_used(spec.cpu_expr, used)
                    if any(i >= self._probe_ncols for i in used):
                        raise K.NotLowerable("host expr over build side")
                    continue
                if spec.col_index >= self._probe_ncols:
                    if spec.kind != "column":
                        raise K.NotLowerable(f"join leaf kind {spec.kind}")
                    spec.kind = "join_col"
                    j = spec.col_index - self._probe_ncols
                    self._join_slots[name] = j
                    self._join_slots[f"{name}__valid"] = j
        # only the build columns the KERNEL reads ship to the device
        # (group-only build columns resolve on host at materialize)
        self._device_build_cols: list[int] = []
        if fused.join is not None and self._join_slots:
            device_js = sorted(set(self._join_slots.values()))
            dense = {j: k for k, j in enumerate(device_js)}
            self._join_slots = {
                n: dense[j] for n, j in self._join_slots.items()
            }
            self._device_build_cols = [
                fused.join.build_cols[j] for j in device_js
            ]

        self._leaf_names = list(self.leaves.keys())
        self._flat_names = K.flat_arg_names(self.leaves)
        self._mode = K.precision_mode()
        join_sig = ()
        if fused.join is not None:
            join_sig = (
                str(fused.join.probe_key),
                fused.join.build_key_index,
                tuple(fused.join.build_cols),
                str(fused.join.build.schema),
            )
        sig = (
            tuple(str(f) for f in fused.filters),
            (
                tuple(
                    (s.func, s.pair, s.int_minmax, s.ord_pair)
                    for s in specs
                ),
                tuple(str(a.arg) for a in fused.aggs),
                tuple(e[0] for e in emit),
            ),
            self.capacity,
            tuple(self._flat_names),
            str(fused.source.schema),
            self._mode,
            join_sig,
        )
        self._sig = sig

        # group plan: which GROUP BY positions encode on host vs resolve
        # from the build table at materialize (functionally dependent on
        # the probe join key — unique build keys)
        self._group_plan: list[tuple[str, int]] = []
        slot = 0
        for g, _n in fused.group_exprs:
            if (
                fused.join is not None
                and isinstance(g, pe.Col)
                and g.index >= self._probe_ncols
            ):
                self._group_plan.append(("build", g.index - self._probe_ncols))
            else:
                self._group_plan.append(("enc", slot))
                slot += 1
        self._n_encoded_groups = slot
        # group exprs at host-ENCODED positions, in slot order (the
        # device key-encode path evaluates these raw and derives codes
        # on device)
        self._enc_group_exprs = [
            g
            for (g, _n), (kind, _s) in zip(
                fused.group_exprs, self._group_plan
            )
            if kind == "enc"
        ]
        self._jk_slot = self._jk_pos = None
        if fused.join is not None:
            pk = fused.join.probe_key
            for pos, (g, _n) in enumerate(fused.group_exprs):
                if (
                    self._group_plan[pos][0] == "enc"
                    and isinstance(g, pe.Col)
                    and isinstance(pk, pe.Col)
                    and g.index == pk.index
                ):
                    self._jk_slot = self._group_plan[pos][1]
                    self._jk_pos = pos
                    break
            if any(k == "build" for k, _ in self._group_plan) and (
                self._jk_slot is None
            ):
                raise K.NotLowerable("build group keys without probe key")
        self._build_state = None  # lazily prepared per instance
        self._build_lock = __import__("threading").Lock()
        # (exprs, n_out) installed by a downstream ShuffleWriterExec so
        # the hash-partition ids ride the device instead of the host
        self._shuffle_hint = None
        # whole-stage fusion (ballista.tpu.whole_stage_fusion): set per
        # execute from the ops/fusion.py plan — _fuse_pid asks the fused
        # runner to derive the shuffle pid column inside its trace, and
        # _fused_pids carries the result to _materialize
        self._fuse_pid = False
        self._fused_pids = None

        # raw kernel kept for mesh gang execution: shard_map needs the
        # untraced function to wrap with the cross-chip reduction
        self._raw_kernel, self._jit_kernel = self._kernel_for(self.capacity)

    def _timed_jit(self, fn):
        """Wrap a shared jitted kernel with THIS stage's compile/execute
        attribution: a call that grows the jit's compiled-signature cache
        paid trace + XLA compilation (jit compiles synchronously inside
        the call; only execution is async), everything else is dispatch.
        Backs the /api/jobs/{id}/profile compile-vs-execute split."""
        import time as _t

        metrics = self.metrics
        cache_size = getattr(fn, "_cache_size", None)

        def call(*args):
            before = cache_size() if cache_size is not None else -1
            t0 = _t.perf_counter_ns()
            out = fn(*args)
            dt = _t.perf_counter_ns() - t0
            if before >= 0 and cache_size() > before:
                metrics.add("tpu_compile_ns", dt)
                metrics.add("kernel_compiles", 1)
            else:
                metrics.add("tpu_execute_ns", dt)
            return out

        return call

    def _note_kernel_cache(self, hit: bool) -> None:
        """Process-wide compiled-kernel cache accounting (plans rebuild
        per query; a miss here means a fresh trace + XLA compile)."""
        self.metrics.add(
            "compile_cache_hits" if hit else "compile_cache_misses", 1
        )

    def _kernel_for(self, capacity: int, dense: bool = False):
        """(raw, jitted) fused kernel at the given segment capacity.

        Group cardinality is data-dependent; capacities grow in 4x buckets
        (execute-time) so the number of distinct XLA compilations stays
        logarithmic while the segment table tracks the data.  ``dense``
        selects the direct-probe join wrapper (decided per execution from
        the prepared build side's key span).
        """
        key = (
            self._sig[:2] + (capacity,) + self._sig[3:]
            + (("dense",) if dense else ())
            + K.algo_cache_token()
        )
        cached = _KERNEL_CACHE.get(key)
        self._note_kernel_cache(cached is not None)
        if cached is None:
            import jax

            with self.metrics.timer("tpu_compile_ns"):
                inner = K.make_partial_agg_kernel(
                    self._filter_closure,
                    self._arg_closures,
                    self.specs,
                    capacity,
                    self._flat_names,
                    # variance moments need the per-element-compensated scan
                    force_sort=any(e[0] == "var" for e in self._emit),
                )
                if self.fused.join is not None:
                    kernel = K.make_join_kernel(
                        inner,
                        self._flat_names,
                        self._join_slots,
                        len(self._device_build_cols),
                        dense=dense,
                    )
                else:
                    kernel = inner
                cached = (kernel, jax.jit(kernel))
            _KERNEL_CACHE[key] = cached
        return cached[0], self._timed_jit(cached[1])

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.fused.source.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.fused.source]

    def with_new_children(self, children):
        new_original = self.original.with_new_children(
            [_replace_leaf(self.original.input, self.fused.source, children[0])]
        )
        # same fold-then-retry ladder as maybe_accelerate: a shape that
        # lowers only with the join on CPU must not lose acceleration here
        for fold in (True, False):
            fused = _flatten(new_original, fold_join=fold)
            if fused is None:
                return new_original
            try:
                return TpuStageExec(new_original, fused, self.config)
            except K.NotLowerable:
                if fused.join is None:
                    return new_original
        return new_original

    def __str__(self) -> str:
        return (
            f"TpuStageExec: mode={self.fused.mode}, "
            f"gby={[n for _, n in self.fused.group_exprs]}, "
            f"aggr={[a.name for a in self.fused.aggs]}, "
            f"filters={len(self.fused.filters)}, capacity={self.capacity}"
        )

    def install_shuffle_hint(self, exprs, n_out: int) -> None:
        """Downstream ShuffleWriterExec announces its hash partitioning
        (exprs over THIS stage's output schema, n_out partitions):
        ``_materialize`` then computes the partition-id column through
        the jitted device hash kernel (``K.device_partition_ids``) and
        appends it as ``SHUFFLE_PID_COLUMN``, so the writer's split skips
        the host hash.  Assignments match the host partitioner
        bit-for-bit by construction; keys the kernel can't hash (strings,
        computed expressions) simply leave the hint unused."""
        self._shuffle_hint = (list(exprs), int(n_out))

    def _fused_pid_spec(self):
        """``(slots, n_out)`` when the shuffle pid column can be derived
        INSIDE the fused dispatch, else None.

        Eligible exactly when every hint key is a host-encoded group
        column with a device-hashable type: the group table then holds
        every kept group's key codes at dispatch time, so decoding them
        feeds the same ``partition_id_hash`` the post-materialize kernel
        would run — over identical values, hence bit-identical pids —
        without a second dispatch.  ``slots`` is ``[(enc_slot, out_pos),
        ...]`` in hint-key order (the hash combine is order-sensitive).
        """
        hint = self._shuffle_hint
        if hint is None or not self.fused.group_exprs:
            return None
        exprs, n_out = hint
        if not exprs or n_out <= 0 or n_out > K.PID_MAX_PARTITIONS:
            return None
        slots = []
        for e in exprs:
            if not isinstance(e, pe.Col) or not (
                0 <= e.index < len(self._group_plan)
            ):
                return None
            kind, slot = self._group_plan[e.index]
            if kind != "enc":
                return None
            t = self._schema.field(e.index).type
            if not (
                pa.types.is_integer(t)
                or pa.types.is_floating(t)
                or pa.types.is_boolean(t)
                or pa.types.is_date(t)
                or pa.types.is_timestamp(t)
            ):
                return None
            slots.append((slot, e.index))
        return slots, n_out

    # ------------------------------------------------------------ execute
    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        try:
            yield from self._execute_device(partition, ctx)
            return
        except _JoinIneligible:
            # non-unique or unrepresentable build keys: run the join on
            # CPU and keep ONLY the aggregate on device (round-2 shape)
            self.metrics.add("join_fallback", 1)
            yield from self._nojoin_stage().execute(partition, ctx)
            return
        except _SmallInput as si:
            # partition under tpu.min_rows: run the CPU operator path over
            # the batches the peek already pulled (no source re-scan), and
            # OUTSIDE this try so real CPU errors propagate instead of
            # being mistaken for device failures
            self.metrics.add("cpu_fallback", 1)
            cpu_plan = self.original.with_new_children(
                [
                    _replace_leaf(
                        self.original.input,
                        self.fused.source,
                        _BufferedExec(self.fused.source, si.batches),
                    )
                ]
            )
        except _KeyedRoute as kr:
            # groups ~ rows: device-keyed aggregation (group ids assigned
            # by the device sort, no host hash encode); late key overflow,
            # cardinality past the segment ceiling, or device OOM (the
            # keyed path buffers the stage input in HBM) drop to the CPU
            # operator path below
            self.metrics.add("keyed_path", 1)
            tail = _TrackingIter(kr.tail)
            try:
                host_states, groups, n_rows_in, aux = (
                    self._run_keyed(kr.batches, tail, kr.key_encoders, ctx)
                )
                out_batches = list(
                    self._materialize(
                        host_states, kr.key_encoders, groups, n_rows_in,
                        ctx, partition, aux=aux,
                    )
                )
            except (_CapacityExceeded, ExecutionError, RuntimeError):
                self.metrics.add("tpu_fallback", 1)
                if not tail.consumed:
                    # failed before touching the live source: replay the
                    # already-buffered batches + chain the tail (no
                    # re-scan, _HighCardinality-style)
                    cpu_plan = self.original.with_new_children(
                        [
                            _replace_leaf(
                                self.original.input,
                                self.fused.source,
                                _BufferedExec(
                                    self.fused.source,
                                    [b for b, _ in kr.batches],
                                    tail,
                                ),
                            )
                        ]
                    )
                else:
                    if kr.ra is not None:
                        kr.ra.close()
                    cpu_plan = self.original
                yield from cpu_plan.execute(partition, ctx)
                return
            yield from out_batches
            return
        except _HighCardinality as hc:
            # groups ~ rows with highcard_mode=cpu: hand the stage to the
            # C++ hash aggregate, replaying the consumed batch + chaining
            # the live source
            self.metrics.add("highcard_fallback", 1)
            cpu_plan = self.original.with_new_children(
                [
                    _replace_leaf(
                        self.original.input,
                        self.fused.source,
                        _BufferedExec(self.fused.source, hc.batches, hc.tail),
                    )
                ]
            )
        except _CapacityExceeded:
            self.metrics.add("tpu_fallback", 1)
            if self.fused.join is not None:
                # a join-fused stage's gid table holds every distinct
                # PROBE key, pre-filter — q3 SF10 has 15M orderkeys
                # against the 2M ceiling even though only 1.26M groups
                # survive the join.  The round-2 shape (join on CPU,
                # aggregate on device over POST-join rows) keys the gid
                # table on surviving groups instead, which is how r03
                # captured q3 at 1.13x; its own execute() still falls to
                # full CPU if even that overflows.
                self.metrics.add("join_fallback", 1)
                yield from self._nojoin_stage().execute(partition, ctx)
                return
            cpu_plan = self.original
        except (ExecutionError, _JaxRuntimeError):
            # a column type slipped past plan-time lowering checks, or
            # the device/compiler failed mid-stage (BENCH_SUITE_r05 h2o:
            # a SIGKILLed tpu_compile_helper surfaced as JaxRuntimeError
            # and killed the query instead of degrading) — re-run this
            # partition on the CPU operator path.  Only jax's runtime
            # error is caught (a blanket RuntimeError would silently
            # convert genuine bugs into fallbacks); Cancelled is a
            # BallistaError sibling and still propagates.
            self.metrics.add("tpu_fallback", 1)
            cpu_plan = self.original
        yield from cpu_plan.execute(partition, ctx)

    def _cache_key(self, ctx: TaskContext):
        """(provider, signature) when the stage source is a cacheable scan."""
        if not ctx.config.tpu_cache_columns:
            return None
        from ..exec.operators import ScanExec

        node = self.fused.source
        while isinstance(node, RenameSchemaExec):
            node = node.children()[0]
        if not isinstance(node, ScanExec):
            return None
        # leaf col_index values are scan-relative, so the signature must pin
        # the scan's actual column identity (projection / schema names) or two
        # queries over different columns of the same provider would collide
        source_cols = ",".join(self.fused.source.schema.names)
        sig = "|".join(
            [
                f"{s.kind}:{s.col_index}:{s.cpu_expr}" for s in self.leaves.values()
            ]
            + [str(g) for g, _ in self.fused.group_exprs]
            + [f"proj={node.projection}", f"cols={source_cols}"]
            + [str(ctx.batch_size), f"cap={self.capacity}", self._mode]
        )
        return node.provider, sig

    def _execute_device(
        self, partition: int, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        from . import device_cache

        fused = self.fused
        build = None
        if fused.join is not None:
            build = self._prepare_build(ctx)
            if build[0] == "empty":
                # inner join against an empty build side: no rows at all
                yield from self._materialize(
                    None, [], None, 0, ctx, partition
                )
                return
        # the device column cache keys on scan inputs; join stages add
        # build-side state and median stages must route keyed, so both
        # skip it (probe sources are usually joins/filters anyway)
        ck = (
            self._cache_key(ctx)
            if fused.join is None and not self._needs_keyed
            else None
        )
        # whole-stage fusion plan (ballista.tpu.whole_stage_fusion;
        # default off keeps today's dispatch sequence byte-identical):
        # when every compute op lands in segment 0, batches are retained
        # and the stage executes as ONE fused dispatch even without a
        # cache key — with the shuffle pid column derived inside the
        # same trace when the pid op fused too
        self._fuse_pid = False
        fusion_retain = False
        if (
            fused.join is None
            and not self._needs_keyed
            and self.config.tpu_whole_stage_fusion
        ):
            from .fusion import plan_segments, stage_ops

            fplan = plan_segments(stage_ops(self), _fusion_max_ops())
            self.metrics.add("fused_segments", len(fplan.segments))
            self.metrics.add(
                "fused_ops_per_dispatch", fplan.max_segment_ops
            )
            fusion_retain = fplan.compute_fused()
            self._fuse_pid = fplan.pid_fused()
        if ck is not None:
            cached = device_cache.get(ck[0], partition, ck[1])
            if cached is not None:
                entries, key_encoders, group_table, n_rows_in, cap = cached
                with self.metrics.timer("tpu_stage_time_ns"):
                    with self.metrics.timer("device_time_ns"):
                        host_states = self._run_fused(
                            entries, cap,
                            group_table if fused.group_exprs else None,
                            key_encoders,
                        )
                self.metrics.add("cache_hits", 1)
                yield from self._materialize(
                    host_states, key_encoders, group_table, n_rows_in, ctx,
                    partition,
                )
                return

        src = fused.source.execute(partition, ctx)
        coalesce = _shuffle_coalesce_rows(self.config)
        if coalesce > 0 and _reads_shuffle(fused.source):
            # shuffle readers yield one fragment per map task; combine
            # them to the target batch size on host so each device
            # dispatch moves a full batch (fetch + coalesce then overlap
            # device compute through the _ReadAhead pump below)
            from .bridge import coalesce_batches

            src = coalesce_batches(src, coalesce, self.metrics)
        min_rows = self.config.tpu_min_rows
        if min_rows > 0:
            # peek: kernel-launch/compile latency dominates tiny inputs, so
            # partitions under the threshold run the CPU operator path
            # (signalled to execute() with the buffered batches)
            import itertools

            buffered: list[pa.RecordBatch] = []
            total = 0
            exhausted = True
            for b in src:
                buffered.append(b)
                total += b.num_rows
                if total >= min_rows:
                    exhausted = False
                    break
            if exhausted and total < min_rows:
                raise _SmallInput(buffered)
            src = itertools.chain(buffered, src)

        depth = self.config.tpu_readahead
        ra: Optional[_ReadAhead] = None
        if depth > 0:
            src = ra = _ReadAhead(src, depth)

        from .bridge import make_key_encoder
        from .groups import GroupTable

        # encoders exist only for host-ENCODED group positions (build-side
        # group keys resolve from the build table at materialize)
        key_encoders = [
            make_key_encoder(self._schema.field(pos).type)
            for pos, (kind, _s) in enumerate(self._group_plan)
            if kind == "enc"
        ]
        group_table = GroupTable(max(self._n_encoded_groups, 1))
        entries = []

        import jax
        import jax.numpy as jnp

        acc = None
        n_rows_in = 0
        cap = self.capacity
        dense_join = build is not None and build[0] == "dense"
        _, kernel = self._kernel_for(cap, dense=dense_join)
        with _closing_on_error(ra), self.metrics.timer("tpu_stage_time_ns"):
            for batch in src:
                if batch.num_rows == 0:
                    continue
                n = batch.num_rows
                n_rows_in += n
                n_pad = K.bucket_rows(n)

                if fused.group_exprs:
                    if acc is None and not entries:
                        # pre-encode fast path: keyed-pinned stages with
                        # device-encodable keys route to _run_keyed
                        # BEFORE any host group encode — the raw key
                        # columns cross the bridge inside the fused
                        # dispatch and key_encode_time_ns stays ~0
                        fast = self._keyed_fast_encoders(batch)
                        if fast is not None:
                            raise _KeyedRoute([(batch, None)], src, fast, ra)
                    with self.metrics.timer("key_encode_time_ns"):
                        codes = self._encode_codes(batch, key_encoders)
                    if acc is None and not entries:
                        # keys the device can't take raw (i32 overflow
                        # in x32) disqualify the keyed path up front:
                        # host-assigned gids are always dense i32, so
                        # the gid-table path stays available
                        keyed_ok = self._mode != "x32" or all(
                            len(c) == 0
                            or (
                                c.min() >= -(1 << 31)
                                and c.max() < (1 << 31)
                            )
                            for c in codes
                        )
                        if self._needs_keyed:
                            # median stages live on the keyed path at any
                            # cardinality; unshippable keys → CPU (replay)
                            if keyed_ok:
                                raise _KeyedRoute(
                                    [(batch, codes)], src, key_encoders, ra
                                )
                            raise _HighCardinality([batch], src)
                        try:
                            with self.metrics.timer("key_encode_time_ns"):
                                seg = self._assign_gids(codes, group_table)
                            first_groups = group_table.n_groups
                        except _CapacityExceeded:
                            # ONE batch outran the gid table / key radix:
                            # definitionally high-cardinality
                            first_groups = None
                        if first_groups is None or _highcard_detect(
                            first_groups, n
                        ):
                            if keyed_route_wanted(self.config) and keyed_ok:
                                raise _KeyedRoute(
                                    [(batch, codes)], src, key_encoders, ra
                                )
                            if (
                                self.config.tpu_highcard_mode == "gid"
                                and first_groups is not None
                            ):
                                pass  # pinned gid-table path (A/B)
                            elif fused.join is None:
                                raise _HighCardinality([batch], src)
                            # fused device join at high cardinality:
                            # stay on the gid-table path while it can
                            # fit — but the table keys on every distinct
                            # PROBE key pre-filter, so when batch 1
                            # alone fills half the ceiling the stream
                            # total will overflow it after the host has
                            # paid the encode (q3 SF10: 15M orderkeys vs
                            # the 2M cap, overflow discovered mid-stream)
                            # — bail to the round-2 shape NOW
                            if first_groups is None or (
                                first_groups > self.max_capacity // 2
                            ):
                                raise _CapacityExceeded()
                        # first batch: shrink the segment table to the
                        # OBSERVED cardinality (2x headroom) — matmul-path
                        # FLOPs scale with capacity, so a 6-group q1 must
                        # not pay for the 1024-slot default table
                        tight = 64
                        while tight < 2 * max(1, group_table.n_groups):
                            tight *= 4
                        if tight < cap:
                            cap = min(tight, self.max_capacity)
                            _, kernel = self._kernel_for(
                                cap, dense=dense_join
                            )
                    else:
                        with self.metrics.timer("key_encode_time_ns"):
                            seg = self._assign_gids(codes, group_table)
                    # adaptive capacity: grow the segment table in 4x
                    # buckets when the data's cardinality outruns it,
                    # padding accumulated states (VERDICT round-1: fixed
                    # 4096 caps fell back to CPU on q3/h2o shapes)
                    if group_table.n_groups > cap:
                        while cap < group_table.n_groups:
                            cap *= 4
                        cap = min(cap, self.max_capacity)
                        acc = K.pad_states(self.specs, acc, cap, self._mode)
                        _, kernel = self._kernel_for(
                            cap, dense=dense_join
                        )
                        self.metrics.add("capacity_growths", 1)
                else:
                    seg = None  # all rows → group 0, synthesized on device
                if seg is not None:
                    seg = K._pad(seg, n_pad)

                with self.metrics.timer("bridge_time_ns"):
                    args, trivial_idx = self._kernel_args(
                        batch, n, n_pad, build
                    )
                with self.metrics.timer("device_time_ns"):
                    if ck is None and fusion_retain:
                        # fusion-only retention (whole-stage fusion on a
                        # non-cache-eligible stage): the entries are
                        # consumed ONCE by the fused dispatch right
                        # after this loop, so everything stays on host —
                        # no per-batch eager device op at all; the one
                        # jitted call transfers its operands in bulk
                        tail = np.arange(n_pad, dtype=np.int32) < n
                        args = [
                            tail if i in trivial_idx else a
                            for i, a in enumerate(args)
                        ]
                        seg_h = (
                            np.zeros(n_pad, dtype=np.int32)
                            if seg is None
                            else seg
                        )
                        entries.append((seg_h, tail, args))
                        continue
                    # device-built row tail mask, shared by the global
                    # valid slot and every all-true leaf companion: two
                    # eager ops replace n_pad*(1+n_trivial) host→HBM
                    # bytes on the tunnel
                    tail = jnp.arange(n_pad, dtype=jnp.int32) < n
                    args = [
                        tail if i in trivial_idx else a
                        for i, a in enumerate(args)
                    ]
                    seg_d = (
                        jnp.zeros(n_pad, dtype=jnp.int32)
                        if seg is None
                        else jax.device_put(seg)
                    )
                    if ck is not None:
                        # retained for the device cache (and the fused
                        # single-dispatch run after the loop): each arg
                        # pins on device because the entries outlive
                        # this query
                        args = [
                            a if a is tail else jax.device_put(a)
                            for a in args
                        ]
                        entries.append((seg_d, tail, args))
                    else:
                        out = kernel(seg_d, tail, *args)
                        acc = K.combine_states(
                            self.specs, acc, out, self._mode
                        )

            # Cache-eligible stages dispatch ONCE per query: a single
            # jitted call runs every entry's kernel, combines, and packs
            # (dispatches carry tens of ms of latency on the
            # tunnel-attached TPU, so per-batch dispatch was the q6/q1
            # latency floor).  The packed fetch is the only reliable
            # device sync there (block_until_ready is a no-op), so it
            # lives INSIDE the device timer: device_time_ns covers
            # queue + compute + result fetch (VERDICT round-2 weakness #2)
            with self.metrics.timer("device_time_ns"):
                if (ck is not None or fusion_retain) and entries:
                    host_states = self._run_fused(
                        entries, cap,
                        group_table if fused.group_exprs else None,
                        key_encoders,
                        # below the measured amortization floor a fused
                        # dispatch costs more than it saves: stream the
                        # retained entries per-batch instead (the cache
                        # path keeps its unconditional fused call)
                        stream=(
                            ck is None
                            and n_rows_in < _fusion_min_rows()
                        ),
                    )
                else:
                    host_states = self._fetch_states(
                        acc,
                        group_table.n_groups if fused.group_exprs else None,
                    )

        if ck is not None and entries:
            device_cache.put(
                ck[0], partition, ck[1],
                (entries, key_encoders, group_table, n_rows_in, cap),
            )
        yield from self._materialize(
            host_states, key_encoders, group_table, n_rows_in, ctx, partition
        )

    def _kernel_args(
        self, batch, n: int, n_pad: int, build
    ) -> tuple[list, set]:
        """(args, trivial_idx) — host-side leaf env + join operands for
        one batch (the bridge work shared by the gid-table and keyed
        execution paths).  ``trivial_idx`` holds positions in ``args``
        whose array is exactly the row tail mask (all-true validity):
        the device sections substitute one shared device-built iota mask
        for those instead of shipping the bytes."""
        trivial: set = set()
        env = K.build_env(batch, self.leaves, n_pad, trivial_valid=trivial)
        names = [
            nm for nm in self._flat_names if nm not in self._join_slots
        ]
        args = [env[nm] for nm in names]
        trivial_idx = {i for i, nm in enumerate(names) if nm in trivial}
        if self.fused.join is not None:
            pk = _eval_arr(self.fused.join.probe_key, batch)
            from .bridge import arrow_to_numpy

            pkv, pk_valid = arrow_to_numpy(pk)
            pkv = pkv.astype(np.int64)
            if pk_valid is None:
                pk_valid = np.ones(n, dtype=bool)
            if self._mode == "x32":
                # probe keys outside i32 cannot match the
                # (range-checked) build keys: mask, don't fail
                in_range = (pkv >= -(1 << 31)) & (pkv < 1 << 31)
                if not in_range.all():
                    pk_valid = pk_valid & in_range
                    pkv = np.where(in_range, pkv, 0)
                pkv = pkv.astype(np.int32)
            args += [
                K._pad(pkv, n_pad),
                K._pad(pk_valid, n_pad),
                build[1],  # bkeys (device) / dense slot table
            ]
            if build[0] == "dense":
                args.append(build[6])  # kmin (probe offset scalar)
            args += build[2] + build[3]  # bvals, bvalids
        return args, trivial_idx

    # ---------------------------------------------------- keyed aggregate
    def _keyed_prep(self, dense: bool = False, key_kinds=None):
        """(holder, raw kernel, jitted prep kernel) for the keyed path,
        cached with the other compiled kernels on the stage signature.
        The raw (untraced) kernel backs the fused single-dispatch runner;
        ``key_kinds`` enables the in-kernel device key encode."""
        key = (
            self._sig + ("keyed_prep", key_kinds)
            + (("dense",) if dense else ())
            + K.algo_cache_token()
        )
        cached = _KERNEL_CACHE.get(key)
        self._note_kernel_cache(cached is not None)
        if cached is None:
            import jax

            holder: dict = {}
            inner = K.make_keyed_prep_kernel(
                self._filter_closure,
                self._arg_closures,
                self.specs,
                self._flat_names,
                holder,
                extra_names=self._median_extra_names(),
                key_kinds=key_kinds,
            )
            if self.fused.join is not None:
                kernel = K.make_join_kernel(
                    inner,
                    self._flat_names,
                    self._join_slots,
                    len(self._device_build_cols),
                    dense=dense,
                )
            else:
                kernel = inner
            cached = (holder, kernel, jax.jit(kernel))
            _KERNEL_CACHE[key] = cached
        return cached[0], cached[1], self._timed_jit(cached[2])

    def _key_kinds_for(self, key_encoders) -> tuple:
        """Per-encoded-key device-encode kind ("code" = host encode /
        dictionary handoff), derived from the encoder instances actually
        in play so code spaces can never mix across batches."""
        from .bridge import (
            BoolKeyEncoder,
            FloatKeyEncoder,
            IdentityKeyEncoder,
        )

        if not self.config.tpu_device_encode:
            return tuple("code" for _ in key_encoders)
        kinds = []
        for enc in key_encoders:
            if isinstance(enc, IdentityKeyEncoder):
                kinds.append("ident")
            elif isinstance(enc, BoolKeyEncoder):
                kinds.append("bool")
            elif isinstance(enc, FloatKeyEncoder):
                kinds.append(enc.kind)
            else:
                kinds.append("code")
        return tuple(kinds)

    def _keyed_fast_encoders(self, batch) -> Optional[list]:
        """Encoder set for the PRE-ENCODE keyed fast path, or None when
        this stage/batch must take the legacy host-encode routing.

        The fast path fires when the stage is pinned keyed (median/corr
        stages, or ``highcard_mode=device``), device encode is enabled,
        and at least one key has a device encoding — the batch then
        routes to :meth:`_run_keyed` with NO host group encode at all
        (``key_encode_time_ns`` stays ~0; only dictionary keys still pay
        the host handoff per batch).  A first-batch range precheck sends
        identity keys the device cannot represent (negative values, or
        past-i32 in x32 mode) back to the legacy routing, which lands on
        the measured host fallbacks."""
        cfg = self.config
        if not cfg.tpu_device_encode:
            return None
        if not (self._needs_keyed or cfg.tpu_highcard_mode == "device"):
            return None
        from .bridge import arrow_to_numpy, device_key_encoder

        encs: list = []
        kinds: list = []
        for pos, (kind, _s) in enumerate(self._group_plan):
            if kind != "enc":
                continue
            enc, k = device_key_encoder(
                self._schema.field(pos).type, self._mode
            )
            encs.append(enc)
            kinds.append(k)
        if not encs or all(k is None for k in kinds):
            return None
        for k, g in zip(kinds, self._enc_group_exprs):
            if k != "ident":
                continue
            try:
                vals, _valid = arrow_to_numpy(_eval_arr(g, batch))
            except ExecutionError:
                return None
            v = vals.astype(np.int64, copy=False)
            if len(v) and (
                v.min() < 0
                or (self._mode == "x32" and v.max() > (1 << 31) - 2)
            ):
                return None
        return encs

    def _keyed_key_ops(
        self, batch, kinds, key_state: dict, key_encoders, codes,
        n: int, n_pad: int,
    ) -> tuple:
        """Per-key prep-kernel operand tuples for one batch.

        "code" kinds host-encode (dictionary handoff; ``codes`` reuses
        the detection path's already-encoded first batch).  Device kinds
        ship the RAW evaluated key column as (values, validity);
        identity keys choose a target integer dtype on the first batch —
        i32 when the range allows, unlocking the packed-u64 single-
        operand sort even in x64 mode (measured 6.8x on the sort) — and
        a later batch that overflows the choice raises ExecutionError:
        the late-key-overflow host-route fallback the legacy path has."""
        from .bridge import arrow_to_numpy

        def note_range(slot: int, min_code, max_code) -> None:
            """Track the running per-slot CODE range (None = the slot
            has no non-negative bounded code space): the fused runner
            folds min-rebased codes into one sort word using the exact
            stream-wide spans."""
            if max_code is None or key_state.get(("max", slot), 0) is None:
                key_state[("max", slot)] = None
                return
            key_state[("max", slot)] = max(
                key_state.get(("max", slot), 0), int(max_code)
            )
            cur_min = key_state.get(("min", slot))
            key_state[("min", slot)] = (
                int(min_code)
                if cur_min is None
                else min(cur_min, int(min_code))
            )

        ops: list = []
        for slot, (kind, enc) in enumerate(zip(kinds, key_encoders)):
            g = self._enc_group_exprs[slot]
            if kind == "code":
                if codes is not None and codes[slot] is not None:
                    c = codes[slot]
                else:
                    with self.metrics.timer("key_encode_time_ns"):
                        c = enc.encode(_eval_arr(g, batch))
                note_range(slot, 0, c.max(initial=0))
                ops.append((K._pad(K.coerce_host_values(c), n_pad),))
                continue
            vals, valid = arrow_to_numpy(_eval_arr(g, batch))
            if valid is None:
                valid = np.ones(n, dtype=bool)
            if kind == "ident":
                v = vals.astype(np.int64, copy=False)
                if len(v) and v.min() < 0:
                    raise ExecutionError(
                        "negative group key in identity key encoder"
                    )
                # code = value + 1; null rows carry code 0, so any null
                # in the batch pins the range floor there
                note_range(
                    slot,
                    0 if (not len(v) or not valid.all())
                    else int(v.min()) + 1,
                    v.max(initial=0) + 1,
                )
                dt = key_state.get(("dtype", slot))
                if dt is None:
                    if int(v.max(initial=0)) <= (1 << 31) - 2:
                        dt = np.int32
                    elif self._mode == "x32":
                        raise ExecutionError(
                            "int64 group key exceeds i32 range in x32 mode"
                        )
                    else:
                        dt = np.int64
                    key_state[("dtype", slot)] = dt
                elif dt is np.int32 and len(v) and (
                    int(v.max(initial=0)) > (1 << 31) - 2
                ):
                    raise ExecutionError(
                        "group key outgrew the i32 device encoding"
                    )
                vals = v.astype(dt, copy=False)
            elif kind == "bool":
                vals = np.asarray(vals, dtype=bool)
                note_range(slot, 0, 2)
            else:  # f32 / f64: raw bit-pattern codes
                note_range(slot, 0, None)  # signed bits: no radix fold
                if kind == "f32":
                    vals = vals.astype(np.float32, copy=False)
                    bits = vals.view(np.int32)
                    null = K.FLOAT32_NULL_BITS
                else:
                    vals = vals.astype(np.float64, copy=False)
                    bits = vals.view(np.int64)
                    null = K.FLOAT64_NULL_BITS
                if bool(np.any((bits == null) & valid)):
                    # the one NaN payload reserved for NULL appears as
                    # DATA: no device encoding — host-route fallback
                    raise ExecutionError(
                        "float group key collides with the reserved "
                        "null pattern"
                    )
            ops.append((K._pad(vals, n_pad), K._pad(valid, n_pad)))
        return tuple(ops)

    def _median_extra_names(self) -> tuple:
        """Env names of the median/corr argument leaves, buffered raw
        through the keyed prep for the post-sort passes."""
        out: list[str] = []
        for ci in self._median_cols:
            base = f"col_{ci}__ordpair"
            out.extend([f"{base}__ohi", f"{base}__olo", f"{base}__valid"])
        for ci in self._corr_cols:
            if self._mode == "x32":
                base = f"col_{ci}__pair"
                out.extend(
                    [f"{base}__hi", f"{base}__lo", f"{base}__valid"]
                )
            else:
                out.extend([f"col_{ci}", f"col_{ci}__valid"])
        return tuple(out)

    def _run_keyed(self, first: list, src, key_encoders, ctx: TaskContext):
        """Device-keyed aggregation (VERDICT r3 item 2): per batch the
        fused filter/join/project runs and masked scan-form columns
        buffer in HBM alongside the RAW key codes; at stream end ONE
        multi-key sort assigns group ids from key-change boundaries, one
        segmented scan reduces every aggregate, and one packed fetch
        returns states + unique key codes.  Host work per batch is one
        astype per key — no hash probe, no factorize.

        Returns ``(host_states, _KeyedGroups, n_rows_in, aux)`` where
        ``aux = {"median": [...], "corr": [...]}`` holds the post-sort
        pass results; raises ``ExecutionError`` (keys can't ship) or
        ``_CapacityExceeded`` (cardinality past tpu.max_capacity) for
        the caller's CPU fallback.
        """
        fused = self.fused
        build = None
        if fused.join is not None:
            # cached by the _execute_device run that raised _KeyedRoute
            # (an empty build side returns there, before any routing)
            build = self._prepare_build(ctx)
        dense_join = build is not None and build[0] == "dense"
        kinds = self._key_kinds_for(key_encoders)
        use_kinds = (
            kinds if any(k != "code" for k in kinds) else None
        )
        holder, _prep_raw, prep = self._keyed_prep(
            dense=dense_join, key_kinds=use_kinds
        )
        n_keys = self._n_encoded_groups
        buf: list = []
        chunks: list = []  # flushed (states, key_codes, n_groups) blocks
        buffered = 0
        n_rows_in = 0
        key_state: dict = {}
        # single-dispatch fusion: batches accumulate HOST-side and the
        # whole encode→sort pipeline runs as ONE jitted call at stream
        # end; past the unroll cap or the HBM budget the accumulated
        # entries drain through the per-batch streaming prep instead
        pending: list = []  # (keys_ops, n_live, trivial_idx, args)
        pending_bytes = 0
        fuse = True

        def flush():
            # HBM budget reached: reduce the buffered block to its
            # [distinct]-sized keyed states NOW and merge blocks on host
            # at stream end (merge_keyed_host, the mesh cross-shard
            # combine) instead of letting the buffer grow to the final
            # sort — at SF100 a partition's buffered columns can exceed
            # v5e HBM (16 GiB)
            nonlocal buf, buffered
            if not buf:
                return
            if self._median_cols or self._corr_pairs:
                # medians/corr need every row in ONE sort; refuse the
                # unbounded buffer and fall back before the device OOMs
                raise ExecutionError(
                    "keyed buffer budget exceeded with median/corr "
                    "(order statistics cannot chunk-merge)"
                )
            states, key_codes, n_groups, _post = self._keyed_reduce(
                buf, holder, n_keys
            )
            chunks.append((states, key_codes, n_groups))
            self.metrics.add("keyed_chunks", 1)
            buf = []
            buffered = 0

        import jax.numpy as jnp

        def dispatch_prep(keys_ops, n_live, trivial_idx, args):
            nonlocal buffered
            n_pad = len(args[0]) if args else len(keys_ops[0][0])
            with self.metrics.timer("device_time_ns"):
                # device-built tail mask replaces the host validity ship,
                # shared with every all-true leaf companion (see the
                # gid-path device section)
                tail = jnp.arange(n_pad, dtype=jnp.int32) < n_live
                args = [
                    tail if i in trivial_idx else a
                    for i, a in enumerate(args)
                ]
                keys_in = (
                    keys_ops
                    if use_kinds is not None
                    else tuple(k[0] for k in keys_ops)
                )
                out = prep(keys_in, tail, *args)
            buf.append(out)
            buffered += sum(int(a.nbytes) for a in out)
            if self.keyed_buffer_bytes and buffered >= self.keyed_buffer_bytes:
                flush()

        def feed(batch, codes):
            nonlocal pending_bytes, fuse
            n = batch.num_rows
            n_pad = K.bucket_rows(n)
            keys_ops = self._keyed_key_ops(
                batch, kinds, key_state, key_encoders, codes, n, n_pad
            )
            with self.metrics.timer("bridge_time_ns"):
                args, trivial_idx = self._kernel_args(
                    batch, n, n_pad, build
                )
            if use_kinds is not None:
                self.metrics.add("device_encode_batches", 1)
            if fuse:
                # budget-account only the HOST arrays buffered per batch:
                # device-resident join-build tensors ride every entry's
                # args but are one shared allocation, not per-batch HBM
                ebytes = sum(
                    int(a.nbytes)
                    for a in args
                    if isinstance(a, np.ndarray)
                ) + sum(int(o.nbytes) for op in keys_ops for o in op)
                if len(pending) < _FUSED_MAX_ENTRIES and (
                    not self.keyed_buffer_bytes
                    or pending_bytes + ebytes < self.keyed_buffer_bytes
                ):
                    pending.append((keys_ops, n, trivial_idx, args))
                    pending_bytes += ebytes
                    return
                # over the unroll cap / budget: drain into streaming mode
                fuse = False
                for entry in pending:
                    dispatch_prep(*entry)
                pending.clear()
            dispatch_prep(keys_ops, n, trivial_idx, args)

        with self.metrics.timer("tpu_stage_time_ns"):
            for batch, codes in first:
                n_rows_in += batch.num_rows
                feed(batch, codes)
            for batch in src:
                if batch.num_rows == 0:
                    continue
                n_rows_in += batch.num_rows
                feed(batch, None)

            if chunks:
                flush()
                with self.metrics.timer("keyed_merge_time_ns"):
                    merged, merged_keys, n_groups = K.merge_keyed_host(
                        self.specs, self._mode, chunks
                    )
                if n_groups > self.max_capacity:
                    raise _CapacityExceeded()
                return (
                    merged,
                    _KeyedGroups(merged_keys, n_groups),
                    n_rows_in,
                    {"median": [], "corr": []},
                )

            if pending:
                states, key_codes, n_groups, post = (
                    self._keyed_reduce_fused(
                        pending, holder, n_keys, use_kinds, dense_join,
                        # the radix fold is part of the device-encode
                        # feature; the knob-off leg stays the plain
                        # host-encode baseline
                        combine_bits=(
                            _radix_combine_bits(key_state, n_keys)
                            if use_kinds is not None
                            else None
                        ),
                    )
                )
            else:
                states, key_codes, n_groups, post = self._keyed_reduce(
                    buf, holder, n_keys
                )
            mask, keys, extras, s2, perm, cap = post
            per_corr = 3 if self._mode == "x32" else 2
            with self.metrics.timer("device_time_ns"):
                med_results: list[np.ndarray] = []
                for j in range(len(self._median_cols)):
                    med_fn = K.keyed_median_kernel(n_keys, cap)
                    med_packed = med_fn(
                        mask, tuple(keys),
                        extras[3 * j], extras[3 * j + 1],
                        extras[3 * j + 2],
                    )
                    med_results.append(np.asarray(med_packed))
                corr_results: list[np.ndarray] = []
                corr_base = 3 * len(self._median_cols)

                def corr_col(slot: int):
                    o = corr_base + per_corr * slot
                    return extras[o:o + per_corr]

                for sx, sy in self._corr_pairs:
                    cf = K.keyed_corr_kernel(cap, self._mode)
                    packed_c = cf(
                        s2, perm, *corr_col(sx), *corr_col(sy)
                    )
                    corr_results.append(np.asarray(packed_c))
        aux = {"median": med_results, "corr": corr_results}
        return states, _KeyedGroups(key_codes, n_groups), n_rows_in, aux

    def _keyed_reduce(self, buf: list, holder: dict, n_keys: int):
        """ONE multi-key sort + segmented scan over the buffered blocks.

        Returns ``(host_states, key_codes, n_groups, post)`` where
        ``post = (mask, keys, extras, s2, perm, cap)`` keeps the sorted
        arrays alive for the single-block median/corr passes.  Raises
        ``_CapacityExceeded`` past tpu.max_capacity.
        """
        import jax.numpy as jnp

        with self.metrics.timer("device_time_ns"):
            parts = list(zip(*buf))
            if len(buf) == 1:
                fields = [p[0] for p in parts]
            else:
                fields = [jnp.concatenate(p) for p in parts]
            total = int(fields[0].shape[0])
            n2 = K.bucket_rows(total)
            if n2 != total:
                # pad rows carry mask=False and sink past every
                # boundary in the sort — values never read
                fields = [jnp.pad(f, (0, n2 - total)) for f in fields]
            mask = fields[0]
            per_corr = 3 if self._mode == "x32" else 2
            n_extras = 3 * len(self._median_cols) + per_corr * len(
                self._corr_cols
            )
            keys = fields[1:1 + n_keys]
            flat_end = len(fields) - n_extras
            flat_cols = fields[1 + n_keys:flat_end]
            extras = fields[flat_end:]
            out = K.keyed_sort_kernel(n_keys)(mask, *keys)
            s2, perm = out[0], out[1]
            sk = out[2:-1]
            # the scalar fetch is the one host sync before capacity
            # is known (~one tunnel roundtrip)
            n_groups = int(np.asarray(out[-1]))
        if n_groups > self.max_capacity:
            raise _CapacityExceeded()
        cap = max(64, 1 << (max(n_groups, 1) - 1).bit_length())
        finish = K.keyed_finish_kernel(
            holder["kinds"], holder["plan"], self.specs, n_keys, cap,
            self._mode,
        )
        with self.metrics.timer("device_time_ns"):
            packed = finish(s2, perm, tuple(sk), tuple(flat_cols))
            host = np.asarray(packed)
        states, key_codes = K.unpack_keyed_host(
            self.specs, host, self._mode, n_keys
        )
        return states, key_codes, n_groups, (mask, keys, extras, s2, perm, cap)

    def _keyed_reduce_fused(
        self, pending: list, holder: dict, n_keys: int, use_kinds,
        dense: bool, combine_bits=None,
    ):
        """Single-dispatch keyed reduction: every buffered batch's
        (device key encode →) filter/join prep, the cross-batch
        concatenate, and the packed-u64 sort run as ONE jitted call —
        a keyed batch crosses the bridge exactly once, and the whole
        stream costs two device dispatches (this one, then the
        capacity-sized finish once ``n_groups`` is known — the one
        scalar the host must sync on before it can fix the finish
        kernel's static shapes).  Same return contract as
        :meth:`_keyed_reduce`.
        """
        shapes = tuple(
            len(e[3][0]) if e[3] else len(e[0][0][0]) for e in pending
        )
        key_ops_sig = tuple(len(op) for op in pending[0][0])
        n_args = len(pending[0][3])
        trivials = tuple(tuple(sorted(e[2])) for e in pending)
        fn = self._keyed_fused_sort_for(
            shapes, key_ops_sig, n_args, trivials, use_kinds, dense,
            combine_bits,
        )
        flat: list = []
        for keys_ops, n_live, _tidx, args in pending:
            flat.append(np.int32(n_live))
            for op in keys_ops:
                flat.extend(op)
            flat.extend(args)
        with self.metrics.timer("device_time_ns"):
            outs = fn(*flat)
            self.metrics.add("fused_keyed_dispatches", 1)
            n_sort = 2 + n_keys + 1  # s2, perm, sorted keys, n_groups
            fields = outs[:-n_sort]
            s2, perm = outs[-n_sort], outs[-n_sort + 1]
            sk = outs[-n_sort + 2:-1]
            # the scalar fetch is the one host sync before capacity is
            # known (~one tunnel roundtrip)
            n_groups = int(np.asarray(outs[-1]))
        if n_groups > self.max_capacity:
            raise _CapacityExceeded()
        per_corr = 3 if self._mode == "x32" else 2
        n_extras = 3 * len(self._median_cols) + per_corr * len(
            self._corr_cols
        )
        mask = fields[0]
        keys = fields[1:1 + n_keys]
        flat_end = len(fields) - n_extras
        flat_cols = fields[1 + n_keys:flat_end]
        extras = fields[flat_end:]
        cap = max(64, 1 << (max(n_groups, 1) - 1).bit_length())
        finish = K.keyed_finish_kernel(
            holder["kinds"], holder["plan"], self.specs, n_keys, cap,
            self._mode,
        )
        with self.metrics.timer("device_time_ns"):
            packed = finish(s2, perm, tuple(sk), tuple(flat_cols))
            host = np.asarray(packed)
        states, key_codes = K.unpack_keyed_host(
            self.specs, host, self._mode, n_keys
        )
        return states, key_codes, n_groups, (mask, keys, extras, s2, perm, cap)

    def _keyed_fused_sort_for(
        self, shapes: tuple, key_ops_sig: tuple, n_args: int,
        trivials: tuple, use_kinds, dense: bool, combine_bits=None,
    ):
        """Jitted (prep×entries → concat → sort) runner, cached on the
        stage signature + per-entry row buckets and trivial-validity
        layouts (both pow2/stable per stage in practice, so distinct
        traces stay bounded like the join-free fused runner's).

        ``combine_bits`` (per-key radix widths, exact because the fused
        runner sees the WHOLE stream's code maxima before tracing)
        folds every key's code into ONE non-negative i32 sort word —
        multi-key plans then ride the u64x1 packed sort instead of
        pairwise words, and the sorted per-key codes unpack back out by
        shifts, so the finish kernel and decode see exactly the codes
        they always did."""
        key = (
            self._sig
            + ("keyedfused", shapes, key_ops_sig, n_args, trivials,
               use_kinds, combine_bits)
            + (("dense",) if dense else ())
            + K.algo_cache_token()
        )
        cached = _KERNEL_CACHE.get(key)
        self._note_kernel_cache(cached is not None)
        if cached is None:
            import jax
            import jax.numpy as jnp

            _holder, prep_raw, _ = self._keyed_prep(
                dense=dense, key_kinds=use_kinds
            )
            n_keys = self._n_encoded_groups
            sort_body = K.keyed_sort_body(
                1 if combine_bits is not None else n_keys
            )
            n_key_flat = sum(key_ops_sig)
            stride = 1 + n_key_flat + n_args
            n_entries = len(shapes)
            total = sum(shapes)
            n2 = K.bucket_rows(total)

            def fn(*flat):
                prep_outs = []
                for e in range(n_entries):
                    base = e * stride
                    n_live = flat[base]
                    keys_ops = []
                    o = base + 1
                    for cnt in key_ops_sig:
                        keys_ops.append(tuple(flat[o:o + cnt]))
                        o += cnt
                    args = list(flat[o:base + stride])
                    tail = (
                        jnp.arange(shapes[e], dtype=jnp.int32) < n_live
                    )
                    args = [
                        tail if i in trivials[e] else a
                        for i, a in enumerate(args)
                    ]
                    keys_in = (
                        tuple(keys_ops)
                        if use_kinds is not None
                        else tuple(k[0] for k in keys_ops)
                    )
                    prep_outs.append(prep_raw(keys_in, tail, *args))
                parts = list(zip(*prep_outs))
                fields = [
                    p[0] if len(p) == 1 else jnp.concatenate(p)
                    for p in parts
                ]
                if n2 != total:
                    # pad rows carry mask=False and sink past every
                    # boundary in the sort — values never read
                    fields = [
                        jnp.pad(f, (0, n2 - total)) for f in fields
                    ]
                mask = fields[0]
                keys_c = fields[1:1 + n_keys]
                if combine_bits is None:
                    sout = sort_body(mask, *keys_c)
                    return tuple(fields) + tuple(sout)
                # radix-combine: one i32 word carries every key's
                # MIN-REBASED code (spans are exact stream-wide ranges,
                # so the fold is injective and stays non-negative)
                m0, _w0 = combine_bits[0]
                comb = keys_c[0].astype(jnp.int32) - jnp.int32(m0)
                for (mk, bk), kk in zip(combine_bits[1:], keys_c[1:]):
                    comb = (comb << bk) | (
                        kk.astype(jnp.int32) - jnp.int32(mk)
                    )
                sout = sort_body(mask, comb)
                s2, perm, skc, n_groups = sout
                sks = []
                rem = skc
                for mk, bk in reversed(combine_bits[1:]):
                    sks.append(
                        (rem & jnp.int32((1 << bk) - 1)) + jnp.int32(mk)
                    )
                    rem = rem >> bk
                sks.append(rem + jnp.int32(combine_bits[0][0]))
                sks.reverse()
                return (
                    tuple(fields) + (s2, perm) + tuple(sks) + (n_groups,)
                )

            cached = jax.jit(fn)
            _KERNEL_CACHE[key] = cached
        return self._timed_jit(cached)

    # ------------------------------------------------------- device join
    def _nojoin_stage(self) -> "TpuStageExec":
        """Sibling stage with the join UNFOLDED (join on CPU, aggregate on
        device) for data the device join cannot handle."""
        with self._build_lock:
            cached = getattr(self, "_nojoin", None)
            if cached is None:
                fused = _flatten(self.original, fold_join=False)
                cached = TpuStageExec(self.original, fused, self.config)
                cached.metrics = self.metrics  # one bag for observability
                self._nojoin = cached
            return cached

    def _prepare_build(self, ctx: TaskContext):
        """Collect + sort the build side once: device arrays for the
        kernel's searchsorted/gather, host copies for group resolution.
        Raises ExecutionError (→ CPU fallback) on non-unique keys or
        un-shippable key/column ranges."""
        from .bridge import arrow_to_numpy

        with self._build_lock:
            if self._build_state is not None:
                return self._build_state
            import jax

            spec = self.fused.join
            batches = []
            for p in range(spec.build.output_partitioning().n):
                for b in spec.build.execute(p, ctx):
                    ctx.check_cancelled()
                    if b.num_rows:
                        batches.append(b)
            if batches:
                table = pa.Table.from_batches(batches, schema=spec.build.schema)
            else:
                table = spec.build.schema.empty_table()
            key_col = table.column(spec.build_key_index)
            kv, kvalid = arrow_to_numpy(
                key_col.combine_chunks()
                if isinstance(key_col, pa.ChunkedArray)
                else key_col
            )
            kv = kv.astype(np.int64)
            if kvalid is not None:
                table = table.filter(pa.array(kvalid))
                kv = kv[kvalid]  # null build keys never match an inner join
            order = np.argsort(kv, kind="stable")
            kv_sorted = kv[order]
            if len(kv_sorted) > 1 and bool(
                np.any(kv_sorted[1:] == kv_sorted[:-1])
            ):
                raise _JoinIneligible("device join requires unique build keys")
            table = table.take(pa.array(order))

            if len(kv_sorted) == 0:
                self._build_state = ("empty",)
                return self._build_state

            try:
                bkeys_dev = jax.device_put(K.coerce_host_values(kv_sorted))
                bvals, bvalids = [], []
                for ci in self._device_build_cols:
                    col = table.column(ci).combine_chunks()
                    vals, validity = arrow_to_numpy(col)
                    bvals.append(jax.device_put(K.coerce_host_values(vals)))
                    if validity is None:
                        validity = np.ones(len(vals), dtype=bool)
                    bvalids.append(jax.device_put(validity))
            except ExecutionError as e:
                # un-shippable key/column ranges or types: join on CPU,
                # aggregate on device (not a full-CPU fallback)
                raise _JoinIneligible(str(e)) from e
            kmin = int(kv_sorted[0])
            span = int(kv_sorted[-1]) - kmin + 1
            if span <= _DENSE_JOIN_SPAN_CAP:
                # Dense-key direct probe (BENCH_SUITE_r05 starjoin row:
                # searchsorted's log2(m) serial gather passes dominated
                # 38s of device time): scatter build rows into a
                # [span]-slot table once, probe with ONE gather.  Built
                # device-side so only bkeys (already resident) feed the
                # scatter — the table itself never crosses the tunnel.
                # TPC-H integer keys (orderkey/custkey/partkey) always
                # qualify at SF<=10; wider spans keep the sorted probe.
                import jax.numpy as jnp

                m = len(kv_sorted)
                span_b = max(16, 1 << (span - 1).bit_length())
                slots = (
                    jnp.asarray(bkeys_dev, jnp.int64)
                    - jnp.int64(kmin)
                ).astype(jnp.int32)
                tbl = jnp.zeros(span_b, jnp.int32).at[slots].set(
                    jnp.arange(1, m + 1, dtype=jnp.int32)
                )
                self._build_state = (
                    "dense", tbl, bvals, bvalids, kv_sorted, table,
                    np.int64(kmin),
                )
                self.metrics.add("dense_join", 1)
                return self._build_state
            self._build_state = (
                "ok", bkeys_dev, bvals, bvalids, kv_sorted, table
            )
            return self._build_state

    def _fetch_states(self, acc, n_groups: Optional[int] = None) -> Optional[list]:
        """One packed device→host fetch of the whole state tuple.

        ``n_groups`` (when the stage aggregates by key) bounds the fetch:
        only the pow2 bucket covering the assigned group ids moves over
        the tunnel instead of the full grown capacity (up to 4x fewer
        bytes at high cardinality)."""
        if acc is None:
            return None
        keep = None if n_groups is None else _keep_bucket(n_groups)
        packed = K.pack_for_fetch(self.specs, acc, self._mode, keep=keep)
        return K.unpack_host(self.specs, np.asarray(packed), self._mode)

    def _run_fused(
        self, entries, cap: int, group_table, key_encoders=None,
        stream: bool = False,
    ) -> Optional[list]:
        """ONE jitted dispatch for the whole query over retained entries:
        per-entry kernel → cross-entry combine → packed fetch layout.

        On the tunnel-attached TPU each dispatch carries tens of ms of
        latency; the previous per-batch loop (kernel dispatch per entry,
        eager combine ops, separate pack dispatch) put 3+ round trips on
        q6's critical path even with every column device-resident.  All
        entries run at the FINAL capacity, so mid-stream state padding
        disappears with the per-batch dispatches.

        The runner UNROLLS one kernel body per entry, so entry count is
        capped: past _FUSED_MAX_ENTRIES (default batch sizes can give
        hundreds of batches per partition) the XLA program would hit a
        compile cliff, and the per-batch dispatch loop degrades linearly
        instead."""
        # cache-eligible stages are join-free (_cache_key); the dense
        # join-kernel variant must never replay through this runner,
        # which builds the sorted-probe form
        assert self.fused.join is None, "fused runner is join-free"
        self._fused_pids = None
        n_groups = group_table.n_groups if group_table is not None else None
        if stream or len(entries) > _FUSED_MAX_ENTRIES:
            acc = None
            _, kernel = self._kernel_for(cap)
            for seg, valid, args in entries:
                out = kernel(seg, valid, *args)
                acc = K.combine_states(self.specs, acc, out, self._mode)
            return self._fetch_states(acc, n_groups)
        keep = None if n_groups is None else _keep_bucket(n_groups)
        # shuffle-pid-in-kernel (whole-stage fusion): the group table is
        # complete at dispatch time, so every group's hint-key values
        # decode NOW and their hash rides the same trace — the stage's
        # compute + partition-id derivation become ONE dispatch
        pid_args = None
        pid_static = None
        if (
            self._fuse_pid
            and group_table is not None
            and key_encoders is not None
        ):
            spec = self._fused_pid_spec()
            if spec is not None:
                slots, n_out = spec
                arrs = [
                    key_encoders[slot].decode(
                        group_table.codes_for(np.arange(n_groups), slot),
                        self._schema.field(pos).type,
                    )
                    for slot, pos in slots
                ]
                pid_args = K.pid_limb_args(arrs, min(keep, cap))
                if pid_args is not None:
                    pid_static = (len(slots), n_out)
        shapes = tuple(int(e[1].shape[0]) for e in entries)
        n_args = len(entries[0][2])
        fn = self._fused_for(cap, shapes, n_args, keep, pid_static)
        flat = []
        for seg, valid, args in entries:
            flat.append(seg)
            flat.append(valid)
            flat.extend(args)
        if pid_static is not None:
            flat.extend(pid_args)
        try:
            packed = fn(*flat)
        except Exception:
            # trace/compile failure of the unrolled program: degrade to
            # the per-batch dispatch loop instead of failing the stage
            # (knob-off keeps the pre-fusion failure path: the execute()
            # ladder falls back to the CPU operators)
            if not self.config.tpu_whole_stage_fusion:
                raise
            self.metrics.add("fused_degraded", 1)
            acc = None
            _, kernel = self._kernel_for(cap)
            for seg, valid, args in entries:
                out = kernel(seg, valid, *args)
                acc = K.combine_states(self.specs, acc, out, self._mode)
            return self._fetch_states(acc, n_groups)
        self.metrics.add("fused_dispatches", 1)
        packed_np = np.asarray(packed)
        if pid_static is not None:
            # last packed row is the int pid lane; peel it for
            # _materialize and hand the rest to the normal unpack
            self._fused_pids = packed_np[-1].astype(np.int64)
            packed_np = packed_np[:-1]
            self.metrics.add("fused_pid_in_kernel", 1)
        return K.unpack_host(self.specs, packed_np, self._mode)

    def _fused_for(
        self, cap: int, shapes: tuple, n_args: int, keep, pid=None
    ):
        """Jitted (kernel×entries → combine → pack) runner, cached on the
        stage signature + per-entry row buckets (pow2, so distinct traces
        stay logarithmic in partition size).  ``pid`` (static
        ``(n_key_cols, n_out)`` or None) extends the trace with the
        shuffle partition-id hash over trailing limb args, appended to
        the packed fetch as one extra integer row."""
        key = (
            self._sig[:2] + (cap,) + self._sig[3:]
            + ("fusedall", shapes, n_args, keep, pid)
            + K.algo_cache_token()
        )
        cached = _KERNEL_CACHE.get(key)
        self._note_kernel_cache(cached is not None)
        if cached is None:
            import jax
            import jax.numpy as jnp

            raw, _ = self._kernel_for(cap)
            specs, mode = self.specs, self._mode
            stride = 2 + n_args
            n_entries = len(shapes)

            def fn(*flat):
                acc = None
                for i in range(n_entries):
                    seg = flat[i * stride]
                    valid = flat[i * stride + 1]
                    args = flat[i * stride + 2:(i + 1) * stride]
                    out = raw(seg, valid, *args)
                    acc = K.combine_states(specs, acc, out, mode)
                packed = K.pack_states(specs, acc, mode, keep)
                if pid is not None:
                    pids = K.partition_id_hash(
                        flat[n_entries * stride:], pid[1]
                    )
                    packed = jnp.concatenate(
                        [packed, pids[None, :].astype(packed.dtype)],
                        axis=0,
                    )
                return packed

            cached = jax.jit(fn)
            _KERNEL_CACHE[key] = cached
        return self._timed_jit(cached)

    def _encode_groups(self, batch, key_encoders, group_table):
        """Vectorized multi-key → dense group id encoding, any key count.

        Per-key global dictionary codes fold into one int64 via growing
        per-key radix bits; known combinations resolve through a pandas
        hash-index probe and only MISSES pay one pandas.factorize
        (ops/groups.py — the round-2 design looped Python over every new
        combination: 6 of q3 SF10's 7.8 stage-seconds).  The keyed path
        (:meth:`_run_keyed`) skips the gid table entirely and ships the
        per-key codes raw.
        """
        return self._assign_gids(
            self._encode_codes(batch, key_encoders), group_table
        )

    def _encode_codes(self, batch, key_encoders) -> list[np.ndarray]:
        """Per-key dictionary/identity code arrays for one batch."""
        encoded_exprs = [
            g
            for (g, _), (kind, _s) in zip(
                self.fused.group_exprs, self._group_plan
            )
            if kind == "enc"
        ]
        return [
            enc.encode(_eval_arr(g, batch))
            for g, enc in zip(encoded_exprs, key_encoders)
        ]

    def _assign_gids(self, code_arrays: list, group_table) -> np.ndarray:
        from .groups import RadixOverflow

        try:
            gids = group_table.encode(code_arrays)
        except RadixOverflow:
            raise _CapacityExceeded()
        if group_table.n_groups > self.max_capacity:
            raise _CapacityExceeded()
        return gids

    # ------------------------------------------------------- materialize
    def _materialize(
        self, host_states, key_encoders, group_table, n_rows_in,
        ctx: TaskContext, partition: int, aux=None,
    ) -> Iterator[pa.RecordBatch]:
        """Build the output batch from already-fetched numpy state arrays
        (``host_states`` comes from :meth:`_fetch_states`; device work and
        the fetch are accounted to device_time_ns by then).  Everything is
        vectorized — per-group Python loops cost seconds at q3/h2o
        cardinalities."""
        fused = self.fused
        schema = self._schema

        if host_states is None:
            if not fused.group_exprs:
                # empty input, global aggregate: the CPU operator supplies
                # the exact SQL empty-input row for THIS (empty) partition
                yield from self.original.execute(partition, ctx)
            return

        n_groups = group_table.n_groups if fused.group_exprs else 1
        host = [a[:n_groups] for a in host_states]
        presence = host[-1]
        keep = np.nonzero(presence > 0)[0] if fused.group_exprs else np.arange(1)

        cols: list[pa.Array] = []
        jk_positions = None
        for pos, (kind, slot) in enumerate(self._group_plan):
            field_t = schema.field(len(cols)).type
            if kind == "enc":
                codes = group_table.codes_for(keep, slot)
                cols.append(key_encoders[slot].decode(codes, field_t))
                continue
            # build-resolved group key: look the kept groups' probe join
            # keys up in the sorted build table (unique keys => exact)
            if jk_positions is None:
                jk_codes = group_table.codes_for(keep, self._jk_slot)
                jk_vals = (
                    key_encoders[self._jk_slot]
                    .decode(jk_codes, schema.field(self._jk_pos).type)
                    .cast(pa.int64())
                    .to_numpy(zero_copy_only=False)
                    .astype(np.int64)
                )
                bkeys_host = self._build_state[4]
                jk_positions = np.searchsorted(bkeys_host, jk_vals)
                jk_positions = np.minimum(
                    jk_positions, max(len(bkeys_host) - 1, 0)
                )
            build_table = self._build_state[5]
            ci = fused.join.build_cols[slot]
            vals = build_table.column(ci).take(pa.array(jk_positions))
            if not vals.type.equals(field_t):
                import pyarrow.compute as pc

                vals = pc.cast(vals, field_t)
            cols.append(
                vals.combine_chunks()
                if isinstance(vals, pa.ChunkedArray)
                else vals
            )

        partial = fused.mode == PARTIAL
        # state-field offset of each kernel spec in the host arrays
        offs: list[int] = []
        off = 0
        for spec in self.specs:
            offs.append(off)
            off += len(K.state_fields(spec, self._mode))

        def sum_and_n(o: int):
            """(Σ as f64, count) of a sum-spec's states at offset o."""
            if self._mode == "x32":
                v = (
                    host[o][keep].astype(np.float64)
                    + host[o + 1][keep].astype(np.float64)
                )
                return v, host[o + 2][keep]
            return host[o][keep].astype(np.float64), host[o + 1][keep]

        for entry in self._emit:
            if entry[0] == "corr":
                if aux is None:
                    raise ExecutionError("corr requires the keyed path")
                pkd = aux["corr"][entry[1]]
                if self._mode == "x32":
                    f32 = np.float32
                    sxy = (
                        pkd[0][keep].view(f32).astype(np.float64)
                        + pkd[1][keep].view(f32)
                    )
                    sxx = (
                        pkd[2][keep].view(f32).astype(np.float64)
                        + pkd[3][keep].view(f32)
                    )
                    syy = (
                        pkd[4][keep].view(f32).astype(np.float64)
                        + pkd[5][keep].view(f32)
                    )
                    n_arr = pkd[6][keep]
                else:
                    sxy = pkd[0][keep].view(np.float64)
                    sxx = pkd[1][keep].view(np.float64)
                    syy = pkd[2][keep].view(np.float64)
                    n_arr = pkd[3][keep]
                empty = (n_arr < 2) | (sxx <= 0) | (syy <= 0)
                with np.errstate(all="ignore"):
                    r = sxy / np.sqrt(sxx * syy)
                r = np.where(empty, 0.0, r)
                field_t = schema.field(len(cols)).type
                arr = pa.array(r, pa.float64(), mask=empty)
                if not arr.type.equals(field_t):
                    import pyarrow.compute as pc

                    arr = pc.cast(arr, field_t, safe=False)
                cols.append(arr)
                continue
            if entry[0] == "cdist":
                if aux is None:
                    raise ExecutionError(
                        "count_distinct requires the keyed path"
                    )
                cd = aux["median"][entry[1]][5][keep].astype(np.int64)
                field_t = schema.field(len(cols)).type
                arr = pa.array(cd, pa.int64())
                if not arr.type.equals(field_t):
                    import pyarrow.compute as pc

                    arr = pc.cast(arr, field_t, safe=False)
                cols.append(arr)
                continue
            if entry[0] == "median":
                if aux is None:
                    # only the keyed path buffers the value columns
                    raise ExecutionError("median requires the keyed path")
                from .bridge import order_decode_f64

                med = aux["median"][entry[1]]
                cv = med[4][keep]
                empty = cv == 0
                va = order_decode_f64(
                    np.where(empty, 0, med[0][keep]).astype(np.int32),
                    np.where(empty, 0, med[1][keep]).astype(np.int32),
                )
                vb = order_decode_f64(
                    np.where(empty, 0, med[2][keep]).astype(np.int32),
                    np.where(empty, 0, med[3][keep]).astype(np.int32),
                )
                v = (va + vb) / 2.0
                field_t = schema.field(len(cols)).type
                arr = pa.array(v, pa.float64(), mask=empty)
                if not arr.type.equals(field_t):
                    import pyarrow.compute as pc

                    arr = pc.cast(arr, field_t, safe=False)
                cols.append(arr)
                continue
            if entry[0] == "var":
                _, si, qi, ddof, use_sqrt = entry
                s_v, n_arr = sum_and_n(offs[si])
                q_v, _n2 = sum_and_n(offs[qi])
                n_f = n_arr.astype(np.float64)
                empty = n_arr < (ddof + 1)
                with np.errstate(all="ignore"):
                    var = (
                        q_v - s_v * s_v / np.maximum(n_f, 1.0)
                    ) / np.maximum(n_f - ddof, 1.0)
                # conditioning guard: when the subtraction consumed more
                # reliable digits than the compensated moments carry
                # (~2^-45 in x32 via the forced scan path, ~2^-52 in
                # x64), only the exact CPU path can answer — incl. var
                # cancelled all the way to <= 0.  Constant columns trip
                # too (their true variance IS the rounding floor); the
                # CPU re-run returns the exact 0.
                with np.errstate(all="ignore"):
                    m2 = q_v / np.maximum(n_f, 1.0)
                live = (~empty) & (m2 > 0)
                kmax = 1e-6 if self._mode == "x32" else 1e-8
                if bool(np.any(live & (var < m2 * kmax))):
                    raise ExecutionError(
                        "variance cancellation past device moment precision"
                    )
                var = np.where(var < 0, 0.0, var)  # rounding guard
                out_v = np.sqrt(var) if use_sqrt else var
                field_t = schema.field(len(cols)).type
                arr = pa.array(out_v, pa.float64(), mask=empty)
                if not arr.type.equals(field_t):
                    import pyarrow.compute as pc

                    arr = pc.cast(arr, field_t, safe=False)
                cols.append(arr)
                continue
            spec = self.specs[entry[1]]
            i = offs[entry[1]]
            if spec.func in ("count", "count_star"):
                cols.append(pa.array(host[i][keep], pa.int64()))
                i += 1
                continue
            if spec.ord_pair:
                # order-pair f64 extremum: lexicographic (hi, lo) i32
                # decodes to the BIT-exact f64 min/max
                from .bridge import order_decode_f64

                ohi = host[i][keep]
                olo = host[i + 1][keep]
                n_arr = host[i + 2][keep]
                i += 3
                empty = n_arr == 0
                v = order_decode_f64(
                    np.where(empty, 0, ohi).astype(np.int32),
                    np.where(empty, 0, olo).astype(np.int32),
                )
                field_t = schema.field(len(cols)).type
                cols.append(pa.array(v, field_t, mask=empty))
                continue
            if spec.int_minmax:
                # integer extrema stay in INT dtype end-to-end (an f64
                # round-trip would round int64 values above 2^53 — the
                # exactness this path exists to guarantee)
                v_exact = host[i][keep]
                n_arr = host[i + 1][keep]
                i += 2
                empty = n_arr == 0
                field_t = schema.field(len(cols)).type
                vals = np.where(empty, 0, v_exact).astype(np.int64)
                if pa.types.is_date32(field_t):
                    cols.append(
                        pa.array(
                            vals.astype("datetime64[D]"), field_t, mask=empty
                        )
                    )
                else:
                    cols.append(pa.array(vals, field_t, mask=empty))
                continue
            if spec.func in ("sum", "avg") and self._mode == "x32":
                # double-float state: hi + lo recombine in f64 on host,
                # recovering ~48-bit precision from f32 device math
                v = (
                    host[i][keep].astype(np.float64)
                    + host[i + 1][keep].astype(np.float64)
                )
                n_arr = host[i + 2][keep]
                i += 3
            else:
                v = host[i][keep].astype(np.float64)
                n_arr = host[i + 1][keep]
                i += 2
            empty = n_arr == 0
            if spec.func == "avg":
                if partial:
                    cols.append(pa.array(v, pa.float64()))
                    cols.append(pa.array(n_arr, pa.int64()))
                else:
                    denom = np.where(empty, 1, n_arr)
                    cols.append(
                        pa.array(v / denom, pa.float64(), mask=empty)
                    )
                continue
            field_t = schema.field(len(cols)).type
            if pa.types.is_integer(field_t) or pa.types.is_date32(field_t):
                # device accumulates in f64; exact for |sum| < 2^53
                # (±inf extrema identities of empty groups are masked out,
                # zeroed first so the int cast can't warn)
                v_int = np.round(np.where(np.isfinite(v), v, 0.0)).astype(
                    np.int64
                )
                if pa.types.is_date32(field_t):
                    cols.append(
                        pa.array(
                            v_int.astype("datetime64[D]"), field_t, mask=empty
                        )
                    )
                else:
                    cols.append(pa.array(v_int, field_t, mask=empty))
            else:
                cols.append(pa.array(v, field_t, mask=empty))

        out = pa.RecordBatch.from_arrays(cols, schema=schema)
        self.metrics.add("output_rows", out.num_rows)
        self.metrics.add("input_rows", n_rows_in)
        hint = self._shuffle_hint
        if hint is not None and out.num_rows:
            fp = self._fused_pids
            if fp is not None:
                # already derived INSIDE the fused dispatch over the full
                # group table — select the kept groups' ids; bit-identical
                # to the separate kernel by construction (same limb prep,
                # same hash, identical decoded key values)
                self._fused_pids = None
                pids = fp[:n_groups][keep]
            else:
                pids = K.device_partition_ids(out, hint[0], hint[1])
            if pids is not None:
                from ..exec.operators import SHUFFLE_PID_COLUMN

                # device_pid_batches is counted ONCE, by the consuming
                # writer — a second add here would double it in the
                # per-stage profile rollup
                out = pa.RecordBatch.from_arrays(
                    out.columns + [pa.array(pids.astype(np.int32), pa.int32())],
                    schema=schema.append(
                        pa.field(SHUFFLE_PID_COLUMN, pa.int32())
                    ),
                )
        yield out


def _radix_combine_bits(key_state: dict, n_keys: int) -> Optional[tuple]:
    """Per-key ``(min_code, width)`` plan when every key's MIN-REBASED
    codes fold into one non-negative i32 sort word (None otherwise).
    Ranges are the EXACT stream-wide code spans ``_keyed_key_ops``
    tracked — the fused runner traces after the whole stream buffered,
    so unlike the host ``GroupTable``'s growing radixes there is no
    mid-stream regrow or overflow: the plan is right by construction.
    Rebasing matters: q3's orderdate key spans ~121 distinct days but
    its identity codes sit near 9000 — 7 bits after rebase vs 14 raw."""
    if n_keys < 2:
        return None
    plan = []
    total = 0
    for slot in range(n_keys):
        m = key_state.get(("max", slot), None)
        if m is None:
            return None  # float bit-pattern codes are signed: no fold
        if int(m) > (1 << 31) - 2:
            # the fold runs in i32: a key whose CODES exceed i32 (wide
            # int64 values with a narrow span still ship as i64 arrays)
            # must not reach the jnp.int32 casts — rebasing would wrap
            return None
        lo = key_state.get(("min", slot), 0) or 0
        width = max(1, int(m - lo).bit_length())
        plan.append((int(lo), width))
        total += width
    if total > 31:
        return None
    return tuple(plan)


def _eval_arr(e: pe.PhysicalExpr, batch: pa.RecordBatch) -> pa.Array:
    v = e.evaluate(batch)
    if isinstance(v, pa.ChunkedArray):
        v = v.combine_chunks()
    if isinstance(v, pa.Scalar):
        v = pa.array([v.as_py()] * batch.num_rows, v.type)
    return v


def _replace_leaf(
    plan: ExecutionPlan, old: ExecutionPlan, new: ExecutionPlan
) -> ExecutionPlan:
    if plan is old:
        return new
    kids = plan.children()
    if not kids:
        return plan
    return plan.with_new_children([_replace_leaf(c, old, new) for c in kids])


# ------------------------------------------------------------------ rule
def maybe_accelerate(plan: ExecutionPlan, config: BallistaConfig) -> ExecutionPlan:
    """PhysicalOptimizerRule: replace eligible aggregates with TpuStageExec
    (counterpart of the north star's operator-level TPU plugin)."""
    if not config.tpu_enable:
        return plan
    kids = plan.children()
    if kids:
        plan = plan.with_new_children([maybe_accelerate(c, config) for c in kids])
    from ..exec.window import WindowExec

    if isinstance(plan, WindowExec):
        from .window_compiler import TpuWindowExec

        try:
            return TpuWindowExec(plan, config)
        except K.NotLowerable:
            return plan
    if isinstance(plan, HashAggregateExec) and plan.mode in (PARTIAL, SINGLE):
        fused = _flatten(plan)
        if fused is None:
            return plan
        try:
            return TpuStageExec(plan, fused, config)
        except K.NotLowerable:
            if fused.join is not None:
                # the folded-join shape didn't lower (e.g. a pair/cpu
                # leaf over the build side): retry with the join on CPU
                # so the aggregate still accelerates (round-2 shape)
                fused = _flatten(plan, fold_join=False)
                if fused is not None:
                    try:
                        return TpuStageExec(plan, fused, config)
                    except K.NotLowerable:
                        return plan
            return plan
    return plan
