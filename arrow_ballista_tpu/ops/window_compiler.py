"""Device lowering of WindowExec (VERDICT r3 item 7).

Pure TPU-first differentiation: the reference's distributed planner
raises NotImplemented for WindowAggExec (``scheduler/src/planner.rs:81-
170``); this engine evaluates eligible window stages as ONE device
program per window signature (``ops/window_kernel.py``): multi-key sort,
boundary flags, segmented scans, gathers, packed fetch.

Host responsibilities here:
* eligibility (plan time): supported function set, default RANGE or
  ROWS frames (incl. framed min/max via a sparse-table range extremum),
  numeric/date/STRING ORDER BY (strings order-encode as ranks among the
  sorted uniques), numeric arguments — anything else stays on the
  vectorized CPU path (``exec/window.py``), which remains the oracle;
* ORDER-preserving integer key encoding: every ORDER BY key becomes a
  null-rank flag plus integer key(s) whose SIGNED order equals the SQL
  order — an i64 in x64 mode, an (hi, lo) i32 pair in x32 mode, so f64 /
  i64 / date keys sort EXACTLY on a device without 64-bit dtypes (tie
  structure, and therefore rank/dense_rank, cannot drift);
* PARTITION BY keys ride the group-key encoders (identity / dict codes —
  equality-only, which is all partitioning needs);
* output materialization: bitcast unpack, empty-frame NULL masks, dtype
  casts mirroring the CPU operator.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from ..config import BallistaConfig
from ..errors import ExecutionError
from ..exec.operators import ExecutionPlan, Partitioning, TaskContext
from ..exec.window import RANKING, VALUE_FNS, WindowExec, WindowSpec
from . import kernels as K
from .bridge import arrow_to_numpy, make_key_encoder

_AGG_FNS = {"sum", "avg", "min", "max", "count"}


def _is_string_like(t: pa.DataType) -> bool:
    return (
        pa.types.is_string(t)
        or pa.types.is_large_string(t)
        or (pa.types.is_dictionary(t) and pa.types.is_string(t.value_type))
    )


def _orderable_type(t: pa.DataType) -> bool:
    """Types the device window can ORDER BY (order-encodable)."""
    return (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_date(t)
        or pa.types.is_boolean(t)
        or pa.types.is_timestamp(t)
        or pa.types.is_decimal(t)
        or _is_string_like(t)
    )


def _arg_type_ok(t: pa.DataType) -> bool:
    """Types a window function argument can ship to the device."""
    return (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_date(t)
        or pa.types.is_boolean(t)
        or pa.types.is_decimal(t)
    )


# ------------------------------------------------------- key encoding
from .bridge import split_u64_i32, to_u64_order  # noqa: E402

_to_u64_order = to_u64_order


def _split_u64(u: np.ndarray, mode: str) -> list:
    """Integer key arrays whose lexicographic SIGNED order equals the
    unsigned order of ``u``: one i64 (x64) or an (hi, lo) i32 pair."""
    if mode == "x64":
        return [(u ^ (np.uint64(1) << np.uint64(63))).view(np.int64)]
    return list(split_u64_i32(u))


def _string_order_ranks(arr: pa.Array):
    """(ranks int64, validity) — rank of each string among the SORTED
    unique strings: an order-preserving integer key.  Rank equality is
    string equality, so tie structure (rank/dense_rank peers) is exact.
    ``pc.sort_indices`` does the ordering — the same collation the CPU
    window operator sorts with, so the two paths cannot disagree."""
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    denc = arr.dictionary_encode() if not pa.types.is_dictionary(
        arr.type
    ) else arr
    d = denc.dictionary
    codes = denc.indices
    if len(d) == 0:  # every row is NULL: one rank, all rows invalid
        return (
            np.zeros(len(arr), dtype=np.int64),
            np.zeros(len(arr), dtype=bool),
        )
    code_vals = np.asarray(codes.fill_null(0), dtype=np.int64)
    validity = (
        np.asarray(pc.is_valid(codes)) if codes.null_count else None
    )
    if d.null_count:
        # pre-encoded dictionaries (e.g. from Parquet) may hold a null
        # SLOT: a valid index pointing at it is still a NULL row
        slot_valid = np.asarray(pc.is_valid(d))[code_vals]
        validity = (
            slot_valid if validity is None else validity & slot_valid
        )
    sort_idx = np.asarray(pc.sort_indices(d), dtype=np.int64)
    rank_of = np.empty(len(d), dtype=np.int64)
    rank_of[sort_idx] = np.arange(len(d), dtype=np.int64)
    return rank_of[code_vals], validity


def _order_keys(arr: pa.Array, asc: bool, nulls_first: Optional[bool],
                mode: str) -> list:
    """[null_rank, key...] integer arrays for one ORDER BY expression."""
    if nulls_first is None:
        nulls_first = not asc  # SQL default: NULLS LAST for ASC
    t = arr.type
    if not _orderable_type(t):
        raise K.NotLowerable(f"window ORDER BY type {t}")
    if pa.types.is_decimal(t):
        import pyarrow.compute as pc

        arr = pc.cast(arr, pa.float64())
    if pa.types.is_boolean(t):
        import pyarrow.compute as pc

        arr = pc.cast(arr, pa.int32())
    if _is_string_like(t):
        values, validity = _string_order_ranks(arr)
    else:
        values, validity = arrow_to_numpy(arr)
    u = _to_u64_order(values)
    if not asc:
        u = ~u
    if validity is None:
        null_rank = np.zeros(len(values), dtype=np.int32)
    else:
        is_null = ~validity
        null_rank = np.where(is_null, 0 if nulls_first else 1,
                             1 if nulls_first else 0).astype(np.int32)
        u = np.where(is_null, np.uint64(0), u)  # nulls are peers
    return [null_rank] + _split_u64(u, mode)


class TpuWindowExec(ExecutionPlan):
    """WindowExec evaluated on device; falls back to the CPU operator
    per partition on runtime ineligibility (no source re-scan — windows
    buffer their input anyway)."""

    def __init__(self, original: WindowExec, config: BallistaConfig):
        super().__init__()
        self.original = original
        self.input = original.input
        self.config = config
        self._mode = K.precision_mode()
        # group specs by window signature (like the CPU operator): one
        # kernel invocation per distinct (PARTITION BY, ORDER BY)
        self._groups: dict = {}
        schema = original.input.schema
        for pos, spec in enumerate(original.specs):
            self._check_spec(spec)
            for e, _a, _nf in spec.order_by:
                t = K._infer_pa_type(e, schema)
                if not _orderable_type(t):
                    raise K.NotLowerable(f"window ORDER BY type {t}")
            if spec.arg is not None:
                t = K._infer_pa_type(spec.arg, schema)
                if not _arg_type_ok(t):
                    raise K.NotLowerable(f"window argument type {t}")
            sig = (
                tuple(str(p) for p in spec.partition_by),
                tuple((str(e), a, nf) for e, a, nf in spec.order_by),
            )
            self._groups.setdefault(sig, []).append((pos, spec))

    def _check_spec(self, spec: WindowSpec) -> None:
        if spec.frame is not None and spec.func not in (
            "sum", "count", "avg", "min", "max",
        ):
            raise K.NotLowerable(f"window ROWS frame for {spec.func}")
        if spec.func in RANKING:
            return
        if spec.func in VALUE_FNS:
            if spec.offset < 0:
                raise K.NotLowerable("negative lag/lead offset")
            return
        if spec.func not in _AGG_FNS:
            raise K.NotLowerable(f"window fn {spec.func}")
        if spec.arg is None and spec.func != "count":
            raise K.NotLowerable(f"window {spec.func} without argument")

    # ------------------------------------------------------------- plan
    @property
    def schema(self) -> pa.Schema:
        return self.original.schema

    def output_partitioning(self) -> Partitioning:
        return self.original.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        new_original = self.original.with_new_children(children)
        try:
            return TpuWindowExec(new_original, self.config)
        except K.NotLowerable:
            return new_original

    def __str__(self) -> str:
        return "TpuWindowExec: " + ", ".join(
            f"{s.func}->{s.name}" for s in self.original.specs
        )

    # ---------------------------------------------------------- execute
    def execute(
        self, partition: int, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        batches = list(self.input.execute(partition, ctx))
        if not batches:
            return
        n = sum(b.num_rows for b in batches)
        if n == 0 or n < self.config.tpu_min_rows:
            yield from self._cpu(batches, partition, ctx)
            return
        try:
            with self.metrics.timer("window_time_ns"):
                win_cols = self._device_eval(batches, n)
        except (K.NotLowerable, ExecutionError, RuntimeError) as e:
            self.metrics.add("tpu_fallback", 1)
            import logging

            logging.getLogger(__name__).debug(
                "window device path fell back: %s", e
            )
            yield from self._cpu(batches, partition, ctx)
            return
        table = pa.Table.from_batches(batches, schema=self.input.schema)
        out = table
        for spec, col in zip(self.original.specs, win_cols):
            out = out.append_column(pa.field(spec.name, spec.out_type), col)
        self.metrics.add("output_rows", out.num_rows)
        self.metrics.add("tpu_window", 1)
        for b in out.to_batches(max_chunksize=ctx.batch_size):
            yield b

    def _cpu(self, batches, partition, ctx):
        from .stage_compiler import _BufferedExec

        cpu = self.original.with_new_children(
            [_BufferedExec(self.input, batches)]
        )
        cpu.metrics = self.metrics
        yield from cpu.execute(partition, ctx)

    # ------------------------------------------------------ device eval
    def _device_eval(self, batches, n: int) -> list:
        mode = self._mode

        def eval_col(e):
            parts = []
            for b in batches:
                v = e.evaluate(b)
                if isinstance(v, pa.Scalar):
                    v = pa.array([v.as_py()] * b.num_rows, type=v.type)
                parts.append(v)
            arr = (
                pa.chunked_array(parts).combine_chunks()
                if len(parts) > 1
                else parts[0]
            )
            return arr

        n_pad = K.bucket_rows(n)
        is_pad = np.zeros(n_pad, dtype=np.int32)
        is_pad[n:] = 1

        win_cols: list = [None] * len(self.original.specs)
        for sig, members in self._groups.items():
            spec0 = members[0][1]
            # ---- keys
            part_keys: list = [is_pad]
            for p in spec0.partition_by:
                codes = make_key_encoder(
                    K._infer_pa_type(p, self.input.schema)
                ).encode(eval_col(p))
                u = _to_u64_order(codes.astype(np.int64))
                part_keys.extend(
                    K._pad(k, n_pad) for k in _split_u64(u, mode)
                )
            order_keys: list = []
            for e, asc, nf in spec0.order_by:
                for k in _order_keys(eval_col(e), asc, nf, mode):
                    order_keys.append(K._pad(k, n_pad))

            # ---- args (deduped per expression)
            slot_of: dict = {}
            args: list = []
            kspecs: list = []
            for _pos, spec in members:
                kspecs.append(self._kernel_spec(spec, slot_of, args,
                                                eval_col, n_pad))
            from .window_kernel import make_window_kernel

            kernel = make_window_kernel(
                tuple(kspecs), len(part_keys), len(order_keys),
                len(args), mode,
            )
            packed = np.asarray(
                kernel(tuple(part_keys), tuple(order_keys), tuple(args))
            )
            self._unpack(packed, members, kspecs, n, win_cols)
        return win_cols

    def _kernel_spec(self, spec, slot_of, args, eval_col, n_pad):
        if spec.func == "ntile":
            return ("ntile", spec.offset)
        if spec.func in RANKING:
            return (spec.func,)
        if spec.func == "count" and spec.arg is None:
            if spec.frame is not None:
                return ("aggf", "count", None, spec.frame[0],
                        spec.frame[1], False)
            return ("agg", "count", None, False)
        key = str(spec.arg)

        def checked_arr():
            arr = eval_col(spec.arg)
            t = arr.type
            if not _arg_type_ok(t):
                raise K.NotLowerable(f"window argument type {t}")
            if pa.types.is_decimal(t) or pa.types.is_boolean(t):
                import pyarrow.compute as pc

                arr = pc.cast(arr, pa.float64())
            return arr

        # x32 integer sum/avg: an f32 cast at the scan input loses low
        # bits above 2^24 and the int-typed output rounds the inexact
        # total — ship the argument as an exact (hi, lo) f32 pair, same
        # 48-bit discipline as the aggregate path's column_pair
        if (
            self._mode == "x32"
            and spec.func in ("sum", "avg")
            and pa.types.is_integer(
                K._infer_pa_type(spec.arg, self.input.schema)
            )
        ):
            pkey = (key, "pair")
            slot = slot_of.get(pkey)
            if slot is None:
                values, validity = arrow_to_numpy(checked_arr())
                v = values.astype(np.float64)
                if len(v) and np.abs(v).max() >= float(1 << 48):
                    raise K.NotLowerable(
                        "int window sum exceeds 48-bit pair range in x32"
                    )
                hi = v.astype(np.float32)
                lo = (v - hi.astype(np.float64)).astype(np.float32)
                if validity is None:
                    validity = np.ones(len(v), dtype=bool)
                slot = len(args)
                args.append(
                    (
                        (K._pad(hi, n_pad), K._pad(lo, n_pad)),
                        K._pad(validity, n_pad),
                    )
                )
                slot_of[pkey] = slot
            if spec.frame is not None:
                return ("aggf", spec.func, slot, spec.frame[0],
                        spec.frame[1], True)
            return ("agg", spec.func, slot, True)

        # plain argument slot (value + validity), padded & coerced
        slot = slot_of.get(key)
        if slot is None:
            values, validity = arrow_to_numpy(checked_arr())
            values = K.coerce_host_values(values)
            if validity is None:
                validity = np.ones(len(values), dtype=bool)
            slot = len(args)
            args.append(
                (K._pad(values, n_pad), K._pad(validity, n_pad))
            )
            slot_of[key] = slot
        if spec.func in VALUE_FNS:
            return ("val", spec.func, slot, spec.offset)
        if spec.frame is not None:
            return ("aggf", spec.func, slot, spec.frame[0],
                    spec.frame[1], False)
        return ("agg", spec.func, slot, False)

    # -------------------------------------------------------- unpack
    def _unpack(self, packed, members, kspecs, n, win_cols) -> None:
        mode = self._mode
        fdt = np.float64 if mode == "x64" else np.float32
        ri = 0

        def int_row():
            nonlocal ri
            r = packed[ri][:n]
            ri += 1
            return r

        def float_row():
            nonlocal ri
            r = packed[ri][:n].view(fdt).astype(np.float64)
            ri += 1
            return r

        for (pos, spec), kspec in zip(members, kspecs):
            kind = kspec[0]
            if kind in ("row_number", "rank", "dense_rank", "ntile"):
                col = pa.array(int_row().astype(np.int64), pa.int64())
            elif kind == "agg":
                fn = kspec[1]
                if fn == "count":
                    col = pa.array(int_row().astype(np.int64), pa.int64())
                elif fn in ("sum", "avg"):
                    if mode == "x32":
                        v = float_row() + float_row()
                    else:
                        v = float_row()
                    cnt = int_row()
                    empty = cnt == 0
                    if fn == "avg":
                        denom = np.where(empty, 1, cnt)
                        col = pa.array(v / denom, pa.float64(), mask=empty)
                    elif pa.types.is_integer(spec.out_type):
                        vi = np.round(
                            np.where(np.isfinite(v), v, 0.0)
                        ).astype(np.int64)
                        col = pa.array(vi, pa.int64(), mask=empty)
                    else:
                        col = pa.array(v, pa.float64(), mask=empty)
                else:  # min / max
                    if pa.types.is_integer(spec.out_type) or pa.types.is_date(
                        spec.out_type
                    ):
                        v = int_row().astype(np.int64)
                        cnt = int_row()
                        empty = cnt == 0
                        col = pa.array(
                            np.where(empty, 0, v), pa.int64(), mask=empty
                        )
                    else:
                        v = float_row()
                        cnt = int_row()
                        empty = cnt == 0
                        col = pa.array(
                            np.where(empty, 0.0, v), pa.float64(),
                            mask=empty,
                        )
            elif kind == "aggf":
                fn = kspec[1]
                if kspec[2] is None or fn == "count":
                    col = pa.array(int_row().astype(np.int64), pa.int64())
                elif fn in ("min", "max"):
                    if pa.types.is_integer(spec.out_type) or pa.types.is_date(
                        spec.out_type
                    ):
                        v = int_row().astype(np.int64)
                        empty = int_row() == 0
                        col = pa.array(
                            np.where(empty, 0, v), pa.int64(), mask=empty
                        )
                    else:
                        v = float_row()
                        empty = int_row() == 0
                        col = pa.array(
                            np.where(empty, 0.0, v), pa.float64(),
                            mask=empty,
                        )
                else:
                    if mode == "x32":
                        hi_v = float_row() + float_row()
                        lo_v = float_row() + float_row()
                    else:
                        hi_v = float_row()
                        lo_v = float_row()
                    cnt = int_row()
                    v = hi_v - lo_v
                    emptym = cnt == 0
                    if fn == "avg":
                        col = pa.array(
                            v / np.where(emptym, 1, cnt), pa.float64(),
                            mask=emptym,
                        )
                    elif pa.types.is_integer(spec.out_type):
                        vi = np.round(
                            np.where(np.isfinite(v), v, 0.0)
                        ).astype(np.int64)
                        col = pa.array(vi, pa.int64(), mask=emptym)
                    else:
                        col = pa.array(v, pa.float64(), mask=emptym)
            else:  # val fns
                int_arg = pa.types.is_integer(spec.out_type) or (
                    pa.types.is_date(spec.out_type)
                )
                v = (
                    int_row().astype(np.int64)
                    if int_arg
                    else float_row()
                )
                ok = int_row() != 0
                col = pa.array(
                    np.where(ok, v, 0),
                    pa.int64() if int_arg else pa.float64(),
                    mask=~ok,
                )
            if not col.type.equals(spec.out_type):
                import pyarrow.compute as pc

                col = pc.cast(col, spec.out_type, safe=False)
            win_cols[pos] = col
