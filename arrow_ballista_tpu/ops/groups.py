"""Vectorized multi-key group table for the fused aggregate path.

Round-2 assigned dense group ids with a Python loop over every NEW key
combination (``stage_compiler._encode_groups``) — ~3M loop iterations on
q3 SF10, 6 of the stage's 7.8 seconds.  This table keeps everything in
numpy:

* per-key dictionary codes fold into ONE int64 via per-key bit radixes
  (bits grow with the observed code range; the stored table re-combines
  vectorized when a radix grows);
* known combinations resolve through ``np.searchsorted`` on a sorted
  (combined → gid) index — no Python per-row/per-group work;
* new combinations batch-append: one hash-based ``pandas.factorize``
  over the misses only (the sort-based ``np.unique`` it replaced was
  10x slower at q3 SF10 scale: 9.6s vs 1.0s on 30M i64 keys).

Group ids are row indices of ``key_mat`` (assignment order), so device
states stay valid as the table grows — matching the adaptive-capacity
contract of the kernels.
"""

from __future__ import annotations

import numpy as np

# combined keys live in int64: total radix bits must stay under 63
_MAX_TOTAL_BITS = 62


class RadixOverflow(Exception):
    """Combined key space exceeds 62 bits — caller falls back."""


class GroupTable:
    def __init__(self, n_keys: int):
        self.n_keys = n_keys
        self.key_mat = np.empty((0, n_keys), dtype=np.int64)
        self._bits = [1] * n_keys
        self._sorted_combined = np.empty(0, dtype=np.int64)
        self._sorted_gids = np.empty(0, dtype=np.int32)

    @property
    def n_groups(self) -> int:
        return len(self.key_mat)

    def codes_for(self, gids: np.ndarray, key: int) -> np.ndarray:
        """Per-key dictionary codes for the given group ids (vectorized)."""
        return self.key_mat[gids, key]

    # ------------------------------------------------------------ internal
    def _combine(self, code_cols: list[np.ndarray]) -> np.ndarray:
        combined = code_cols[0].astype(np.int64)
        for bits, c in zip(self._bits[1:], code_cols[1:]):
            combined = (combined << bits) | c.astype(np.int64)
        return combined

    def _grow_radix(self, code_arrays: list[np.ndarray]) -> None:
        changed = False
        for k, c in enumerate(code_arrays):
            if len(c) == 0:
                continue
            need = max(1, int(c.max()).bit_length())
            if need > self._bits[k]:
                self._bits[k] = need
                changed = True
        if sum(self._bits) > _MAX_TOTAL_BITS:
            raise RadixOverflow(
                f"combined group-key space needs {sum(self._bits)} bits"
            )
        if changed and self.n_groups:
            combined = self._combine(
                [self.key_mat[:, k] for k in range(self.n_keys)]
            )
            order = np.argsort(combined, kind="stable")
            self._sorted_combined = combined[order]
            self._sorted_gids = order.astype(np.int32)

    # ------------------------------------------------------------- encode
    def encode(self, code_arrays: list[np.ndarray]) -> np.ndarray:
        """Dense stable group ids for one batch of per-key code columns."""
        self._grow_radix(code_arrays)
        combined = self._combine(code_arrays)
        known = self._sorted_combined
        if len(known):
            pos = np.searchsorted(known, combined)
            pos_c = np.minimum(pos, len(known) - 1)
            found = known[pos_c] == combined
            gids = np.where(found, self._sorted_gids[pos_c], -1).astype(
                np.int32
            )
        else:
            found = np.zeros(len(combined), dtype=bool)
            gids = np.full(len(combined), -1, dtype=np.int32)

        if not found.all():
            import pandas as pd

            miss_rows = np.nonzero(~found)[0]
            miss = combined[miss_rows]
            # hash-based dedup: codes are first-appearance ordinals, uniq is
            # in first-appearance order — new gids therefore keep the
            # assignment-order contract (gid = key_mat row index)
            codes, uniq = pd.factorize(miss, sort=False)
            codes = codes.astype(np.int32, copy=False)
            # first occurrence of code k is where the running code maximum
            # first reaches k (codes are assigned sequentially)
            cummax = np.maximum.accumulate(codes)
            first = np.empty(len(codes), dtype=bool)
            if len(codes):
                first[0] = True
                first[1:] = cummax[1:] > cummax[:-1]
            rep = miss_rows[first]
            base = self.n_groups
            new_gids = base + np.arange(len(uniq), dtype=np.int32)
            new_mat = np.stack(
                [c[rep].astype(np.int64) for c in code_arrays], axis=1
            )
            self.key_mat = np.concatenate([self.key_mat, new_mat])
            all_combined = np.concatenate([self._sorted_combined, uniq])
            all_gids = np.concatenate([self._sorted_gids, new_gids])
            order = np.argsort(all_combined, kind="stable")
            self._sorted_combined = all_combined[order]
            self._sorted_gids = all_gids[order]
            gids[miss_rows] = base + codes
        return gids
