"""Vectorized multi-key group table for the fused aggregate path.

Round-2 assigned dense group ids with a Python loop over every NEW key
combination (``stage_compiler._encode_groups``) — ~3M loop iterations on
q3 SF10, 6 of the stage's 7.8 seconds.  This table keeps everything in
numpy/pandas hash land:

* per-key dictionary codes fold into ONE int64 via per-key bit radixes
  (bits grow with the observed code range; the stored table re-combines
  vectorized when a radix grows);
* known combinations resolve through a ``pandas.Index`` HASH lookup on
  the combined keys in gid order — ``get_indexer`` IS the gid, and at
  q3/h2o scale the hash probe is ~13x faster than the
  ``np.searchsorted`` binary search it replaced (1.0s vs 13.1s for 15M
  lookups into 2M groups: binary search is cache-hostile);
* new combinations batch-append: one hash-based ``pandas.factorize``
  over the misses only (the sort-based ``np.unique`` it replaced was
  10x slower at q3 SF10 scale: 9.6s vs 1.0s on 30M i64 keys).

Group ids are row indices of ``key_mat`` (assignment order), so device
states stay valid as the table grows — matching the adaptive-capacity
contract of the kernels.
"""

from __future__ import annotations

import numpy as np

# combined keys live in int64: total radix bits must stay under 63
_MAX_TOTAL_BITS = 62


class RadixOverflow(Exception):
    """Combined key space exceeds 62 bits — caller falls back."""


class GroupTable:
    def __init__(self, n_keys: int):
        self.n_keys = n_keys
        self.key_mat = np.empty((0, n_keys), dtype=np.int64)
        self._bits = [1] * n_keys
        # combined keys in GID ORDER (row g == combined key of gid g);
        # the pandas hash index over it is built lazily and invalidated
        # by appends and radix regrowth
        self._combined = np.empty(0, dtype=np.int64)
        self._index = None

    @property
    def n_groups(self) -> int:
        return len(self.key_mat)

    def codes_for(self, gids: np.ndarray, key: int) -> np.ndarray:
        """Per-key dictionary codes for the given group ids (vectorized)."""
        return self.key_mat[gids, key]

    # ------------------------------------------------------------ internal
    def _combine(self, code_cols: list[np.ndarray]) -> np.ndarray:
        combined = code_cols[0].astype(np.int64)
        for bits, c in zip(self._bits[1:], code_cols[1:]):
            combined = (combined << bits) | c.astype(np.int64)
        return combined

    def _grow_radix(self, code_arrays: list[np.ndarray]) -> None:
        changed = False
        for k, c in enumerate(code_arrays):
            if len(c) == 0:
                continue
            need = max(1, int(c.max()).bit_length())
            if need > self._bits[k]:
                self._bits[k] = need
                changed = True
        if sum(self._bits) > _MAX_TOTAL_BITS:
            raise RadixOverflow(
                f"combined group-key space needs {sum(self._bits)} bits"
            )
        if changed and self.n_groups:
            self._combined = self._combine(
                [self.key_mat[:, k] for k in range(self.n_keys)]
            )
            self._index = None

    def _lookup(self, combined: np.ndarray) -> np.ndarray:
        """gid per combined key, -1 for unknown combinations (hash probe)."""
        if self.n_groups == 0:
            return np.full(len(combined), -1, dtype=np.int64)
        if self._index is None:
            import pandas as pd

            self._index = pd.Index(self._combined)
        return self._index.get_indexer(combined)

    # ------------------------------------------------------------- encode
    def encode(self, code_arrays: list[np.ndarray]) -> np.ndarray:
        """Dense stable group ids for one batch of per-key code columns."""
        import pandas as pd

        self._grow_radix(code_arrays)
        combined = self._combine(code_arrays)
        gids = self._lookup(combined).astype(np.int32)

        miss_rows = np.nonzero(gids < 0)[0]
        if len(miss_rows):
            miss = combined[miss_rows]
            # hash-based dedup: codes are first-appearance ordinals, uniq
            # is in first-appearance order — new gids therefore keep the
            # assignment-order contract (gid = key_mat row index)
            codes, uniq = pd.factorize(miss, sort=False)
            codes = codes.astype(np.int32, copy=False)
            # first occurrence of code k is where the running code maximum
            # first reaches k (codes are assigned sequentially)
            cummax = np.maximum.accumulate(codes)
            first = np.empty(len(codes), dtype=bool)
            first[0] = True
            first[1:] = cummax[1:] > cummax[:-1]
            rep = miss_rows[first]
            base = self.n_groups
            new_mat = np.stack(
                [c[rep].astype(np.int64) for c in code_arrays], axis=1
            )
            self.key_mat = np.concatenate([self.key_mat, new_mat])
            self._combined = np.concatenate([self._combined, uniq])
            self._index = None
            gids[miss_rows] = base + codes
        return gids
