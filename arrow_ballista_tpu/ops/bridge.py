"""Arrow ⇄ device (HBM) column bridge.

The reference keeps data in Arrow RecordBatches end-to-end; the TPU path
(BASELINE.json north star) moves columns across an Arrow → numpy → jax
bridge into HBM.  Design rules, per the TPU memory model:

* numeric / date columns transfer zero-copy where Arrow's buffer layout
  allows (no nulls → plain numpy view);
* validity bitmaps become separate float/bool masks — downstream kernels
  use masking, never compaction, so shapes stay static for XLA;
* strings never cross to the device raw: they are dictionary-encoded on
  host and only the int32 codes transfer (group keys / comparisons work on
  codes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..errors import ExecutionError


def _is_device_friendly(t: pa.DataType) -> bool:
    return (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_boolean(t)
        or pa.types.is_date(t)
        or pa.types.is_timestamp(t)
    )


def arrow_to_numpy(arr: pa.Array) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """Arrow array → (values ndarray, validity bool ndarray or None).

    Nulls are filled with 0 in the value buffer; the validity mask carries
    the null information to the device.
    """
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    validity = None
    if arr.null_count:
        validity = np.asarray(pc.is_valid(arr))
        arr = arr.fill_null(_zero_for(t))
    if pa.types.is_date32(t):
        values = np.asarray(arr.cast(pa.int32()))
    elif pa.types.is_date64(t) or pa.types.is_timestamp(t):
        values = np.asarray(arr.cast(pa.int64()))
    elif pa.types.is_boolean(t):
        values = np.asarray(arr)
    elif _is_device_friendly(t):
        values = np.asarray(arr)
    else:
        raise ExecutionError(f"type {t} cannot cross the device bridge directly")
    return values, validity


def _zero_for(t: pa.DataType):
    if pa.types.is_date32(t):
        import datetime

        return datetime.date(1970, 1, 1)
    if pa.types.is_timestamp(t):
        import datetime

        return datetime.datetime(1970, 1, 1)
    if pa.types.is_boolean(t):
        return False
    if pa.types.is_floating(t):
        return 0.0
    return 0


@dataclass
class DictEncoder:
    """Stable host-side dictionary encoder shared across batches.

    Per-batch ``dictionary_encode`` yields batch-local codes; group keys
    must agree across every batch of a stage (and across partitions when
    the codes feed a device segment-sum), so this encoder owns the global
    value → code map.  The reverse table materializes the key column of the
    aggregate output.
    """

    values: dict = None  # value -> code
    reverse: list = None

    def __post_init__(self) -> None:
        self.values = {}
        self.reverse = []

    def encode(self, arr: pa.Array) -> np.ndarray:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        enc = arr.dictionary_encode()
        local_dict = enc.dictionary.to_pylist()
        mapping = np.empty(len(local_dict), dtype=np.int32)
        for i, v in enumerate(local_dict):
            code = self.values.get(v)
            if code is None:
                code = len(self.reverse)
                self.values[v] = code
                self.reverse.append(v)
            mapping[i] = code
        idx = enc.indices
        has_null = idx.null_count > 0 or arr.null_count > 0
        codes = np.asarray(idx.fill_null(0))
        out = mapping[codes] if len(mapping) else np.zeros(len(arr), np.int32)
        if has_null:
            null_code = self.values.get(None)
            if null_code is None:
                null_code = len(self.reverse)
                self.values[None] = null_code
                self.reverse.append(None)
            mask = np.asarray(pc.is_null(arr))
            out = np.where(mask, np.int32(null_code), out)
        return out.astype(np.int32)

    @property
    def size(self) -> int:
        return len(self.reverse)

    def to_arrow(self, dtype: pa.DataType) -> pa.Array:
        return pa.array(self.reverse, dtype)

    def decode(
        self, codes: np.ndarray, t: pa.DataType,
        mask: Optional[np.ndarray] = None,
    ) -> pa.Array:
        """codes → original values (vectorized object fancy-index);
        ``mask`` marks null rows (their codes may be garbage)."""
        rev = np.asarray(self.reverse, dtype=object)
        if mask is not None:
            safe = np.where(mask, 0, codes)
            vals = rev[safe] if len(rev) else np.full(len(safe), None)
            return pa.array(vals.tolist(), t, mask=mask)
        return pa.array(rev[codes].tolist(), t)


class IdentityKeyEncoder:
    """Group-key encoder for int/date32 columns: VALUE + 1 is the code
    (code 0 is the NULL key, so nullable key columns stay on device).

    Dictionary-hashing numeric keys costs a Python mapping loop per
    distinct value (2.8s of q3 SF10's stage time in round 3's first cut);
    identity codes cost one astype.  Negative values raise ExecutionError
    — the stage executor turns that into a CPU fallback (rare: pre-1970
    dates or negative keys as GROUP BY columns).
    """

    def encode(self, arr) -> np.ndarray:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        values, validity = arrow_to_numpy(arr)
        v = values.astype(np.int64)
        if len(v) and v.min() < 0:
            raise ExecutionError("negative group key in identity key encoder")
        codes = v + 1
        if validity is not None:
            codes = np.where(validity, codes, 0)
        return codes

    def decode(self, codes: np.ndarray, t: pa.DataType) -> pa.Array:
        mask = codes == 0
        vals = np.where(mask, 0, codes - 1)
        if pa.types.is_date32(t):
            return pa.array(vals.astype("datetime64[D]"), t, mask=mask)
        return pa.array(vals, t, mask=mask)


def make_key_encoder(t: pa.DataType):
    """Identity for int/date32 group keys, dictionary otherwise."""
    if pa.types.is_integer(t) or pa.types.is_date32(t):
        return IdentityKeyEncoder()
    return DictEncoder()

