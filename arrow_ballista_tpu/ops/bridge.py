"""Arrow ⇄ device (HBM) column bridge.

The reference keeps data in Arrow RecordBatches end-to-end; the TPU path
(BASELINE.json north star) moves columns across an Arrow → numpy → jax
bridge into HBM.  Design rules, per the TPU memory model:

* numeric / date columns transfer zero-copy where Arrow's buffer layout
  allows (no nulls → plain numpy view);
* validity bitmaps become separate float/bool masks — downstream kernels
  use masking, never compaction, so shapes stay static for XLA;
* strings never cross to the device raw: they are dictionary-encoded on
  host and only the int32 codes transfer (group keys / comparisons work on
  codes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..errors import ExecutionError


def _is_device_friendly(t: pa.DataType) -> bool:
    return (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_boolean(t)
        or pa.types.is_date(t)
        or pa.types.is_timestamp(t)
    )


def arrow_to_numpy(arr: pa.Array) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """Arrow array → (values ndarray, validity bool ndarray or None).

    Nulls are filled with 0 in the value buffer; the validity mask carries
    the null information to the device.
    """
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    validity = None
    if arr.null_count:
        validity = np.asarray(pc.is_valid(arr))
        arr = arr.fill_null(_zero_for(t))
    if pa.types.is_date32(t):
        values = np.asarray(arr.cast(pa.int32()))
    elif pa.types.is_date64(t) or pa.types.is_timestamp(t):
        values = np.asarray(arr.cast(pa.int64()))
    elif pa.types.is_boolean(t):
        values = np.asarray(arr)
    elif _is_device_friendly(t):
        values = np.asarray(arr)
    else:
        raise ExecutionError(f"type {t} cannot cross the device bridge directly")
    return values, validity


def _zero_for(t: pa.DataType):
    if pa.types.is_date32(t):
        import datetime

        return datetime.date(1970, 1, 1)
    if pa.types.is_timestamp(t):
        import datetime

        return datetime.datetime(1970, 1, 1)
    if pa.types.is_boolean(t):
        return False
    if pa.types.is_floating(t):
        return 0.0
    return 0


def to_u64_order(values: np.ndarray) -> np.ndarray:
    """uint64 whose unsigned order equals the values' natural order
    (IEEE-754 sign-flip trick for floats, bias flip for ints)."""
    if values.dtype.kind == "f":
        v = values.astype(np.float64)
        bits = v.view(np.uint64)
        neg = (bits >> np.uint64(63)) == 1
        mask = np.where(
            neg,
            np.uint64(0xFFFFFFFFFFFFFFFF),
            np.uint64(1) << np.uint64(63),
        )
        return bits ^ mask
    return values.astype(np.int64).view(np.uint64) ^ (
        np.uint64(1) << np.uint64(63)
    )


def split_u64_i32(u: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(hi, lo) i32 pair whose LEXICOGRAPHIC signed order equals the
    unsigned order of ``u`` — 64-bit order relations on a device without
    64-bit dtypes (sort keys, exact f64 min/max in x32 mode)."""
    hi = ((u >> np.uint64(32)).astype(np.int64) - (1 << 31)).astype(np.int32)
    lo = ((u & np.uint64(0xFFFFFFFF)).astype(np.int64) - (1 << 31)).astype(
        np.int32
    )
    return hi, lo


def join_u64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Inverse of ``split_u64_i32``: biased (hi, lo) i32 pair → u64
    whose unsigned order equals the pair's lexicographic signed order.
    MUST stay in uint64 — packing in int64 wraps negative for every
    biased hi >= 2^31 (all non-negative values), inverting the order."""
    return (
        ((hi.astype(np.int64) + (1 << 31)).astype(np.uint64) << np.uint64(32))
        | (lo.astype(np.int64) + (1 << 31)).astype(np.uint64)
    )


def order_decode_f64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Inverse of ``to_u64_order`` + ``split_u64_i32`` for f64 values."""
    u = join_u64(hi, lo)
    neg = (u >> np.uint64(63)) == 0  # sign bit was flipped on encode
    mask = np.where(
        neg, np.uint64(0xFFFFFFFFFFFFFFFF), np.uint64(1) << np.uint64(63)
    )
    return (u ^ mask).view(np.float64)


@dataclass
class DictEncoder:
    """Stable host-side dictionary encoder shared across batches.

    Per-batch ``dictionary_encode`` yields batch-local codes; group keys
    must agree across every batch of a stage (and across partitions when
    the codes feed a device segment-sum), so this encoder owns the global
    value → code map: an ARROW array whose position IS the code, probed
    with ``pc.index_in`` (C++ hash).  The round-3 design round-tripped
    every batch's local dictionary through Python objects — seconds per
    batch at h2o id3 scale (~1e6 distinct strings); no Python value ever
    materializes here.  NULL keys get a real (null) slot in the array, so
    ``decode`` is a single ``take``.
    """

    _dict: Optional[pa.Array] = None  # position == code; may hold 1 null

    def encode(self, arr: pa.Array) -> np.ndarray:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        enc = arr.dictionary_encode()
        local = enc.dictionary  # distinct NON-NULL values, arrow-native
        n_local = len(local)
        if self._dict is not None and not self._dict.type.equals(local.type):
            local = local.cast(self._dict.type)
        if self._dict is None or len(self._dict) == 0:
            got_np = np.full(n_local, -1, dtype=np.int64)
        else:
            got = pc.index_in(local, value_set=self._dict)
            got_np = np.asarray(got.fill_null(-1)).astype(np.int64)
        mapping = got_np
        miss = mapping < 0
        n_miss = int(miss.sum())
        if n_miss:
            new_vals = local.filter(pa.array(miss))
            base = len(self._dict) if self._dict is not None else 0
            self._dict = (
                pa.concat_arrays([self._dict, new_vals])
                if self._dict is not None
                else new_vals
            )
            mapping = mapping.copy()
            mapping[miss] = base + np.arange(n_miss)
        idx = enc.indices
        has_null = idx.null_count > 0 or arr.null_count > 0
        codes = np.asarray(idx.fill_null(0))
        out = mapping[codes] if n_local else np.zeros(len(arr), np.int64)
        if has_null:
            out = np.where(
                np.asarray(pc.is_null(arr)), self._null_code(local.type), out
            )
        return out.astype(np.int32)

    def _null_code(self, t: pa.DataType) -> int:
        """Code of the NULL key: a real null slot in the value array, so
        decode's take materializes it as null with no special case."""
        if self._dict is not None:
            nulls = np.asarray(pc.is_null(self._dict))
            hit = np.nonzero(nulls)[0]
            if len(hit):
                return int(hit[0])
        code = len(self._dict) if self._dict is not None else 0
        null1 = pa.nulls(1, self._dict.type if self._dict is not None else t)
        self._dict = (
            pa.concat_arrays([self._dict, null1])
            if self._dict is not None
            else null1
        )
        return code

    @property
    def size(self) -> int:
        return len(self._dict) if self._dict is not None else 0

    def to_arrow(self, dtype: pa.DataType) -> pa.Array:
        if self._dict is None:
            return pa.nulls(0, dtype)
        return (
            self._dict
            if self._dict.type.equals(dtype)
            else self._dict.cast(dtype)
        )

    def decode(
        self, codes: np.ndarray, t: pa.DataType,
        mask: Optional[np.ndarray] = None,
    ) -> pa.Array:
        """codes → original values (one arrow ``take``); ``mask`` marks
        null rows (their codes may be garbage)."""
        if self._dict is None or len(self._dict) == 0:
            return pa.nulls(len(codes), t)
        safe = np.where(mask, 0, codes) if mask is not None else codes
        vals = self._dict.take(pa.array(safe.astype(np.int64)))
        if not vals.type.equals(t):
            vals = vals.cast(t)
        if mask is not None and mask.any():
            vals = pc.if_else(pa.array(mask), pa.scalar(None, t), vals)
        return vals


class IdentityKeyEncoder:
    """Group-key encoder for int/date32 columns: VALUE + 1 is the code
    (code 0 is the NULL key, so nullable key columns stay on device).

    Dictionary-hashing numeric keys costs a Python mapping loop per
    distinct value (2.8s of q3 SF10's stage time in round 3's first cut);
    identity codes cost one astype.  Negative values raise ExecutionError
    — the stage executor turns that into a CPU fallback (rare: pre-1970
    dates or negative keys as GROUP BY columns).
    """

    def encode(self, arr) -> np.ndarray:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        values, validity = arrow_to_numpy(arr)
        v = values.astype(np.int64)
        if len(v) and v.min() < 0:
            raise ExecutionError("negative group key in identity key encoder")
        codes = v + 1
        if validity is not None:
            codes = np.where(validity, codes, 0)
        return codes

    def decode(self, codes: np.ndarray, t: pa.DataType) -> pa.Array:
        mask = codes == 0
        vals = np.where(mask, 0, codes - 1)
        if pa.types.is_date32(t):
            return pa.array(vals.astype("datetime64[D]"), t, mask=mask)
        return pa.array(vals, t, mask=mask)


class BoolKeyEncoder:
    """Group-key encoder for bool columns: null → 0, False → 1, True → 2.

    Identity-style (one astype, no dictionary hashing) and pure in the
    VALUE, so the device twin (``kernels.device_encode_key("bool", …)``)
    produces bit-identical codes and bool keys ride the fused keyed
    path."""

    def encode(self, arr) -> np.ndarray:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        values, validity = arrow_to_numpy(arr)
        codes = values.astype(np.int64) + 1
        if validity is not None:
            codes = np.where(validity, codes, 0)
        return codes

    def decode(self, codes: np.ndarray, t: pa.DataType) -> pa.Array:
        mask = codes == 0
        return pa.array(np.maximum(codes - 1, 0).astype(bool), t, mask=mask)


class FloatKeyEncoder:
    """Group-key encoder for float columns: the code IS the raw bit
    pattern (f32 → i32 bits, f64 → i64 bits).  Pure bit-pattern
    grouping matches the CPU hash aggregate exactly — its
    ``dictionary_encode`` distinguishes ``-0.0`` from ``+0.0`` and NaN
    payloads from each other (measured), and the CPU-vs-TPU identity
    contract follows the engine, not IEEE equality.  NULL takes ONE
    reserved NaN pattern; data that contains that exact payload raises
    ``ExecutionError`` (→ host-route fallback), the same escape hatch
    the identity encoder uses for negative keys.  Pure in the value (no
    dictionary state), so the device twin produces bit-identical codes;
    codes can be negative, which the keyed sort handles but
    ``GroupTable`` radix-combining does not — the gid route keeps its
    dictionary encoder for floats, this encoder exists for the
    device-encoded keyed route."""

    def __init__(self, kind: str):  # "f32" | "f64"
        self.kind = kind

    def encode(self, arr) -> np.ndarray:
        from . import kernels as K

        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        values, validity = arrow_to_numpy(arr)
        if self.kind == "f32":
            v = values.astype(np.float32)
            bits = v.view(np.int32).astype(np.int64)
            null = K.FLOAT32_NULL_BITS
        else:
            v = values.astype(np.float64)
            bits = v.view(np.int64).copy()
            null = K.FLOAT64_NULL_BITS
        if validity is not None:
            hit = (bits == null) & validity
            bits = np.where(validity, bits, null)
        else:
            hit = bits == null
        if bool(np.any(hit)):
            raise ExecutionError(
                "float group key collides with the reserved null pattern"
            )
        return bits.astype(np.int64)

    def decode(self, codes: np.ndarray, t: pa.DataType) -> pa.Array:
        from . import kernels as K

        if self.kind == "f32":
            mask = codes == K.FLOAT32_NULL_BITS
            vals = (
                np.where(mask, 0, codes).astype(np.int32).view(np.float32)
            )
        else:
            mask = codes == K.FLOAT64_NULL_BITS
            vals = np.where(mask, 0, codes).astype(np.int64).view(np.float64)
        arr = pa.array(vals.astype(np.float64), pa.float64(), mask=mask)
        return arr if arr.type.equals(t) else arr.cast(t)


def make_key_encoder(t: pa.DataType):
    """Identity for int/date32 group keys, bool codes for booleans,
    dictionary otherwise."""
    if pa.types.is_integer(t) or pa.types.is_date32(t):
        return IdentityKeyEncoder()
    if pa.types.is_boolean(t):
        return BoolKeyEncoder()
    return DictEncoder()


def device_key_encoder(t: pa.DataType, mode: str):
    """(encoder, device-kind) for the device-encoded keyed route.

    The kind names a :func:`kernels.device_encode_key` branch whose
    device codes are bit-identical to ``encoder.encode``; ``None`` means
    the key stays on the host dictionary handoff (strings, decimals —
    and f64 in x32 mode, whose 64-bit pattern cannot ship).  Falls back
    to :func:`make_key_encoder` for the ``None`` kinds so decode
    behavior matches the host route exactly."""
    if pa.types.is_integer(t) or pa.types.is_date32(t):
        return IdentityKeyEncoder(), "ident"
    if pa.types.is_boolean(t):
        return BoolKeyEncoder(), "bool"
    if pa.types.is_float32(t):
        return FloatKeyEncoder("f32"), "f32"
    if pa.types.is_float64(t) and mode != "x32":
        return FloatKeyEncoder("f64"), "f64"
    return make_key_encoder(t), None


def coalesce_batches(source, target_rows: int, metrics=None):
    """Host-side batch coalescer feeding the device bridge.

    Shuffle readers yield one fragment per map task — with 16 map tasks an
    8192-row batch arrives as ~512-row slivers, and each sliver would pay
    a full key-encode + host→HBM dispatch.  Combine consecutive fragments
    up to ``target_rows`` before they cross the bridge; batches already at
    or above the target pass through untouched (no re-copy of big data).
    Row content and order of the combined stream are unchanged.
    """
    buf: list[pa.RecordBatch] = []
    rows = 0
    for b in source:
        if b.num_rows == 0:
            continue
        if b.num_rows >= target_rows:
            # big batch: flush pending fragments, then pass it through
            # untouched — never fold big data into a concat just to
            # prepend a sliver
            if buf:
                if metrics is not None:
                    metrics.add("coalesced_source_batches", len(buf))
                yield _concat_batches(buf)
                buf, rows = [], 0
            yield b
            continue
        if buf and rows + b.num_rows > target_rows:
            # flush BEFORE appending: an emitted batch never exceeds the
            # target, or it would land in a larger device padding bucket
            # than batch_size and trigger a fresh XLA compile
            if metrics is not None:
                metrics.add("coalesced_source_batches", len(buf))
            yield _concat_batches(buf)
            buf, rows = [], 0
        buf.append(b)
        rows += b.num_rows
        if rows >= target_rows:
            if metrics is not None:
                metrics.add("coalesced_source_batches", len(buf))
            yield _concat_batches(buf)
            buf, rows = [], 0
    if buf:
        if metrics is not None:
            metrics.add("coalesced_source_batches", len(buf))
        yield _concat_batches(buf)


def _concat_batches(parts: list) -> pa.RecordBatch:
    if len(parts) == 1:
        return parts[0]
    tbl = pa.Table.from_batches(parts).combine_chunks()
    batches = tbl.to_batches()
    return batches[0] if batches else parts[0]

