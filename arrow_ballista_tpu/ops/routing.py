"""Measured routing table for the device operator paths.

The r04/r05 verdict discipline — "routing constants must cite a measured
artifact, not a guess" — becomes code here: every threshold that steers
a batch between device strategies (matmul vs sort segment reduction, the
groups~rows high-cardinality detector, whether ``auto`` routes keyed
plans to the fused device-KEYED path) loads from a machine-readable
artifact emitted by ``dev/analyze_grid.py --emit`` over KERNELBENCH
grids.  ``arrow_ballista_tpu/ops/routing_table.json`` ships the table
generated from the most recent grid capture; regenerate it with::

    python dev/analyze_grid.py KERNELBENCH_rXX.json --emit \
        arrow_ballista_tpu/ops/routing_table.json

``BALLISTA_ROUTING_TABLE`` overrides the artifact path (empty string
disables loading).  With no artifact present the BUILTIN defaults apply
— the exact constants that lived in ``ops/kernels.py`` and
``ops/stage_compiler.py`` before this table existed (their measurement
provenance is recorded per field below), so behavior without an
artifact is unchanged.

Thresholds are PER PLATFORM (``jax.default_backend()``): the same
kernel grid that says matmul wins to capacity 8192 on a v5e says
scatter wins everywhere on the CPU backend.  A platform missing from
the artifact falls back to the builtin defaults.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, fields

SCHEMA = "ballista.routing/v1"

# Builtin defaults == the pre-table constants, with their provenance:
#   matmul_max_cap / matmul_max_elems — r05 chip capture: MXU one-hot
#     einsum beats the sort path while capacity <= 8192 and
#     rows x capacity <= 2^36 (ops/kernels.py segment-strategy comment);
#   highcard_min_groups / highcard_ratio — groups~rows detector bounds
#     (heuristic pending a full chip kernel grid, BENCH_SUITE_r05);
#   keyed_route_auto — whether 'auto' highcard mode routes groups~rows
#     to the device-keyed fused path: False everywhere measured so far
#     (KERNELBENCH_r05 segment_reduce: keyed 2.2M rows/s vs scatter
#     140-240M on the cpu platform; BENCH_SUITE_r05 q3 SF10 keyed =
#     0.036x CPU on chip);
#   fusion_max_ops — widest operator run the whole-stage fusion planner
#     packs into one traced segment before forcing a capacity cut (the
#     pre-table _FUSED_MAX_ENTRIES unroll discipline applied to operator
#     count: XLA programs linear in fused-op count stay cheap to this
#     width on every platform measured);
#   fusion_min_rows — below this many stage input rows a fused dispatch
#     does not amortize its trace/launch overhead and the per-batch
#     streamed path runs instead (matches the pre-table small-input
#     routing floor).
_DEFAULTS = {
    "matmul_max_cap": 8192,
    "matmul_max_elems": 1 << 36,
    "highcard_min_groups": 1 << 16,
    "highcard_ratio": 0.05,
    "keyed_route_auto": False,
    "fusion_max_ops": 8,
    "fusion_min_rows": 2048,
}

# the emitted per-platform section: exactly these keys (a unit test pins
# the shape so regenerating from a new grid can't silently drift)
PLATFORM_FIELDS = tuple(sorted(_DEFAULTS))


@dataclass(frozen=True)
class RoutingTable:
    matmul_max_cap: int
    matmul_max_elems: int
    highcard_min_groups: int
    highcard_ratio: float
    keyed_route_auto: bool
    fusion_max_ops: int
    fusion_min_rows: int
    source: str = "builtin defaults (pre-table ops/ constants)"


_BUILTIN = RoutingTable(**_DEFAULTS)


def default_artifact_path() -> str:
    return os.path.join(os.path.dirname(__file__), "routing_table.json")


def _load_artifact() -> dict:
    """platform -> RoutingTable from the artifact (empty on any problem:
    routing must never break a query — the builtin defaults always
    work)."""
    path = os.environ.get("BALLISTA_ROUTING_TABLE")
    if path == "":
        return {}
    if path is None:
        path = default_artifact_path()
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") != SCHEMA:
            return {}
        out = {}
        for platform, vals in (doc.get("platforms") or {}).items():
            merged = dict(_DEFAULTS)
            merged.update(
                {k: vals[k] for k in PLATFORM_FIELDS if k in vals}
            )
            out[platform] = RoutingTable(
                **merged, source=os.path.abspath(path)
            )
        return out
    except (OSError, ValueError, TypeError):
        return {}


_TABLES: dict = _load_artifact()


def reload(path: str | None = None) -> None:
    """Re-read the artifact (tests; ``path`` overrides the env/default
    resolution for this call)."""
    global _TABLES
    if path is not None:
        old = os.environ.get("BALLISTA_ROUTING_TABLE")
        os.environ["BALLISTA_ROUTING_TABLE"] = path
        try:
            _TABLES = _load_artifact()
        finally:
            if old is None:
                del os.environ["BALLISTA_ROUTING_TABLE"]
            else:
                os.environ["BALLISTA_ROUTING_TABLE"] = old
    else:
        _TABLES = _load_artifact()


def current() -> RoutingTable:
    """The table for the active jax platform (resolved lazily — import
    must not initialize a device backend)."""
    import jax

    return _TABLES.get(jax.default_backend(), _BUILTIN)


def value(name: str):
    """One threshold for the active platform (name is a RoutingTable
    field)."""
    return getattr(current(), name)


def field_names() -> tuple:
    return tuple(f.name for f in fields(RoutingTable) if f.name != "source")
