"""Device-resident column cache.

The reference re-reads Arrow batches from disk/Flight on every query; on
TPU the dominant per-query cost is host→HBM transfer plus host-side key
encoding.  This cache pins a scan's prepared kernel inputs (padded leaf
arrays, validity masks, segment ids, group dictionaries) in device memory
keyed by (provider, partition, stage signature): repeated analytical
queries over registered tables then run entirely out of HBM — the
TPU-native equivalent of a warehouse buffer pool.

Bounded: entries are LRU-evicted once the pinned-byte budget (default
4 GiB, ~¼ of a v5e chip's HBM) is exceeded, and dropped when the owning
TableProvider is garbage-collected.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Any, Optional

DEFAULT_BUDGET_BYTES = 4 << 30

_CACHE: "OrderedDict[tuple[int, int, str], tuple[Any, int]]" = OrderedDict()
_REGISTERED: set[int] = set()
_total_bytes = 0
_budget = DEFAULT_BUDGET_BYTES


def staging_bytes() -> int:
    """Bytes sitting in shuffle prefetch queues (fetched but not yet
    consumed / transferred).  Tracked jax-free in ``shuffle.fetcher``;
    surfaced here so stats() shows BOTH memory pressures of the data
    plane — pinned HBM and in-flight host staging — in one place."""
    from ..shuffle.fetcher import staging_bytes as _fetch_staging

    return _fetch_staging()


def set_budget(n_bytes: int) -> None:
    global _budget
    _budget = n_bytes
    _evict_to_budget()


def _entry_bytes(value: Any) -> int:
    """Estimate pinned bytes: sum of .nbytes over device arrays inside."""
    n = 0
    entries = value[0] if isinstance(value, tuple) and value else []
    for item in entries:
        seg, valid, args = item
        for a in (seg, valid, *args):
            n += getattr(a, "nbytes", 0)
    return n


def _evict_provider(pid: int) -> None:
    global _total_bytes
    for k in [k for k in _CACHE if k[0] == pid]:
        _, nb = _CACHE.pop(k)
        _total_bytes -= nb
    _REGISTERED.discard(pid)


def _evict_to_budget() -> None:
    global _total_bytes
    while _total_bytes > _budget and _CACHE:
        _, (_, nb) = _CACHE.popitem(last=False)  # LRU
        _total_bytes -= nb


def get(provider: Any, partition: int, signature: str) -> Optional[Any]:
    k = (id(provider), partition, signature)
    hit = _CACHE.get(k)
    if hit is None:
        return None
    _CACHE.move_to_end(k)
    return hit[0]


def put(provider: Any, partition: int, signature: str, value: Any) -> None:
    global _total_bytes
    pid = id(provider)
    if pid not in _REGISTERED:
        try:
            weakref.finalize(provider, _evict_provider, pid)
            _REGISTERED.add(pid)
        except TypeError:
            return  # provider not weakref-able: skip caching
    nb = _entry_bytes(value)
    if nb > _budget:
        return  # larger than the whole budget: not worth pinning
    k = (pid, partition, signature)
    old = _CACHE.pop(k, None)
    if old is not None:
        _total_bytes -= old[1]
    _CACHE[k] = (value, nb)
    _total_bytes += nb
    _evict_to_budget()


def clear() -> None:
    global _total_bytes
    _CACHE.clear()
    _REGISTERED.clear()
    _total_bytes = 0


def stats() -> dict:
    return {
        "entries": len(_CACHE),
        "bytes": _total_bytes,
        "budget": _budget,
        "staging_bytes": staging_bytes(),
    }
