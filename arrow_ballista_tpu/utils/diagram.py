"""GraphViz plan / execution-graph diagrams.

Counterpart of the reference's ``produce_diagram``
(``core/src/utils.rs:109-224``), which renders a job's query-stage DAG as
dot: one cluster per stage, one node per operator, edges child→parent
inside a stage and shuffle edges between stages.  Render with
``dot -Tsvg``.
"""

from __future__ import annotations

from typing import Optional


def _esc(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def _label(op) -> str:
    text = str(op)
    return text if len(text) <= 80 else text[:77] + "..."


def produce_plan_diagram(plan, title: str = "plan") -> str:
    """Dot text for a single (logical or physical) operator tree."""
    lines = [
        "digraph G {",
        f'  label = "{_esc(title)}";',
        "  node [shape=box, fontname=monospace, fontsize=10];",
    ]
    counter = [0]

    def walk(op) -> int:
        my_id = counter[0]
        counter[0] += 1
        lines.append(f'  n{my_id} [label="{_esc(_label(op))}"];')
        for child in op.children():
            cid = walk(child)
            lines.append(f"  n{cid} -> n{my_id};")
        return my_id

    walk(plan)
    lines.append("}")
    return "\n".join(lines)


def produce_diagram(graph, title: Optional[str] = None) -> str:
    """Dot text for a job's ExecutionGraph: one subgraph cluster per stage
    (labelled with its state), operator nodes inside, and shuffle edges
    from each stage's root to the stages that consume its output
    (``output_links``) — the shape of ``core/src/utils.rs:109-224``."""
    from ..shuffle.execution_plans import ShuffleReaderExec, UnresolvedShuffleExec

    lines = [
        "digraph G {",
        f'  label = "{_esc(title or f"job {graph.job_id}")}";',
        "  compound = true;",
        "  node [shape=box, fontname=monospace, fontsize=10];",
    ]
    counter = [0]
    stage_root: dict[int, int] = {}  # stage id → root node id
    stage_readers: dict[int, list[tuple[int, int]]] = {}  # producer → [(node, consumer)]

    for sid in sorted(graph.stages):
        stage = graph.stages[sid]
        state = type(stage).__name__.replace("Stage", "")
        lines.append(f"  subgraph cluster_{sid} {{")
        lines.append(f'    label = "Stage {sid} [{state}]";')

        def walk(op) -> int:
            my_id = counter[0]
            counter[0] += 1
            lines.append(f'    n{my_id} [label="{_esc(_label(op))}"];')
            if isinstance(op, (ShuffleReaderExec, UnresolvedShuffleExec)):
                producer = getattr(op, "stage_id", None)
                if producer is not None:
                    stage_readers.setdefault(producer, []).append((my_id, sid))
            for child in op.children():
                cid = walk(child)
                lines.append(f"    n{cid} -> n{my_id};")
            return my_id

        stage_root[sid] = walk(stage.plan)
        lines.append("  }")

    # shuffle edges: producer stage root → consumer stage's reader node
    for producer, readers in stage_readers.items():
        if producer in stage_root:
            for node, _consumer in readers:
                lines.append(
                    f"  n{stage_root[producer]} -> n{node} [style=dashed];"
                )
    # fall back to output_links for stages whose consumers hold resolved
    # readers without stage ids
    for sid in sorted(graph.stages):
        stage = graph.stages[sid]
        for link in getattr(stage, "output_links", []) or []:
            if link in stage_root and sid not in stage_readers:
                lines.append(
                    f"  n{stage_root[sid]} -> n{stage_root[link]}"
                    " [style=dashed, color=gray];"
                )
    lines.append("}")
    return "\n".join(lines)


def save_diagram(graph, path: str, title: Optional[str] = None) -> None:
    with open(path, "w") as f:
        f.write(produce_diagram(graph, title))
