"""Shared utilities."""

from __future__ import annotations

import os


def apply_jax_platform_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative.

    jax honors the env var itself, but platform *plugins* registered via
    entry points can pin a different backend regardless; the config API
    always wins, so process entry points (scheduler/executor binaries,
    benchmark harnesses) call this before any jax compute to guarantee
    ``JAX_PLATFORMS=cpu`` really means cpu.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
