"""arrow-ballista-tpu: a TPU-native distributed SQL query engine.

A from-scratch rebuild of Apache Arrow Ballista's capability set
(reference at /root/reference) on a JAX/XLA/TPU execution backend:
eligible per-stage subplans run as fused XLA kernels on TPU, partial
aggregates reduce across chips over ICI, and an Arrow Flight data plane
moves shuffle partitions between executors over DCN.
"""

__version__ = "0.1.0"

from .config import BallistaConfig, TaskSchedulingPolicy
from .context import DataFrame, SessionContext
from .errors import BallistaError
from .plan.expressions import col, lit

__all__ = [
    "BallistaConfig",
    "TaskSchedulingPolicy",
    "SessionContext",
    "DataFrame",
    "BallistaError",
    "col",
    "lit",
]
