"""Recursive-descent SQL parser for the TPC-H dialect + client DDL.

The reference relies on sqlparser-rs via DataFusion; this is a from-scratch
frontend sized to the reference's supported surface: SELECT queries with
joins / subqueries / aggregates (benchmarks/queries/q1-q22 in the reference),
plus CREATE EXTERNAL TABLE / SHOW / SET handled by the client context
(reference client/src/context.rs:313-460).
"""

from __future__ import annotations

from typing import Optional

from ..errors import SqlError
from . import ast
from .lexer import Token, TokType, tokenize

_RESERVED_STOPWORDS = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "ON",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "AND", "OR", "NOT",
    "AS", "BY", "ASC", "DESC", "UNION", "SELECT", "WHEN", "THEN", "ELSE",
    "END", "CASE", "IS", "IN", "BETWEEN", "LIKE", "EXISTS", "NULLS", "SET",
    "USING", "OUTER", "SEMI", "ANTI",
}


class Parser:
    def __init__(self, sql: str) -> None:
        self.toks = tokenize(sql)
        self.i = 0

    # ------------------------------------------------------------- helpers
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.type is not TokType.EOF:
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.type is TokType.IDENT and t.upper in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise SqlError(f"expected {kw}, found {self.peek().value!r} at {self.peek().pos}")

    def expect(self, type_: TokType) -> Token:
        t = self.next()
        if t.type is not type_:
            raise SqlError(f"expected {type_.name}, found {t.value!r} at {t.pos}")
        return t

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.type is TokType.OP and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    # ---------------------------------------------------------- statements
    def parse_statement(self) -> ast.Statement:
        stmt = self._statement()
        if self.peek().type is TokType.SEMICOLON:
            self.next()
        if self.peek().type is not TokType.EOF:
            raise SqlError(f"unexpected trailing input at {self.peek().pos}: {self.peek().value!r}")
        return stmt

    def _statement(self) -> ast.Statement:
        if self.at_kw("SELECT", "WITH") or self.peek().type is TokType.LPAREN:
            return self.parse_query()
        if self.at_kw("CREATE"):
            return self._create_external_table()
        if self.at_kw("SHOW"):
            self.next()
            parts = []
            while self.peek().type is TokType.IDENT:
                parts.append(self.next().value)
            return ast.ShowStmt(parts)
        if self.at_kw("SET"):
            self.next()
            name_parts = [self.expect(TokType.IDENT).value]
            while self.eat_op("."):
                name_parts.append(self.expect(TokType.IDENT).value)
            if not self.eat_op("="):
                self.expect_kw("TO")
            t = self.next()
            if t.type not in (TokType.STRING, TokType.NUMBER, TokType.IDENT):
                raise SqlError(f"bad SET value at {t.pos}")
            return ast.SetVariable(".".join(name_parts), t.value)
        if self.at_kw("EXPLAIN"):
            self.next()
            verbose = self.eat_kw("VERBOSE")
            analyze = self.eat_kw("ANALYZE")
            return ast.Explain(self.parse_query(), verbose, analyze)
        if self.at_kw("DROP"):
            self.next()
            self.expect_kw("TABLE")
            if_exists = False
            if self.eat_kw("IF"):
                self.expect_kw("EXISTS")
                if_exists = True
            return ast.DropTable(self._identifier(), if_exists)
        raise SqlError(f"unsupported statement starting with {self.peek().value!r}")

    def _create_external_table(self) -> ast.CreateExternalTable:
        self.expect_kw("CREATE")
        self.expect_kw("EXTERNAL")
        self.expect_kw("TABLE")
        if_not_exists = False
        if self.eat_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            if_not_exists = True
        name = self._identifier()
        columns: list[tuple[str, str]] = []
        if self.peek().type is TokType.LPAREN:
            self.next()
            while True:
                col = self._identifier()
                ty_parts = [self._identifier()]
                # multi-word / parameterized types: DECIMAL(12,2), DOUBLE PRECISION
                if self.peek().type is TokType.LPAREN:
                    self.next()
                    ty_parts.append("(")
                    while self.peek().type is not TokType.RPAREN:
                        ty_parts.append(self.next().value)
                    self.next()
                    ty_parts.append(")")
                elif self.at_kw("PRECISION"):
                    ty_parts.append(self.next().value)
                columns.append((col, " ".join(ty_parts)))
                if not self.eat_op(",") and self.peek().type is not TokType.COMMA:
                    break
                if self.peek().type is TokType.COMMA:
                    self.next()
            self.expect(TokType.RPAREN)
        file_type = "CSV"
        has_header = False
        delimiter = ","
        if self.eat_kw("STORED"):
            self.expect_kw("AS")
            file_type = self.next().upper
        if self.eat_kw("WITH"):
            self.expect_kw("HEADER")
            self.expect_kw("ROW")
            has_header = True
        if self.eat_kw("DELIMITER"):
            delimiter = self.expect(TokType.STRING).value
        self.expect_kw("LOCATION")
        location = self.expect(TokType.STRING).value
        return ast.CreateExternalTable(
            name, file_type, location, columns, has_header, delimiter, if_not_exists
        )

    # -------------------------------------------------------------- queries
    def parse_query(self) -> ast.Query:
        if self.peek().type is TokType.LPAREN:
            # parenthesized query
            self.next()
            q = self.parse_query()
            self.expect(TokType.RPAREN)
            return q
        ctes: list[tuple[str, ast.Query]] = []
        if self.eat_kw("WITH"):
            while True:
                name = self._identifier()
                self.expect_kw("AS")
                self.expect(TokType.LPAREN)
                sub = self.parse_query()
                self.expect(TokType.RPAREN)
                ctes.append((name, sub))
                if self.peek().type is TokType.COMMA:
                    self.next()
                else:
                    break
        self.expect_kw("SELECT")
        q = ast.Query()
        q.ctes = ctes
        q.distinct = self.eat_kw("DISTINCT")
        self.eat_kw("ALL")
        q.select = self._select_list()
        if self.eat_kw("FROM"):
            q.from_ = self._table_refs()
        if self.eat_kw("WHERE"):
            q.where = self.parse_expr()
        if self.eat_kw("GROUP"):
            self.expect_kw("BY")
            q.group_by = self._expr_list()
        if self.eat_kw("HAVING"):
            q.having = self.parse_expr()
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            q.order_by = self._order_items()
        if self.eat_kw("LIMIT"):
            q.limit = int(self.expect(TokType.NUMBER).value)
        if self.eat_kw("OFFSET"):
            q.offset = int(self.expect(TokType.NUMBER).value)
        return q

    def _select_list(self) -> list[ast.SelectItem]:
        items = [self._select_item()]
        while self.peek().type is TokType.COMMA:
            self.next()
            items.append(self._select_item())
        return items

    def _select_item(self) -> ast.SelectItem:
        expr = self.parse_expr()
        alias: Optional[str] = None
        if self.eat_kw("AS"):
            alias = self._identifier()
        elif self.peek().type is TokType.IDENT and self.peek().upper not in _RESERVED_STOPWORDS:
            alias = self.next().value
        elif self.peek().type is TokType.QUOTED_IDENT:
            alias = self.next().value
        return ast.SelectItem(expr, alias)

    def _table_refs(self) -> list[ast.TableRef]:
        refs = [self._table_ref_with_joins()]
        while self.peek().type is TokType.COMMA:
            self.next()
            refs.append(self._table_ref_with_joins())
        return refs

    def _table_ref_with_joins(self) -> ast.TableRef:
        left = self._table_primary()
        while True:
            kind = None
            if self.at_kw("JOIN"):
                kind = "INNER"
                self.next()
            elif self.at_kw("INNER") and self.peek(1).upper == "JOIN":
                self.next(); self.next()
                kind = "INNER"
            elif self.at_kw("LEFT", "RIGHT", "FULL"):
                kind = self.next().upper
                self.eat_kw("OUTER")
                self.expect_kw("JOIN")
            elif self.at_kw("CROSS") and self.peek(1).upper == "JOIN":
                self.next(); self.next()
                kind = "CROSS"
            else:
                break
            right = self._table_primary()
            on = None
            if kind != "CROSS":
                self.expect_kw("ON")
                on = self.parse_expr()
            left = ast.JoinClause(left, right, kind, on)
        return left

    def _table_primary(self) -> ast.TableRef:
        if self.peek().type is TokType.LPAREN:
            self.next()
            q = self.parse_query()
            self.expect(TokType.RPAREN)
            self.eat_kw("AS")
            alias = self._identifier()
            return ast.DerivedTable(q, alias)
        name = self._identifier()
        while self.eat_op("."):  # schema-qualified: keep last part
            name = self._identifier()
        alias = None
        if self.eat_kw("AS"):
            alias = self._identifier()
        elif (
            self.peek().type is TokType.IDENT
            and self.peek().upper not in _RESERVED_STOPWORDS
        ):
            alias = self.next().value
        return ast.NamedTable(name, alias)

    def _rows_frame(self) -> ast.WindowFrame:
        """ROWS BETWEEN <bound> AND <bound>, or the one-bound shorthand
        ROWS <bound> (= BETWEEN <bound> AND CURRENT ROW)."""

        def bound():
            """(offset | None, direction) — direction disambiguates which
            side UNBOUNDED points to."""
            if self.eat_kw("UNBOUNDED"):
                if self.eat_kw("PRECEDING"):
                    return None, "preceding"
                self.expect_kw("FOLLOWING")
                return None, "following"
            if self.eat_kw("CURRENT"):
                self.expect_kw("ROW")
                return 0, "current"
            tok = self.expect(TokType.NUMBER)
            try:
                n = int(tok.value)
            except ValueError as err:
                raise SqlError(
                    f"ROWS frame bound must be an integer, got {tok.value!r}"
                ) from err
            if self.eat_kw("PRECEDING"):
                return -n, "preceding"
            self.expect_kw("FOLLOWING")
            return n, "following"

        if self.eat_kw("BETWEEN"):
            start, sdir = bound()
            self.expect_kw("AND")
            end, edir = bound()
            if start is None and sdir == "following":
                raise SqlError("frame start cannot be UNBOUNDED FOLLOWING")
            if end is None and edir == "preceding":
                raise SqlError("frame end cannot be UNBOUNDED PRECEDING")
        else:
            start, sdir = bound()
            if sdir == "following" and start is not None and start > 0:
                raise SqlError(
                    "a one-bound ROWS frame must start at or before "
                    "CURRENT ROW"
                )
            if start is None and sdir == "following":
                raise SqlError("frame start cannot be UNBOUNDED FOLLOWING")
            end = 0
        return ast.WindowFrame(start, end)

    def _order_items(self) -> list[ast.OrderItem]:
        items = []
        while True:
            e = self.parse_expr()
            asc = True
            if self.eat_kw("ASC"):
                asc = True
            elif self.eat_kw("DESC"):
                asc = False
            nulls_first = None
            if self.eat_kw("NULLS"):
                if self.eat_kw("FIRST"):
                    nulls_first = True
                else:
                    self.expect_kw("LAST")
                    nulls_first = False
            items.append(ast.OrderItem(e, asc, nulls_first))
            if self.peek().type is TokType.COMMA:
                self.next()
                continue
            break
        return items

    def _expr_list(self) -> list[ast.SqlExpr]:
        out = [self.parse_expr()]
        while self.peek().type is TokType.COMMA:
            self.next()
            out.append(self.parse_expr())
        return out

    def _identifier(self) -> str:
        t = self.next()
        if t.type in (TokType.IDENT, TokType.QUOTED_IDENT):
            return t.value
        raise SqlError(f"expected identifier, found {t.value!r} at {t.pos}")

    # ---------------------------------------------------------- expressions
    def parse_expr(self) -> ast.SqlExpr:
        return self._or_expr()

    def _or_expr(self) -> ast.SqlExpr:
        left = self._and_expr()
        while self.eat_kw("OR"):
            left = ast.Binary("OR", left, self._and_expr())
        return left

    def _and_expr(self) -> ast.SqlExpr:
        left = self._not_expr()
        while self.eat_kw("AND"):
            left = ast.Binary("AND", left, self._not_expr())
        return left

    def _not_expr(self) -> ast.SqlExpr:
        if self.eat_kw("NOT"):
            return ast.Unary("NOT", self._not_expr())
        return self._comparison()

    def _comparison(self) -> ast.SqlExpr:
        left = self._additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                left = ast.Binary(op, left, self._additive())
                continue
            negated = False
            save = self.i
            if self.eat_kw("NOT"):
                negated = True
            if self.eat_kw("BETWEEN"):
                low = self._additive()
                self.expect_kw("AND")
                high = self._additive()
                left = ast.Between(left, low, high, negated)
                continue
            if self.eat_kw("IN"):
                self.expect(TokType.LPAREN)
                if self.at_kw("SELECT"):
                    q = self.parse_query()
                    self.expect(TokType.RPAREN)
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = self._expr_list()
                    self.expect(TokType.RPAREN)
                    left = ast.InList(left, items, negated)
                continue
            if self.eat_kw("LIKE"):
                left = ast.Like(left, self._additive(), negated)
                continue
            if negated:
                self.i = save  # NOT belonged to something else
                break
            if self.eat_kw("IS"):
                neg = self.eat_kw("NOT")
                self.expect_kw("NULL")
                left = ast.IsNull(left, neg)
                continue
            break
        return left

    def _additive(self) -> ast.SqlExpr:
        left = self._multiplicative()
        while self.at_op("+", "-", "||"):
            op = self.next().value
            left = ast.Binary(op, left, self._multiplicative())
        return left

    def _multiplicative(self) -> ast.SqlExpr:
        left = self._unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = ast.Binary(op, left, self._unary())
        return left

    def _unary(self) -> ast.SqlExpr:
        if self.eat_op("-"):
            return ast.Unary("-", self._unary())
        if self.eat_op("+"):
            return self._unary()
        return self._primary()

    def _primary(self) -> ast.SqlExpr:
        t = self.peek()
        if t.type is TokType.NUMBER:
            self.next()
            return ast.NumberLit(t.value)
        if t.type is TokType.STRING:
            self.next()
            return ast.StringLit(t.value)
        if t.type is TokType.LPAREN:
            self.next()
            if self.at_kw("SELECT"):
                q = self.parse_query()
                self.expect(TokType.RPAREN)
                return ast.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect(TokType.RPAREN)
            return e
        if t.type is TokType.OP and t.value == "*":
            self.next()
            return ast.Star()
        if t.type is TokType.QUOTED_IDENT:
            self.next()
            return self._maybe_compound(ast.ColumnRef(t.value))
        if t.type is not TokType.IDENT:
            raise SqlError(f"unexpected token {t.value!r} at {t.pos}")

        kw = t.upper
        if kw == "CASE":
            return self._case()
        if kw == "CAST":
            self.next()
            self.expect(TokType.LPAREN)
            e = self.parse_expr()
            self.expect_kw("AS")
            ty_parts = [self._identifier()]
            if self.peek().type is TokType.LPAREN:
                self.next()
                ty_parts.append("(")
                while self.peek().type is not TokType.RPAREN:
                    ty_parts.append(self.next().value)
                self.next()
                ty_parts.append(")")
            elif self.at_kw("PRECISION"):
                ty_parts.append(self.next().value)
            self.expect(TokType.RPAREN)
            return ast.CastExpr(e, " ".join(ty_parts))
        if kw == "EXTRACT":
            self.next()
            self.expect(TokType.LPAREN)
            fieldname = self._identifier().upper()
            self.expect_kw("FROM")
            e = self.parse_expr()
            self.expect(TokType.RPAREN)
            return ast.Extract(fieldname, e)
        if kw == "SUBSTRING":
            self.next()
            self.expect(TokType.LPAREN)
            e = self.parse_expr()
            if self.eat_kw("FROM"):
                start = self.parse_expr()
                length = None
                if self.eat_kw("FOR"):
                    length = self.parse_expr()
            else:
                self.expect(TokType.COMMA) if self.peek().type is TokType.COMMA else None
                start = self.parse_expr()
                length = None
                if self.peek().type is TokType.COMMA:
                    self.next()
                    length = self.parse_expr()
            self.expect(TokType.RPAREN)
            return ast.Substring(e, start, length)
        if kw == "DATE" and self.peek(1).type is TokType.STRING:
            self.next()
            return ast.DateLit(self.next().value)
        if kw == "TIMESTAMP" and self.peek(1).type is TokType.STRING:
            self.next()
            return ast.DateLit(self.next().value.split(" ")[0])
        if kw == "INTERVAL":
            self.next()
            v = self.next()
            if v.type is TokType.STRING:
                parts = v.value.strip().split()
                if len(parts) == 2:
                    return ast.IntervalLit(parts[0], parts[1].upper().rstrip("S"))
                amount = parts[0]
            else:
                amount = v.value
            unit = self._identifier().upper().rstrip("S")
            return ast.IntervalLit(amount, unit)
        if kw == "EXISTS" and self.peek(1).type is TokType.LPAREN:
            self.next()
            self.next()
            q = self.parse_query()
            self.expect(TokType.RPAREN)
            return ast.Exists(q)
        if kw == "NULL":
            self.next()
            return ast.NullLit()
        if kw == "TRUE":
            self.next()
            return ast.BoolLit(True)
        if kw == "FALSE":
            self.next()
            return ast.BoolLit(False)

        # function call or column reference
        if self.peek(1).type is TokType.LPAREN:
            name = self.next().value
            self.next()  # (
            distinct = self.eat_kw("DISTINCT")
            if self.at_op("*"):
                self.next()
                args: list[ast.SqlExpr] = [ast.Star()]
            elif self.peek().type is TokType.RPAREN:
                args = []
            else:
                args = self._expr_list()
            self.expect(TokType.RPAREN)
            call = ast.FunctionCall(name.lower(), args, distinct)
            if self.eat_kw("OVER"):
                self.expect(TokType.LPAREN)
                spec = ast.WindowSpec()
                if self.eat_kw("PARTITION"):
                    self.expect_kw("BY")
                    spec.partition_by = self._expr_list()
                if self.eat_kw("ORDER"):
                    self.expect_kw("BY")
                    spec.order_by = self._order_items()
                if self.eat_kw("ROWS"):
                    spec.frame = self._rows_frame()
                self.expect(TokType.RPAREN)
                call.over = spec
            return call
        self.next()
        return self._maybe_compound(ast.ColumnRef(t.value))

    def _maybe_compound(self, col: ast.ColumnRef) -> ast.SqlExpr:
        if self.eat_op("."):
            if self.at_op("*"):
                self.next()
                return ast.Star(qualifier=col.name)
            part = self._identifier()
            return ast.ColumnRef(part, qualifier=col.name)
        return col

    def _case(self) -> ast.SqlExpr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        whens = []
        while self.eat_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            whens.append((cond, self.parse_expr()))
        else_expr = None
        if self.eat_kw("ELSE"):
            else_expr = self.parse_expr()
        self.expect_kw("END")
        return ast.Case(operand, whens, else_expr)


def parse_sql(sql: str) -> ast.Statement:
    return Parser(sql).parse_statement()
