"""SQL abstract syntax tree.

Produced by :mod:`arrow_ballista_tpu.sql.parser`, consumed by
:mod:`arrow_ballista_tpu.plan.builder` which resolves names against the
catalog and emits a logical plan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union


# ---------------------------------------------------------------- expressions
class SqlExpr:
    pass


@dataclass
class ColumnRef(SqlExpr):
    """``name`` or ``qualifier.name``."""

    name: str
    qualifier: Optional[str] = None

    def __str__(self) -> str:
        return f"{self.qualifier}.{self.name}" if self.qualifier else self.name


@dataclass
class Star(SqlExpr):
    qualifier: Optional[str] = None


@dataclass
class NumberLit(SqlExpr):
    value: str  # kept textual; builder decides int vs float/decimal


@dataclass
class StringLit(SqlExpr):
    value: str


@dataclass
class BoolLit(SqlExpr):
    value: bool


@dataclass
class NullLit(SqlExpr):
    pass


@dataclass
class DateLit(SqlExpr):
    value: str  # 'YYYY-MM-DD'


@dataclass
class IntervalLit(SqlExpr):
    value: str  # e.g. "3"
    unit: str  # DAY | MONTH | YEAR ...


@dataclass
class Binary(SqlExpr):
    op: str  # + - * / % = <> < <= > >= AND OR LIKE ||
    left: SqlExpr
    right: SqlExpr


@dataclass
class Unary(SqlExpr):
    op: str  # NOT | -
    operand: SqlExpr


@dataclass
class IsNull(SqlExpr):
    operand: SqlExpr
    negated: bool = False


@dataclass
class Between(SqlExpr):
    operand: SqlExpr
    low: SqlExpr
    high: SqlExpr
    negated: bool = False


@dataclass
class InList(SqlExpr):
    operand: SqlExpr
    items: list[SqlExpr]
    negated: bool = False


@dataclass
class InSubquery(SqlExpr):
    operand: SqlExpr
    query: "Query"
    negated: bool = False


@dataclass
class Exists(SqlExpr):
    query: "Query"
    negated: bool = False


@dataclass
class ScalarSubquery(SqlExpr):
    query: "Query"


@dataclass
class Like(SqlExpr):
    operand: SqlExpr
    pattern: SqlExpr
    negated: bool = False


@dataclass
class WindowFrame:
    """ROWS frame bounds as row offsets relative to the current row:
    negative = preceding, 0 = current row, positive = following,
    None = unbounded in that direction."""

    start: Optional[int]
    end: Optional[int]


@dataclass
class WindowSpec:
    partition_by: list[SqlExpr] = field(default_factory=list)
    order_by: list["OrderItem"] = field(default_factory=list)
    frame: Optional[WindowFrame] = None  # None = default RANGE frame


@dataclass
class FunctionCall(SqlExpr):
    name: str
    args: list[SqlExpr]
    distinct: bool = False
    over: Optional[WindowSpec] = None  # OVER (...) makes it a window fn


@dataclass
class Case(SqlExpr):
    operand: Optional[SqlExpr]
    whens: list[tuple[SqlExpr, SqlExpr]]
    else_expr: Optional[SqlExpr]


@dataclass
class CastExpr(SqlExpr):
    operand: SqlExpr
    type_name: str  # textual SQL type


@dataclass
class Extract(SqlExpr):
    field: str  # YEAR | MONTH | DAY ...
    operand: SqlExpr


@dataclass
class Substring(SqlExpr):
    operand: SqlExpr
    start: SqlExpr
    length: Optional[SqlExpr]


# ---------------------------------------------------------------- queries
@dataclass
class SelectItem:
    expr: SqlExpr
    alias: Optional[str] = None


@dataclass
class TableRef:
    pass


@dataclass
class NamedTable(TableRef):
    name: str
    alias: Optional[str] = None


@dataclass
class DerivedTable(TableRef):
    query: "Query"
    alias: str = ""


@dataclass
class JoinClause(TableRef):
    left: TableRef
    right: TableRef
    kind: str  # INNER | LEFT | RIGHT | FULL | CROSS | SEMI | ANTI
    on: Optional[SqlExpr] = None


@dataclass
class OrderItem:
    expr: SqlExpr
    asc: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class Query:
    ctes: list[tuple[str, "Query"]] = field(default_factory=list)  # WITH name AS (...)
    select: list[SelectItem] = field(default_factory=list)
    distinct: bool = False
    from_: list[TableRef] = field(default_factory=list)  # comma-separated refs
    where: Optional[SqlExpr] = None
    group_by: list[SqlExpr] = field(default_factory=list)
    having: Optional[SqlExpr] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


# ---------------------------------------------------------------- statements
Statement = Union["Query", "CreateExternalTable", "ShowStmt", "SetVariable", "Explain", "DropTable"]


@dataclass
class CreateExternalTable:
    """Reference: handled client-side at client/src/context.rs:377-425."""

    name: str
    file_type: str  # CSV | PARQUET | AVRO | NDJSON
    location: str
    columns: list[tuple[str, str]] = field(default_factory=list)  # (name, type)
    has_header: bool = False
    delimiter: str = ","
    if_not_exists: bool = False


@dataclass
class ShowStmt:
    variable: list[str]  # e.g. ["TABLES"] or ["COLUMNS","FROM","t"]


@dataclass
class SetVariable:
    name: str
    value: str


@dataclass
class Explain:
    query: Query
    verbose: bool = False
    analyze: bool = False  # EXPLAIN ANALYZE: execute + runtime metrics


@dataclass
class DropTable:
    name: str
    if_exists: bool = False
