"""SQL tokenizer.

The reference delegates SQL parsing to DataFusion/sqlparser-rs; this rebuild
ships its own frontend (SURVEY.md §7 step 2).  The token set covers the
TPC-H dialect plus the DDL/utility statements the client context handles
(CREATE EXTERNAL TABLE, SHOW, SET — reference client/src/context.rs:313-460).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum, auto

from ..errors import SqlError


class TokType(Enum):
    IDENT = auto()
    QUOTED_IDENT = auto()
    STRING = auto()
    NUMBER = auto()
    OP = auto()  # + - * / % = <> != < <= > >= || .
    LPAREN = auto()
    RPAREN = auto()
    COMMA = auto()
    SEMICOLON = auto()
    EOF = auto()


@dataclass(frozen=True)
class Token:
    type: TokType
    value: str
    pos: int

    @property
    def upper(self) -> str:
        return self.value.upper()


_TWO_CHAR_OPS = {"<>", "!=", "<=", ">=", "||"}
_ONE_CHAR_OPS = set("+-*/%=<>.")


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":  # line comment
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":  # block comment
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SqlError(f"unterminated block comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":  # escaped ''
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            else:
                raise SqlError(f"unterminated string literal at {i}")
            toks.append(Token(TokType.STRING, "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlError(f"unterminated quoted identifier at {i}")
            toks.append(Token(TokType.QUOTED_IDENT, sql[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot)):
                if sql[j] == ".":
                    seen_dot = True
                j += 1
            if j < n and sql[j] in "eE":
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    while k < n and sql[k].isdigit():
                        k += 1
                    j = k
            toks.append(Token(TokType.NUMBER, sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            toks.append(Token(TokType.IDENT, sql[i:j], i))
            i = j
            continue
        if sql[i : i + 2] in _TWO_CHAR_OPS:
            toks.append(Token(TokType.OP, sql[i : i + 2], i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            toks.append(Token(TokType.OP, c, i))
            i += 1
            continue
        if c == "(":
            toks.append(Token(TokType.LPAREN, c, i))
        elif c == ")":
            toks.append(Token(TokType.RPAREN, c, i))
        elif c == ",":
            toks.append(Token(TokType.COMMA, c, i))
        elif c == ";":
            toks.append(Token(TokType.SEMICOLON, c, i))
        else:
            raise SqlError(f"unexpected character {c!r} at {i}")
        i += 1
    toks.append(Token(TokType.EOF, "", n))
    return toks
