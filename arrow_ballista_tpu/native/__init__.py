"""Native (C++) data-plane kernels, bound via ctypes.

``partitioner.cc`` is compiled lazily to ``build/libabt_native.so`` on
first import (g++ is part of the baked toolchain); if compilation is
impossible the pure-Python fallbacks take over transparently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np
import pyarrow as pa

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "partitioner.cc")
_BUILD_DIR = os.path.join(_HERE, "build")
_SO = os.path.join(_BUILD_DIR, "libabt_native.so")

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_failed = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    try:
        subprocess.run(
            [
                "g++",
                "-O3",
                "-march=native",
                "-shared",
                "-fPIC",
                "-std=c++17",
                "-o",
                _SO,
                _SRC,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _compile():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        u8p = ctypes.c_void_p
        lib.abt_hash_int.argtypes = [
            u8p,
            ctypes.c_int32,
            ctypes.c_int32,
            u8p,
            ctypes.c_int64,
            u8p,
        ]
        lib.abt_hash_f64.argtypes = [u8p, u8p, ctypes.c_int64, u8p]
        lib.abt_hash_f32.argtypes = [u8p, u8p, ctypes.c_int64, u8p]
        lib.abt_hash_bool.argtypes = [u8p, u8p, ctypes.c_int64, u8p]
        lib.abt_hash_str32.argtypes = [u8p, u8p, u8p, ctypes.c_int64, u8p]
        lib.abt_hash_str64.argtypes = [u8p, u8p, u8p, ctypes.c_int64, u8p]
        lib.abt_finish_mod.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64, u8p]
        _lib = lib
        return _lib


# arrow type -> (byte width, is_signed); mirrors the python fallback's
# astype(int64) sign/zero extension semantics
_INT_SPECS = {
    pa.int8(): (1, 1),
    pa.int16(): (2, 1),
    pa.int32(): (4, 1),
    pa.int64(): (8, 1),
    pa.uint8(): (1, 0),
    pa.uint16(): (2, 0),
    pa.uint32(): (4, 0),
    pa.date32(): (4, 1),
    pa.date64(): (8, 1),
}


def _np_ptr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


def native_hash_partition_indices(
    batch: pa.RecordBatch, exprs, n: int
) -> Optional[np.ndarray]:
    """Partition ids via the C++ kernel; None → caller falls back to Python.

    Bit-identical to exec.operators.hash_partition_indices by construction
    (see partitioner.cc header).
    """
    lib = get_lib()
    if lib is None:
        return None

    n_rows = batch.num_rows
    h = np.zeros(n_rows, dtype=np.uint64)
    hp = _np_ptr(h)

    cols = []
    for e in exprs:
        v = e.evaluate(batch)
        if isinstance(v, pa.ChunkedArray):
            v = v.combine_chunks()
        if isinstance(v, pa.Scalar):
            return None  # constant keys: let the python path handle it
        if v.offset != 0:
            v = pa.concat_arrays([v])  # re-materialize at offset 0
            if v.offset != 0:
                return None
        cols.append(v)

    for v in cols:
        t = v.type
        bufs = v.buffers()
        validity = bufs[0].address if bufs[0] is not None and v.null_count else None
        vp = ctypes.c_void_p(validity) if validity else None
        if pa.types.is_string(t):
            lib.abt_hash_str32(
                ctypes.c_void_p(bufs[1].address),
                ctypes.c_void_p(bufs[2].address),
                vp,
                n_rows,
                hp,
            )
        elif pa.types.is_large_string(t):
            lib.abt_hash_str64(
                ctypes.c_void_p(bufs[1].address),
                ctypes.c_void_p(bufs[2].address),
                vp,
                n_rows,
                hp,
            )
        elif pa.types.is_boolean(t):
            lib.abt_hash_bool(ctypes.c_void_p(bufs[1].address), vp, n_rows, hp)
        elif pa.types.is_float64(t):
            lib.abt_hash_f64(ctypes.c_void_p(bufs[1].address), vp, n_rows, hp)
        elif pa.types.is_float32(t):
            lib.abt_hash_f32(ctypes.c_void_p(bufs[1].address), vp, n_rows, hp)
        elif pa.types.is_timestamp(t):
            lib.abt_hash_int(ctypes.c_void_p(bufs[1].address), 8, 1, vp, n_rows, hp)
        elif t in _INT_SPECS:
            size, signed = _INT_SPECS[t]
            lib.abt_hash_int(
                ctypes.c_void_p(bufs[1].address), size, signed, vp, n_rows, hp
            )
        else:
            return None  # unsupported key type → python fallback

    out = np.empty(n_rows, dtype=np.int64)
    lib.abt_finish_mod(hp, n_rows, n, _np_ptr(out))
    return out
