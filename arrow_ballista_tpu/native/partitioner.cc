// Native hash partitioner — the shuffle-write hot path.
//
// Role counterpart of the reference's BatchPartitioner
// (ballista/rust/core/src/execution_plans/shuffle_writer.rs:201-285): given
// the key columns of a record batch, produce the output-partition id of
// every row.  The algorithm MUST stay bit-identical to the Python fallback
// in exec/operators.py::hash_partition_indices — map- and reduce-side tasks
// may run in different processes and both sides re-derive the same
// assignment.
//
// Per column hash hv(i):
//   numeric  : x = (uint64)(int64)value   (floats: f64 bit pattern)
//              hv = x * 0x9E3779B97F4A7C15;  hv ^= hv >> 32
//   string   : FNV-1a 64 over the utf8 bytes
//   null     : 0xA5A5A5A5DEADBEEFULL
// Combine    : h = h * 31 + hv
// Finish     : out = h % n_partitions
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image);
// callers pass raw Arrow buffer addresses (zero-copy).

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t kNullHash = 0xA5A5A5A5DEADBEEFULL;
constexpr uint64_t kMix = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t kFnvBasis = 1469598103934665603ULL;
constexpr uint64_t kFnvPrime = 1099511628211ULL;

inline bool bit_get(const uint8_t* bits, int64_t i) {
  return (bits[i >> 3] >> (i & 7)) & 1;
}

inline uint64_t mix_int(uint64_t x) {
  uint64_t hv = x * kMix;
  hv ^= hv >> 32;
  return hv;
}

inline uint64_t fnv1a(const uint8_t* data, int64_t len) {
  uint64_t h = kFnvBasis;
  for (int64_t i = 0; i < len; ++i) {
    h = (h ^ data[i]) * kFnvPrime;
  }
  return h;
}

inline void combine(uint64_t* h, int64_t i, uint64_t hv) {
  h[i] = h[i] * 31u + hv;
}

template <typename T>
void hash_fixed_col(const uint8_t* vals, const uint8_t* validity, int64_t n,
                     uint64_t* h) {
  const T* v = reinterpret_cast<const T*>(vals);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t hv;
    if (validity != nullptr && !bit_get(validity, i)) {
      hv = kNullHash;
    } else {
      hv = mix_int(static_cast<uint64_t>(static_cast<int64_t>(v[i])));
    }
    combine(h, i, hv);
  }
}

}  // namespace

extern "C" {

// elem_size in {1,2,4,8}; signed values sign-extend to int64, unsigned
// zero-extend — matching numpy's astype(int64) in the python fallback
void abt_hash_int(const uint8_t* vals, int32_t elem_size, int32_t is_signed,
                  const uint8_t* validity, int64_t n, uint64_t* h) {
  if (is_signed) {
    switch (elem_size) {
      case 1:
        hash_fixed_col<int8_t>(vals, validity, n, h);
        break;
      case 2:
        hash_fixed_col<int16_t>(vals, validity, n, h);
        break;
      case 4:
        hash_fixed_col<int32_t>(vals, validity, n, h);
        break;
      default:
        hash_fixed_col<int64_t>(vals, validity, n, h);
    }
  } else {
    switch (elem_size) {
      case 1:
        hash_fixed_col<uint8_t>(vals, validity, n, h);
        break;
      case 2:
        hash_fixed_col<uint16_t>(vals, validity, n, h);
        break;
      case 4:
        hash_fixed_col<uint32_t>(vals, validity, n, h);
        break;
      default:
        hash_fixed_col<int64_t>(vals, validity, n, h);
    }
  }
}

void abt_hash_f64(const double* vals, const uint8_t* validity, int64_t n,
                  uint64_t* h) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t hv;
    if (validity != nullptr && !bit_get(validity, i)) {
      hv = kNullHash;
    } else {
      uint64_t bits;
      std::memcpy(&bits, &vals[i], sizeof(bits));
      hv = mix_int(bits);
    }
    combine(h, i, hv);
  }
}

void abt_hash_f32(const float* vals, const uint8_t* validity, int64_t n,
                  uint64_t* h) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t hv;
    if (validity != nullptr && !bit_get(validity, i)) {
      hv = kNullHash;
    } else {
      double d = static_cast<double>(vals[i]);
      uint64_t bits;
      std::memcpy(&bits, &d, sizeof(bits));
      hv = mix_int(bits);
    }
    combine(h, i, hv);
  }
}

// boolean columns are bit-packed; python path hashes them as int 0/1
void abt_hash_bool(const uint8_t* vals, const uint8_t* validity, int64_t n,
                   uint64_t* h) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t hv;
    if (validity != nullptr && !bit_get(validity, i)) {
      hv = kNullHash;
    } else {
      hv = mix_int(bit_get(vals, i) ? 1u : 0u);
    }
    combine(h, i, hv);
  }
}

// utf8 with 32-bit offsets (arrow `string`)
void abt_hash_str32(const int32_t* offsets, const uint8_t* data,
                    const uint8_t* validity, int64_t n, uint64_t* h) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t hv;
    if (validity != nullptr && !bit_get(validity, i)) {
      hv = kNullHash;
    } else {
      hv = fnv1a(data + offsets[i], offsets[i + 1] - offsets[i]);
    }
    combine(h, i, hv);
  }
}

// utf8 with 64-bit offsets (arrow `large_string`)
void abt_hash_str64(const int64_t* offsets, const uint8_t* data,
                    const uint8_t* validity, int64_t n, uint64_t* h) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t hv;
    if (validity != nullptr && !bit_get(validity, i)) {
      hv = kNullHash;
    } else {
      hv = fnv1a(data + offsets[i], offsets[i + 1] - offsets[i]);
    }
    combine(h, i, hv);
  }
}

void abt_finish_mod(const uint64_t* h, int64_t n, int64_t n_partitions,
                    int64_t* out) {
  const uint64_t m = static_cast<uint64_t>(n_partitions);
  for (int64_t i = 0; i < n; ++i) {
    out[i] = static_cast<int64_t>(h[i] % m);
  }
}

}  // extern "C"
