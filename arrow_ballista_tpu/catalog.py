"""Table providers and catalog.

Counterpart of DataFusion's ``TableProvider`` + the reference client's table
registry (``client/src/context.rs:212-311``).  Providers expose a schema and
partitioned batch streams; file-backed providers treat each file (or
row-group chunk) as one partition so scans parallelize across tasks.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from .errors import PlanError


class TableProvider:
    """A registered table: schema + partitioned scan."""

    @property
    def schema(self) -> pa.Schema:
        raise NotImplementedError

    def num_partitions(self) -> int:
        raise NotImplementedError

    def scan_partition(
        self, partition: int, projection: Optional[list[str]], batch_size: int = 8192
    ) -> Iterator[pa.RecordBatch]:
        raise NotImplementedError

    def describe(self) -> dict:
        """Serializable description for plan serde; see serde/plans.py."""
        raise NotImplementedError


def _expand_path(path: str, suffix: str) -> list[str]:
    if os.path.isdir(path):
        files = sorted(
            _glob.glob(os.path.join(path, f"**/*{suffix}"), recursive=True)
        )
        if not files:
            files = sorted(_glob.glob(os.path.join(path, "**/*"), recursive=True))
            files = [f for f in files if os.path.isfile(f)]
    else:
        files = sorted(_glob.glob(path)) if any(c in path for c in "*?[") else [path]
    if not files:
        raise PlanError(f"no files found at {path!r}")
    return files


class ParquetTable(TableProvider):
    def __init__(self, path: str, schema: Optional[pa.Schema] = None):
        self.path = path
        self.files = _expand_path(path, ".parquet")
        self._schema = schema or pq.read_schema(self.files[0])

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self.files)

    def scan_partition(
        self, partition: int, projection: Optional[list[str]], batch_size: int = 8192
    ) -> Iterator[pa.RecordBatch]:
        f = pq.ParquetFile(self.files[partition])
        yield from f.iter_batches(batch_size=batch_size, columns=projection)

    def describe(self) -> dict:
        return {"kind": "parquet", "path": self.path}


class AvroTable(TableProvider):
    """Avro object-container files (reference: register_avro / read_avro,
    client/src/context.rs:212-311); decoded by the built-in pure-python
    reader (avro.py) — no external avro library required."""

    def __init__(self, path: str):
        from .avro import AvroFile

        self.path = path
        self.files = _expand_path(path, ".avro")
        self._readers = [AvroFile(f) for f in self.files]
        self._schema = self._readers[0].schema

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self.files)

    def scan_partition(
        self, partition: int, projection: Optional[list[str]], batch_size: int = 8192
    ) -> Iterator[pa.RecordBatch]:
        yield from self._readers[partition].read_batches(projection, batch_size)

    def describe(self) -> dict:
        return {"kind": "avro", "path": self.path}


class CsvTable(TableProvider):
    def __init__(
        self,
        path: str,
        schema: Optional[pa.Schema] = None,
        has_header: bool = True,
        delimiter: str = ",",
    ):
        self.path = path
        self.has_header = has_header
        self.delimiter = delimiter
        self.files = _expand_path(path, ".csv")
        if schema is not None:
            self._schema = schema
        else:
            ropts = pacsv.ReadOptions(
                autogenerate_column_names=not has_header, block_size=1 << 20
            )
            popts = pacsv.ParseOptions(delimiter=delimiter)
            with pacsv.open_csv(self.files[0], read_options=ropts, parse_options=popts) as r:
                self._schema = r.schema

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self.files)

    def scan_partition(
        self, partition: int, projection: Optional[list[str]], batch_size: int = 8192
    ) -> Iterator[pa.RecordBatch]:
        names = self._schema.names
        ropts = pacsv.ReadOptions(
            column_names=names if not self.has_header else None,
            block_size=max(batch_size * 128, 1 << 20),
        )
        popts = pacsv.ParseOptions(delimiter=self.delimiter)
        copts = pacsv.ConvertOptions(
            column_types={f.name: f.type for f in self._schema},
            include_columns=projection,
        )
        with pacsv.open_csv(
            self.files[partition], read_options=ropts, parse_options=popts,
            convert_options=copts,
        ) as reader:
            for batch in reader:
                yield batch

    def describe(self) -> dict:
        return {
            "kind": "csv",
            "path": self.path,
            "has_header": self.has_header,
            "delimiter": self.delimiter,
            "schema": self._schema.serialize().to_pybytes().hex(),
        }


class MemoryTable(TableProvider):
    def __init__(self, partitions: list[list[pa.RecordBatch]], schema: Optional[pa.Schema] = None):
        if schema is None:
            if not partitions or not partitions[0]:
                raise PlanError("MemoryTable needs a schema or at least one batch")
            schema = partitions[0][0].schema
        self._schema = schema
        self.partitions = partitions

    @classmethod
    def from_table(cls, table: pa.Table, partitions: int = 1) -> "MemoryTable":
        n = max(1, partitions)
        rows = table.num_rows
        per = (rows + n - 1) // n if rows else 0
        parts: list[list[pa.RecordBatch]] = []
        for i in range(n):
            chunk = table.slice(i * per, per) if rows else table
            parts.append(chunk.combine_chunks().to_batches())
        return cls(parts, table.schema)

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def num_partitions(self) -> int:
        return max(1, len(self.partitions))

    def scan_partition(
        self, partition: int, projection: Optional[list[str]], batch_size: int = 8192
    ) -> Iterator[pa.RecordBatch]:
        batches = self.partitions[partition] if partition < len(self.partitions) else []
        for b in batches:
            if projection is not None:
                b = b.select(projection)
            yield b

    def describe(self) -> dict:
        # Memory tables are serialized inline (small tables only: Values, test fixtures)
        sink = pa.BufferOutputStream()
        part_batches = []  # batches per partition, to rebuild partitioning
        with pa.ipc.new_stream(sink, self._schema) as w:
            for part in self.partitions:
                part_batches.append(len(part))
                for b in part:
                    w.write_batch(b)
        return {
            "kind": "memory",
            "partition_batches": part_batches,
            "data": sink.getvalue().to_pybytes().hex(),
        }


def provider_from_description(d: dict) -> TableProvider:
    kind = d["kind"]
    if kind == "parquet":
        return ParquetTable(d["path"])
    if kind == "avro":
        return AvroTable(d["path"])
    if kind == "csv":
        schema = None
        if "schema" in d:
            schema = pa.ipc.read_schema(pa.py_buffer(bytes.fromhex(d["schema"])))
        return CsvTable(d["path"], schema, d.get("has_header", True), d.get("delimiter", ","))
    if kind == "memory":
        buf = pa.py_buffer(bytes.fromhex(d["data"]))
        with pa.ipc.open_stream(buf) as r:
            batches = [b for b in r]
            schema = r.schema
        counts = d.get("partition_batches")
        if counts:
            parts: list[list[pa.RecordBatch]] = []
            i = 0
            for c in counts:
                parts.append(batches[i : i + c])
                i += c
        else:
            parts = [batches] if batches else [[]]
        return MemoryTable(parts, schema)
    raise PlanError(f"unknown provider kind {kind!r}")


class Catalog:
    """Named table registry (one per session)."""

    def __init__(self) -> None:
        self.tables: dict[str, TableProvider] = {}

    def register(self, name: str, provider: TableProvider) -> None:
        self.tables[name.lower()] = provider

    def deregister(self, name: str) -> None:
        self.tables.pop(name.lower(), None)

    def get(self, name: str) -> TableProvider:
        p = self.tables.get(name.lower())
        if p is None:
            raise PlanError(f"table {name!r} not found; registered: {sorted(self.tables)}")
        return p

    def names(self) -> list[str]:
        return sorted(self.tables)
