"""Expression tree ⇄ protobuf conversion (logical and physical).

Counterpart of the reference's ``core/src/serde/physical_plan/
{from_proto,to_proto}.rs`` expression sections and the DataFusion logical
expr serde.  One ``ExprNode`` message serves both trees: logical columns
carry names, physical columns carry resolved indices.
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa

from ..errors import PlanError
from ..exec import expressions as pex
from ..plan import expressions as lex
from ..proto import pb
from .arrow_utils import (
    array_from_ipc,
    array_to_ipc,
    dtype_from_bytes,
    dtype_to_bytes,
    value_from_ipc,
    value_to_ipc,
)

# ---------------------------------------------------------------------------
# physical expressions
# ---------------------------------------------------------------------------


def physical_expr_to_proto(e: pex.PhysicalExpr) -> pb.ExprNode:
    n = pb.ExprNode()
    if isinstance(e, pex.Col):
        n.column.name = e.colname
        n.column.index = e.index
        return n
    if isinstance(e, pex.Lit):
        untyped = pa.types.is_null(e.dtype) and e.value is not None
        n.literal.ipc_value = value_to_ipc(
            e.value, None if untyped else e.dtype
        )
        n.literal.untyped = untyped
        return n
    if isinstance(e, pex.IntervalLit):
        n.interval.months = e.months
        n.interval.days = e.days
        return n
    if isinstance(e, pex.Binary):
        n.binary.left.CopyFrom(physical_expr_to_proto(e.left))
        n.binary.op = e.op
        n.binary.right.CopyFrom(physical_expr_to_proto(e.right))
        return n
    if isinstance(e, pex.Not):
        n.logical_not.expr.CopyFrom(physical_expr_to_proto(e.expr))
        return n
    if isinstance(e, pex.Negative):
        n.negative.expr.CopyFrom(physical_expr_to_proto(e.expr))
        return n
    if isinstance(e, pex.IsNull):
        n.is_null.expr.CopyFrom(physical_expr_to_proto(e.expr))
        n.is_null.negated = e.negated
        return n
    if isinstance(e, pex.InList):
        n.in_list.expr.CopyFrom(physical_expr_to_proto(e.expr))
        n.in_list.ipc_items = array_to_ipc(e.items)
        n.in_list.negated = e.negated
        return n
    if isinstance(e, pex.Like):
        n.like.expr.CopyFrom(physical_expr_to_proto(e.expr))
        n.like.pattern_str = e.pattern
        n.like.negated = e.negated
        return n
    if isinstance(e, pex.Case):
        n.case_expr.SetInParent()
        for w, t in e.whens:
            wt = n.case_expr.whens.add()
            wt.when.CopyFrom(physical_expr_to_proto(w))
            wt.then.CopyFrom(physical_expr_to_proto(t))
        if e.else_expr is not None:
            n.case_expr.else_expr.CopyFrom(physical_expr_to_proto(e.else_expr))
            n.case_expr.has_else = True
        n.case_expr.out_type = dtype_to_bytes(e.out_type)
        return n
    if isinstance(e, pex.Cast):
        n.cast.expr.CopyFrom(physical_expr_to_proto(e.expr))
        n.cast.to_type = dtype_to_bytes(e.to_type)
        return n
    if isinstance(e, pex.ScalarFn):
        n.scalar_fn.fname = e.fname
        for a in e.args:
            n.scalar_fn.args.add().CopyFrom(physical_expr_to_proto(a))
        n.scalar_fn.out_type = dtype_to_bytes(e.out_type)
        return n
    if isinstance(e, pex.ScalarUdf):
        n.udf.name = e.fname
        for a in e.args:
            n.udf.args.add().CopyFrom(physical_expr_to_proto(a))
        n.udf.out_type = dtype_to_bytes(e.out_type)
        return n
    raise PlanError(f"cannot serialize physical expr {type(e).__name__}")


def physical_expr_from_proto(n: pb.ExprNode) -> pex.PhysicalExpr:
    kind = n.WhichOneof("expr")
    if kind == "column":
        return pex.Col(n.column.index, n.column.name)
    if kind == "literal":
        value, dtype = value_from_ipc(n.literal.ipc_value)
        return pex.Lit(value, pa.null() if n.literal.untyped else dtype)
    if kind == "interval":
        return pex.IntervalLit(n.interval.months, n.interval.days)
    if kind == "binary":
        return pex.Binary(
            physical_expr_from_proto(n.binary.left),
            n.binary.op,
            physical_expr_from_proto(n.binary.right),
        )
    if kind == "logical_not":
        return pex.Not(physical_expr_from_proto(n.logical_not.expr))
    if kind == "negative":
        return pex.Negative(physical_expr_from_proto(n.negative.expr))
    if kind == "is_null":
        return pex.IsNull(physical_expr_from_proto(n.is_null.expr), n.is_null.negated)
    if kind == "in_list":
        items = tuple(array_from_ipc(n.in_list.ipc_items).to_pylist())
        return pex.InList(
            physical_expr_from_proto(n.in_list.expr), items, n.in_list.negated
        )
    if kind == "like":
        return pex.Like(
            physical_expr_from_proto(n.like.expr), n.like.pattern_str, n.like.negated
        )
    if kind == "case_expr":
        whens = tuple(
            (physical_expr_from_proto(w.when), physical_expr_from_proto(w.then))
            for w in n.case_expr.whens
        )
        else_e = (
            physical_expr_from_proto(n.case_expr.else_expr)
            if n.case_expr.has_else
            else None
        )
        return pex.Case(whens, else_e, dtype_from_bytes(n.case_expr.out_type))
    if kind == "cast":
        return pex.Cast(
            physical_expr_from_proto(n.cast.expr), dtype_from_bytes(n.cast.to_type)
        )
    if kind == "scalar_fn":
        return pex.ScalarFn(
            n.scalar_fn.fname,
            tuple(physical_expr_from_proto(a) for a in n.scalar_fn.args),
            dtype_from_bytes(n.scalar_fn.out_type),
        )
    if kind == "udf":
        return pex.ScalarUdf(
            n.udf.name,
            tuple(physical_expr_from_proto(a) for a in n.udf.args),
            dtype_from_bytes(n.udf.out_type),
        )
    raise PlanError(f"cannot deserialize physical expr node {kind!r}")


# ---------------------------------------------------------------------------
# logical expressions
# ---------------------------------------------------------------------------


def _frame_to_proto(frame: tuple, node) -> None:
    start, end = frame
    if start is None:
        node.start_unbounded = True
    else:
        node.start = start
    if end is None:
        node.end_unbounded = True
    else:
        node.end = end


def _frame_from_proto(node) -> tuple:
    return (
        None if node.start_unbounded else node.start,
        None if node.end_unbounded else node.end,
    )


def logical_expr_to_proto(e: lex.Expr) -> pb.ExprNode:
    n = pb.ExprNode()
    if isinstance(e, lex.Column):
        n.column.name = e.cname
        n.column.qualifier = e.qualifier or ""
        n.column.index = -1
        return n
    if isinstance(e, lex.Literal):
        untyped = pa.types.is_null(e.dtype) and e.value is not None
        n.literal.ipc_value = value_to_ipc(e.value, None if untyped else e.dtype)
        n.literal.untyped = untyped
        return n
    if isinstance(e, lex.IntervalLiteral):
        n.interval.months = e.months
        n.interval.days = e.days
        return n
    if isinstance(e, lex.Alias):
        n.alias.expr.CopyFrom(logical_expr_to_proto(e.expr))
        n.alias.alias = e.alias_name
        return n
    if isinstance(e, lex.BinaryExpr):
        n.binary.left.CopyFrom(logical_expr_to_proto(e.left))
        n.binary.op = e.op
        n.binary.right.CopyFrom(logical_expr_to_proto(e.right))
        return n
    if isinstance(e, lex.NotExpr):
        n.logical_not.expr.CopyFrom(logical_expr_to_proto(e.expr))
        return n
    if isinstance(e, lex.NegativeExpr):
        n.negative.expr.CopyFrom(logical_expr_to_proto(e.expr))
        return n
    if isinstance(e, lex.IsNullExpr):
        n.is_null.expr.CopyFrom(logical_expr_to_proto(e.expr))
        n.is_null.negated = e.negated
        return n
    if isinstance(e, lex.BetweenExpr):
        n.between.expr.CopyFrom(logical_expr_to_proto(e.expr))
        n.between.low.CopyFrom(logical_expr_to_proto(e.low))
        n.between.high.CopyFrom(logical_expr_to_proto(e.high))
        n.between.negated = e.negated
        return n
    if isinstance(e, lex.InListExpr):
        n.in_list.expr.CopyFrom(logical_expr_to_proto(e.expr))
        for item in e.items:
            n.in_list.items.add().CopyFrom(logical_expr_to_proto(item))
        n.in_list.negated = e.negated
        return n
    if isinstance(e, lex.LikeExpr):
        n.like.expr.CopyFrom(logical_expr_to_proto(e.expr))
        n.like.pattern.CopyFrom(logical_expr_to_proto(e.pattern))
        n.like.negated = e.negated
        return n
    if isinstance(e, lex.CaseExpr):
        n.case_expr.SetInParent()
        if e.operand is not None:
            n.case_expr.operand.CopyFrom(logical_expr_to_proto(e.operand))
            n.case_expr.has_operand = True
        for w, t in e.whens:
            wt = n.case_expr.whens.add()
            wt.when.CopyFrom(logical_expr_to_proto(w))
            wt.then.CopyFrom(logical_expr_to_proto(t))
        if e.else_expr is not None:
            n.case_expr.else_expr.CopyFrom(logical_expr_to_proto(e.else_expr))
            n.case_expr.has_else = True
        return n
    if isinstance(e, lex.CastExpr):
        n.cast.expr.CopyFrom(logical_expr_to_proto(e.expr))
        n.cast.to_type = dtype_to_bytes(e.to_type)
        return n
    if isinstance(e, lex.ScalarFunction):
        n.scalar_fn.fname = e.fname
        for a in e.args:
            n.scalar_fn.args.add().CopyFrom(logical_expr_to_proto(a))
        return n
    if isinstance(e, lex.ScalarUDFExpr):
        n.udf.name = e.fname
        for a in e.args:
            n.udf.args.add().CopyFrom(logical_expr_to_proto(a))
        n.udf.out_type = dtype_to_bytes(e.return_type)
        return n
    if isinstance(e, lex.AggregateExpr):
        n.aggregate.func = e.func
        if e.arg is not None:
            n.aggregate.arg.CopyFrom(logical_expr_to_proto(e.arg))
            n.aggregate.has_arg = True
        if e.arg2 is not None:
            n.aggregate.arg2.CopyFrom(logical_expr_to_proto(e.arg2))
            n.aggregate.has_arg2 = True
        n.aggregate.distinct = e.distinct
        if e.func.startswith("udaf:"):
            # ship the return type: the scheduler may not have the UDAF
            t = e.udaf_type
            if t is None:
                t = e.data_type(pa.schema([]))
            n.aggregate.udaf_out_type = dtype_to_bytes(t)
        return n
    if isinstance(e, lex.WindowExpr):
        n.window.func = e.func
        n.window.offset = e.offset
        if e.frame is not None:
            _frame_to_proto(e.frame, n.window.frame)
        if e.arg is not None:
            n.window.arg.CopyFrom(logical_expr_to_proto(e.arg))
            n.window.has_arg = True
        for p in e.partition_by:
            n.window.partition_by.add().CopyFrom(logical_expr_to_proto(p))
        for s in e.order_by:
            so = n.window.order_by.add()
            so.expr.CopyFrom(logical_expr_to_proto(s.expr))
            so.asc = s.asc
            so.nulls_first = (
                0 if s.nulls_first is None else (1 if s.nulls_first else 2)
            )
        return n
    if isinstance(e, lex.SortExpr):
        n.sort.expr.CopyFrom(logical_expr_to_proto(e.expr))
        n.sort.asc = e.asc
        n.sort.nulls_first = (
            0 if e.nulls_first is None else (1 if e.nulls_first else 2)
        )
        return n
    if isinstance(e, lex.ScalarSubqueryExpr):
        from .logical_plan import logical_plan_to_proto

        n.scalar_subquery.plan.CopyFrom(logical_plan_to_proto(e.plan))
        return n
    raise PlanError(f"cannot serialize logical expr {type(e).__name__}")


def logical_expr_from_proto(n: pb.ExprNode) -> lex.Expr:
    kind = n.WhichOneof("expr")
    if kind == "column":
        return lex.Column(n.column.name, n.column.qualifier or None)
    if kind == "literal":
        value, dtype = value_from_ipc(n.literal.ipc_value)
        return lex.Literal(value, pa.null() if n.literal.untyped else dtype)
    if kind == "interval":
        return lex.IntervalLiteral(n.interval.months, n.interval.days)
    if kind == "alias":
        return lex.Alias(logical_expr_from_proto(n.alias.expr), n.alias.alias)
    if kind == "binary":
        return lex.BinaryExpr(
            logical_expr_from_proto(n.binary.left),
            n.binary.op,
            logical_expr_from_proto(n.binary.right),
        )
    if kind == "logical_not":
        return lex.NotExpr(logical_expr_from_proto(n.logical_not.expr))
    if kind == "negative":
        return lex.NegativeExpr(logical_expr_from_proto(n.negative.expr))
    if kind == "is_null":
        return lex.IsNullExpr(
            logical_expr_from_proto(n.is_null.expr), n.is_null.negated
        )
    if kind == "between":
        return lex.BetweenExpr(
            logical_expr_from_proto(n.between.expr),
            logical_expr_from_proto(n.between.low),
            logical_expr_from_proto(n.between.high),
            n.between.negated,
        )
    if kind == "in_list":
        return lex.InListExpr(
            logical_expr_from_proto(n.in_list.expr),
            tuple(logical_expr_from_proto(i) for i in n.in_list.items),
            n.in_list.negated,
        )
    if kind == "like":
        return lex.LikeExpr(
            logical_expr_from_proto(n.like.expr),
            logical_expr_from_proto(n.like.pattern),
            n.like.negated,
        )
    if kind == "case_expr":
        operand = (
            logical_expr_from_proto(n.case_expr.operand)
            if n.case_expr.has_operand
            else None
        )
        whens = tuple(
            (logical_expr_from_proto(w.when), logical_expr_from_proto(w.then))
            for w in n.case_expr.whens
        )
        else_e = (
            logical_expr_from_proto(n.case_expr.else_expr)
            if n.case_expr.has_else
            else None
        )
        return lex.CaseExpr(operand, whens, else_e)
    if kind == "cast":
        return lex.CastExpr(
            logical_expr_from_proto(n.cast.expr), dtype_from_bytes(n.cast.to_type)
        )
    if kind == "scalar_fn":
        return lex.ScalarFunction(
            n.scalar_fn.fname,
            tuple(logical_expr_from_proto(a) for a in n.scalar_fn.args),
        )
    if kind == "udf":
        return lex.ScalarUDFExpr(
            n.udf.name,
            tuple(logical_expr_from_proto(a) for a in n.udf.args),
            dtype_from_bytes(n.udf.out_type),
        )
    if kind == "aggregate":
        arg = (
            logical_expr_from_proto(n.aggregate.arg) if n.aggregate.has_arg else None
        )
        udaf_type = (
            dtype_from_bytes(n.aggregate.udaf_out_type)
            if n.aggregate.udaf_out_type
            else None
        )
        arg2 = (
            logical_expr_from_proto(n.aggregate.arg2)
            if n.aggregate.has_arg2
            else None
        )
        return lex.AggregateExpr(
            n.aggregate.func, arg, n.aggregate.distinct,
            udaf_type=udaf_type, arg2=arg2,
        )
    if kind == "window":
        warg = (
            logical_expr_from_proto(n.window.arg) if n.window.has_arg else None
        )
        parts = tuple(
            logical_expr_from_proto(p) for p in n.window.partition_by
        )
        orders = tuple(
            lex.SortExpr(
                logical_expr_from_proto(s.expr),
                s.asc,
                None if s.nulls_first == 0 else s.nulls_first == 1,
            )
            for s in n.window.order_by
        )
        return lex.WindowExpr(
            n.window.func, warg, parts, orders, n.window.offset,
            _frame_from_proto(n.window.frame)
            if n.window.HasField("frame")
            else None,
        )
    if kind == "sort":
        nf: Optional[bool] = (
            None if n.sort.nulls_first == 0 else n.sort.nulls_first == 1
        )
        return lex.SortExpr(logical_expr_from_proto(n.sort.expr), n.sort.asc, nf)
    if kind == "scalar_subquery":
        from .logical_plan import logical_plan_from_proto

        return lex.ScalarSubqueryExpr(logical_plan_from_proto(n.scalar_subquery.plan))
    raise PlanError(f"cannot deserialize logical expr node {kind!r}")
