"""Logical plan ⇄ protobuf conversion.

Counterpart of the reference's vendored DataFusion logical plan serde
(``core/proto/datafusion.proto`` + its from/to_proto code).  This is what
travels client → scheduler in ``ExecuteQuery``.
"""

from __future__ import annotations

import json

import pyarrow as pa

from ..catalog import provider_from_description
from ..errors import PlanError
from ..plan import logical as lp
from ..proto import pb
from .arrow_utils import (
    schema_from_bytes,
    schema_to_bytes,
    table_from_ipc,
    table_to_ipc,
)
from .expressions import logical_expr_from_proto, logical_expr_to_proto


def logical_plan_to_proto(plan: lp.LogicalPlan) -> pb.LogicalPlanNode:
    n = pb.LogicalPlanNode()
    if isinstance(plan, lp.TableScan):
        n.table_scan.table_name = plan.table_name
        n.table_scan.provider.json = json.dumps(plan.provider.describe())
        if plan.projection is not None:
            n.table_scan.projection.extend(plan.projection)
            n.table_scan.has_projection = True
        for f in plan.filters:
            n.table_scan.filters.add().CopyFrom(logical_expr_to_proto(f))
        return n
    if isinstance(plan, lp.SubqueryAlias):
        n.subquery_alias.input.CopyFrom(logical_plan_to_proto(plan.input))
        n.subquery_alias.alias = plan.alias
        return n
    if isinstance(plan, lp.Projection):
        for e in plan.exprs:
            n.projection.exprs.add().CopyFrom(logical_expr_to_proto(e))
        n.projection.input.CopyFrom(logical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, lp.Filter):
        n.filter.predicate.CopyFrom(logical_expr_to_proto(plan.predicate))
        n.filter.input.CopyFrom(logical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, lp.Aggregate):
        for g in plan.group_exprs:
            n.aggregate.group_exprs.add().CopyFrom(logical_expr_to_proto(g))
        for a in plan.agg_exprs:
            n.aggregate.agg_exprs.add().CopyFrom(logical_expr_to_proto(a))
        n.aggregate.input.CopyFrom(logical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, lp.Sort):
        for s in plan.sort_exprs:
            n.sort.sort_exprs.add().CopyFrom(logical_expr_to_proto(s))
        n.sort.input.CopyFrom(logical_plan_to_proto(plan.input))
        n.sort.fetch = -1 if plan.fetch is None else plan.fetch
        return n
    if isinstance(plan, lp.Limit):
        n.limit.input.CopyFrom(logical_plan_to_proto(plan.input))
        n.limit.skip = plan.skip
        n.limit.fetch = -1 if plan.fetch is None else plan.fetch
        return n
    if isinstance(plan, lp.Join):
        n.join.left.CopyFrom(logical_plan_to_proto(plan.left))
        n.join.right.CopyFrom(logical_plan_to_proto(plan.right))
        for l, r in plan.on:
            pair = n.join.on.add()
            pair.left.CopyFrom(logical_expr_to_proto(l))
            pair.right.CopyFrom(logical_expr_to_proto(r))
        n.join.join_type = plan.join_type
        if plan.filter is not None:
            n.join.filter.CopyFrom(logical_expr_to_proto(plan.filter))
            n.join.has_filter = True
        return n
    if isinstance(plan, lp.CrossJoin):
        n.cross_join.left.CopyFrom(logical_plan_to_proto(plan.left))
        n.cross_join.right.CopyFrom(logical_plan_to_proto(plan.right))
        return n
    if isinstance(plan, lp.Union):
        for i in plan.inputs:
            n.union_all.inputs.add().CopyFrom(logical_plan_to_proto(i))
        return n
    if isinstance(plan, lp.Distinct):
        n.distinct.input.CopyFrom(logical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, lp.Window):
        for w in plan.window_exprs:
            n.window.window_exprs.add().CopyFrom(logical_expr_to_proto(w))
        n.window.input.CopyFrom(logical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, lp.EmptyRelation):
        n.empty.produce_one_row = plan.produce_one_row
        n.empty.schema = schema_to_bytes(plan.schema_)
        return n
    if isinstance(plan, lp.Values):
        arrays = []
        for i, f in enumerate(plan.schema_):
            arrays.append(pa.array([r[i] for r in plan.rows], f.type))
        tbl = pa.Table.from_arrays(arrays, schema=plan.schema_)
        n.values.ipc_data = table_to_ipc(tbl)
        return n
    raise PlanError(f"cannot serialize logical plan {type(plan).__name__}")


def logical_plan_from_proto(n: pb.LogicalPlanNode) -> lp.LogicalPlan:
    kind = n.WhichOneof("plan")
    if kind == "table_scan":
        provider = provider_from_description(json.loads(n.table_scan.provider.json))
        projection = (
            list(n.table_scan.projection) if n.table_scan.has_projection else None
        )
        filters = [logical_expr_from_proto(f) for f in n.table_scan.filters]
        return lp.TableScan(n.table_scan.table_name, provider, projection, filters)
    if kind == "subquery_alias":
        return lp.SubqueryAlias(
            logical_plan_from_proto(n.subquery_alias.input), n.subquery_alias.alias
        )
    if kind == "projection":
        return lp.Projection(
            [logical_expr_from_proto(e) for e in n.projection.exprs],
            logical_plan_from_proto(n.projection.input),
        )
    if kind == "filter":
        return lp.Filter(
            logical_expr_from_proto(n.filter.predicate),
            logical_plan_from_proto(n.filter.input),
        )
    if kind == "aggregate":
        return lp.Aggregate(
            [logical_expr_from_proto(g) for g in n.aggregate.group_exprs],
            [logical_expr_from_proto(a) for a in n.aggregate.agg_exprs],
            logical_plan_from_proto(n.aggregate.input),
        )
    if kind == "sort":
        return lp.Sort(
            [logical_expr_from_proto(s) for s in n.sort.sort_exprs],
            logical_plan_from_proto(n.sort.input),
            None if n.sort.fetch < 0 else n.sort.fetch,
        )
    if kind == "limit":
        return lp.Limit(
            logical_plan_from_proto(n.limit.input),
            n.limit.skip,
            None if n.limit.fetch < 0 else n.limit.fetch,
        )
    if kind == "join":
        on = [
            (logical_expr_from_proto(p.left), logical_expr_from_proto(p.right))
            for p in n.join.on
        ]
        jfilter = logical_expr_from_proto(n.join.filter) if n.join.has_filter else None
        return lp.Join(
            logical_plan_from_proto(n.join.left),
            logical_plan_from_proto(n.join.right),
            on,
            n.join.join_type,
            jfilter,
        )
    if kind == "cross_join":
        return lp.CrossJoin(
            logical_plan_from_proto(n.cross_join.left),
            logical_plan_from_proto(n.cross_join.right),
        )
    if kind == "union_all":
        return lp.Union([logical_plan_from_proto(i) for i in n.union_all.inputs])
    if kind == "distinct":
        return lp.Distinct(logical_plan_from_proto(n.distinct.input))
    if kind == "window":
        return lp.Window(
            [logical_expr_from_proto(w) for w in n.window.window_exprs],
            logical_plan_from_proto(n.window.input),
        )
    if kind == "empty":
        return lp.EmptyRelation(
            n.empty.produce_one_row, schema_from_bytes(n.empty.schema)
        )
    if kind == "values":
        tbl = table_from_ipc(n.values.ipc_data)
        rows = [list(r.values()) for r in tbl.to_pylist()]
        return lp.Values(rows, tbl.schema)
    raise PlanError(f"cannot deserialize logical plan node {kind!r}")
