"""Scheduler-domain value types + proto conversions.

Counterpart of the reference's ``core/src/serde/scheduler/{mod,from_proto,
to_proto}.rs``: the plain-data types shared between scheduler, executor and
client (executor identity, partition identity/locations, shuffle-write
stats), each with bidirectional protobuf conversion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..proto import pb


@dataclass(frozen=True)
class ExecutorSpecification:
    task_slots: int = 4

    def to_proto(self) -> pb.ExecutorSpecification:
        return pb.ExecutorSpecification(task_slots=self.task_slots)

    @staticmethod
    def from_proto(p: pb.ExecutorSpecification) -> "ExecutorSpecification":
        return ExecutorSpecification(task_slots=p.task_slots or 4)


@dataclass(frozen=True)
class ExecutorMetadata:
    """Where an executor can be reached (Flight data port + gRPC port)."""

    id: str
    host: str
    flight_port: int
    grpc_port: int = 0
    specification: ExecutorSpecification = field(default_factory=ExecutorSpecification)

    def to_proto(self) -> pb.ExecutorMetadata:
        return pb.ExecutorMetadata(
            id=self.id,
            host=self.host,
            flight_port=self.flight_port,
            grpc_port=self.grpc_port,
            specification=self.specification.to_proto(),
        )

    @staticmethod
    def from_proto(p: pb.ExecutorMetadata) -> "ExecutorMetadata":
        return ExecutorMetadata(
            id=p.id,
            host=p.host,
            flight_port=p.flight_port,
            grpc_port=p.grpc_port,
            specification=ExecutorSpecification.from_proto(p.specification),
        )


@dataclass(frozen=True)
class PartitionId:
    """(job, stage, partition) task identity (reference:
    core/src/serde/scheduler/mod.rs PartitionId)."""

    job_id: str
    stage_id: int
    partition_id: int

    def to_proto(self) -> pb.PartitionId:
        return pb.PartitionId(
            job_id=self.job_id,
            stage_id=self.stage_id,
            partition_id=self.partition_id,
        )

    @staticmethod
    def from_proto(p: pb.PartitionId) -> "PartitionId":
        return PartitionId(p.job_id, p.stage_id, p.partition_id)

    def __str__(self) -> str:
        return f"{self.job_id}/{self.stage_id}/{self.partition_id}"


@dataclass(frozen=True)
class PartitionStats:
    num_rows: int = -1
    num_batches: int = -1
    num_bytes: int = -1

    def to_proto(self) -> pb.PartitionStats:
        return pb.PartitionStats(
            num_rows=self.num_rows,
            num_batches=self.num_batches,
            num_bytes=self.num_bytes,
        )

    @staticmethod
    def from_proto(p: pb.PartitionStats) -> "PartitionStats":
        return PartitionStats(p.num_rows, p.num_batches, p.num_bytes)


@dataclass(frozen=True)
class PartitionLocation:
    """A completed map-side shuffle partition an executor can serve.

    ``replica_path`` ("" = single copy) names an external-store copy the
    fetch path fails over to when the serving executor is unreachable;
    the scheduler re-points whole locations at it on executor loss."""

    partition_id: PartitionId
    executor_meta: ExecutorMetadata
    partition_stats: PartitionStats
    path: str
    replica_path: str = ""

    def to_proto(self) -> pb.PartitionLocation:
        return pb.PartitionLocation(
            partition_id=self.partition_id.to_proto(),
            executor_meta=self.executor_meta.to_proto(),
            partition_stats=self.partition_stats.to_proto(),
            path=self.path,
            replica_path=self.replica_path,
        )

    @staticmethod
    def from_proto(p: pb.PartitionLocation) -> "PartitionLocation":
        return PartitionLocation(
            PartitionId.from_proto(p.partition_id),
            ExecutorMetadata.from_proto(p.executor_meta),
            PartitionStats.from_proto(p.partition_stats),
            p.path,
            p.replica_path,
        )


@dataclass(frozen=True)
class ShuffleWritePartition:
    """Stats for one output partition written by a shuffle-write task
    (reference: shuffle_writer.rs ShuffleWritePartition).
    ``replica_path`` carries the external-store copy's path ("" = single
    copy)."""

    partition_id: int
    path: str
    num_batches: int
    num_rows: int
    num_bytes: int
    replica_path: str = ""

    def to_proto(self) -> pb.ShuffleWritePartition:
        return pb.ShuffleWritePartition(
            partition_id=self.partition_id,
            path=self.path,
            num_batches=self.num_batches,
            num_rows=self.num_rows,
            num_bytes=self.num_bytes,
            replica_path=self.replica_path,
        )

    @staticmethod
    def from_proto(p: pb.ShuffleWritePartition) -> "ShuffleWritePartition":
        return ShuffleWritePartition(
            p.partition_id, p.path, p.num_batches, p.num_rows, p.num_bytes,
            p.replica_path,
        )
