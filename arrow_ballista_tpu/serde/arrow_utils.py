"""Arrow ⇄ bytes helpers for the wire format.

Schemas, data types and literal values travel as Arrow IPC bytes — exact
round-tripping without re-modelling the Arrow type system in protobuf.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import pyarrow as pa


def schema_to_bytes(schema: pa.Schema) -> bytes:
    return schema.serialize().to_pybytes()


def schema_from_bytes(b: bytes) -> pa.Schema:
    return pa.ipc.read_schema(pa.py_buffer(b))


def dtype_to_bytes(dtype: pa.DataType) -> bytes:
    return schema_to_bytes(pa.schema([pa.field("t", dtype)]))


def dtype_from_bytes(b: bytes) -> pa.DataType:
    return schema_from_bytes(b).field(0).type


def value_to_ipc(value: Any, dtype: Optional[pa.DataType] = None) -> bytes:
    """Encode one value (+ its exact type) as a single-row IPC stream."""
    arr = pa.array([value], type=dtype)
    batch = pa.record_batch([arr], names=["v"])
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue().to_pybytes()


def value_from_ipc(b: bytes) -> Tuple[Any, pa.DataType]:
    with pa.ipc.open_stream(pa.py_buffer(b)) as r:
        batch = r.read_next_batch()
    col = batch.column(0)
    return col[0].as_py(), col.type


def array_to_ipc(values, dtype: Optional[pa.DataType] = None) -> bytes:
    arr = pa.array(list(values), type=dtype)
    batch = pa.record_batch([arr], names=["v"])
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue().to_pybytes()


def array_from_ipc(b: bytes) -> pa.Array:
    with pa.ipc.open_stream(pa.py_buffer(b)) as r:
        batch = r.read_next_batch()
    return batch.column(0)


def table_to_ipc(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def table_from_ipc(b: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.py_buffer(b)) as r:
        return r.read_all()
