"""Plan + domain-type serde (protobuf wire format).

``BallistaCodec`` bundles the logical and physical codecs the way the
reference's ``BallistaCodec`` does (``core/src/serde/mod.rs:124-164``).
"""

from .arrow_utils import (
    dtype_from_bytes,
    dtype_to_bytes,
    schema_from_bytes,
    schema_to_bytes,
)
from .expressions import (
    logical_expr_from_proto,
    logical_expr_to_proto,
    physical_expr_from_proto,
    physical_expr_to_proto,
)
from .logical_plan import logical_plan_from_proto, logical_plan_to_proto
from .physical_plan import (
    partitioning_from_proto,
    partitioning_to_proto,
    physical_plan_from_proto,
    physical_plan_to_proto,
)
from .scheduler_types import (
    ExecutorMetadata,
    ExecutorSpecification,
    PartitionId,
    PartitionLocation,
    PartitionStats,
    ShuffleWritePartition,
)


class BallistaCodec:
    """Logical + physical codec bundle."""

    @staticmethod
    def encode_logical(plan) -> bytes:
        return logical_plan_to_proto(plan).SerializeToString()

    @staticmethod
    def decode_logical(data: bytes):
        from ..proto import pb

        return logical_plan_from_proto(pb.LogicalPlanNode.FromString(data))

    @staticmethod
    def encode_physical(plan) -> bytes:
        return physical_plan_to_proto(plan).SerializeToString()

    @staticmethod
    def decode_physical(data: bytes, work_dir: str = "/tmp/ballista-tpu"):
        from ..proto import pb

        return physical_plan_from_proto(pb.PhysicalPlanNode.FromString(data), work_dir)


__all__ = [
    "BallistaCodec",
    "ExecutorMetadata",
    "ExecutorSpecification",
    "PartitionId",
    "PartitionLocation",
    "PartitionStats",
    "ShuffleWritePartition",
    "dtype_from_bytes",
    "dtype_to_bytes",
    "logical_expr_from_proto",
    "logical_expr_to_proto",
    "logical_plan_from_proto",
    "logical_plan_to_proto",
    "partitioning_from_proto",
    "partitioning_to_proto",
    "physical_expr_from_proto",
    "physical_expr_to_proto",
    "physical_plan_from_proto",
    "physical_plan_to_proto",
    "schema_from_bytes",
    "schema_to_bytes",
]
