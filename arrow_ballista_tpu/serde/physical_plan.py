"""Physical plan ⇄ protobuf conversion.

Counterpart of the reference's ``core/src/serde/physical_plan/mod.rs``
(operator encode/decode; roundtrip-tested the same way).  Stage plans
travel scheduler → executor inside ``TaskDefinition.plan``.

``ShuffleWriterExec.work_dir`` deliberately does NOT travel on the wire:
the receiving executor rebuilds the writer against its local work dir,
exactly like the reference (``executor/src/executor.rs:137-161``).
"""

from __future__ import annotations

import json
from typing import Optional

from ..catalog import provider_from_description
from ..errors import PlanError
from ..exec import aggregates as agg
from ..exec import joins as jn
from ..exec.operators import (
    CoalescePartitionsExec,
    EmptyExec,
    ExecutionPlan,
    FilterExec,
    LimitExec,
    Partitioning,
    ProjectionExec,
    RepartitionExec,
    ScanExec,
    SortExec,
    UnionExec,
)
from ..exec.planner import RenameSchemaExec
from ..exec.window import WindowExec, WindowSpec
from ..proto import pb
from ..shuffle import ShuffleReaderExec, ShuffleWriterExec, UnresolvedShuffleExec
from .arrow_utils import (
    dtype_from_bytes,
    dtype_to_bytes,
    schema_from_bytes,
    schema_to_bytes,
)
from .expressions import (
    _frame_from_proto,
    _frame_to_proto,
    physical_expr_from_proto,
    physical_expr_to_proto,
)
from .scheduler_types import PartitionLocation


def _selections_from_json(raw: str):
    """AQE read-selection triples from their JSON wire form ('' = none)."""
    if not raw:
        return None
    return [[tuple(t) for t in task] for task in json.loads(raw)]


def partitioning_to_proto(p: Partitioning) -> pb.PhysicalPartitioning:
    msg = pb.PhysicalPartitioning(kind=p.kind, partition_count=p.n)
    for e in p.exprs:
        msg.exprs.add().CopyFrom(physical_expr_to_proto(e))
    return msg


def partitioning_from_proto(msg: pb.PhysicalPartitioning) -> Partitioning:
    exprs = tuple(physical_expr_from_proto(e) for e in msg.exprs)
    return Partitioning(msg.kind, msg.partition_count, exprs)


def physical_plan_to_proto(plan: ExecutionPlan) -> pb.PhysicalPlanNode:
    from ..ops.stage_compiler import TpuStageExec
    from ..ops.window_compiler import TpuWindowExec

    if isinstance(plan, (TpuStageExec, TpuWindowExec)):
        # accelerated stages travel as their unaccelerated operator
        # subtree; the receiving executor re-applies maybe_accelerate
        # under its own session config (acceleration is a local
        # physical-optimizer rule, mirroring the reference's
        # PhysicalExtensionCodec plugin hook)
        return physical_plan_to_proto(plan.original)

    n = pb.PhysicalPlanNode()
    if isinstance(plan, ScanExec):
        n.scan.table_name = plan.table_name
        n.scan.provider.json = json.dumps(plan.provider.describe())
        if plan.projection is not None:
            n.scan.projection.extend(plan.projection)
            n.scan.has_projection = True
        return n
    if isinstance(plan, FilterExec):
        n.filter.predicate.CopyFrom(physical_expr_to_proto(plan.predicate))
        n.filter.input.CopyFrom(physical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, ProjectionExec):
        for e, name in plan.exprs:
            ne = n.projection.exprs.add()
            ne.expr.CopyFrom(physical_expr_to_proto(e))
            ne.name = name
        n.projection.input.CopyFrom(physical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, agg.HashAggregateExec):
        n.aggregate.mode = plan.mode
        for e, name in plan.group_exprs:
            ne = n.aggregate.group_exprs.add()
            ne.expr.CopyFrom(physical_expr_to_proto(e))
            ne.name = name
        for spec in plan.aggs:
            sp = n.aggregate.aggs.add()
            sp.func = spec.func
            if spec.arg is not None:
                sp.arg.CopyFrom(physical_expr_to_proto(spec.arg))
                sp.has_arg = True
            if spec.arg2 is not None:
                sp.arg2.CopyFrom(physical_expr_to_proto(spec.arg2))
                sp.has_arg2 = True
            sp.name = spec.name
            sp.out_type = dtype_to_bytes(spec.out_type)
        n.aggregate.input.CopyFrom(physical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, jn.HashJoinExec):
        n.join.left.CopyFrom(physical_plan_to_proto(plan.left))
        n.join.right.CopyFrom(physical_plan_to_proto(plan.right))
        for l, r in plan.on:
            pair = n.join.on.add()
            pair.left.CopyFrom(physical_expr_to_proto(l))
            pair.right.CopyFrom(physical_expr_to_proto(r))
        n.join.join_type = plan.join_type
        n.join.partition_mode = plan.partition_mode
        if plan.filter is not None:
            n.join.filter.CopyFrom(physical_expr_to_proto(plan.filter))
            n.join.has_filter = True
        return n
    if isinstance(plan, jn.CrossJoinExec):
        n.cross_join.left.CopyFrom(physical_plan_to_proto(plan.left))
        n.cross_join.right.CopyFrom(physical_plan_to_proto(plan.right))
        return n
    if isinstance(plan, SortExec):
        for e, asc, nf in plan.sort_keys:
            k = n.sort.keys.add()
            k.expr.CopyFrom(physical_expr_to_proto(e))
            k.asc = asc
            k.nulls_first = 0 if nf is None else (1 if nf else 2)
        n.sort.input.CopyFrom(physical_plan_to_proto(plan.input))
        n.sort.fetch = -1 if plan.fetch is None else plan.fetch
        return n
    if isinstance(plan, WindowExec):
        for s in plan.specs:
            sp = n.window.specs.add()
            sp.func = s.func
            if s.arg is not None:
                sp.arg.CopyFrom(physical_expr_to_proto(s.arg))
                sp.has_arg = True
            for p in s.partition_by:
                sp.partition_by.add().CopyFrom(physical_expr_to_proto(p))
            for e, asc, nf in s.order_by:
                k = sp.order_by.add()
                k.expr.CopyFrom(physical_expr_to_proto(e))
                k.asc = asc
                k.nulls_first = 0 if nf is None else (1 if nf else 2)
            sp.name = s.name
            sp.out_type = dtype_to_bytes(s.out_type)
            sp.offset = s.offset
            if s.frame is not None:
                _frame_to_proto(s.frame, sp.frame)
        n.window.input.CopyFrom(physical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, LimitExec):
        n.limit.input.CopyFrom(physical_plan_to_proto(plan.input))
        n.limit.skip = plan.skip
        n.limit.fetch = -1 if plan.fetch is None else plan.fetch
        return n
    if isinstance(plan, UnionExec):
        for i in plan.inputs:
            n.union_all.inputs.add().CopyFrom(physical_plan_to_proto(i))
        return n
    if isinstance(plan, RepartitionExec):
        n.repartition.input.CopyFrom(physical_plan_to_proto(plan.input))
        n.repartition.partitioning.CopyFrom(partitioning_to_proto(plan.partitioning))
        return n
    if isinstance(plan, CoalescePartitionsExec):
        n.coalesce.input.CopyFrom(physical_plan_to_proto(plan.input))
        return n
    if isinstance(plan, RenameSchemaExec):
        n.rename.input.CopyFrom(physical_plan_to_proto(plan.input))
        n.rename.schema = schema_to_bytes(plan.schema)
        return n
    if isinstance(plan, EmptyExec):
        n.empty.produce_one_row = plan.produce_one_row
        n.empty.schema = schema_to_bytes(plan.schema)
        return n
    if isinstance(plan, ShuffleWriterExec):
        n.shuffle_writer.job_id = plan.job_id
        n.shuffle_writer.stage_id = plan.stage_id
        n.shuffle_writer.input.CopyFrom(physical_plan_to_proto(plan.input))
        if plan.shuffle_output_partitioning is not None:
            n.shuffle_writer.output_partitioning.CopyFrom(
                partitioning_to_proto(plan.shuffle_output_partitioning)
            )
            n.shuffle_writer.has_output_partitioning = True
        return n
    if isinstance(plan, ShuffleReaderExec):
        n.shuffle_reader.stage_id = plan.stage_id
        n.shuffle_reader.schema = schema_to_bytes(plan.schema)
        for locs in plan.partition:
            ll = n.shuffle_reader.partition.add()
            for loc in locs:
                ll.locations.add().CopyFrom(loc.to_proto())
        # AQE provenance: lets executor-loss rollback rebuild the
        # REWRITTEN placeholder after a scheduler restart too
        if plan.selections is not None:
            n.shuffle_reader.selections_json = json.dumps(plan.selections)
        if plan.source_partition_count:
            n.shuffle_reader.source_partition_count = plan.source_partition_count
        if plan.tail:
            # pipelined execution: the executor tails the scheduler's
            # shuffle-location feed instead of reading static locations
            n.shuffle_reader.tail = True
        return n
    if isinstance(plan, UnresolvedShuffleExec):
        n.unresolved_shuffle.stage_id = plan.stage_id
        n.unresolved_shuffle.schema = schema_to_bytes(plan.schema)
        n.unresolved_shuffle.input_partition_count = plan.input_partition_count
        n.unresolved_shuffle.output_partition_count = plan.output_partition_count
        if plan.selections is not None:
            n.unresolved_shuffle.selections_json = json.dumps(plan.selections)
        return n
    from ..parallel.mesh_stage import MeshGangExec, MeshRepartitionExec

    if isinstance(plan, MeshRepartitionExec):
        n.mesh_repartition.input.CopyFrom(physical_plan_to_proto(plan.input))
        n.mesh_repartition.partitioning.CopyFrom(
            partitioning_to_proto(plan.partitioning)
        )
        n.mesh_repartition.n_devices = plan.n_devices
        return n

    if isinstance(plan, MeshGangExec):
        n.mesh_gang.input.CopyFrom(physical_plan_to_proto(plan.input))
        n.mesh_gang.n_devices = plan.n_devices
        return n
    raise PlanError(f"cannot serialize physical plan {type(plan).__name__}")


def physical_plan_from_proto(
    n: pb.PhysicalPlanNode, work_dir: str = "/tmp/ballista-tpu"
) -> ExecutionPlan:
    def rec(m: pb.PhysicalPlanNode) -> ExecutionPlan:
        return physical_plan_from_proto(m, work_dir)

    kind = n.WhichOneof("plan")
    if kind == "scan":
        provider = provider_from_description(json.loads(n.scan.provider.json))
        projection = list(n.scan.projection) if n.scan.has_projection else None
        return ScanExec(n.scan.table_name, provider, projection)
    if kind == "filter":
        return FilterExec(
            physical_expr_from_proto(n.filter.predicate), rec(n.filter.input)
        )
    if kind == "projection":
        exprs = [
            (physical_expr_from_proto(e.expr), e.name) for e in n.projection.exprs
        ]
        return ProjectionExec(exprs, rec(n.projection.input))
    if kind == "aggregate":
        groups = [
            (physical_expr_from_proto(e.expr), e.name)
            for e in n.aggregate.group_exprs
        ]
        specs = [
            agg.AggSpec(
                sp.func,
                physical_expr_from_proto(sp.arg) if sp.has_arg else None,
                sp.name,
                dtype_from_bytes(sp.out_type),
                arg2=(
                    physical_expr_from_proto(sp.arg2) if sp.has_arg2 else None
                ),
            )
            for sp in n.aggregate.aggs
        ]
        return agg.HashAggregateExec(
            n.aggregate.mode, groups, specs, rec(n.aggregate.input)
        )
    if kind == "join":
        on = [
            (physical_expr_from_proto(p.left), physical_expr_from_proto(p.right))
            for p in n.join.on
        ]
        jfilter = (
            physical_expr_from_proto(n.join.filter) if n.join.has_filter else None
        )
        return jn.HashJoinExec(
            rec(n.join.left),
            rec(n.join.right),
            on,
            n.join.join_type,
            n.join.partition_mode,
            jfilter,
        )
    if kind == "cross_join":
        return jn.CrossJoinExec(rec(n.cross_join.left), rec(n.cross_join.right))
    if kind == "sort":
        keys = [
            (
                physical_expr_from_proto(k.expr),
                k.asc,
                None if k.nulls_first == 0 else k.nulls_first == 1,
            )
            for k in n.sort.keys
        ]
        return SortExec(
            keys, rec(n.sort.input), None if n.sort.fetch < 0 else n.sort.fetch
        )
    if kind == "window":
        specs = [
            WindowSpec(
                sp.func,
                physical_expr_from_proto(sp.arg) if sp.has_arg else None,
                tuple(
                    physical_expr_from_proto(p) for p in sp.partition_by
                ),
                tuple(
                    (
                        physical_expr_from_proto(k.expr),
                        k.asc,
                        None if k.nulls_first == 0 else k.nulls_first == 1,
                    )
                    for k in sp.order_by
                ),
                sp.name,
                dtype_from_bytes(sp.out_type),
                sp.offset,
                _frame_from_proto(sp.frame)
                if sp.HasField("frame")
                else None,
            )
            for sp in n.window.specs
        ]
        return WindowExec(rec(n.window.input), specs)
    if kind == "limit":
        return LimitExec(
            rec(n.limit.input),
            n.limit.skip,
            None if n.limit.fetch < 0 else n.limit.fetch,
        )
    if kind == "union_all":
        return UnionExec([rec(i) for i in n.union_all.inputs])
    if kind == "repartition":
        return RepartitionExec(
            rec(n.repartition.input),
            partitioning_from_proto(n.repartition.partitioning),
        )
    if kind == "coalesce":
        return CoalescePartitionsExec(rec(n.coalesce.input))
    if kind == "rename":
        return RenameSchemaExec(rec(n.rename.input), schema_from_bytes(n.rename.schema))
    if kind == "empty":
        return EmptyExec(n.empty.produce_one_row, schema_from_bytes(n.empty.schema))
    if kind == "shuffle_writer":
        part: Optional[Partitioning] = None
        if n.shuffle_writer.has_output_partitioning:
            part = partitioning_from_proto(n.shuffle_writer.output_partitioning)
        return ShuffleWriterExec(
            n.shuffle_writer.job_id,
            n.shuffle_writer.stage_id,
            rec(n.shuffle_writer.input),
            work_dir,
            part,
        )
    if kind == "shuffle_reader":
        partition = [
            [PartitionLocation.from_proto(loc) for loc in ll.locations]
            for ll in n.shuffle_reader.partition
        ]
        return ShuffleReaderExec(
            n.shuffle_reader.stage_id,
            schema_from_bytes(n.shuffle_reader.schema),
            partition,
            selections=_selections_from_json(n.shuffle_reader.selections_json),
            source_partition_count=(
                n.shuffle_reader.source_partition_count or None
            ),
            tail=bool(n.shuffle_reader.tail),
        )
    if kind == "unresolved_shuffle":
        return UnresolvedShuffleExec(
            n.unresolved_shuffle.stage_id,
            schema_from_bytes(n.unresolved_shuffle.schema),
            n.unresolved_shuffle.input_partition_count,
            n.unresolved_shuffle.output_partition_count,
            selections=_selections_from_json(
                n.unresolved_shuffle.selections_json
            ),
        )
    if kind == "mesh_gang":
        from ..parallel.mesh_stage import MeshGangExec

        return MeshGangExec(rec(n.mesh_gang.input), n.mesh_gang.n_devices)
    if kind == "mesh_repartition":
        from ..parallel.mesh_stage import MeshRepartitionExec

        return MeshRepartitionExec(
            rec(n.mesh_repartition.input),
            partitioning_from_proto(n.mesh_repartition.partitioning),
            n.mesh_repartition.n_devices,
        )
    raise PlanError(f"cannot deserialize physical plan node {kind!r}")
