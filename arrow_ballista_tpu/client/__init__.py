from .context import BallistaContext, BallistaDataFrame

__all__ = ["BallistaContext", "BallistaDataFrame"]
