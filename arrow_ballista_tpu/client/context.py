"""BallistaContext: the user-facing distributed query entry point.

Counterpart of the reference's ``client/src/context.rs``:

* ``BallistaContext.remote(host, port, config)`` — calls ExecuteQuery with
  no query to mint a server-side session id (`:85-138`), then every
  DataFrame/SQL collect becomes a distributed job;
* ``BallistaContext.standalone(...)`` — spins up an in-proc scheduler +
  executor(s) (`:140-210`);
* ``read_/register_{csv,parquet}`` keep a client-side table registry
  (`:212-311`); ``sql()`` handles SHOW / CREATE EXTERNAL TABLE / SET
  client-side (`:313-460`).

The collect path is the counterpart of ``DistributedQueryExec``
(``core/src/execution_plans/distributed_query.rs:161-333``): serialize the
logical plan, ExecuteQuery, poll GetJobStatus every 100ms, then fetch the
completed partitions (local-file fast path, Arrow Flight otherwise).
"""

from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

import pyarrow as pa

from ..config import BallistaConfig, TaskSchedulingPolicy
from ..context import DataFrame, SessionContext, _unqualify
from ..errors import BallistaError, ExecutionError
from ..proto import pb
from ..proto.rpc import SchedulerGrpcStub, make_channel
from ..serde import BallistaCodec
from ..serde.scheduler_types import PartitionLocation

log = logging.getLogger(__name__)



class BallistaDataFrame(DataFrame):
    """DataFrame whose collect() runs on the cluster.  Transformations
    inherited from DataFrame stay lazy and preserve this type."""

    def collect(self) -> pa.Table:
        remote: BallistaContext = self.ctx.ballista_context
        return remote._collect_distributed(self.plan)


class BallistaContext:
    def __init__(
        self,
        host: str,
        port: int,
        config: Optional[BallistaConfig] = None,
        _standalone_handles: Optional[tuple] = None,
        endpoints: Optional[List] = None,
    ):
        self.config = config or BallistaConfig()
        # scheduler failover (ISSUE 20): `endpoints` lists BACKUP
        # schedulers ("host:port" strings or (host, port) pairs) sharing
        # the primary's state backend.  Idempotent RPCs rotate to the
        # next endpoint on a transient failure; with no extras the list
        # is just the primary and behavior matches a single-endpoint
        # client.
        eps: List[tuple] = [(host, int(port))]
        for ep in endpoints or []:
            if isinstance(ep, str):
                h, _, p = ep.rpartition(":")
                eps.append((h, int(p)))
            else:
                eps.append((str(ep[0]), int(ep[1])))
        self._endpoints: List[tuple] = []
        for ep in eps:
            if ep not in self._endpoints:
                self._endpoints.append(ep)
        self._endpoint_idx = 0
        self._stubs: dict = {}
        self.host, self.port = self._endpoints[0]
        self.stub = self._stub_for(self._endpoints[0])
        self._session = SessionContext(self.config)
        self._session.ballista_context = self
        self._standalone_handles = _standalone_handles
        self._job_ids: set[str] = set()

        # mint a server-side session id (reference: context.rs:103-119);
        # an empty-query bootstrap is idempotent, so it rides the retry/
        # rotation path like every other session RPC
        result = self._call(
            "ExecuteQuery",
            pb.ExecuteQueryParams(settings=self._settings()),
            timeout=20,
        )
        self.session_id = result.session_id
        self._session.session_id = result.session_id

    # ------------------------------------------------------------- factory
    @staticmethod
    def remote(
        host: str,
        port: int,
        config: Optional[BallistaConfig] = None,
        endpoints: Optional[List] = None,
    ) -> "BallistaContext":
        return BallistaContext(host, port, config, endpoints=endpoints)

    @staticmethod
    def standalone(
        config: Optional[BallistaConfig] = None,
        num_executors: int = 1,
        concurrent_tasks: int = 4,
        policy: TaskSchedulingPolicy = TaskSchedulingPolicy.PULL_STAGED,
        work_dir: Optional[str] = None,
        heartbeat_interval_s: float = 5.0,
        task_isolation: str = "thread",
        plugin_dir: str = "",
        event_journal_dir: str = "",
    ) -> "BallistaContext":
        """In-proc cluster: scheduler + executors over real gRPC/Flight on
        random localhost ports (reference: context.rs:140-210)."""
        from ..executor.standalone import new_standalone_executor
        from ..scheduler.standalone import new_standalone_scheduler

        scheduler = new_standalone_scheduler(
            policy, event_journal_dir=event_journal_dir
        )
        executors = [
            new_standalone_executor(
                scheduler.host,
                scheduler.port,
                concurrent_tasks=concurrent_tasks,
                policy=policy,
                work_dir=work_dir,
                heartbeat_interval_s=heartbeat_interval_s,
                task_isolation=task_isolation,
                plugin_dir=plugin_dir,
            )
            for _ in range(num_executors)
        ]
        return BallistaContext(
            scheduler.host,
            scheduler.port,
            config,
            _standalone_handles=(scheduler, executors),
        )

    def close(self) -> None:
        # release this client's memory-plane shuffle partitions (the
        # counterpart of the executor janitor's work-dir sweep for jobs
        # that ran with ballista.shuffle.to_memory / mesh gang stages)
        from ..shuffle import memory_store, store

        ext = self.config.shuffle_external_path
        for job_id in self._job_ids:
            memory_store.delete_job(job_id)
            # external partitions/replicas of this client's jobs go too
            # (the object-store analogue of the work-dir sweep)
            store.delete_job(ext, job_id)
        self._job_ids.clear()
        if self._standalone_handles is not None:
            scheduler, executors = self._standalone_handles
            for e in executors:
                e.shutdown()
            scheduler.shutdown()
            self._standalone_handles = None

    def __enter__(self) -> "BallistaContext":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------- registration
    def register_parquet(self, name: str, path: str) -> None:
        self._session.register_parquet(name, path)

    def register_csv(self, name: str, path: str, **kw) -> None:
        self._session.register_csv(name, path, **kw)

    def register_avro(self, name: str, path: str) -> None:
        self._session.register_avro(name, path)

    def register_table(self, name: str, provider) -> None:
        self._session.register_table(name, provider)

    def read_parquet(self, path: str) -> BallistaDataFrame:
        return self._wrap(self._session.read_parquet(path))

    def read_csv(self, path: str, **kw) -> BallistaDataFrame:
        return self._wrap(self._session.read_csv(path, **kw))

    def read_avro(self, path: str) -> BallistaDataFrame:
        return self._wrap(self._session.read_avro(path))

    def table(self, name: str) -> BallistaDataFrame:
        return self._wrap(self._session.table(name))

    def tables(self) -> List[str]:
        return list(self._session.catalog.tables.keys())

    # ---------------------------------------------------------------- sql
    def sql(self, query: str) -> BallistaDataFrame:
        """SQL → lazy distributed DataFrame.  DDL (CREATE EXTERNAL TABLE),
        SHOW and SET are handled client-side by the wrapped SessionContext,
        like the reference (context.rs:313-460)."""
        df = self._session.sql(query)
        # SET ballista.* mutates the session config; keep ours in sync so
        # the next ExecuteQuery ships the updated settings
        self.config = self._session.config
        return self._wrap(df)

    def _wrap(self, df: DataFrame) -> DataFrame:
        """Distributed frame for real queries; client-side results (SHOW /
        SET / EXPLAIN produce small in-memory values tables) stay local like
        the reference (context.rs:313-460 handles them without a job)."""
        from ..catalog import MemoryTable
        from ..plan import logical as lp

        plan = df.plan
        if isinstance(plan, lp.TableScan) and isinstance(plan.provider, MemoryTable):
            return DataFrame(self._session, plan)
        return BallistaDataFrame(self._session, plan)

    # ------------------------------------------------------------ internal
    def _settings(self) -> List[pb.KeyValuePair]:
        return [
            pb.KeyValuePair(key=k, value=v)
            for k, v in self.config.to_dict().items()
        ]

    def _stub_for(self, endpoint: tuple) -> SchedulerGrpcStub:
        stub = self._stubs.get(endpoint)
        if stub is None:
            stub = SchedulerGrpcStub(make_channel(endpoint[0], endpoint[1]))
            self._stubs[endpoint] = stub
        return stub

    def _rotate_endpoint(self) -> None:
        self._endpoint_idx = (self._endpoint_idx + 1) % len(self._endpoints)
        self.host, self.port = self._endpoints[self._endpoint_idx]
        self.stub = self._stub_for(self._endpoints[self._endpoint_idx])
        log.warning(
            "rotating to scheduler endpoint %s:%d", self.host, self.port
        )

    @staticmethod
    def _retryable(e) -> bool:
        """Transient failures worth retrying: the scheduler is down/
        restarting (UNAVAILABLE) or wedged past the RPC deadline
        (DEADLINE_EXCEEDED).  Everything else — bad plan, unknown
        session, internal errors — surfaces immediately."""
        import grpc

        code = e.code() if hasattr(e, "code") else None
        return code in (
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
        )

    def _call(self, method: str, request, timeout: float):
        """One scheduler RPC with bounded transient-failure retry
        (``ballista.client.rpc_retries``) and, with multiple endpoints,
        rotation to the next scheduler per retry — the client-session
        failover path (ISSUE 20).  Only idempotent RPCs go through here
        (status polls, session bootstrap, token-carrying submits).
        Sleeps ride the same jittered exponential backoff as the status
        poll so a mass failover doesn't thunder onto the survivor.
        ``rpc_retries=0`` with a single endpoint restores the old
        fail-fast behavior exactly (one attempt, error raised raw)."""
        import grpc

        retries = max(0, self.config.client_rpc_retries)
        attempts = retries + 1
        if len(self._endpoints) > 1:
            # enough attempts to visit every endpoint at least twice —
            # a takeover needs one failed dial to notice the primary
            # died and one rotation to land on the adopting backup
            attempts = max(attempts, 2 * len(self._endpoints))
        from ..scheduler.task_status import PollBackoff

        backoff = PollBackoff(
            self.config.client_poll_interval_seconds,
            self.config.client_poll_max_interval_seconds,
        )
        last: Optional[Exception] = None
        for attempt in range(attempts):
            try:
                return getattr(self.stub, method)(request, timeout=timeout)
            except grpc.RpcError as e:
                if not self._retryable(e):
                    raise
                last = e
                if attempt + 1 >= attempts:
                    break
                if len(self._endpoints) > 1:
                    self._rotate_endpoint()
                time.sleep(backoff.next_delay())
        raise last

    def _collect_distributed(self, plan) -> pa.Table:
        import os

        job_id = self.execute_logical_plan(plan)
        self._job_ids.add(job_id)
        # cold XLA compiles on a slow host can push a legitimate job past
        # the default 300s (observed: full-TPC-H sweeps on a 1-core box);
        # benchmarks/operators raise it via env without touching the API,
        # sessions via SET ballista.client.job_timeout_seconds
        timeout_s = float(
            os.environ.get(
                "BALLISTA_JOB_TIMEOUT_S",
                self.config.client_job_timeout_seconds,
            )
        )
        status = self.wait_for_job(job_id, timeout_s=timeout_s)
        return self.fetch_job_output(status)

    def execute_logical_plan(self, plan) -> str:
        import grpc

        params = pb.ExecuteQueryParams(
            logical_plan=BallistaCodec.encode_logical(plan),
            settings=self._settings(),
            session_id=self.session_id,
        )
        if max(0, self.config.client_rpc_retries) > 0 or len(self._endpoints) > 1:
            # a submit that may be RETRIED must not double-run: the
            # scheduler dedups on this client-minted token, so every
            # attempt of this call returns the same job id.  A
            # retry-disabled single-endpoint client sends no token and
            # its request bytes match the pre-failover client exactly.
            import uuid

            params.idempotency_token = uuid.uuid4().hex
        try:
            result = self._call("ExecuteQuery", params, timeout=60)
        except grpc.RpcError as e:
            raise ExecutionError(
                f"query submission failed: {e.details() if hasattr(e, 'details') else e}"
            ) from e
        return result.job_id

    def wait_for_job(
        self,
        job_id: str,
        timeout_s: float = 300.0,
        progress=None,
    ) -> dict:
        """Poll GetJobStatus until terminal (reference:
        distributed_query.rs:232-309).

        Polling starts at ``ballista.client.poll_interval_seconds`` and
        backs off exponentially with jitter (capped at
        ``ballista.client.poll_max_interval_seconds``), resetting on the
        queued→running transition — hundreds of concurrent waiting
        clients must not hammer the scheduler in lockstep.

        ``progress``, if given, is called with the live progress
        snapshot (per-stage done/running/pending task counts, bytes,
        ETA — the ``/api/jobs/{id}/progress`` shape) on every poll that
        returns one.

        Queue-aware: a job held by admission control reports QUEUED with
        its pool + queue position, and a timeout message splits the
        deadline into time-spent-queued vs time-spent-running — a job
        that starved in a saturated queue reads differently from one
        that wedged mid-execution.

        Failover-aware: GetJobStatus is idempotent, so a transient RPC
        failure (scheduler restarting, or mid-takeover by a backup) does
        NOT kill the wait — the poll keeps going, rotating endpoints
        when the context has spares, until the job resolves or the
        deadline passes.  An adopted job reports queued/running from the
        survivor and the wait reattaches transparently."""
        import json

        import grpc

        from ..scheduler.task_status import (
            PollBackoff,
            job_status_from_proto,
            poll_timeout_breakdown,
        )

        backoff = PollBackoff(
            self.config.client_poll_interval_seconds,
            self.config.client_poll_max_interval_seconds,
        )
        # monotonic deadline: immune to wall-clock jumps mid-poll
        start = time.monotonic()
        deadline = start + timeout_s
        running_since: Optional[float] = None
        last_queued: dict = {}
        while True:
            try:
                result = self._call(
                    "GetJobStatus",
                    pb.GetJobStatusParams(
                        job_id=job_id, include_progress=progress is not None
                    ),
                    timeout=20,
                )
            except grpc.RpcError as e:
                # _call exhausted its attempts on a TRANSIENT error (a
                # non-retryable one raised out of the except above): the
                # scheduler may still be coming back — keep polling
                # until the job deadline, not the RPC budget, expires
                if not self._retryable(e) or time.monotonic() > deadline:
                    raise
                log.warning(
                    "scheduler unreachable while waiting for job %s; "
                    "retrying until the %.0fs deadline", job_id, timeout_s,
                )
                backoff.sleep(deadline)
                continue
            status = job_status_from_proto(result.status)
            state = status["state"]
            if state == "queued":
                last_queued = status
            elif running_since is None:
                running_since = time.monotonic()
                # the job just left the queue: poll tightly again
                backoff.reset()
            if progress is not None and result.progress_json:
                try:
                    progress(json.loads(result.progress_json.decode()))
                except ExecutionError:
                    raise
                except Exception:  # noqa: BLE001 - observer must not kill the wait
                    log.debug("progress callback failed", exc_info=True)
            if state == "completed":
                return status
            if state == "failed":
                raise ExecutionError(
                    f"job {job_id} failed: {status.get('error', 'unknown error')}"
                )
            if time.monotonic() > deadline:
                raise ExecutionError(
                    f"job {job_id} timed out after {timeout_s}s"
                    + poll_timeout_breakdown(start, running_since, last_queued)
                )
            backoff.sleep(deadline)

    def job_report(self, job_id: str) -> dict:
        """The scheduler's diagnosis bundle for a job this session ran:
        ``{"profile", "critical_path", "doctor"}`` — the same numbers
        ``/api/jobs/{id}/profile`` and ``/critical_path`` serve."""
        import json

        result = self._call(
            "GetJobStatus",
            pb.GetJobStatusParams(job_id=job_id, include_profile=True),
            timeout=20,
        )
        if not result.profile_json:
            raise BallistaError(
                f"no profile available for job {job_id!r} (unknown job, "
                "or still queued)"
            )
        return json.loads(result.profile_json.decode())

    def explain_analyze(self, job_id: str) -> str:
        """EXPLAIN-ANALYZE-style text tree for a finished (or running)
        job: wall-clock breakdown, critical path, doctor findings and
        per-stage stats.  Print it."""
        from ..obs.doctor import render_explain_analyze

        return render_explain_analyze(self.job_report(job_id))

    def fetch_job_output(self, status: dict) -> pa.Table:
        """Fetch completed partitions (reference:
        distributed_query.rs:311-333).  The schema comes from the partition
        files themselves, so zero-row results collect cleanly."""
        locations: List[PartitionLocation] = status.get("locations", [])
        batches: List[pa.RecordBatch] = []
        schema: Optional[pa.Schema] = None
        for loc in locations:
            part_schema, part_batches = _fetch_partition(loc)
            schema = schema or part_schema
            for batch in part_batches:
                if batch.num_rows:
                    batches.append(batch)
        if schema is None:
            raise BallistaError("completed job returned no partitions")
        return _unqualify(pa.Table.from_batches(batches, schema=schema))


def _fetch_partition(loc: PartitionLocation):
    """Returns (schema, batches) for one completed partition.  A dead
    result-serving executor degrades to the external-store replica when
    the location names one (ISSUE 6) instead of failing the collect."""
    # local fast path (standalone mode shares the filesystem)
    if loc.path and os.path.exists(loc.path):
        with pa.OSFile(loc.path, "rb") as f:
            reader = pa.ipc.open_file(f)
            batches = [
                reader.get_batch(i) for i in range(reader.num_record_batches)
            ]
        return reader.schema, batches
    try:
        from ..flight.client import BallistaClient

        client = BallistaClient.get(
            loc.executor_meta.host, loc.executor_meta.flight_port
        )
        return client.fetch_partition_with_schema(
            loc.partition_id.job_id,
            loc.partition_id.stage_id,
            loc.partition_id.partition_id,
            loc.path,
        )
    except Exception:
        # only fail over to a replica that actually EXISTS: async
        # replication stamps the path optimistically, and a dangling one
        # must not mask the original Flight error with FileNotFoundError
        if not loc.replica_path or not os.path.exists(loc.replica_path):
            raise
        from ..shuffle.store import read_batches, read_schema

        log.warning(
            "fetching job output %s from its replica %s (executor %s "
            "unreachable)", loc.path, loc.replica_path, loc.executor_meta.id,
        )
        batches = list(read_batches(loc.replica_path))
        if not batches:  # zero-row partitions still carry a schema
            return read_schema(loc.replica_path), []
        return batches[0].schema, batches
