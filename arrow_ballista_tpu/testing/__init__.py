"""Test-support package: deterministic fault injection for the
scheduler/executor fault-tolerance paths (see ``faults.py``)."""

from .faults import (  # noqa: F401
    FaultInjected,
    arm,
    clear,
    fault_point,
    hits,
    inject,
)
