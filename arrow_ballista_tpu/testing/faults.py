"""Process-wide fault-injection harness.

The fault-tolerance machinery (bounded task retries, executor quarantine,
stage rollback) is only trustworthy if its failure paths can be exercised
deterministically.  This module plants named **injection points** on the
hot paths — task launch (``scheduler.launch_task``), task execution
(``executor.execute_task``), the process-isolated worker loop
(``executor.task_runner``), shuffle fetch (``shuffle.fetch``), the
executor heartbeat (``executor.heartbeat``) and the autoscaler's provider
launch (``executor.launch`` — ``raise`` models a fleet-API refusal,
``delay`` a slow cold-start that must trip the launch timeout without
hanging the tick) — that are free when disarmed
(one attribute read) and raise :class:`FaultInjected` (or kill the
process, for worker-crash simulation) when armed.

Arming is either programmatic::

    from arrow_ballista_tpu.testing import faults
    faults.arm("executor.execute_task", times=2)          # next 2 hits fail
    faults.arm("shuffle.fetch", times=1,
               match=lambda path="", **_: "stage-1" in path)
    with faults.inject("executor.heartbeat", times=3):    # scoped
        ...

or via the ``BALLISTA_FAULTS`` environment variable (so task-runner
subprocesses, which inherit the environment, participate)::

    BALLISTA_FAULTS="executor.execute_task:2,executor.task_runner:1:exit"

Spec grammar: ``name[:times[:action]]`` comma-separated; ``times``
defaults to 1 (``-1`` = unlimited), ``action`` is ``raise`` (default),
``exit`` (``os._exit`` — a hard worker crash) or ``delay[=ms]`` (sleep at
the point instead of raising — a manufactured straggler/wedged task;
default 1000ms).  The variable is read once at import; production
processes never set it, so **injection defaults to off everywhere**.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..errors import ExecutionError


class FaultInjected(ExecutionError):
    """Raised by an armed injection point.  Classified transient by the
    scheduler (``scheduler/failure.py``) — an injected fault models an
    infrastructure failure, not a plan bug."""


@dataclass
class _Fault:
    name: str
    remaining: int  # -1 = unlimited
    action: str = "raise"  # "raise" | "exit" | "delay"
    message: str = ""
    match: Optional[Callable[..., bool]] = None
    hits: int = 0
    delay_ms: int = 0  # action="delay": sleep this long instead of raising


_lock = threading.Lock()
_faults: Dict[str, List[_Fault]] = {}
_hit_counts: Dict[str, int] = {}
# fast-path flag: fault_point() returns immediately while nothing is armed
_active = False


def _refresh_active() -> None:
    global _active
    _active = any(
        f.remaining != 0 for fl in _faults.values() for f in fl
    )


def arm(
    name: str,
    times: int = 1,
    action: str = "raise",
    message: str = "",
    match: Optional[Callable[..., bool]] = None,
    delay_ms: int = 0,
) -> None:
    """Arm ``name`` for the next ``times`` matching hits (-1 = unlimited).

    ``action="delay"`` sleeps ``delay_ms`` at the injection point instead
    of raising — a deterministic straggler/wedged-task factory for the
    speculation and deadline-reaper tests.  A delay at a site that passes
    a ``cancel_event`` in its context wakes early when the task is
    cancelled (the site's own cancellation check then fires)."""
    if action not in ("raise", "exit", "delay"):
        raise ValueError(f"unknown fault action {action!r}")
    with _lock:
        _faults.setdefault(name, []).append(
            _Fault(name, times, action, message, match, delay_ms=delay_ms)
        )
        _refresh_active()


def clear(name: Optional[str] = None) -> None:
    """Disarm one point (or, with no argument, everything)."""
    with _lock:
        if name is None:
            _faults.clear()
            _hit_counts.clear()
        else:
            _faults.pop(name, None)
            _hit_counts.pop(name, None)
        _refresh_active()


def hits(name: str) -> int:
    """How many times ``name`` actually fired (for test assertions)."""
    with _lock:
        return _hit_counts.get(name, 0)


class inject:
    """Context manager: arm on enter, disarm this arming on exit."""

    def __init__(self, name: str, **kwargs):
        self.name = name
        self.kwargs = kwargs

    def __enter__(self) -> "inject":
        arm(self.name, **self.kwargs)
        return self

    def __exit__(self, *exc) -> None:
        clear(self.name)


def fault_point(name: str, **ctx) -> None:
    """Injection point.  No-op while nothing is armed; when an armed fault
    matches, raises :class:`FaultInjected` (or hard-exits the process).

    ``ctx`` carries call-site context (executor_id, partition, path, …)
    for ``match`` predicates — predicates must accept ``**kwargs`` since
    each site passes different keys.
    """
    if not _active:
        return
    with _lock:
        for f in _faults.get(name, []):
            if f.remaining == 0:
                continue
            if f.match is not None:
                try:
                    if not f.match(**ctx):
                        continue
                except Exception:  # noqa: BLE001 - a bad predicate never fires
                    continue
            if f.remaining > 0:
                f.remaining -= 1
            f.hits += 1
            _hit_counts[name] = _hit_counts.get(name, 0) + 1
            _refresh_active()
            action, message, delay_ms = f.action, f.message, f.delay_ms
            break
        else:
            return
    if action == "exit":
        # hard crash (worker-kill simulation): no cleanup, no status reply
        os._exit(17)
    if action == "delay":
        # manufactured straggler: sleep instead of raising.  A site that
        # passes its cancel_event lets the sleep end early on abort (the
        # site's own cancellation check raises right after).
        cancel = ctx.get("cancel_event")
        delay_s = max(0, delay_ms) / 1000.0
        if cancel is not None and hasattr(cancel, "wait"):
            cancel.wait(delay_s)
        else:
            import time

            time.sleep(delay_s)
        return
    raise FaultInjected(
        message or f"fault injected at {name} ({ctx or 'no context'})"
    )


def _load_env(spec: str) -> None:
    """Parse ``BALLISTA_FAULTS``: comma-separated
    ``name[:times[:action[:key=value]]]``.  The optional ``key=value``
    gates the fault on an integer context field, e.g.
    ``executor.task_runner:-1:exit:attempt=0`` crashes the worker only on
    first attempts so retries can succeed."""
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        name = fields[0]
        try:
            times = int(fields[1]) if len(fields) > 1 else 1
        except ValueError:
            times = 1
        action = fields[2] if len(fields) > 2 else "raise"
        delay_ms = 0
        if action.startswith("delay"):
            # "delay=500" sleeps 500ms at the point (default 1000)
            _, _, ms = action.partition("=")
            try:
                delay_ms = int(ms) if ms else 1000
            except ValueError:
                delay_ms = 1000
            action = "delay"
        match = None
        if len(fields) > 3 and "=" in fields[3]:
            key, _, raw = fields[3].partition("=")

            def match(__key=key.strip(), __want=raw.strip(), **ctx):
                return str(ctx.get(__key)) == __want

        try:
            arm(name, times=times, action=action, match=match, delay_ms=delay_ms)
        except ValueError:
            arm(name, times=times, match=match)


_env_spec = os.environ.get("BALLISTA_FAULTS", "")
if _env_spec:
    _load_env(_env_spec)
