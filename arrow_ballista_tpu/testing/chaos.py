"""Scheduler crash/failover chaos harness (ISSUE 20).

Utilities shared by ``benchmarks/scheduler_chaos.py`` and the chaos-
marked tests: spawn a REAL scheduler process (``python -m
arrow_ballista_tpu.scheduler``), SIGKILL it mid-burst, restart it (or
fail over to a backup) and audit the outcome through the client RPCs,
the REST API and the on-disk event journal.

Everything here runs the scheduler as a *subprocess* — a SIGKILL must
take down an actual process with no chance to flush, or the crash
window being tested (queue admitted but graph unpersisted, intents in
memory, children orphaned) does not exist.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

import pyarrow as pa


def free_port() -> int:
    """An OS-assigned free TCP port (the usual bind-and-release race is
    acceptable for tests: the scheduler binds it back within ms)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def fingerprint(table: pa.Table) -> str:
    """Order-insensitive sha256 over the rows — result identity across
    legs/restarts without depending on partition interleave."""
    rows = sorted(zip(*[c.to_pylist() for c in table.columns]))
    h = hashlib.sha256()
    for row in rows:
        h.update(repr(row).encode())
    return h.hexdigest()


def read_journal(path: str, kind: Optional[str] = None) -> List[dict]:
    """Read a scheduler's event-journal directory offline (segment files
    oldest → active), tolerating torn tail lines — the journal outlives
    the process that wrote it, which is the whole point here."""
    from ..obs.events import ACTIVE_NAME, _SEGMENT_RE

    try:
        names = os.listdir(path)
    except OSError:
        return []
    seqs = sorted(
        int(_SEGMENT_RE.match(n).group(1))
        for n in names
        if _SEGMENT_RE.match(n)
    )
    files = [os.path.join(path, f"events-{s}.jsonl") for s in seqs]
    if ACTIVE_NAME in names:
        files.append(os.path.join(path, ACTIVE_NAME))
    out: List[dict] = []
    for fp in files:
        try:
            with open(fp, encoding="utf-8") as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except Exception:  # noqa: BLE001 - torn tail
                        continue
                    if isinstance(ev, dict) and (
                        kind is None or ev.get("kind") == kind
                    ):
                        out.append(ev)
        except OSError:
            continue
    return out


def kill_orphans(work_dir_root: str) -> int:
    """SIGKILL every executor child recorded in ``executor.pid`` files
    under an autoscaler work dir — test cleanup for fleets whose
    scheduler died and was never restarted.  Returns the kill count."""
    killed = 0
    try:
        entries = os.listdir(work_dir_root)
    except OSError:
        return 0
    for eid in entries:
        pid_path = os.path.join(work_dir_root, eid, "executor.pid")
        try:
            with open(pid_path, encoding="utf-8") as f:
                pid = int(f.read().split()[0])
        except (OSError, ValueError, IndexError):
            continue
        try:
            os.kill(pid, signal.SIGKILL)
            killed += 1
        except OSError:
            pass
        try:
            os.unlink(pid_path)
        except OSError:
            pass
    return killed


class SchedulerProc:
    """One scheduler subprocess.  ``kill()`` is SIGKILL — the process
    gets no chance to flush, drain or deregister, exactly like an OOM
    kill or node loss; ``stop()`` is the graceful SIGTERM path."""

    def __init__(
        self,
        port: int,
        rest_port: int = 0,
        args: Optional[List[str]] = None,
        env: Optional[Dict[str, str]] = None,
        log_path: str = "",
    ):
        self.port = port
        self.rest_port = rest_port
        cmd = [
            sys.executable, "-m", "arrow_ballista_tpu.scheduler",
            "--bind-host", "127.0.0.1",
            "--bind-port", str(port),
            "--rest-port", str(rest_port),
            *(args or []),
        ]
        full_env = {**os.environ, **(env or {})}
        # same PYTHONPATH pinning as LocalProcessProvider: the harness
        # may import the package via a sys.path edit
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        existing = full_env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            full_env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing if existing else "")
            )
        self.log_path = log_path
        sink = open(log_path, "ab") if log_path else subprocess.DEVNULL  # noqa: SIM115
        self.proc = subprocess.Popen(  # noqa: S603 - our own binary
            cmd,
            stdout=sink,
            stderr=subprocess.STDOUT if log_path else subprocess.DEVNULL,
            env=full_env,
        )
        if log_path:
            sink.close()

    @property
    def pid(self) -> int:
        return self.proc.pid

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        """Block until the scheduler answers a session-bootstrap
        ExecuteQuery (the cheapest end-to-end readiness probe: gRPC
        bound + state backend open + session manager serving)."""
        import grpc

        from ..proto import pb
        from ..proto.rpc import SchedulerGrpcStub, make_channel

        deadline = time.monotonic() + timeout_s
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"scheduler on port {self.port} exited rc="
                    f"{self.proc.returncode} before becoming ready"
                    + (f" (log: {self.log_path})" if self.log_path else "")
                )
            try:
                stub = SchedulerGrpcStub(make_channel("127.0.0.1", self.port))
                stub.ExecuteQuery(pb.ExecuteQueryParams(), timeout=5)
                return
            except grpc.RpcError as e:
                last = e
                time.sleep(0.2)
        raise RuntimeError(
            f"scheduler on port {self.port} not ready in {timeout_s:.0f}s: {last}"
        )

    def rest_get(self, route: str, timeout_s: float = 10.0) -> dict:
        import urllib.request

        url = f"http://127.0.0.1:{self.rest_port}{route}"
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:  # noqa: S310
            return json.loads(resp.read().decode())

    def wait_alive_executors(self, n: int, timeout_s: float = 90.0) -> None:
        """Poll ``/api/state`` until ``n`` executors report alive."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                state = self.rest_get("/api/state")
                alive = sum(1 for e in state["executors"] if e["alive"])
                if alive >= n:
                    return
            except Exception:  # noqa: BLE001 - scheduler may be mid-boot
                pass
            time.sleep(0.3)
        raise RuntimeError(
            f"scheduler on port {self.port}: {n} executor(s) never registered"
        )

    def kill(self) -> float:
        """SIGKILL; returns the kill timestamp (``time.time()``, the
        clock the event journal stamps — MTTR math subtracts it from
        journal event timestamps)."""
        t = time.time()
        try:
            self.proc.kill()
        except OSError:
            pass
        self.proc.wait(timeout=10)
        return t

    def stop(self, timeout_s: float = 15.0) -> None:
        if self.proc.poll() is not None:
            return
        try:
            self.proc.terminate()
        except OSError:
            return
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)
