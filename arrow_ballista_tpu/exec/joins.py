"""Hash join operator.

Counterpart of DataFusion's HashJoinExec as serialized by the reference
(``core/proto/ballista.proto:265-278``), with both partition modes:
``Partitioned`` (both sides hash-repartitioned on keys) and ``CollectLeft``
(build side broadcast — reference PartitionMode::COLLECT_LEFT).

The CPU implementation computes matching (left_index, right_index) pairs via
acero on index-augmented key tables, then gathers both sides; this keeps
exact control of output schema/order and maps 1:1 onto the TPU join kernel's
gather-based design.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..errors import NotImplementedYet
from .expressions import PhysicalExpr
from .operators import ExecutionPlan, Partitioning, TaskContext

PARTITIONED = "partitioned"
COLLECT_LEFT = "collect_left"

_ACERO_TYPE = {
    "inner": "inner",
    "left": "left outer",
    "right": "right outer",
    "full": "full outer",
    "semi": "left semi",
    "anti": "left anti",
}


class HashJoinExec(ExecutionPlan):
    def __init__(
        self,
        left: ExecutionPlan,
        right: ExecutionPlan,
        on: list[tuple[PhysicalExpr, PhysicalExpr]],
        join_type: str = "inner",
        partition_mode: str = PARTITIONED,
        filter: Optional[PhysicalExpr] = None,
    ):
        super().__init__()
        self.left = left
        self.right = right
        self.on = on
        self.join_type = join_type
        self.partition_mode = partition_mode
        self.filter = filter
        self._collect_left_cache: Optional[pa.Table] = None
        self._lock = threading.Lock()

    @property
    def schema(self) -> pa.Schema:
        if self.join_type in ("semi", "anti"):
            return self.left.schema
        lf = list(self.left.schema)
        rf = list(self.right.schema)
        if self.join_type in ("left", "full"):
            rf = [f.with_nullable(True) for f in rf]
        if self.join_type in ("right", "full"):
            lf = [f.with_nullable(True) for f in lf]
        return pa.schema(lf + rf)

    def output_partitioning(self) -> Partitioning:
        if self.partition_mode == COLLECT_LEFT:
            return self.right.output_partitioning()
        return self.left.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.left, self.right]

    def with_new_children(self, children):
        return HashJoinExec(
            children[0], children[1], self.on, self.join_type,
            self.partition_mode, self.filter,
        )

    def as_collect_left(
        self, left: Optional[ExecutionPlan] = None,
        right: Optional[ExecutionPlan] = None,
    ) -> "HashJoinExec":
        """This join rebuilt in COLLECT_LEFT (build-side broadcast) mode,
        optionally with replacement inputs — the AQE shuffle→broadcast
        conversion (scheduler/adaptive.py) swaps the probe-side shuffle
        read for the producer's inlined subtree.  Only valid for inner
        joins: broadcasting the build side against each probe partition
        would emit per-partition unmatched/duplicate rows for any other
        type (see the physical planner's mode selection)."""
        assert self.join_type == "inner", "COLLECT_LEFT requires an inner join"
        return HashJoinExec(
            left if left is not None else self.left,
            right if right is not None else self.right,
            self.on, self.join_type, COLLECT_LEFT, self.filter,
        )

    def __str__(self) -> str:
        on = ", ".join(f"{l}={r}" for l, r in self.on)
        return (
            f"HashJoinExec: type={self.join_type}, mode={self.partition_mode}, on=[{on}]"
        )

    # ------------------------------------------------------------ execution
    def _collect_side(
        self, side: ExecutionPlan, partition: Optional[int], ctx: TaskContext
    ) -> pa.Table:
        batches: list[pa.RecordBatch] = []
        if partition is None:
            for p in range(side.output_partitioning().n):
                batches.extend(side.execute(p, ctx))
        else:
            batches.extend(side.execute(partition, ctx))
        return pa.Table.from_batches(batches, schema=side.schema)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if self.partition_mode == COLLECT_LEFT:
            with self._lock:
                if self._collect_left_cache is None:
                    with self.metrics.timer("build_time_ns"):
                        self._collect_left_cache = self._collect_side(
                            self.left, None, ctx
                        )
            left_tbl = self._collect_left_cache
            right_tbl = self._collect_side(self.right, partition, ctx)
        else:
            with self.metrics.timer("build_time_ns"):
                left_tbl = self._collect_side(self.left, partition, ctx)
            right_tbl = self._collect_side(self.right, partition, ctx)

        with self.metrics.timer("join_time_ns"):
            out = self._join_tables(left_tbl, right_tbl)
        self.metrics.add("output_rows", out.num_rows)
        for b in out.to_batches(max_chunksize=ctx.batch_size):
            yield b

    def _key_table(
        self, tbl: pa.Table, exprs: list[PhysicalExpr], idx_name: str
    ) -> pa.Table:
        cols: dict[str, pa.ChunkedArray] = {}
        batches = tbl.to_batches() if tbl.num_rows else [
            pa.RecordBatch.from_arrays([pa.nulls(0, f.type) for f in tbl.schema], schema=tbl.schema)
        ]
        for i, e in enumerate(exprs):
            vals = [e.evaluate(b) for b in batches]
            cols[f"__k{i}"] = pa.chunked_array(
                [v.combine_chunks() if isinstance(v, pa.ChunkedArray) else v for v in vals]
            )
        cols[idx_name] = pa.chunked_array([pa.array(np.arange(tbl.num_rows, dtype=np.int64))])
        return pa.table(cols)

    def _join_tables(self, left: pa.Table, right: pa.Table) -> pa.Table:
        lkeys = self._key_table(left, [l for l, _ in self.on], "__li")
        rkeys = self._key_table(right, [r for _, r in self.on], "__ri")
        keys = [f"__k{i}" for i in range(len(self.on))]
        schema = self.schema

        jt = self.join_type
        if jt in ("semi", "anti") and self.filter is None:
            idx = lkeys.join(rkeys, keys=keys, join_type=_ACERO_TYPE[jt])
            li = idx.column("__li")
            out = left.take(li)
            return out.combine_chunks().cast(schema)

        if jt in ("semi", "anti") and self.filter is not None:
            pairs = lkeys.join(rkeys, keys=keys, join_type="inner")
            joined = _gather_pair(left, right, pairs, pa.schema(list(left.schema) + list(right.schema)))
            mask = self.filter.evaluate(_as_batch(joined))
            matched_li = pairs.column("__li").filter(mask)
            matched = np.unique(np.asarray(matched_li))
            if jt == "semi":
                take = matched
            else:
                all_idx = np.arange(left.num_rows, dtype=np.int64)
                take = np.setdiff1d(all_idx, matched, assume_unique=False)
            return left.take(pa.array(take)).combine_chunks().cast(schema)

        if jt in ("left", "right", "full") and self.filter is not None:
            # Residual filter on an outer join (e.g. TPC-H q13's ON-clause
            # `not like` predicate): the filter applies to *matched* pairs
            # only — rows of the preserved side whose every match fails the
            # filter still appear once, null-padded.  Reference semantics:
            # DataFusion JoinFilter on HashJoinExec (ballista.proto:265-278).
            pairs = lkeys.join(rkeys, keys=keys, join_type="inner")
            inner_schema = pa.schema(list(self.left.schema) + list(self.right.schema))
            joined = _gather_pair(left, right, pairs, inner_schema)
            mask = pc.fill_null(self.filter.evaluate(_as_batch(joined)), False)
            pairs = pairs.filter(mask)
            li = np.asarray(pairs.column("__li"), dtype=np.int64)
            ri = np.asarray(pairs.column("__ri"), dtype=np.int64)
            li_parts, ri_parts = [li], [ri]
            li_mask_parts = [np.zeros(len(li), dtype=bool)]
            ri_mask_parts = [np.zeros(len(ri), dtype=bool)]
            if jt in ("left", "full"):
                lonely = np.setdiff1d(np.arange(left.num_rows, dtype=np.int64), li)
                li_parts.append(lonely)
                ri_parts.append(np.zeros(len(lonely), dtype=np.int64))
                li_mask_parts.append(np.zeros(len(lonely), dtype=bool))
                ri_mask_parts.append(np.ones(len(lonely), dtype=bool))
            if jt in ("right", "full"):
                lonely = np.setdiff1d(np.arange(right.num_rows, dtype=np.int64), ri)
                li_parts.append(np.zeros(len(lonely), dtype=np.int64))
                ri_parts.append(lonely)
                li_mask_parts.append(np.ones(len(lonely), dtype=bool))
                ri_mask_parts.append(np.zeros(len(lonely), dtype=bool))
            padded = pa.table(
                {
                    "__li": pa.array(
                        np.concatenate(li_parts), mask=np.concatenate(li_mask_parts)
                    ),
                    "__ri": pa.array(
                        np.concatenate(ri_parts), mask=np.concatenate(ri_mask_parts)
                    ),
                }
            )
            return _gather_pair(left, right, padded, schema)

        pairs = lkeys.join(rkeys, keys=keys, join_type=_ACERO_TYPE[jt])
        out = _gather_pair(left, right, pairs, schema)
        if self.filter is not None:
            mask = self.filter.evaluate(_as_batch(out))
            out = out.filter(mask)
        return out

    # TPU note: the device-side join kernel replaces acero's hash table with
    # a sorted-merge over hashed keys (ops/kernels.py) — same (li, ri) pair
    # contract, so this operator is the single source of join semantics.


def _gather_pair(
    left: pa.Table, right: pa.Table, pairs: pa.Table, schema: pa.Schema
) -> pa.Table:
    li = pairs.column("__li")
    ri = pairs.column("__ri")
    lcols = [left.column(i).take(li) for i in range(left.num_columns)]
    rcols = [right.column(i).take(ri) for i in range(right.num_columns)]
    cols = lcols + rcols
    cols = [
        c if c.type.equals(f.type) else pc.cast(c, f.type, safe=False)
        for c, f in zip(cols, schema)
    ]
    return pa.Table.from_arrays(cols, schema=schema)


def _as_batch(tbl: pa.Table) -> pa.RecordBatch:
    tbl = tbl.combine_chunks()
    if tbl.num_rows == 0:
        return pa.RecordBatch.from_arrays(
            [pa.nulls(0, f.type) for f in tbl.schema], schema=tbl.schema
        )
    return tbl.to_batches()[0]


class CrossJoinExec(ExecutionPlan):
    """Cartesian product; left side collected, right side streamed."""

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan):
        super().__init__()
        self.left = left
        self.right = right
        self._left_cache: Optional[pa.Table] = None
        self._lock = threading.Lock()

    @property
    def schema(self) -> pa.Schema:
        return pa.schema(list(self.left.schema) + list(self.right.schema))

    def output_partitioning(self) -> Partitioning:
        return self.right.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.left, self.right]

    def with_new_children(self, children):
        return CrossJoinExec(children[0], children[1])

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        with self._lock:
            if self._left_cache is None:
                batches: list[pa.RecordBatch] = []
                for p in range(self.left.output_partitioning().n):
                    batches.extend(self.left.execute(p, ctx))
                self._left_cache = pa.Table.from_batches(
                    batches, schema=self.left.schema
                )
        left = self._left_cache
        nl = left.num_rows
        schema = self.schema
        for rb in self.right.execute(partition, ctx):
            nr = rb.num_rows
            if nr == 0 or nl == 0:
                continue
            li = pa.array(np.repeat(np.arange(nl, dtype=np.int64), nr))
            ri = pa.array(np.tile(np.arange(nr, dtype=np.int64), nl))
            lcols = [left.column(i).take(li) for i in range(left.num_columns)]
            rcols = [rb.column(i).take(ri) for i in range(rb.num_columns)]
            out = pa.Table.from_arrays(lcols + rcols, schema=schema)
            self.metrics.add("output_rows", out.num_rows)
            for b in out.to_batches(max_chunksize=ctx.batch_size):
                yield b
