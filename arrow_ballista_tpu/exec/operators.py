"""Physical operators (CPU path).

Counterpart of DataFusion's ``ExecutionPlan`` operators as used by the
reference.  Operators are pull-based: ``execute(partition, ctx)`` yields
Arrow RecordBatches.  Per-operator metrics mirror the reference's
``MetricsSet`` (e.g. ``shuffle_writer.rs:89-106`` timers/counters).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..config import BallistaConfig
from ..errors import ExecutionError
from .expressions import PhysicalExpr


# ------------------------------------------------------------------- metrics
class Metrics:
    """Per-operator metric set (counters in ns / rows / bytes)."""

    def __init__(self) -> None:
        self.values: dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, name: str, v: int) -> None:
        with self._lock:
            self.values[name] = self.values.get(name, 0) + int(v)

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def to_dict(self) -> dict[str, int]:
        return dict(self.values)


class _Timer:
    def __init__(self, m: Metrics, name: str) -> None:
        self.m, self.name = m, name

    def __enter__(self) -> "_Timer":
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        self.m.add(self.name, time.perf_counter_ns() - self.t0)


# -------------------------------------------------------------- partitioning
@dataclass(frozen=True)
class Partitioning:
    kind: str  # "unknown" | "hash" | "round_robin"
    n: int
    exprs: tuple[PhysicalExpr, ...] = ()

    @staticmethod
    def unknown(n: int) -> "Partitioning":
        return Partitioning("unknown", n)

    @staticmethod
    def hash(exprs: tuple[PhysicalExpr, ...], n: int) -> "Partitioning":
        return Partitioning("hash", n, exprs)


@dataclass
class TaskContext:
    """Session/runtime info handed to every operator execution.

    Reference: DataFusion TaskContext built in
    ``executor/src/executor_server.rs:321-328``.
    """

    session_id: str = "default"
    config: BallistaConfig = field(default_factory=BallistaConfig)
    work_dir: str = "/tmp/ballista-tpu"
    job_id: str = ""
    stage_id: int = 0
    # Cooperative cancellation: set by Executor.cancel_task, checked at batch
    # granularity by the stage driver (the Python analogue of the reference's
    # ``futures::abortable`` wrapper, executor/src/executor.rs:97-134).
    cancel_event: Optional[threading.Event] = None

    @property
    def batch_size(self) -> int:
        return self.config.batch_size

    def check_cancelled(self) -> None:
        if self.cancel_event is not None and self.cancel_event.is_set():
            from ..errors import Cancelled

            raise Cancelled("task cancelled")


class ExecutionPlan:
    """Base physical operator."""

    def __init__(self) -> None:
        self.metrics = Metrics()

    @property
    def schema(self) -> pa.Schema:
        raise NotImplementedError

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> list["ExecutionPlan"]:
        return []

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        raise NotImplementedError

    def with_new_children(self, children: list["ExecutionPlan"]) -> "ExecutionPlan":
        raise NotImplementedError

    def display(self, indent: int = 0, with_metrics: bool = False) -> str:
        line = "  " * indent + str(self)
        if with_metrics and self.metrics.values:
            line += f"  metrics={self.metrics.to_dict()}"
        for c in self.children():
            line += "\n" + c.display(indent + 1, with_metrics)
        return line

    def __str__(self) -> str:
        return type(self).__name__


def collect(plan: ExecutionPlan, ctx: Optional[TaskContext] = None) -> pa.Table:
    """Execute every partition and concatenate (reference: utils.rs:99-107)."""
    ctx = ctx or TaskContext()
    batches: list[pa.RecordBatch] = []
    for p in range(plan.output_partitioning().n):
        batches.extend(plan.execute(p, ctx))
    return pa.Table.from_batches(batches, schema=plan.schema)


# ------------------------------------------------------------------- scan
class ScanExec(ExecutionPlan):
    """Leaf scan over a TableProvider partition (csv/parquet/memory)."""

    def __init__(self, table_name: str, provider, projection: Optional[list[str]] = None):
        super().__init__()
        self.table_name = table_name
        self.provider = provider
        self.projection = projection

    @property
    def schema(self) -> pa.Schema:
        base = self.provider.schema
        if self.projection is not None:
            base = pa.schema([base.field(n) for n in self.projection])
        return pa.schema(
            [pa.field(f"{self.table_name}.{f.name}", f.type, f.nullable) for f in base]
        )

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(self.provider.num_partitions())

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        schema = self.schema
        with self.metrics.timer("scan_time_ns"):
            for b in self.provider.scan_partition(
                partition, self.projection, ctx.batch_size
            ):
                self.metrics.add("output_rows", b.num_rows)
                yield pa.RecordBatch.from_arrays(b.columns, schema=schema)

    def with_new_children(self, children):
        assert not children
        return self

    def __str__(self) -> str:
        proj = f" projection={self.projection}" if self.projection is not None else ""
        return f"ScanExec: {self.table_name}{proj}"


class EmptyExec(ExecutionPlan):
    def __init__(self, produce_one_row: bool, schema: pa.Schema):
        super().__init__()
        self._schema = schema
        self.produce_one_row = produce_one_row

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if self.produce_one_row:
            if len(self._schema) == 0:
                # zero columns can't carry num_rows=1 in Arrow; emit a
                # placeholder column so `SELECT <literals>` (no FROM)
                # projects exactly one row
                yield pa.RecordBatch.from_arrays(
                    [pa.nulls(1, pa.null())],
                    schema=pa.schema([pa.field("__row", pa.null())]),
                )
                return
            arrays = [pa.nulls(1, f.type) for f in self._schema]
            yield pa.RecordBatch.from_arrays(arrays, schema=self._schema)

    def with_new_children(self, children):
        return self


# ------------------------------------------------------------------ filter
class FilterExec(ExecutionPlan):
    def __init__(self, predicate: PhysicalExpr, input: ExecutionPlan):
        super().__init__()
        self.predicate = predicate
        self.input = input

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        for batch in self.input.execute(partition, ctx):
            with self.metrics.timer("filter_time_ns"):
                mask = self.predicate.evaluate(batch)
                out = batch.filter(mask)
            self.metrics.add("output_rows", out.num_rows)
            if out.num_rows:
                yield out

    def with_new_children(self, children):
        return FilterExec(self.predicate, children[0])

    def __str__(self) -> str:
        return f"FilterExec: {self.predicate}"


class ProjectionExec(ExecutionPlan):
    def __init__(self, exprs: list[tuple[PhysicalExpr, str]], input: ExecutionPlan):
        super().__init__()
        self.exprs = exprs
        self.input = input
        in_schema = input.schema
        self._schema = pa.schema(
            [pa.field(name, _infer_type(e, in_schema), True) for e, name in exprs]
        )

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        for batch in self.input.execute(partition, ctx):
            with self.metrics.timer("proj_time_ns"):
                cols = []
                for (e, name), f in zip(self.exprs, self._schema):
                    v = e.evaluate(batch)
                    if isinstance(v, pa.Scalar):
                        v = pa.nulls(batch.num_rows, f.type) if v.as_py() is None else pa.array([v.as_py()] * batch.num_rows, f.type)
                    if isinstance(v, pa.ChunkedArray):
                        v = v.combine_chunks()
                    if not v.type.equals(f.type):
                        v = pc.cast(v, f.type, safe=False)
                    cols.append(v)
            out = pa.RecordBatch.from_arrays(cols, schema=self._schema)
            self.metrics.add("output_rows", out.num_rows)
            yield out

    def with_new_children(self, children):
        return ProjectionExec(self.exprs, children[0])

    def __str__(self) -> str:
        return f"ProjectionExec: {[n for _, n in self.exprs]}"


def _infer_type(e: PhysicalExpr, schema: pa.Schema) -> pa.DataType:
    """Infer an expr's output type by evaluating it on an empty batch."""
    empty = pa.RecordBatch.from_arrays(
        [pa.nulls(0, f.type) for f in schema], schema=schema
    )
    v = e.evaluate(empty)
    if isinstance(v, pa.Scalar):
        return v.type
    return v.type


# Transient column name a device stage appends to its output batches when
# a downstream ShuffleWriterExec installed a shuffle hint: int32 partition
# ids computed by the jitted device hash (ops/kernels.py
# device_partition_ids).  The writer pops it before anything is persisted;
# it never appears in a written partition or a reader schema.
SHUFFLE_PID_COLUMN = "__shuffle_pid__"


# ----------------------------------------------------------- partition moves
class CoalescePartitionsExec(ExecutionPlan):
    """Merge all input partitions into one (reference: DataFusion's
    CoalescePartitionsExec — the stage-split trigger in planner.rs:97-125)."""

    def __init__(self, input: ExecutionPlan):
        super().__init__()
        self.input = input

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        for p in range(self.input.output_partitioning().n):
            yield from self.input.execute(p, ctx)

    def with_new_children(self, children):
        return CoalescePartitionsExec(children[0])


def hash_partition_indices(
    batch: pa.RecordBatch, exprs: list[PhysicalExpr], n: int
) -> np.ndarray:
    """Deterministic hash of key columns → partition id per row.

    This is the Python counterpart of the native partitioner
    (native/partitioner.cc); both must produce identical assignments since
    map and reduce sides may run on different executors.
    """
    _NULL_HASH = np.uint64(0xA5A5A5A5DEADBEEF)
    h = np.zeros(batch.num_rows, dtype=np.uint64)
    for e in exprs:
        v = e.evaluate(batch)
        if isinstance(v, pa.ChunkedArray):
            v = v.combine_chunks()
        null_mask = np.asarray(pc.is_null(v)) if v.null_count else None
        if pa.types.is_string(v.type) or pa.types.is_large_string(v.type):
            enc = v.dictionary_encode()
            # hash dictionary values once, map through indices; value hashes
            # are content-based so identical keys in different batches (with
            # different dictionaries) still agree
            dvals = np.asarray(
                [hash_bytes(s.as_py().encode()) if s.is_valid else 0 for s in enc.dictionary],
                dtype=np.uint64,
            )
            codes = np.asarray(enc.indices.fill_null(0))
            hv = dvals[codes] if len(dvals) else np.zeros(batch.num_rows, np.uint64)
        else:
            if pa.types.is_date32(v.type):
                v = v.cast(pa.int32())
            elif pa.types.is_date64(v.type) or pa.types.is_timestamp(v.type):
                v = v.cast(pa.int64())
            elif pa.types.is_boolean(v.type):
                v = v.cast(pa.int8())
            if v.null_count:
                v = v.fill_null(0)
            x = np.asarray(v)
            if x.dtype.kind == "f":
                x = x.view(np.uint64) if x.dtype == np.float64 else x.astype(np.float64).view(np.uint64)
            else:
                x = x.astype(np.int64).view(np.uint64)
            hv = x * np.uint64(0x9E3779B97F4A7C15)
            hv ^= hv >> np.uint64(32)
        if null_mask is not None:
            # nulls form one group: constant hash regardless of batch/dict
            hv = np.where(null_mask, _NULL_HASH, hv)
        h = h * np.uint64(31) + hv
    return (h % np.uint64(n)).astype(np.int64)


def hash_bytes(b: bytes) -> int:
    h = 1469598103934665603  # FNV-1a 64
    for c in b:
        h = ((h ^ c) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


def partition_permutation(
    idx: np.ndarray, n: int
) -> tuple[np.ndarray, np.ndarray]:
    """Stable partition permutation of ``idx`` (row -> partition id in
    [0, n)) as ``(order, bounds)``: ``idx[order]`` is sorted and rows
    ``order[bounds[p]:bounds[p+1]]`` belong to partition ``p``, in their
    original relative order.

    Counting-sort shape: ``bincount`` + ``cumsum`` produce the partition
    bounds in one O(n) pass (no searchsorted), and the permutation runs
    through numpy's radix path by narrowing the key to the smallest
    unsigned dtype that holds ``n`` — one or two counting passes over
    byte keys instead of the O(n log n) comparison argsort on int64
    (measured 4-7x faster at 1M rows).  Shared by every hash-split site
    (shuffle write, in-process repartition) so the map side has exactly
    one permutation code path.
    """
    counts = np.bincount(idx, minlength=n)
    bounds = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=bounds[1:])
    if n <= 1 << 8:
        key = idx.astype(np.uint8)
    elif n <= 1 << 16:
        key = idx.astype(np.uint16)
    else:  # pragma: no cover - >65536 output partitions
        key = idx
    return np.argsort(key, kind="stable"), bounds


class RepartitionExec(ExecutionPlan):
    """In-process hash repartition (single-process mode only; distributed
    repartition happens at shuffle boundaries via ShuffleWriter/Reader)."""

    def __init__(self, input: ExecutionPlan, partitioning: Partitioning):
        super().__init__()
        self.input = input
        self.partitioning = partitioning
        self._cache: Optional[list[list[pa.RecordBatch]]] = None
        self._lock = threading.Lock()

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return self.partitioning

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def _materialize(self, ctx: TaskContext) -> list[list[pa.RecordBatch]]:
        with self._lock:
            if self._cache is not None:
                return self._cache
            n = self.partitioning.n
            buckets: list[list[pa.RecordBatch]] = [[] for _ in range(n)]
            for p in range(self.input.output_partitioning().n):
                for batch in self.input.execute(p, ctx):
                    if self.partitioning.kind == "hash":
                        idx = hash_partition_indices(
                            batch, list(self.partitioning.exprs), n
                        )
                        order, bounds = partition_permutation(idx, n)
                        tbl = batch.take(pa.array(order))
                        for b in range(n):
                            lo, hi = bounds[b], bounds[b + 1]
                            if hi > lo:
                                buckets[b].append(tbl.slice(lo, hi - lo))
                    else:  # round robin by batch
                        buckets[hash(batch.num_rows) % n].append(batch)
            self._cache = buckets
            return buckets

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        with self.metrics.timer("repart_time_ns"):
            buckets = self._materialize(ctx)
        for b in buckets[partition]:
            yield b

    def with_new_children(self, children):
        return RepartitionExec(children[0], self.partitioning)

    def __str__(self) -> str:
        return f"RepartitionExec: {self.partitioning.kind}({self.partitioning.n})"


# -------------------------------------------------------------- sort / limit
class SortExec(ExecutionPlan):
    def __init__(
        self,
        sort_keys: list[tuple[PhysicalExpr, bool, Optional[bool]]],  # expr, asc, nulls_first
        input: ExecutionPlan,
        fetch: Optional[int] = None,
    ):
        super().__init__()
        self.sort_keys = sort_keys
        self.input = input
        self.fetch = fetch

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        # single-partition input is the planner's contract (a
        # CoalescePartitionsExec is inserted upstream when needed) so the
        # distributed planner can split the plan at that boundary
        batches = list(self.input.execute(0, ctx))
        if not batches:
            return
        with self.metrics.timer("sort_time_ns"):
            table = pa.Table.from_batches(batches, schema=self.schema)
            key_arrays = []
            names = []
            for i, (e, asc, nf) in enumerate(self.sort_keys):
                v = pa.chunked_array([e.evaluate(b) for b in batches]) if len(batches) > 1 else e.evaluate(batches[0])
                if isinstance(v, pa.Scalar):
                    v = pa.array([v.as_py()] * table.num_rows)
                names.append(f"__sort_{i}")
                key_arrays.append(v)
            sort_tbl = pa.table(dict(zip(names, key_arrays)))
            keys = []
            for n, (_, asc, nf) in zip(names, self.sort_keys):
                if nf is None:
                    nf = not asc  # SQL default: NULLS LAST for ASC, FIRST for DESC
                keys.append(
                    (n, "ascending" if asc else "descending",
                     "at_start" if nf else "at_end")
                )
            indices = pc.sort_indices(sort_tbl, sort_keys=keys)
            if self.fetch is not None:
                indices = indices.slice(0, self.fetch)
            out = table.take(indices).combine_chunks()
        self.metrics.add("output_rows", out.num_rows)
        for b in out.to_batches(max_chunksize=ctx.batch_size):
            yield b

    def with_new_children(self, children):
        return SortExec(self.sort_keys, children[0], self.fetch)

    def __str__(self) -> str:
        return f"SortExec: fetch={self.fetch}"


class LimitExec(ExecutionPlan):
    """Global limit; requires single input partition."""

    def __init__(self, input: ExecutionPlan, skip: int = 0, fetch: Optional[int] = None):
        super().__init__()
        self.input = input
        self.skip = skip
        self.fetch = fetch

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        remaining_skip = self.skip
        remaining = self.fetch if self.fetch is not None else None
        for batch in self.input.execute(0, ctx):
            if remaining_skip:
                if batch.num_rows <= remaining_skip:
                    remaining_skip -= batch.num_rows
                    continue
                batch = batch.slice(remaining_skip)
                remaining_skip = 0
            if remaining is not None:
                if remaining <= 0:
                    return
                if batch.num_rows > remaining:
                    batch = batch.slice(0, remaining)
                remaining -= batch.num_rows
            self.metrics.add("output_rows", batch.num_rows)
            yield batch

    def with_new_children(self, children):
        return LimitExec(children[0], self.skip, self.fetch)

    def __str__(self) -> str:
        return f"LimitExec: skip={self.skip} fetch={self.fetch}"


class UnionExec(ExecutionPlan):
    def __init__(self, inputs: list[ExecutionPlan]):
        super().__init__()
        self.inputs = inputs

    @property
    def schema(self) -> pa.Schema:
        return self.inputs[0].schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(
            sum(i.output_partitioning().n for i in self.inputs)
        )

    def children(self) -> list[ExecutionPlan]:
        return list(self.inputs)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        off = 0
        schema = self.schema
        for inp in self.inputs:
            n = inp.output_partitioning().n
            if partition < off + n:
                for b in inp.execute(partition - off, ctx):
                    # align column names positionally
                    yield pa.RecordBatch.from_arrays(b.columns, schema=schema)
                return
            off += n
        raise ExecutionError(f"union partition {partition} out of range")

    def with_new_children(self, children):
        return UnionExec(children)
