"""Physical expressions — columnar evaluation over Arrow batches.

Counterpart of the reference's physical expr tree
(``core/proto/ballista.proto:91-124`` PhysicalExprNode and DataFusion's
``PhysicalExpr``).  Columns are resolved to indices at planning time; eval is
vectorized via ``pyarrow.compute``.  The TPU stage compiler
(:mod:`arrow_ballista_tpu.ops.stage_compiler`) lowers this same tree to jax.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Any, Optional

import pyarrow as pa
import pyarrow.compute as pc

from ..errors import ExecutionError, NotImplementedYet, PlanError
from ..plan import expressions as lex


class PhysicalExpr:
    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        raise NotImplementedError

    def children(self) -> list["PhysicalExpr"]:
        return []

    @property
    def name(self) -> str:
        return str(self)


@dataclass(frozen=True)
class Col(PhysicalExpr):
    index: int
    colname: str

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return batch.column(self.index)

    @property
    def name(self) -> str:
        return self.colname

    def __str__(self) -> str:
        return f"{self.colname}@{self.index}"


@dataclass(frozen=True)
class Lit(PhysicalExpr):
    value: Any
    dtype: pa.DataType = field(default_factory=pa.null)

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pa.scalar(self.value, self.dtype if not pa.types.is_null(self.dtype) else None)

    def __str__(self) -> str:
        return repr(self.value)


@dataclass(frozen=True)
class IntervalLit(PhysicalExpr):
    months: int
    days: int

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pa.scalar((self.months, self.days, 0), pa.month_day_nano_interval())

    def __str__(self) -> str:
        return f"interval({self.months}mo,{self.days}d)"


_CMP = {
    "=": pc.equal,
    "<>": pc.not_equal,
    "<": pc.less,
    "<=": pc.less_equal,
    ">": pc.greater,
    ">=": pc.greater_equal,
}
_ARITH = {
    "+": pc.add_checked,
    "-": pc.subtract_checked,
    "*": pc.multiply_checked,
    "/": pc.divide,
}


def _as_compute_val(v):
    return v


@dataclass(frozen=True)
class Binary(PhysicalExpr):
    left: PhysicalExpr
    op: str
    right: PhysicalExpr

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        op = self.op
        if op == "AND":
            return pc.and_kleene(self.left.evaluate(batch), self.right.evaluate(batch))
        if op == "OR":
            return pc.or_kleene(self.left.evaluate(batch), self.right.evaluate(batch))
        l = self.left.evaluate(batch)
        r = self.right.evaluate(batch)
        if op in _CMP:
            return _CMP[op](l, r)
        if op == "%":
            return pc.subtract(l, pc.multiply(pc.floor(pc.divide(l, r)), r))
        if op == "||":
            return pc.binary_join_element_wise(
                pc.cast(l, pa.string()), pc.cast(r, pa.string()), ""
            )
        if op in _ARITH:
            try:
                return _ARITH[op](l, r)
            except pa.ArrowNotImplementedError:
                # e.g. date32 ± month_day_nano_interval needs timestamp hop
                if pa.types.is_date(_type_of(l)):
                    ts = pc.cast(l, pa.timestamp("s"))
                    out = _ARITH[op](ts, r)
                    return pc.cast(out, pa.date32())
                raise
        raise ExecutionError(f"unsupported binary op {op}")

    def children(self) -> list[PhysicalExpr]:
        return [self.left, self.right]

    def __str__(self) -> str:
        return f"({self.left} {self.op} {self.right})"


def _type_of(v) -> pa.DataType:
    return v.type


@dataclass(frozen=True)
class Not(PhysicalExpr):
    expr: PhysicalExpr

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pc.invert(self.expr.evaluate(batch))

    def children(self) -> list[PhysicalExpr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"NOT {self.expr}"


@dataclass(frozen=True)
class Negative(PhysicalExpr):
    expr: PhysicalExpr

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pc.negate(self.expr.evaluate(batch))

    def children(self) -> list[PhysicalExpr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"(- {self.expr})"


@dataclass(frozen=True)
class IsNull(PhysicalExpr):
    expr: PhysicalExpr
    negated: bool = False

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        v = self.expr.evaluate(batch)
        return pc.is_valid(v) if self.negated else pc.is_null(v)

    def children(self) -> list[PhysicalExpr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"{self.expr} IS {'NOT ' if self.negated else ''}NULL"


@dataclass(frozen=True)
class InList(PhysicalExpr):
    expr: PhysicalExpr
    items: tuple[Any, ...]
    negated: bool = False

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        v = self.expr.evaluate(batch)
        mask = pc.is_in(v, value_set=pa.array(list(self.items)))
        return pc.invert(mask) if self.negated else mask

    def __str__(self) -> str:
        return f"{self.expr} IN {self.items}"


@dataclass(frozen=True)
class Like(PhysicalExpr):
    expr: PhysicalExpr
    pattern: str
    negated: bool = False

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        v = self.expr.evaluate(batch)
        m = pc.match_like(v, self.pattern)
        return pc.invert(m) if self.negated else m

    def __str__(self) -> str:
        return f"{self.expr} {'NOT ' if self.negated else ''}LIKE '{self.pattern}'"


@dataclass(frozen=True)
class Case(PhysicalExpr):
    whens: tuple[tuple[PhysicalExpr, PhysicalExpr], ...]
    else_expr: Optional[PhysicalExpr]
    out_type: pa.DataType = field(default_factory=pa.float64)

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        n = batch.num_rows
        if self.else_expr is not None:
            result = _broadcast(self.else_expr.evaluate(batch), n, self.out_type)
        else:
            result = pa.nulls(n, self.out_type)
        for cond_e, then_e in reversed(self.whens):
            cond = _broadcast(cond_e.evaluate(batch), n, pa.bool_())
            then = _broadcast(then_e.evaluate(batch), n, self.out_type)
            result = pc.if_else(cond, then, result)
        return result

    def children(self) -> list[PhysicalExpr]:
        out = []
        for w, t in self.whens:
            out += [w, t]
        if self.else_expr:
            out.append(self.else_expr)
        return out

    def __str__(self) -> str:
        return "CASE " + " ".join(f"WHEN {w} THEN {t}" for w, t in self.whens) + (
            f" ELSE {self.else_expr} END" if self.else_expr else " END"
        )


def _broadcast(v, n: int, dtype: pa.DataType):
    if isinstance(v, pa.Scalar):
        return pc.cast(v, dtype) if not v.type.equals(dtype) else v
    if isinstance(v, (pa.Array, pa.ChunkedArray)):
        return pc.cast(v, dtype) if not v.type.equals(dtype) else v
    return pa.scalar(v, dtype)


@dataclass(frozen=True)
class Cast(PhysicalExpr):
    expr: PhysicalExpr
    to_type: pa.DataType

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pc.cast(self.expr.evaluate(batch), self.to_type, safe=False)

    def children(self) -> list[PhysicalExpr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"CAST({self.expr} AS {self.to_type})"


@dataclass(frozen=True)
class ScalarUdf(PhysicalExpr):
    """User scalar function resolved BY NAME from the process-global UDF
    registry at evaluation time — executors never receive code, only the
    name (reference: plugin-loaded ScalarUDF referenced from TaskContext).
    """

    fname: str
    args: tuple[PhysicalExpr, ...]
    out_type: pa.DataType = field(default_factory=pa.float64)

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        from ..udf import global_registry

        u = global_registry().scalar(self.fname)
        if u is None:
            raise ExecutionError(
                f"scalar UDF {self.fname!r} is not registered on this "
                f"executor; load it via ballista.plugin_dir"
            )
        args = [_as_array_len(x.evaluate(batch), batch.num_rows) for x in self.args]
        out = u.fn(*args)
        if not isinstance(out, (pa.Array, pa.ChunkedArray)):
            out = pa.array(out, type=self.out_type)
        if isinstance(out, pa.ChunkedArray):
            out = out.combine_chunks()
        if len(out) != batch.num_rows:
            # a UDF that mis-sizes its output would silently corrupt row
            # alignment downstream (round-1 advisor finding)
            raise ExecutionError(
                f"scalar UDF {self.fname!r} returned {len(out)} rows for a "
                f"{batch.num_rows}-row batch"
            )
        if not out.type.equals(self.out_type):
            out = pc.cast(out, self.out_type, safe=False)
        return out

    def __str__(self) -> str:
        return f"{self.fname}({', '.join(str(a) for a in self.args)})"


def _as_array_len(v, n: int) -> pa.Array:
    if isinstance(v, pa.ChunkedArray):
        return v.combine_chunks()
    if isinstance(v, pa.Array):
        return v
    if isinstance(v, pa.Scalar):
        return pa.array([v.as_py()] * n, type=v.type)
    return pa.array([v] * n)


@dataclass(frozen=True)
class ScalarFn(PhysicalExpr):
    fname: str
    args: tuple[PhysicalExpr, ...]
    out_type: pa.DataType = field(default_factory=pa.float64)

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        f = self.fname
        a = [x.evaluate(batch) for x in self.args]
        if f == "abs":
            return pc.abs(a[0])
        if f == "ceil":
            return pc.ceil(a[0])
        if f == "floor":
            return pc.floor(a[0])
        if f == "round":
            ndigits = a[1].as_py() if len(a) > 1 else 0
            return pc.round(a[0], ndigits=ndigits)
        if f == "sqrt":
            return pc.sqrt(a[0])
        if f == "exp":
            return pc.exp(a[0])
        if f == "ln":
            return pc.ln(a[0])
        if f == "log10":
            return pc.log10(a[0])
        if f == "log2":
            return pc.log2(a[0])
        if f == "power":
            return pc.power(a[0], a[1])
        if f in ("sin", "cos", "tan"):
            return getattr(pc, f)(a[0])
        if f == "signum":
            return pc.sign(a[0])
        if f == "lower":
            return pc.utf8_lower(a[0])
        if f == "upper":
            return pc.utf8_upper(a[0])
        if f == "trim" or f == "btrim":
            return pc.utf8_trim_whitespace(a[0])
        if f == "ltrim":
            return pc.utf8_ltrim_whitespace(a[0])
        if f == "rtrim":
            return pc.utf8_rtrim_whitespace(a[0])
        if f in ("length", "char_length"):
            return pc.utf8_length(a[0])
        if f in ("substr", "substring"):
            start = a[1].as_py() - 1  # SQL is 1-based
            if len(a) > 2:
                return pc.utf8_slice_codeunits(a[0], start, start + a[2].as_py())
            return pc.utf8_slice_codeunits(a[0], start)
        if f == "concat":
            return pc.binary_join_element_wise(
                *[pc.cast(x, pa.string()) for x in a], ""
            )
        if f == "replace":
            return pc.replace_substring(a[0], pattern=a[1].as_py(), replacement=a[2].as_py())
        if f == "starts_with":
            return pc.starts_with(a[0], pattern=a[1].as_py())
        if f == "strpos":
            return pc.add(pc.find_substring(a[0], pattern=a[1].as_py()), 1)
        if f == "left":
            return pc.utf8_slice_codeunits(a[0], 0, a[1].as_py())
        if f == "right":
            n = a[1].as_py()
            return pc.utf8_slice_codeunits(a[0], -n)
        if f == "repeat":
            return pc.binary_repeat(a[0], a[1].as_py())
        if f == "reverse":
            return pc.utf8_reverse(a[0])
        if f == "ascii":
            raise NotImplementedYet("ascii()")
        if f in ("lpad", "rpad"):
            pad = a[2].as_py() if len(a) > 2 else " "
            fn = pc.utf8_lpad if f == "lpad" else pc.utf8_rpad
            return fn(a[0], width=a[1].as_py(), padding=pad)
        if f == "initcap":
            return pc.utf8_capitalize(a[0])
        if f == "split_part":
            parts = pc.split_pattern(a[0], pattern=a[1].as_py())
            return pc.list_element(parts, a[2].as_py() - 1)
        if f == "date_part" or f == "extract":
            part = a[0].as_py()
            v = a[1]
            if pa.types.is_date(v.type) or pa.types.is_timestamp(v.type):
                fn = {"year": pc.year, "month": pc.month, "day": pc.day,
                      "hour": pc.hour, "minute": pc.minute, "second": pc.second,
                      "quarter": pc.quarter, "week": pc.iso_week,
                      "dow": pc.day_of_week, "doy": pc.day_of_year}.get(part)
                if fn is None:
                    raise NotImplementedYet(f"date_part({part!r})")
                return pc.cast(fn(v), pa.int64())
            raise ExecutionError(f"date_part on non-temporal {v.type}")
        if f == "date_trunc":
            unit = a[0].as_py()
            ts = pc.floor_temporal(pc.cast(a[1], pa.timestamp("us")), unit=unit)
            if unit in ("day", "week", "month", "quarter", "year"):
                return pc.cast(ts, pa.date32())
            return ts  # sub-day truncation keeps the time component
        if f == "to_timestamp":
            return pc.cast(a[0], pa.timestamp("us"))
        if f == "now":
            return pa.scalar(_dt.datetime.utcnow(), pa.timestamp("us"))
        if f == "coalesce":
            return pc.coalesce(*a)
        if f == "nullif":
            eq = pc.equal(a[0], a[1])
            return pc.if_else(eq, pa.nulls(len(a[0]) if hasattr(a[0], "__len__") else 1, a[0].type), a[0])
        raise NotImplementedYet(f"scalar function {f!r}")

    def children(self) -> list[PhysicalExpr]:
        return list(self.args)

    def __str__(self) -> str:
        return f"{self.fname}({', '.join(map(str, self.args))})"


# --------------------------------------------------------------- lowering
def create_physical_expr(e: lex.Expr, schema: pa.Schema) -> PhysicalExpr:
    """Lower a logical expression to a physical one against ``schema``."""
    if isinstance(e, lex.Alias):
        return create_physical_expr(e.expr, schema)
    if isinstance(e, lex.Column):
        idx = e.resolve_index(schema)
        return Col(idx, schema.field(idx).name)
    if isinstance(e, lex.Literal):
        return Lit(e.value, e.dtype)
    if isinstance(e, lex.IntervalLiteral):
        return IntervalLit(e.months, e.days)
    if isinstance(e, lex.BinaryExpr):
        return Binary(
            create_physical_expr(e.left, schema), e.op, create_physical_expr(e.right, schema)
        )
    if isinstance(e, lex.NotExpr):
        return Not(create_physical_expr(e.expr, schema))
    if isinstance(e, lex.NegativeExpr):
        return Negative(create_physical_expr(e.expr, schema))
    if isinstance(e, lex.IsNullExpr):
        return IsNull(create_physical_expr(e.expr, schema), e.negated)
    if isinstance(e, lex.BetweenExpr):
        operand = create_physical_expr(e.expr, schema)
        low = create_physical_expr(e.low, schema)
        high = create_physical_expr(e.high, schema)
        rng = Binary(Binary(operand, ">=", low), "AND", Binary(operand, "<=", high))
        return Not(rng) if e.negated else rng
    if isinstance(e, lex.InListExpr):
        vals = []
        for item in e.items:
            if not isinstance(item, lex.Literal):
                raise NotImplementedYet("IN list with non-literal items")
            vals.append(item.value)
        return InList(create_physical_expr(e.expr, schema), tuple(vals), e.negated)
    if isinstance(e, lex.LikeExpr):
        if not isinstance(e.pattern, lex.Literal):
            raise NotImplementedYet("LIKE with non-literal pattern")
        return Like(create_physical_expr(e.expr, schema), e.pattern.value, e.negated)
    if isinstance(e, lex.CaseExpr):
        out_type = e.data_type(schema)
        whens = []
        for w, t in e.whens:
            cond = (
                lex.BinaryExpr(e.operand, "=", w) if e.operand is not None else w
            )
            whens.append(
                (create_physical_expr(cond, schema), create_physical_expr(t, schema))
            )
        else_e = (
            create_physical_expr(e.else_expr, schema) if e.else_expr is not None else None
        )
        return Case(tuple(whens), else_e, out_type)
    if isinstance(e, lex.CastExpr):
        return Cast(create_physical_expr(e.expr, schema), e.to_type)
    if isinstance(e, lex.ScalarFunction):
        return ScalarFn(
            e.fname,
            tuple(create_physical_expr(a, schema) for a in e.args),
            e.data_type(schema),
        )
    if isinstance(e, lex.ScalarUDFExpr):
        return ScalarUdf(
            e.fname,
            tuple(create_physical_expr(a, schema) for a in e.args),
            e.return_type,
        )
    if isinstance(e, lex.AggregateExpr):
        raise PlanError(f"aggregate {e} cannot be lowered as a scalar physical expr")
    if isinstance(e, lex.ScalarSubqueryExpr):
        raise PlanError("scalar subquery must be materialized before physical lowering")
    raise PlanError(f"cannot lower expression {e!r}")
