"""Window evaluation operator.

Reference parity note: DataFusion's single-node engine evaluates window
functions while the reference's distributed planner raises NotImplemented
for WindowAggExec (``scheduler/src/planner.rs`` WindowAggExec arm).  This
engine goes further: the physical planner hash-repartitions the input on
the PARTITION BY keys (each hash partition then holds whole window
partitions), so windows run distributed with ordinary data parallelism.

Evaluation is fully vectorized: one ``pc.sort_indices`` permutation per
DISTINCT window-key signature (specs sharing PARTITION/ORDER BY — the
common shape — reuse one ``_SortState``), numpy segment boundaries and
segmented cumsums, one type-generic pyarrow hash aggregation for
whole-partition frames — no per-row or per-group Python.

Semantics (SQL defaults):
* ranking functions need ORDER BY (row_number / rank / dense_rank);
* aggregate functions without ORDER BY cover the whole partition;
* with ORDER BY they run over the default frame RANGE BETWEEN UNBOUNDED
  PRECEDING AND CURRENT ROW — peer rows (ties in the order keys) share
  the frame, so each row sees the running value through its LAST peer;
* output rows keep the INPUT order (windows never reorder).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..errors import ExecutionError
from .expressions import PhysicalExpr
from .operators import ExecutionPlan, Partitioning, TaskContext

RANKING = {"row_number", "rank", "dense_rank", "ntile"}
VALUE_FNS = {"lag", "lead", "first_value", "last_value"}


@dataclass(frozen=True)
class WindowSpec:
    func: str  # row_number | rank | dense_rank | lag | lead | first_value
    #            | last_value | sum | avg | min | max | count
    arg: Optional[PhysicalExpr]  # None for ranking and count(*)
    partition_by: tuple  # of PhysicalExpr
    order_by: tuple  # of (PhysicalExpr, asc: bool, nulls_first: Optional[bool])
    name: str
    out_type: pa.DataType
    offset: int = 1  # lag/lead distance; ntile bucket count
    # explicit ROWS frame (start, end) row offsets; None = default RANGE
    frame: Optional[tuple] = None


class WindowExec(ExecutionPlan):
    """Appends one column per window spec to its input."""

    def __init__(self, input: ExecutionPlan, specs: list[WindowSpec]):
        super().__init__()
        self.input = input
        self.specs = specs

    @property
    def schema(self) -> pa.Schema:
        fields = list(self.input.schema)
        fields += [pa.field(s.name, s.out_type, True) for s in self.specs]
        return pa.schema(fields)

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        return WindowExec(children[0], self.specs)

    def __str__(self) -> str:
        return "WindowExec: " + ", ".join(
            f"{s.func}->{s.name}" for s in self.specs
        )

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        batches = list(self.input.execute(partition, ctx))
        if not batches:
            return
        with self.metrics.timer("window_time_ns"):
            table = pa.Table.from_batches(batches, schema=self.input.schema)

            def eval_col(e: PhysicalExpr):
                parts = []
                for b in batches:
                    v = e.evaluate(b)
                    if isinstance(v, pa.Scalar):  # literal argument
                        v = pa.array([v.as_py()] * b.num_rows, type=v.type)
                    parts.append(v)
                return pa.chunked_array(parts) if len(parts) > 1 else parts[0]

            # one _SortState (permutation + segment flags) per distinct
            # window-key signature: specs sharing PARTITION/ORDER BY —
            # the common many-functions-one-window shape — sort once
            states: dict = {}
            win_cols = []
            for spec in self.specs:
                sig = (
                    tuple(str(p) for p in spec.partition_by),
                    tuple(
                        (str(e), asc, nf) for e, asc, nf in spec.order_by
                    ),
                )
                st = states.get(sig)
                if st is None:
                    st = _SortState(table.num_rows, eval_col, spec)
                    states[sig] = st
                win_cols.append(self._evaluate_spec(spec, st, eval_col))
            out = table
            for spec, col in zip(self.specs, win_cols):
                out = out.append_column(pa.field(spec.name, spec.out_type), col)
        self.metrics.add("output_rows", out.num_rows)
        for b in out.to_batches(max_chunksize=ctx.batch_size):
            yield b

    # ------------------------------------------------------------ evaluate
    def _evaluate_spec(
        self, spec: WindowSpec, st: "_SortState", eval_col
    ) -> pa.Array:
        n = st.n
        if spec.func == "ntile":
            sorted_out = _ntile(spec.offset, n, st.seg_id, st.seg_first)
        elif spec.func in RANKING:
            sorted_out = self._ranking(
                spec.func, n, st.seg_flag, st.seg_first, st.peer_flag
            )
        elif spec.func in VALUE_FNS:
            sorted_out = _value_fn(spec, st, eval_col)
        else:
            sorted_out = _aggregate(spec, st, eval_col)

        # scatter back to input row order
        if isinstance(sorted_out, (pa.Array, pa.ChunkedArray)):
            arr = sorted_out.take(pa.array(st.inv)) if n else sorted_out
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
        else:
            out = sorted_out[st.inv] if n else sorted_out
            arr = pa.array(out, from_pandas=True)
        if not arr.type.equals(spec.out_type):
            arr = pc.cast(arr, spec.out_type, safe=False)
        return arr

    @staticmethod
    def _ranking(func, n, seg_flag, seg_first, peer_flag) -> np.ndarray:
        return _ranking_impl(func, n, seg_flag, seg_first, peer_flag)


class _SortState:
    """Sort/segment state shared by every spec with the same window keys:
    one key evaluation, one ``pc.sort_indices`` permutation, one set of
    segment/peer flags, one inverse permutation."""

    def __init__(self, n: int, eval_col, spec: WindowSpec):
        self.n = n
        key_arrays: list = []
        keys: list[tuple] = []
        for i, p in enumerate(spec.partition_by):
            key_arrays.append(eval_col(p))
            keys.append((f"__p{i}", "ascending", "at_start"))
        for i, (e, asc, nf) in enumerate(spec.order_by):
            if nf is None:
                nf = not asc  # SQL default: NULLS LAST for ASC, FIRST for DESC
            key_arrays.append(eval_col(e))
            keys.append(
                (
                    f"__o{i}",
                    "ascending" if asc else "descending",
                    "at_start" if nf else "at_end",
                )
            )
        if keys:
            sort_tbl = pa.table({k[0]: a for k, a in zip(keys, key_arrays)})
            self.perm = pc.sort_indices(sort_tbl, sort_keys=keys).to_numpy()
        else:
            self.perm = np.arange(n, dtype=np.int64)
        # key columns in SORTED order, computed once for both flag passes
        self._sorted_keys = [
            a.take(pa.array(self.perm)) if n else a for a in key_arrays
        ]
        self._n_part = len(spec.partition_by)
        self._peer_flag: Optional[np.ndarray] = None
        self._inv: Optional[np.ndarray] = None

        self.seg_flag = self._change_flags(self._sorted_keys[: self._n_part])
        seg_starts = np.flatnonzero(self.seg_flag)
        # per sorted row: index of its segment's first row
        seg_first = np.zeros(n, dtype=np.int64)
        seg_first[seg_starts] = seg_starts
        self.seg_first = np.maximum.accumulate(seg_first)
        self.seg_id = (
            np.cumsum(self.seg_flag) - 1 if n else np.empty(0, np.int64)
        )

    def _change_flags(self, sorted_arrays: list) -> np.ndarray:
        """flag[i] = sorted row i starts a new group (row 0 always does);
        null == null counts as the same group."""
        n = self.n
        flag = np.zeros(n, dtype=bool)
        if n:
            flag[0] = True
        for s in sorted_arrays:
            cur, prev = s.slice(1), s.slice(0, max(n - 1, 0))
            neq = pc.fill_null(pc.not_equal(cur, prev), False)
            null_diff = pc.xor(pc.is_null(cur), pc.is_null(prev))
            diff = pc.or_(neq, null_diff)
            flag[1:] |= np.asarray(diff, dtype=bool)
        return flag

    @property
    def peer_flag(self) -> np.ndarray:
        """Partition-OR-order-key change flags (peer-group starts)."""
        if self._peer_flag is None:
            self._peer_flag = self._change_flags(self._sorted_keys)
        return self._peer_flag

    @property
    def inv(self) -> np.ndarray:
        if self._inv is None:
            self._inv = np.empty(self.n, dtype=np.int64)
            self._inv[self.perm] = np.arange(self.n, dtype=np.int64)
        return self._inv


def _ranking_impl(func, n, seg_flag, seg_first, peer_flag) -> np.ndarray:
    idx = np.arange(n, dtype=np.int64)
    if func == "row_number":
        return idx - seg_first + 1
    # first row of each peer group
    peer_first = np.zeros(n, dtype=np.int64)
    starts = np.flatnonzero(peer_flag)
    peer_first[starts] = starts
    peer_first = np.maximum.accumulate(peer_first)
    if func == "rank":
        return peer_first - seg_first + 1
    # dense_rank: count of peer-group starts within the segment
    peers_cum = np.cumsum(peer_flag)
    return peers_cum - peers_cum[seg_first] + 1


def _ntile(k: int, n: int, seg_id: np.ndarray, seg_first: np.ndarray) -> np.ndarray:
    """SQL ntile(k): rows split into k buckets by order; the first
    (size % k) buckets get one extra row."""
    if not n:
        return np.empty(0, np.int64)
    sizes = np.bincount(seg_id)[seg_id]  # per-row partition size
    pos = np.arange(n, dtype=np.int64) - seg_first
    q, r = sizes // k, sizes % k
    big = r * (q + 1)  # rows covered by the (q+1)-sized buckets
    # when q == 0 every row is in a "big" (1-row) bucket, so the small
    # branch's divisor q only matters where q >= 1
    in_big = pos < big
    bucket_big = pos // (q + 1) + 1
    bucket_small = r + (pos - big) // np.maximum(q, 1) + 1
    return np.where(in_big, bucket_big, bucket_small)


def _sorted_arg(st: "_SortState", eval_col, arg) -> pa.Array:
    v = eval_col(arg)
    vs = v.take(pa.array(st.perm)) if st.n else v
    return vs.combine_chunks() if isinstance(vs, pa.ChunkedArray) else vs


def _value_fn(spec: WindowSpec, st: "_SortState", eval_col) -> pa.Array:
    """lag/lead/first_value/last_value: pure gathers over sorted rows,
    type-preserving.  last_value honors the default RANGE frame (the
    frame ends at the LAST peer — the classic SQL gotcha)."""
    n = st.n
    vs = _sorted_arg(st, eval_col, spec.arg)
    idx = np.arange(n, dtype=np.int64)
    if spec.func == "first_value":
        src, ok = st.seg_first, np.ones(n, dtype=bool)
    elif spec.func == "last_value":
        src, ok = _last_of_group(st.peer_flag, n), np.ones(n, dtype=bool)
    elif spec.func == "lag":
        # clamp BOTH frame sides: a negative offset (unreachable from SQL
        # but possible via serde / programmatic WindowSpec) reads forward,
        # so the partition end must bound it too
        seg_last = _last_of_group(st.seg_flag, n)
        src = idx - spec.offset
        ok = (src >= st.seg_first) & (src <= seg_last)
    else:  # lead
        seg_last = _last_of_group(st.seg_flag, n)
        src = idx + spec.offset
        ok = (src <= seg_last) & (src >= st.seg_first)
    taken = vs.take(pa.array(np.clip(src, 0, max(n - 1, 0))))
    if ok.all():
        return taken
    return pc.if_else(pa.array(ok), taken, pa.scalar(None, vs.type))


_NUMERIC = (pa.types.is_integer, pa.types.is_floating, pa.types.is_decimal)


def _require_numeric(spec: WindowSpec, t: pa.DataType) -> None:
    if not any(check(t) for check in _NUMERIC):
        extra = (
            f" (whole-partition {spec.func} — no ORDER BY in the window — "
            "supports any ordered type)"
            if spec.func in ("min", "max")
            else ""
        )
        raise ExecutionError(
            f"window {spec.func} needs a numeric argument, got {t}{extra}"
        )


def _running_minmax(spec: WindowSpec, vs, seg_id, seg_first):
    """(cum, cnt_mm): row-exact running min/max over sorted rows, shared
    by the ROWS-framed and default-RANGE paths.  Exact-int inputs return
    a pa.Array (int64 stays exact past 2^53) with cnt_mm None; the float
    path returns a numpy array already NaN-gated on the running count of
    non-missing values (null/NaN rows see the prior valid extremum)."""
    _require_numeric(spec, vs.type)
    import pandas as pd

    if pa.types.is_integer(vs.type) and vs.null_count == 0:
        g = pd.Series(
            vs.to_numpy(zero_copy_only=False).astype(np.int64)
        ).groupby(seg_id)
        cum = (g.cummin() if spec.func == "min" else g.cummax()).to_numpy()
        return pa.array(cum, pa.int64()), None
    fvals = pc.cast(vs, pa.float64(), safe=False).to_numpy(
        zero_copy_only=False
    )
    miss = np.isnan(fvals)
    ident = np.inf if spec.func == "min" else -np.inf
    cnt_mm = _segmented_cumsum((~miss).astype(np.int64), seg_first)
    g = pd.Series(np.where(miss, ident, fvals)).groupby(seg_id)
    cum = (g.cummin() if spec.func == "min" else g.cummax()).to_numpy()
    return np.where(cnt_mm > 0, cum, np.nan), cnt_mm


def _np_range_extremum(v, lo, hi, fn, ident, max_len):
    """Per-row extremum over [lo_i, hi_i]: numpy sparse table (doubling)
    — level k holds the extremum of the size-2^k window starting at each
    row; the query is two overlapping-window gathers.  ``max_len``
    bounds the depth (finite frames need ceil(log2(frame_len)) levels).
    Callers clip lo/hi to the row's segment, so both query windows stay
    inside it even though levels span boundaries."""
    n = len(v)
    if n == 0:
        return v
    ext = np.minimum if fn == "min" else np.maximum
    depth = max(1, int(max(max_len - 1, 1)).bit_length())
    levels = [v]
    cur = v
    for k in range(1, depth + 1):
        s = 1 << (k - 1)
        shifted = np.full(n, ident, dtype=cur.dtype)
        if s < n:
            shifted[: n - s] = cur[s:]
        cur = ext(cur, shifted)
        levels.append(cur)
    table = np.stack(levels)
    length = np.maximum(hi - lo + 1, 1)
    kq = np.zeros(n, dtype=np.int64)
    for k in range(1, depth + 1):
        kq += (length >= (1 << k)).astype(np.int64)
    size = np.left_shift(np.ones(n, dtype=np.int64), kq)
    aidx = np.clip(lo, 0, n - 1)
    bidx = np.clip(hi - size + 1, 0, n - 1)
    flat = table.reshape(-1)
    return ext(flat[kq * n + aidx], flat[kq * n + bidx])


def _rows_frame_aggregate(spec: WindowSpec, st: "_SortState", eval_col):
    """Explicit ROWS frames: row-exact sliding windows (no peer sharing).

    sum/avg/count reduce to two gathers on a segment-clamped prefix sum
    — O(n) regardless of frame width; bounded min/max query a sparse
    table (``_np_range_extremum``) — O(n log frame) build, O(n) query,
    with the running cummin/cummax fast path kept for UNBOUNDED
    PRECEDING .. CURRENT ROW."""
    n = st.n
    seg_first = st.seg_first
    start, end = spec.frame
    idx = np.arange(n, dtype=np.int64)
    seg_last = _last_of_group(st.seg_flag, n)
    lo = seg_first if start is None else np.maximum(seg_first, idx + start)
    hi = seg_last if end is None else np.minimum(seg_last, idx + end)
    empty = hi < lo

    if spec.func in ("min", "max"):
        vs = _sorted_arg(st, eval_col, spec.arg)
        if start is None and end == 0:
            # running fast path: grouped cummin/cummax
            cum, _ = _running_minmax(spec, vs, st.seg_id, seg_first)
            if isinstance(cum, pa.Array):  # exact-int path
                return pc.if_else(
                    pa.array(~empty), cum, pa.scalar(None, cum.type)
                )
            return np.where(~empty, cum, np.nan)  # cum already NaN-gated
        # general ROWS frame: sparse-table range extremum (two gathers
        # over log-depth doubled windows — the same decomposition the
        # device kernel uses, ops/window_kernel._range_extremum)
        _require_numeric(spec, vs.type)
        if start is not None and end is not None:
            max_len = end - start + 1
        else:
            # half-unbounded frames never exceed the largest segment:
            # bound the table depth by it, not n (the device kernel has
            # to use its static padded n — this host path need not)
            max_len = (
                int((seg_last - seg_first + 1).max()) if n else 1
            )
        if pa.types.is_integer(vs.type) and vs.null_count == 0:
            v = vs.to_numpy(zero_copy_only=False).astype(np.int64)
            ident = (
                np.iinfo(np.int64).max
                if spec.func == "min"
                else np.iinfo(np.int64).min
            )
            res = _np_range_extremum(
                v, lo, hi, spec.func, ident, max_len
            )
            return pa.array(res, pa.int64(), mask=empty)
        fvals = pc.cast(vs, pa.float64(), safe=False).to_numpy(
            zero_copy_only=False
        )
        miss = np.isnan(fvals)
        ident = np.inf if spec.func == "min" else -np.inf
        res = _np_range_extremum(
            np.where(miss, ident, fvals), lo, hi, spec.func, ident, max_len
        )
        # frames holding only nulls (or clipped empty) are NULL: count
        # the frame's valid rows via a segment-local prefix difference
        vcum = _segmented_cumsum((~miss).astype(np.int64), seg_first)
        hi_c = np.clip(hi, 0, max(n - 1, 0))
        lom1_c = np.clip(lo - 1, 0, max(n - 1, 0))
        base = np.where(lo > seg_first, vcum[lom1_c], 0)
        vcnt = np.where(empty, 0, vcum[hi_c] - base)
        return np.where(vcnt > 0, res, np.nan)

    if spec.arg is None:  # count(*)
        out = hi - lo + 1
        return np.where(empty, 0, out)

    vs = _sorted_arg(st, eval_col, spec.arg)
    if spec.func in ("sum", "avg"):
        _require_numeric(spec, vs.type)
    valid = ~np.asarray(pc.is_null(vs), dtype=bool)

    # bounds can point past the partition (e.g. 2 FOLLOWING at the last
    # row): clamp the prefix indexes; the empty-frame mask nulls those.
    # Prefixes are SEGMENT-LOCAL (pandas grouped cumsum): a global prefix
    # makes the P[hi]-P[lo-1] cancellation scale with the whole-table
    # magnitude — measured 4e-4 relative error on a small-valued
    # partition following a 1e6-valued one.
    hi_g = np.clip(hi, 0, max(n - 1, 0))
    lom1_g = np.clip(lo - 1, 0, max(n - 1, 0))
    lo_open = lo > seg_first  # P[lo-1] lies inside the segment

    def range_sum(vals):
        import pandas as pd

        ps = (
            pd.Series(vals).groupby(st.seg_id).cumsum().to_numpy()
        )  # inclusive, resets per segment
        base = np.where(lo_open, ps[lom1_g], 0)
        return np.where(empty, 0, ps[hi_g] - base)

    cnt = range_sum(valid.astype(np.int64))
    cnt = np.where(empty, 0, cnt)
    if spec.func == "count":
        return cnt
    if pa.types.is_integer(vs.type) and vs.null_count == 0 and (
        spec.func == "sum"
    ):
        vals = vs.to_numpy(zero_copy_only=False).astype(np.int64)
        total = range_sum(vals)
        # int64 exactness survives: null out empty frames via an Arrow
        # mask instead of routing the values through float64
        return pa.array(total, pa.int64(), mask=cnt == 0)
    fvals = np.nan_to_num(
        pc.cast(vs, pa.float64(), safe=False).to_numpy(zero_copy_only=False),
        nan=0.0,
    )
    total = range_sum(fvals)
    if spec.func == "sum":
        return np.where(cnt > 0, total, np.nan)
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.where(cnt > 0, total / cnt, np.nan)


def _aggregate(spec: WindowSpec, st: "_SortState", eval_col):
    if spec.frame is not None:
        return _rows_frame_aggregate(spec, st, eval_col)
    n = st.n
    seg_id, seg_first = st.seg_id, st.seg_first
    running = bool(spec.order_by)
    if spec.arg is None:  # count(*)
        if not running:
            sizes = np.bincount(seg_id, minlength=seg_id[-1] + 1 if n else 0)
            return sizes[seg_id].astype(np.int64)
        idx = np.arange(n, dtype=np.int64)
        # rows count through the LAST peer (RANGE frame)
        peer_last = _last_of_group(st.peer_flag, n)
        return idx[peer_last] - seg_first + 1

    vs = _sorted_arg(st, eval_col, spec.arg)

    if not running:
        # whole-partition frame: one TYPE-GENERIC pyarrow hash
        # aggregation over the dense segment ids — min/max keep the
        # input type (strings, dates, wide ints stay exact) and an
        # all-null group's sum is null as SQL requires
        fn = {
            "sum": "sum", "avg": "mean", "min": "min", "max": "max",
            "count": "count",
        }[spec.func]
        if spec.func in ("sum", "avg"):
            _require_numeric(spec, vs.type)  # else raw pyarrow kernel error
        seg_tbl = pa.table({"s": pa.array(seg_id), "v": vs})
        res = pa.TableGroupBy(seg_tbl, "s").aggregate([("v", fn)])
        res = res.sort_by([("s", "ascending")])
        return res.column(f"v_{fn}").take(pa.array(seg_id))

    # running frame: cumulative within segment, then peers share the
    # value through their last row
    is_exact_int = pa.types.is_integer(vs.type) and vs.null_count == 0
    valid = ~np.asarray(pc.is_null(vs), dtype=bool)
    cnt = _segmented_cumsum(valid.astype(np.int64), seg_first)
    if spec.func == "count":
        cum = cnt
    elif spec.func in ("sum", "avg"):
        if is_exact_int and spec.func == "sum":
            # exact integer running sum (float64 loses ULPs past 2^53)
            vals = vs.to_numpy(zero_copy_only=False).astype(np.int64)
            cum = _segmented_cumsum(vals, seg_first)
        else:
            _require_numeric(spec, vs.type)
            vals = np.nan_to_num(
                pc.cast(vs, pa.float64(), safe=False).to_numpy(
                    zero_copy_only=False
                ),
                nan=0.0,
            )
            total = _segmented_cumsum(vals, seg_first)
            if spec.func == "sum":
                cum = np.where(cnt > 0, total, np.nan)
            else:
                with np.errstate(invalid="ignore", divide="ignore"):
                    cum = np.where(cnt > 0, total / cnt, np.nan)
    elif spec.func in ("min", "max"):
        cum, _ = _running_minmax(spec, vs, seg_id, seg_first)
    else:
        raise ExecutionError(f"window aggregate {spec.func}")
    peer_last = _last_of_group(st.peer_flag, n)
    if isinstance(cum, pa.Array):  # exact-int running min/max
        return cum.take(pa.array(peer_last))
    return np.asarray(cum)[peer_last]


def _segmented_cumsum(vals: np.ndarray, seg_first: np.ndarray) -> np.ndarray:
    """Within-segment inclusive cumsum over sorted rows: the global cumsum
    minus the global cumsum just BEFORE each row's segment start (exact
    for int64 inputs)."""
    if not len(vals):
        return vals
    cs = np.cumsum(vals)
    before_seg = cs[seg_first] - vals[seg_first]
    return cs - before_seg


def _last_of_group(start_flag: np.ndarray, n: int) -> np.ndarray:
    """Per row: index of the LAST row of its group, given group-start
    flags over sorted rows (vectorized reverse cummax trick)."""
    if not n:
        return np.empty(0, np.int64)
    # last row of group g = (next group's start) - 1; final group ends at n-1
    starts = np.flatnonzero(start_flag)
    nexts = np.append(starts[1:], n)
    group_of_row = np.cumsum(start_flag) - 1
    return nexts[group_of_row] - 1
