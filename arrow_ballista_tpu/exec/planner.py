"""Logical → physical planning.

Counterpart of DataFusion's DefaultPhysicalPlanner as driven by the
reference's session context (``state/session_manager.rs:112-125`` maps
session settings into planner behavior).  Key structural choices mirrored
from the reference so the distributed planner can split stages the same way
(``scheduler/src/planner.rs:81-170``):

* aggregates are planned Partial → RepartitionExec(hash keys) → Final
* joins are planned Partitioned (repartition both sides) or CollectLeft
* sorts/limits sit above an explicit CoalescePartitionsExec

Shuffle boundaries are therefore exactly the RepartitionExec /
CoalescePartitionsExec nodes.
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa

from ..config import BallistaConfig
from ..errors import NotImplementedYet, PlanError
from ..plan import expressions as lex
from ..plan import logical as lp
from . import aggregates as agg
from . import joins as jn
from .expressions import Col, PhysicalExpr, create_physical_expr
from .operators import (
    CoalescePartitionsExec,
    EmptyExec,
    ExecutionPlan,
    FilterExec,
    LimitExec,
    Partitioning,
    ProjectionExec,
    RepartitionExec,
    ScanExec,
    SortExec,
    TaskContext,
    UnionExec,
    collect,
)


class RenameSchemaExec(ExecutionPlan):
    """Pass-through that re-qualifies field names (SubqueryAlias)."""

    def __init__(self, input: ExecutionPlan, schema: pa.Schema):
        super().__init__()
        self.input = input
        self._schema = schema

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def execute(self, partition: int, ctx: TaskContext):
        for b in self.input.execute(partition, ctx):
            yield pa.RecordBatch.from_arrays(b.columns, schema=self._schema)

    def with_new_children(self, children):
        return RenameSchemaExec(children[0], self._schema)

    def __str__(self) -> str:
        return f"RenameSchemaExec: {self._schema.names}"


class PhysicalPlanner:
    def __init__(self, config: Optional[BallistaConfig] = None):
        self.config = config or BallistaConfig()

    # ------------------------------------------------------------ entry
    def create_physical_plan(self, plan: lp.LogicalPlan) -> ExecutionPlan:
        plan = self._materialize_scalar_subqueries(plan)
        return self._plan(plan)

    def _materialize_scalar_subqueries(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        """Execute uncorrelated scalar subqueries eagerly and inline results.

        DataFusion decorrelates these in its optimizer; TPC-H only needs the
        uncorrelated form at top level (q15-style views are handled by the
        derived-table path).
        """

        def rewrite_expr(e: lex.Expr) -> lex.Expr:
            def fn(node: lex.Expr) -> lex.Expr:
                if isinstance(node, lex.ScalarSubqueryExpr):
                    # the embedded plan never went through the session's
                    # optimizer pass (it lives inside an expression), so
                    # fold/simplify here — date arithmetic etc. must be
                    # constant-folded before physical lowering
                    from ..plan.optimizer import optimize as _optimize

                    sub_phys = PhysicalPlanner(self.config).create_physical_plan(
                        _optimize(node.plan)
                    )
                    tbl = collect(sub_phys, TaskContext(config=self.config))
                    if tbl.num_rows != 1:
                        raise PlanError(
                            f"scalar subquery returned {tbl.num_rows} rows"
                        )
                    return lex.Literal(tbl.column(0)[0].as_py(), tbl.schema.field(0).type)
                return node

            return lex.transform(e, fn)

        def fn_plan(p: lp.LogicalPlan) -> lp.LogicalPlan:
            from ..plan.optimizer import _map_exprs

            return _map_exprs(p, rewrite_expr)

        return lp.transform_up(plan, fn_plan)

    # ------------------------------------------------------------- lowering
    def _plan(self, plan: lp.LogicalPlan) -> ExecutionPlan:
        if isinstance(plan, lp.TableScan):
            return ScanExec(plan.table_name, plan.provider, plan.projection)

        if isinstance(plan, lp.SubqueryAlias):
            child = self._plan(plan.input)
            return RenameSchemaExec(child, plan.schema)

        if isinstance(plan, lp.Filter):
            child = self._plan(plan.input)
            pred = create_physical_expr(plan.predicate, child.schema)
            return FilterExec(pred, child)

        if isinstance(plan, lp.Projection):
            child = self._plan(plan.input)
            exprs = [
                (create_physical_expr(e, child.schema), e.name) for e in plan.exprs
            ]
            return ProjectionExec(exprs, child)

        if isinstance(plan, lp.Aggregate):
            return self._plan_aggregate(plan)

        if isinstance(plan, lp.Window):
            return self._plan_window(plan)

        if isinstance(plan, lp.Sort):
            child = self._plan(plan.input)
            if child.output_partitioning().n != 1:
                child = CoalescePartitionsExec(child)
            keys = [
                (create_physical_expr(s.expr, child.schema), s.asc, s.nulls_first)
                for s in plan.sort_exprs
            ]
            return SortExec(keys, child, plan.fetch)

        if isinstance(plan, lp.Limit):
            child = self._plan(plan.input)
            if child.output_partitioning().n != 1:
                child = CoalescePartitionsExec(child)
            return LimitExec(child, plan.skip, plan.fetch)

        if isinstance(plan, lp.Join):
            return self._plan_join(plan)

        if isinstance(plan, lp.CrossJoin):
            return jn.CrossJoinExec(self._plan(plan.left), self._plan(plan.right))

        if isinstance(plan, lp.Union):
            return UnionExec([self._plan(c) for c in plan.inputs])

        if isinstance(plan, lp.Distinct):
            child = self._plan(plan.input)
            group = [
                (Col(i, f.name), f.name) for i, f in enumerate(child.schema)
            ]
            n = self.config.shuffle_partitions
            if child.output_partitioning().n > 1 or n > 1:
                child = RepartitionExec(
                    child, Partitioning.hash(tuple(g for g, _ in group), n)
                )
            return agg.HashAggregateExec(agg.SINGLE, group, [], child)

        if isinstance(plan, lp.EmptyRelation):
            return EmptyExec(plan.produce_one_row, plan.schema)

        if isinstance(plan, lp.Values):
            from ..catalog import MemoryTable

            arrays = []
            for i, f in enumerate(plan.schema_):
                arrays.append(pa.array([r[i] for r in plan.rows], f.type))
            tbl = pa.Table.from_arrays(arrays, schema=plan.schema_)
            return ScanExec("values", MemoryTable.from_table(tbl), None)

        raise NotImplementedYet(f"physical planning for {type(plan).__name__}")

    # ------------------------------------------------------------- window
    def _plan_window(self, plan: lp.Window) -> ExecutionPlan:
        """Distribute windows with data parallelism: when every window
        shares one non-empty PARTITION BY set, hash-repartition the input
        on it (each hash partition holds whole window partitions); any
        other shape coalesces to a single partition.  The reference's
        planner raises NotImplemented here (planner.rs WindowAggExec) —
        this surpasses it."""
        from .window import WindowExec, WindowSpec

        child = self._plan(plan.input)
        in_schema = child.schema
        out_schema = plan.schema
        base = len(in_schema)

        specs: list[WindowSpec] = []
        part_sets = set()
        for i, w in enumerate(plan.window_exprs):
            part_phys = tuple(
                create_physical_expr(p, in_schema) for p in w.partition_by
            )
            order_phys = tuple(
                (create_physical_expr(s.expr, in_schema), s.asc, s.nulls_first)
                for s in w.order_by
            )
            arg_phys = (
                create_physical_expr(w.arg, in_schema)
                if w.arg is not None
                else None
            )
            f = out_schema.field(base + i)
            specs.append(
                WindowSpec(
                    w.func, arg_phys, part_phys, order_phys, f.name, f.type,
                    w.offset, w.frame,
                )
            )
            part_sets.add(tuple(str(p) for p in w.partition_by))

        n_part = self.config.shuffle_partitions
        # one shared NON-EMPTY partition-by set → hash repartition keeps
        # whole window partitions together; anything else must coalesce
        common_keys = len(part_sets) == 1 and bool(next(iter(part_sets)))
        if common_keys:
            if child.output_partitioning().n > 1:
                child = RepartitionExec(
                    child,
                    Partitioning.hash(specs[0].partition_by, n_part),
                )
        elif child.output_partitioning().n != 1:
            child = CoalescePartitionsExec(child)
        return WindowExec(child, specs)

    # ----------------------------------------------------------- aggregate
    def _plan_aggregate(self, plan: lp.Aggregate) -> ExecutionPlan:
        child = self._plan(plan.input)
        in_schema = child.schema
        agg_schema = plan.schema  # groups then aggs

        group_phys: list[tuple[PhysicalExpr, str]] = []
        for i, g in enumerate(plan.group_exprs):
            group_phys.append(
                (create_physical_expr(g, in_schema), agg_schema.field(i).name)
            )

        specs: list[agg.AggSpec] = []
        has_distinct = False
        for j, a in enumerate(plan.agg_exprs):
            inner = a.expr if isinstance(a, lex.Alias) else a
            assert isinstance(inner, lex.AggregateExpr), f"not an aggregate: {a}"
            if (
                inner.func == "count_distinct"
                or inner.distinct
                or inner.func.startswith("udaf:")
                or inner.func in lex.STAT_AGGREGATES
            ):
                # UDAFs and the statistical aggregates (median/stddev/
                # var/corr) have no partial/merge decomposition — run
                # single stage with each group wholly in one partition,
                # the same strategy as distinct aggregates
                has_distinct = True
            arg = (
                create_physical_expr(inner.arg, in_schema)
                if inner.arg is not None
                else None
            )
            arg2 = (
                create_physical_expr(inner.arg2, in_schema)
                if inner.arg2 is not None
                else None
            )
            name = agg_schema.field(len(plan.group_exprs) + j).name
            specs.append(
                agg.AggSpec(
                    inner.func, arg, name, agg_schema.field(name).type,
                    arg2=arg2,
                )
            )

        n_part = self.config.shuffle_partitions
        repartition = self.config.repartition_aggregations and group_phys

        if has_distinct:
            # distinct aggregates need each group wholly in one partition:
            # hash-repartition input on the group keys, run single-stage
            if group_phys:
                child = RepartitionExec(
                    child,
                    Partitioning.hash(tuple(g for g, _ in group_phys), n_part),
                )
            elif child.output_partitioning().n != 1:
                child = CoalescePartitionsExec(child)
            return agg.HashAggregateExec(agg.SINGLE, group_phys, specs, child)

        partial = agg.HashAggregateExec(agg.PARTIAL, group_phys, specs, child)

        if repartition:
            partial_schema = partial.schema
            key_cols = tuple(
                Col(i, partial_schema.field(i).name) for i in range(len(group_phys))
            )
            shuffled: ExecutionPlan = RepartitionExec(
                partial, Partitioning.hash(key_cols, n_part)
            )
        else:
            shuffled = (
                CoalescePartitionsExec(partial)
                if partial.output_partitioning().n != 1
                else partial
            )

        # FINAL mode re-groups by the key columns of the partial output
        final_groups = [
            (Col(i, partial.schema.field(i).name), name)
            for i, (_, name) in enumerate(group_phys)
        ]
        return agg.HashAggregateExec(agg.FINAL, final_groups, specs, shuffled)

    # ---------------------------------------------------------------- join
    def _plan_join(self, plan: lp.Join) -> ExecutionPlan:
        left = self._plan(plan.left)
        right = self._plan(plan.right)
        lkeys = [create_physical_expr(l, left.schema) for l, _ in plan.on]
        rkeys = [create_physical_expr(r, right.schema) for _, r in plan.on]
        jfilter = (
            create_physical_expr(
                plan.filter, pa.schema(list(left.schema) + list(right.schema))
            )
            if plan.filter is not None
            else None
        )
        n_part = self.config.shuffle_partitions
        if self.config.repartition_joins:
            left = RepartitionExec(left, Partitioning.hash(tuple(lkeys), n_part))
            right = RepartitionExec(right, Partitioning.hash(tuple(rkeys), n_part))
            mode = jn.PARTITIONED
        elif plan.join_type == "inner":
            # broadcasting the build side against each probe partition is
            # only correct for inner joins (other types would emit
            # per-partition unmatched/duplicate rows)
            mode = jn.COLLECT_LEFT
        else:
            if left.output_partitioning().n != 1:
                left = CoalescePartitionsExec(left)
            if right.output_partitioning().n != 1:
                right = CoalescePartitionsExec(right)
            mode = jn.PARTITIONED
        return jn.HashJoinExec(
            left, right, list(zip(lkeys, rkeys)), plan.join_type, mode, jfilter
        )
