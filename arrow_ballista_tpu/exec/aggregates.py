"""Hash aggregation with Partial/Final split.

Counterpart of DataFusion's ``AggregateExec`` with ``AggregateMode`` as
serialized by the reference (``core/proto/ballista.proto:316-320``): the
Partial stage computes per-partition accumulator states, a shuffle hashes
rows by group key, and the Final stage merges states.  This split is exactly
what lets the TPU path reduce partials with ``psum`` across chips
(SURVEY.md §2.5) before the shuffle.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.compute as pc

from ..errors import ExecutionError
from .expressions import PhysicalExpr, _as_array_len
from .operators import ExecutionPlan, Partitioning, TaskContext

PARTIAL = "partial"
FINAL = "final"
SINGLE = "single"


@dataclass(frozen=True)
class AggSpec:
    func: str  # sum | avg | min | max | count | count_distinct | median
    #            | stddev | stddev_pop | var | var_pop | corr | udaf:<name>
    arg: Optional[PhysicalExpr]  # None for count(*)
    name: str  # output column name
    out_type: pa.DataType
    arg2: Optional[PhysicalExpr] = None  # corr's second argument

    def state_fields(self) -> list[pa.Field]:
        """Partial-state columns this aggregate contributes."""
        if self.func == "avg":
            return [
                pa.field(f"{self.name}#sum", pa.float64()),
                pa.field(f"{self.name}#count", pa.int64()),
            ]
        if self.func in ("count", "count_distinct"):
            return [pa.field(self.name, pa.int64())]
        if self.func == "sum":
            t = self.out_type
            return [pa.field(self.name, t)]
        return [pa.field(self.name, self.out_type)]  # min / max


class HashAggregateExec(ExecutionPlan):
    def __init__(
        self,
        mode: str,
        group_exprs: list[tuple[PhysicalExpr, str]],
        aggs: list[AggSpec],
        input: ExecutionPlan,
    ):
        super().__init__()
        assert mode in (PARTIAL, FINAL, SINGLE)
        self.mode = mode
        self.group_exprs = group_exprs
        self.aggs = aggs
        self.input = input
        in_schema = input.schema
        gfields = []
        for e, name in group_exprs:
            from .operators import _infer_type

            gfields.append(pa.field(name, _infer_type(e, in_schema), True))
        if mode == PARTIAL:
            afields = [f for a in aggs for f in a.state_fields()]
        else:
            afields = [pa.field(a.name, a.out_type, True) for a in aggs]
        self._schema = pa.schema(gfields + afields)

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        return HashAggregateExec(self.mode, self.group_exprs, self.aggs, children[0])

    def __str__(self) -> str:
        return (
            f"HashAggregateExec: mode={self.mode}, "
            f"gby=[{', '.join(n for _, n in self.group_exprs)}], "
            f"aggr=[{', '.join(a.name for a in self.aggs)}]"
        )

    # ------------------------------------------------------------ execution
    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        batches = list(self.input.execute(partition, ctx))
        with self.metrics.timer("agg_time_ns"):
            if self.mode == FINAL:
                out = self._execute_final(batches)
            else:
                out = self._execute_partial_or_single(batches)
        self.metrics.add("output_rows", out.num_rows)
        for b in out.to_batches(max_chunksize=ctx.batch_size):
            yield b

    def _prepared_table(self, batches: list[pa.RecordBatch]) -> Optional[pa.Table]:
        """Evaluate group + arg exprs into a flat table g0..gk, a0..am
        (plus b{j} second-argument columns for corr)."""
        if not batches:
            return None
        cols: dict[str, pa.ChunkedArray] = {}
        for i, (e, name) in enumerate(self.group_exprs):
            cols[f"__g{i}"] = pa.chunked_array(
                [_as_array_len(e.evaluate(b), b.num_rows) for b in batches]
            )
        for j, a in enumerate(self.aggs):
            if a.arg is not None:
                cols[f"__a{j}"] = pa.chunked_array(
                    [_as_array_len(a.arg.evaluate(b), b.num_rows) for b in batches]
                )
            if a.arg2 is not None:
                cols[f"__b{j}"] = pa.chunked_array(
                    [_as_array_len(a.arg2.evaluate(b), b.num_rows) for b in batches]
                )
        if not cols:  # count(*) with no groups
            return pa.table({"__dummy": pa.array([0] * sum(b.num_rows for b in batches))})
        return pa.table(cols)

    def _execute_partial_or_single(self, batches: list[pa.RecordBatch]) -> pa.Table:
        tbl = self._prepared_table(batches)
        n_groups = len(self.group_exprs)
        partial = self.mode == PARTIAL

        if tbl is None or tbl.num_rows == 0:
            if n_groups == 0:
                return self._empty_global_result(partial)
            return pa.Table.from_batches([], schema=self._schema)

        if n_groups == 0:
            return self._global_agg(tbl, partial)

        gkeys = [f"__g{i}" for i in range(n_groups)]
        requests: list[tuple] = []
        # per OUTPUT field (schema order, after the keys): how to build it
        #   ("col", result_name)           — direct group_by result column
        #   ("udaf", result_name, spec)    — fold collected lists
        #   ("median", src)                — pandas groupby merge pass
        #   ("corr", j)                    — finalize the six sum requests
        emit: list[tuple] = []
        derived: dict[str, object] = {}  # extra columns for corr sums

        def _single_only(what: str) -> None:
            if partial:
                raise ExecutionError(
                    f"{what} must run single-stage after key repartition"
                )

        for j, a in enumerate(self.aggs):
            src = f"__a{j}"
            if a.func == "sum":
                requests.append((src, "sum"))
                emit.append(("col", f"{src}_sum"))
            elif a.func == "avg":
                if partial:
                    requests.append((src, "sum"))
                    emit.append(("col", f"{src}_sum"))
                    requests.append((src, "count"))
                    emit.append(("col", f"{src}_count"))
                else:
                    requests.append((src, "mean"))
                    emit.append(("col", f"{src}_mean"))
            elif a.func == "min":
                requests.append((src, "min"))
                emit.append(("col", f"{src}_min"))
            elif a.func == "max":
                requests.append((src, "max"))
                emit.append(("col", f"{src}_max"))
            elif a.func == "count":
                if a.arg is None:
                    # count(*) counts rows including nulls in the key column
                    requests.append(
                        (gkeys[0], "count", pc.CountOptions(mode="all"))
                    )
                    emit.append(("col", f"{gkeys[0]}_count"))
                else:
                    requests.append((src, "count"))
                    emit.append(("col", f"{src}_count"))
            elif a.func == "count_distinct":
                _single_only("count_distinct")
                requests.append((src, "count_distinct"))
                emit.append(("col", f"{src}_count_distinct"))
            elif a.func in ("stddev", "stddev_pop", "var", "var_pop"):
                _single_only(a.func)
                fn = "stddev" if a.func.startswith("stddev") else "variance"
                ddof = 0 if a.func.endswith("_pop") else 1
                requests.append((src, fn, pc.VarianceOptions(ddof=ddof)))
                emit.append(("col", f"{src}_{fn}"))
            elif a.func == "median":
                _single_only("median")
                emit.append(("median", src))
            elif a.func == "corr":
                _single_only("corr")
                # pairwise-valid sums: rows where either argument is null
                # OR NaN drop out of every sum (pandas treats NaN values
                # as missing in corr; the global path does the same)
                x = pc.cast(tbl.column(src), pa.float64(), safe=False)
                y = pc.cast(tbl.column(f"__b{j}"), pa.float64(), safe=False)
                both = pc.and_(
                    pc.and_(pc.is_valid(x), pc.is_valid(y)),
                    pc.and_(
                        pc.invert(pc.is_nan(x)), pc.invert(pc.is_nan(y))
                    ),
                )
                null = pa.scalar(None, pa.float64())
                xv = pc.if_else(both, x, null)
                yv = pc.if_else(both, y, null)
                # center by the GLOBAL mean (corr-invariant): the n·Σxy −
                # Σx·Σy form cancels catastrophically on raw magnitudes
                xm, ym = pc.mean(xv), pc.mean(yv)
                if xm.is_valid:
                    xv = pc.subtract(xv, xm)
                if ym.is_valid:
                    yv = pc.subtract(yv, ym)
                derived[f"__c{j}x"] = xv
                derived[f"__c{j}y"] = yv
                derived[f"__c{j}xy"] = pc.multiply(xv, yv)
                derived[f"__c{j}xx"] = pc.multiply(xv, xv)
                derived[f"__c{j}yy"] = pc.multiply(yv, yv)
                for nm in (f"__c{j}x", f"__c{j}y", f"__c{j}xy",
                           f"__c{j}xx", f"__c{j}yy"):
                    requests.append((nm, "sum"))
                requests.append((f"__c{j}x", "count"))
                emit.append(("corr", j))
            elif a.func.startswith("udaf:"):
                _single_only("UDAFs")
                # collect each group's values; the UDF folds them below
                requests.append((src, "list"))
                emit.append(("udaf", f"{src}_list", a))
            else:
                raise ExecutionError(f"unsupported aggregate {a.func}")

        grouped_tbl = tbl
        for nm, col in derived.items():
            grouped_tbl = grouped_tbl.append_column(nm, col)
        result = pa.TableGroupBy(grouped_tbl, gkeys).aggregate(requests)

        medians = self._group_medians(
            tbl, result, gkeys, sorted({e[1] for e in emit if e[0] == "median"})
        )

        # group_by output columns are named "<src>_<func>", keys keep names
        out_cols: list = []
        fields = list(self._schema)
        for i in range(len(self.group_exprs)):
            out_cols.append(result.column(f"__g{i}"))
        for entry, f in zip(emit, fields[len(self.group_exprs):]):
            if entry[0] == "col":
                col = result.column(entry[1])
            elif entry[0] == "udaf":
                col = _apply_udaf(entry[2], result.column(entry[1]), f.type)
            elif entry[0] == "median":
                col = medians[entry[1]]
            else:  # corr
                col = _finalize_corr(result, entry[1])
            if not col.type.equals(f.type):
                col = pc.cast(col, f.type, safe=False)
            out_cols.append(col)
        return pa.Table.from_arrays(out_cols, schema=self._schema)

    @staticmethod
    def _group_medians(
        tbl: pa.Table, result: pa.Table, gkeys: list[str], srcs: list[str]
    ) -> dict:
        """EXACT per-group medians (pyarrow only has approximate_median):
        one vectorized pandas groupby, merged back onto the group_by
        result's key rows (pandas merge matches null keys to null keys,
        and how='left' preserves the result row order)."""
        if not srcs:
            return {}
        import pandas as pd  # noqa: F401

        pdf = tbl.select(gkeys + srcs).to_pandas()
        med = (
            # observed=True: dictionary keys become pandas Categoricals,
            # and the default would materialize every UNOBSERVED category
            # combination (cartesian in the key cardinalities)
            pdf.groupby(gkeys, dropna=False, sort=False, observed=True)[srcs]
            .median()
            .reset_index()
        )
        keys_pdf = result.select(gkeys).to_pandas()
        merged = keys_pdf.merge(med, on=gkeys, how="left")
        return {
            src: pa.array(merged[src].to_numpy(), pa.float64(), from_pandas=True)
            for src in srcs
        }

    def _global_agg(self, tbl: pa.Table, partial: bool) -> pa.Table:
        import numpy as np
        cols: list[pa.Array] = []
        for j, a in enumerate(self.aggs):
            src = tbl.column(f"__a{j}") if a.arg is not None else None
            if a.func == "sum":
                v = pc.sum(src)
                cols.append(_scalar_col(v, self._field_for(a.name).type))
            elif a.func == "avg":
                if partial:
                    cols.append(_scalar_col(pc.sum(src), pa.float64()))
                    cols.append(_scalar_col(pc.count(src), pa.int64()))
                else:
                    cols.append(_scalar_col(pc.mean(src), pa.float64()))
            elif a.func == "min":
                cols.append(_scalar_col(pc.min(src), self._field_for(a.name).type))
            elif a.func == "max":
                cols.append(_scalar_col(pc.max(src), self._field_for(a.name).type))
            elif a.func == "count":
                n = tbl.num_rows if a.arg is None else pc.count(src).as_py()
                cols.append(pa.array([n], pa.int64()))
            elif a.func == "count_distinct":
                cols.append(
                    pa.array([pc.count_distinct(src).as_py()], pa.int64())
                )
            elif a.func in ("stddev", "stddev_pop", "var", "var_pop"):
                ddof = 0 if a.func.endswith("_pop") else 1
                fn = pc.stddev if a.func.startswith("stddev") else pc.variance
                cols.append(_scalar_col(fn(src, ddof=ddof), pa.float64()))
            elif a.func == "median":
                v = src.drop_null().to_numpy(zero_copy_only=False)
                out = float(np.median(v)) if len(v) else None
                cols.append(pa.array([out], pa.float64()))
            elif a.func == "corr":
                x = pc.cast(src, pa.float64(), safe=False).to_numpy(
                    zero_copy_only=False
                )
                y = pc.cast(
                    tbl.column(f"__b{j}"), pa.float64(), safe=False
                ).to_numpy(zero_copy_only=False)
                both = ~(np.isnan(x) | np.isnan(y))
                xv, yv = x[both], y[both]
                out = None
                if len(xv) >= 2 and xv.std() > 0 and yv.std() > 0:
                    out = float(np.corrcoef(xv, yv)[0, 1])
                cols.append(pa.array([out], pa.float64()))
            elif a.func.startswith("udaf:"):
                t = self._field_for(a.name).type
                v = _resolve_udaf(a.func).fn(src.combine_chunks())
                cols.append(pa.array([v], type=t))
            else:
                raise ExecutionError(f"unsupported aggregate {a.func}")
        return pa.Table.from_arrays(cols, schema=self._schema)

    def _field_for(self, name: str) -> pa.Field:
        return self._schema.field(name)

    def _empty_global_result(self, partial: bool) -> pa.Table:
        """Zero-row input, no GROUP BY → one row: counts 0, everything else
        NULL (SQL semantics for global aggregates over empty input)."""
        count_fields = set()
        for a in self.aggs:
            if a.func in ("count", "count_distinct"):
                count_fields.add(a.name)
            if a.func == "avg" and partial:
                count_fields.add(f"{a.name}#count")
        cols = []
        for f in self._schema:
            if f.name in count_fields:
                cols.append(pa.array([0], f.type))
            else:
                cols.append(pa.nulls(1, f.type))
        return pa.Table.from_arrays(cols, schema=self._schema)

    def _execute_final(self, batches: list[pa.RecordBatch]) -> pa.Table:
        """Merge partial states (input schema = partial output schema)."""
        n_groups = len(self.group_exprs)
        in_schema = self.input.schema
        if not batches:
            if n_groups == 0:
                return self._empty_global_result(False)
            return pa.Table.from_batches([], schema=self._schema)
        tbl = pa.Table.from_batches(batches, schema=in_schema)
        gkeys = [in_schema.field(i).name for i in range(n_groups)]

        if n_groups == 0:
            cols = []
            for a in self.aggs:
                if a.func == "avg":
                    s = pc.sum(tbl.column(f"{a.name}#sum")).as_py() or 0.0
                    c = pc.sum(tbl.column(f"{a.name}#count")).as_py() or 0
                    cols.append(pa.array([s / c if c else None], pa.float64()))
                elif a.func in ("count", "count_distinct"):
                    cols.append(_scalar_col(pc.sum(tbl.column(a.name)), pa.int64()))
                elif a.func == "sum":
                    cols.append(
                        _scalar_col(pc.sum(tbl.column(a.name)), self._field_for(a.name).type)
                    )
                elif a.func == "min":
                    cols.append(
                        _scalar_col(pc.min(tbl.column(a.name)), self._field_for(a.name).type)
                    )
                elif a.func == "max":
                    cols.append(
                        _scalar_col(pc.max(tbl.column(a.name)), self._field_for(a.name).type)
                    )
                else:
                    raise ExecutionError(f"unsupported aggregate {a.func}")
            return pa.Table.from_arrays(cols, schema=self._schema)

        agg_requests: list[tuple[str, str]] = []
        for a in self.aggs:
            if a.func == "avg":
                agg_requests.append((f"{a.name}#sum", "sum"))
                agg_requests.append((f"{a.name}#count", "sum"))
            elif a.func in ("count", "count_distinct"):
                agg_requests.append((a.name, "sum"))
            elif a.func == "sum":
                agg_requests.append((a.name, "sum"))
            elif a.func == "min":
                agg_requests.append((a.name, "min"))
            elif a.func == "max":
                agg_requests.append((a.name, "max"))
            else:
                raise ExecutionError(f"unsupported aggregate {a.func}")
        result = pa.TableGroupBy(tbl, gkeys).aggregate(agg_requests)

        out_cols: list = []
        for g in gkeys:
            out_cols.append(result.column(g))
        # merged columns are named "<src>_<func>"
        for a in self.aggs:
            f = self._field_for(a.name)
            if a.func == "avg":
                s = result.column(f"{a.name}#sum_sum")
                c = result.column(f"{a.name}#count_sum")
                col = pc.divide(pc.cast(s, pa.float64()), pc.cast(c, pa.float64()))
            elif a.func in ("count", "count_distinct"):
                col = result.column(f"{a.name}_sum")
            elif a.func == "sum":
                col = result.column(f"{a.name}_sum")
            elif a.func == "min":
                col = result.column(f"{a.name}_min")
            else:
                col = result.column(f"{a.name}_max")
            if not col.type.equals(f.type):
                col = pc.cast(col, f.type, safe=False)
            out_cols.append(col)
        return pa.Table.from_arrays(out_cols, schema=self._schema)


def _resolve_udaf(func: str):
    from ..udf import global_registry

    name = func[5:]  # strip "udaf:"
    u = global_registry().aggregate(name)
    if u is None:
        raise ExecutionError(
            f"aggregate UDF {name!r} is not registered on this executor; "
            f"load it via ballista.plugin_dir"
        )
    return u


def _finalize_corr(result: pa.Table, j: int) -> pa.Array:
    """Pearson r from the six per-group sums (pairwise-valid rows):
    r = (n·Σxy − Σx·Σy) / sqrt((n·Σxx − Σx²)(n·Σyy − Σy²));
    groups with n < 2 or zero variance yield null (pandas semantics)."""
    import numpy as np

    def col(name):
        return result.column(name).to_numpy(zero_copy_only=False).astype(
            np.float64
        )

    sx = col(f"__c{j}x_sum")
    sy = col(f"__c{j}y_sum")
    sxy = col(f"__c{j}xy_sum")
    sxx = col(f"__c{j}xx_sum")
    syy = col(f"__c{j}yy_sum")
    n = col(f"__c{j}x_count")
    with np.errstate(invalid="ignore", divide="ignore"):
        cov = n * sxy - sx * sy
        varx = n * sxx - sx * sx
        vary = n * syy - sy * sy
        r = cov / np.sqrt(varx * vary)
    bad = (n < 2) | ~np.isfinite(r)
    return pa.array(np.where(bad, np.nan, r), pa.float64(), from_pandas=True)


def _apply_udaf(spec: AggSpec, lists_col, out_type: pa.DataType) -> pa.ChunkedArray:
    """Fold each group's collected value-list through the UDAF callable."""
    u = _resolve_udaf(spec.func)
    values = [
        u.fn(lst.values if lst.is_valid else pa.array([], type=u.input_type))
        for lst in lists_col.combine_chunks()
    ]
    return pa.chunked_array([pa.array(values, type=out_type)])


def _scalar_col(s: pa.Scalar, t: pa.DataType) -> pa.Array:
    v = s.as_py()
    return pa.array([v], t)
