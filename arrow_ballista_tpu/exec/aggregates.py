"""Hash aggregation with Partial/Final split.

Counterpart of DataFusion's ``AggregateExec`` with ``AggregateMode`` as
serialized by the reference (``core/proto/ballista.proto:316-320``): the
Partial stage computes per-partition accumulator states, a shuffle hashes
rows by group key, and the Final stage merges states.  This split is exactly
what lets the TPU path reduce partials with ``psum`` across chips
(SURVEY.md §2.5) before the shuffle.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.compute as pc

from ..errors import ExecutionError
from .expressions import PhysicalExpr, _as_array_len
from .operators import ExecutionPlan, Partitioning, TaskContext

PARTIAL = "partial"
FINAL = "final"
SINGLE = "single"


@dataclass(frozen=True)
class AggSpec:
    func: str  # sum | avg | min | max | count | count_distinct
    arg: Optional[PhysicalExpr]  # None for count(*)
    name: str  # output column name
    out_type: pa.DataType

    def state_fields(self) -> list[pa.Field]:
        """Partial-state columns this aggregate contributes."""
        if self.func == "avg":
            return [
                pa.field(f"{self.name}#sum", pa.float64()),
                pa.field(f"{self.name}#count", pa.int64()),
            ]
        if self.func in ("count", "count_distinct"):
            return [pa.field(self.name, pa.int64())]
        if self.func == "sum":
            t = self.out_type
            return [pa.field(self.name, t)]
        return [pa.field(self.name, self.out_type)]  # min / max


class HashAggregateExec(ExecutionPlan):
    def __init__(
        self,
        mode: str,
        group_exprs: list[tuple[PhysicalExpr, str]],
        aggs: list[AggSpec],
        input: ExecutionPlan,
    ):
        super().__init__()
        assert mode in (PARTIAL, FINAL, SINGLE)
        self.mode = mode
        self.group_exprs = group_exprs
        self.aggs = aggs
        self.input = input
        in_schema = input.schema
        gfields = []
        for e, name in group_exprs:
            from .operators import _infer_type

            gfields.append(pa.field(name, _infer_type(e, in_schema), True))
        if mode == PARTIAL:
            afields = [f for a in aggs for f in a.state_fields()]
        else:
            afields = [pa.field(a.name, a.out_type, True) for a in aggs]
        self._schema = pa.schema(gfields + afields)

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_new_children(self, children):
        return HashAggregateExec(self.mode, self.group_exprs, self.aggs, children[0])

    def __str__(self) -> str:
        return (
            f"HashAggregateExec: mode={self.mode}, "
            f"gby=[{', '.join(n for _, n in self.group_exprs)}], "
            f"aggr=[{', '.join(a.name for a in self.aggs)}]"
        )

    # ------------------------------------------------------------ execution
    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        batches = list(self.input.execute(partition, ctx))
        with self.metrics.timer("agg_time_ns"):
            if self.mode == FINAL:
                out = self._execute_final(batches)
            else:
                out = self._execute_partial_or_single(batches)
        self.metrics.add("output_rows", out.num_rows)
        for b in out.to_batches(max_chunksize=ctx.batch_size):
            yield b

    def _prepared_table(self, batches: list[pa.RecordBatch]) -> Optional[pa.Table]:
        """Evaluate group + arg exprs into a flat table g0..gk, a0..am."""
        if not batches:
            return None
        cols: dict[str, pa.ChunkedArray] = {}
        for i, (e, name) in enumerate(self.group_exprs):
            cols[f"__g{i}"] = pa.chunked_array(
                [_as_array_len(e.evaluate(b), b.num_rows) for b in batches]
            )
        for j, a in enumerate(self.aggs):
            if a.arg is not None:
                cols[f"__a{j}"] = pa.chunked_array(
                    [_as_array_len(a.arg.evaluate(b), b.num_rows) for b in batches]
                )
        if not cols:  # count(*) with no groups
            return pa.table({"__dummy": pa.array([0] * sum(b.num_rows for b in batches))})
        return pa.table(cols)

    def _execute_partial_or_single(self, batches: list[pa.RecordBatch]) -> pa.Table:
        tbl = self._prepared_table(batches)
        n_groups = len(self.group_exprs)
        partial = self.mode == PARTIAL

        if tbl is None or tbl.num_rows == 0:
            if n_groups == 0:
                return self._empty_global_result(partial)
            return pa.Table.from_batches([], schema=self._schema)

        if n_groups == 0:
            return self._global_agg(tbl, partial)

        gkeys = [f"__g{i}" for i in range(n_groups)]
        agg_requests: list[tuple[str, str]] = []
        out_names: list[str] = []
        for j, a in enumerate(self.aggs):
            src = f"__a{j}"
            if a.func == "sum":
                agg_requests.append((src, "sum"))
                out_names.append(a.name)
            elif a.func == "avg":
                if partial:
                    agg_requests.append((src, "sum"))
                    out_names.append(f"{a.name}#sum")
                    agg_requests.append((src, "count"))
                    out_names.append(f"{a.name}#count")
                else:
                    agg_requests.append((src, "mean"))
                    out_names.append(a.name)
            elif a.func == "min":
                agg_requests.append((src, "min"))
                out_names.append(a.name)
            elif a.func == "max":
                agg_requests.append((src, "max"))
                out_names.append(a.name)
            elif a.func == "count":
                if a.arg is None:
                    # count(*) counts rows including nulls in the key column
                    agg_requests.append(
                        (gkeys[0], "count", pc.CountOptions(mode="all"))
                    )
                else:
                    agg_requests.append((src, "count"))
                out_names.append(a.name)
            elif a.func == "count_distinct":
                if partial:
                    raise ExecutionError(
                        "count_distinct must run single-stage after key repartition"
                    )
                agg_requests.append((src, "count_distinct"))
                out_names.append(a.name)
            elif a.func.startswith("udaf:"):
                if partial:
                    raise ExecutionError(
                        "UDAFs must run single-stage after key repartition"
                    )
                # collect each group's values; the UDF folds them below
                agg_requests.append((src, "list"))
                out_names.append(a.name)
            else:
                raise ExecutionError(f"unsupported aggregate {a.func}")

        result = pa.TableGroupBy(tbl, gkeys).aggregate(agg_requests)
        # group_by output columns are named "<src>_<func>", keys keep names
        out_cols: list[pa.ChunkedArray] = []
        fields = list(self._schema)
        for i in range(len(self.group_exprs)):
            out_cols.append(result.column(f"__g{i}"))
        udaf_iter = iter(
            [a for a in self.aggs if a.func.startswith("udaf:")]
        )
        for req, f in zip(agg_requests, fields[len(self.group_exprs):]):
            src, func = req[0], req[1]
            col = result.column(f"{src}_{func}")
            if func == "list":
                col = _apply_udaf(next(udaf_iter), col, f.type)
            if not col.type.equals(f.type):
                col = pc.cast(col, f.type, safe=False)
            out_cols.append(col)
        return pa.Table.from_arrays(out_cols, schema=self._schema)

    def _global_agg(self, tbl: pa.Table, partial: bool) -> pa.Table:
        cols: list[pa.Array] = []
        for j, a in enumerate(self.aggs):
            src = tbl.column(f"__a{j}") if a.arg is not None else None
            if a.func == "sum":
                v = pc.sum(src)
                cols.append(_scalar_col(v, self._field_for(a.name).type))
            elif a.func == "avg":
                if partial:
                    cols.append(_scalar_col(pc.sum(src), pa.float64()))
                    cols.append(_scalar_col(pc.count(src), pa.int64()))
                else:
                    cols.append(_scalar_col(pc.mean(src), pa.float64()))
            elif a.func == "min":
                cols.append(_scalar_col(pc.min(src), self._field_for(a.name).type))
            elif a.func == "max":
                cols.append(_scalar_col(pc.max(src), self._field_for(a.name).type))
            elif a.func == "count":
                n = tbl.num_rows if a.arg is None else pc.count(src).as_py()
                cols.append(pa.array([n], pa.int64()))
            elif a.func == "count_distinct":
                cols.append(
                    pa.array([pc.count_distinct(src).as_py()], pa.int64())
                )
            elif a.func.startswith("udaf:"):
                t = self._field_for(a.name).type
                v = _resolve_udaf(a.func).fn(src.combine_chunks())
                cols.append(pa.array([v], type=t))
            else:
                raise ExecutionError(f"unsupported aggregate {a.func}")
        return pa.Table.from_arrays(cols, schema=self._schema)

    def _field_for(self, name: str) -> pa.Field:
        return self._schema.field(name)

    def _empty_global_result(self, partial: bool) -> pa.Table:
        """Zero-row input, no GROUP BY → one row: counts 0, everything else
        NULL (SQL semantics for global aggregates over empty input)."""
        count_fields = set()
        for a in self.aggs:
            if a.func in ("count", "count_distinct"):
                count_fields.add(a.name)
            if a.func == "avg" and partial:
                count_fields.add(f"{a.name}#count")
        cols = []
        for f in self._schema:
            if f.name in count_fields:
                cols.append(pa.array([0], f.type))
            else:
                cols.append(pa.nulls(1, f.type))
        return pa.Table.from_arrays(cols, schema=self._schema)

    def _execute_final(self, batches: list[pa.RecordBatch]) -> pa.Table:
        """Merge partial states (input schema = partial output schema)."""
        n_groups = len(self.group_exprs)
        in_schema = self.input.schema
        if not batches:
            if n_groups == 0:
                return self._empty_global_result(False)
            return pa.Table.from_batches([], schema=self._schema)
        tbl = pa.Table.from_batches(batches, schema=in_schema)
        gkeys = [in_schema.field(i).name for i in range(n_groups)]

        if n_groups == 0:
            cols = []
            for a in self.aggs:
                if a.func == "avg":
                    s = pc.sum(tbl.column(f"{a.name}#sum")).as_py() or 0.0
                    c = pc.sum(tbl.column(f"{a.name}#count")).as_py() or 0
                    cols.append(pa.array([s / c if c else None], pa.float64()))
                elif a.func in ("count", "count_distinct"):
                    cols.append(_scalar_col(pc.sum(tbl.column(a.name)), pa.int64()))
                elif a.func == "sum":
                    cols.append(
                        _scalar_col(pc.sum(tbl.column(a.name)), self._field_for(a.name).type)
                    )
                elif a.func == "min":
                    cols.append(
                        _scalar_col(pc.min(tbl.column(a.name)), self._field_for(a.name).type)
                    )
                elif a.func == "max":
                    cols.append(
                        _scalar_col(pc.max(tbl.column(a.name)), self._field_for(a.name).type)
                    )
                else:
                    raise ExecutionError(f"unsupported aggregate {a.func}")
            return pa.Table.from_arrays(cols, schema=self._schema)

        agg_requests: list[tuple[str, str]] = []
        for a in self.aggs:
            if a.func == "avg":
                agg_requests.append((f"{a.name}#sum", "sum"))
                agg_requests.append((f"{a.name}#count", "sum"))
            elif a.func in ("count", "count_distinct"):
                agg_requests.append((a.name, "sum"))
            elif a.func == "sum":
                agg_requests.append((a.name, "sum"))
            elif a.func == "min":
                agg_requests.append((a.name, "min"))
            elif a.func == "max":
                agg_requests.append((a.name, "max"))
            else:
                raise ExecutionError(f"unsupported aggregate {a.func}")
        result = pa.TableGroupBy(tbl, gkeys).aggregate(agg_requests)

        out_cols: list = []
        for g in gkeys:
            out_cols.append(result.column(g))
        # merged columns are named "<src>_<func>"
        for a in self.aggs:
            f = self._field_for(a.name)
            if a.func == "avg":
                s = result.column(f"{a.name}#sum_sum")
                c = result.column(f"{a.name}#count_sum")
                col = pc.divide(pc.cast(s, pa.float64()), pc.cast(c, pa.float64()))
            elif a.func in ("count", "count_distinct"):
                col = result.column(f"{a.name}_sum")
            elif a.func == "sum":
                col = result.column(f"{a.name}_sum")
            elif a.func == "min":
                col = result.column(f"{a.name}_min")
            else:
                col = result.column(f"{a.name}_max")
            if not col.type.equals(f.type):
                col = pc.cast(col, f.type, safe=False)
            out_cols.append(col)
        return pa.Table.from_arrays(out_cols, schema=self._schema)


def _resolve_udaf(func: str):
    from ..udf import global_registry

    name = func[5:]  # strip "udaf:"
    u = global_registry().aggregate(name)
    if u is None:
        raise ExecutionError(
            f"aggregate UDF {name!r} is not registered on this executor; "
            f"load it via ballista.plugin_dir"
        )
    return u


def _apply_udaf(spec: AggSpec, lists_col, out_type: pa.DataType) -> pa.ChunkedArray:
    """Fold each group's collected value-list through the UDAF callable."""
    u = _resolve_udaf(spec.func)
    values = [
        u.fn(lst.values if lst.is_valid else pa.array([], type=u.input_type))
        for lst in lists_col.combine_chunks()
    ]
    return pa.chunked_array([pa.array(values, type=out_type)])


def _scalar_col(s: pa.Scalar, t: pa.DataType) -> pa.Array:
    v = s.as_py()
    return pa.array([v], t)
