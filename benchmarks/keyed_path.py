"""Keyed device-path A/B: device-encode fusion vs the host-encode baseline.

ISSUE 9's rescue of the keyed plans (BENCH_SUITE_r05: q3 SF10 at 0.036x
CPU with 12.6s of host GroupTable hashing) measured in isolation.  Two
workloads, each run on IDENTICAL inputs across three configurations:

* ``fused``    — ``ballista.tpu.device_encode=true`` + the keyed route:
  raw key columns cross the bridge once, group codes derive on device
  (bit-identical to the host encoders), and encode→packed-u64-sort runs
  as ONE jitted dispatch (``fused_keyed_dispatches``).
* ``baseline`` — ``ballista.tpu.device_encode=false`` + the keyed
  route: the host encodes per batch (``key_encode_time_ns``) and int64
  codes take the multi-operand device sort.  This is the knob A/B the
  acceptance criterion names.
* ``gid``      — ``ballista.tpu.highcard_mode=gid``: the gid-table
  device route whose host ``GroupTable`` hashing was the q3 cost
  center, recorded as a second reference point.

Workloads:

* ``run_keyed_agg_bench`` — q3-shaped keyed aggregate: GROUP BY a
  high-cardinality int64 key plus a date-like and a small int key
  (q3's ``l_orderkey, o_orderdate, o_shippriority`` shape),
  sum/count/min over multiple batches.  Multi-key is where the
  packed-u64 sort earns its keep: the fused path packs three i32 code
  fields + iota into two u64 words, the host-encode baseline sorts
  four i64 operands.
* ``run_keyed_starjoin_bench`` — starjoin shape: PK-FK dim join folded
  into the device stage, GROUP BY the high-cardinality probe key.

Both verify bit-identical results across every leg via a sha-256 row
fingerprint (numpy lexsort canonicalization — no ORDER BY, no pyarrow
sort).  Runs on the CPU JAX backend (CI) and on chip unchanged.

Usage: via ``bench_suite.py keyed`` (measurement) or ``dev/tier1.sh
--bench-smoke`` (tiny-input identity/compile smoke via
:func:`run_keyed_smoke`, NOT a measurement).
"""

from __future__ import annotations

import hashlib
import time

import numpy as np
import pyarrow as pa

BASE = {
    "ballista.tpu.enable": "true",
    "ballista.tpu.min_rows": "0",
    # the A/B isolates the execution path, not the device column cache
    "ballista.tpu.cache_columns": "false",
    # jax 0.4.37 in this image lacks shard_map; mesh stages cannot run
    "ballista.mesh.enable": "false",
    "ballista.shuffle.partitions": "1",
}

LEGS = {
    "fused": {
        "ballista.tpu.highcard_mode": "device",
        "ballista.tpu.device_encode": "true",
    },
    "baseline": {
        "ballista.tpu.highcard_mode": "device",
        "ballista.tpu.device_encode": "false",
    },
    "gid": {
        "ballista.tpu.highcard_mode": "gid",
        "ballista.tpu.device_encode": "false",
    },
}

_METRIC_KEYS = (
    "key_encode_time_ns",
    "device_time_ns",
    "bridge_time_ns",
    "tpu_stage_time_ns",
    "device_encode_batches",
    "fused_keyed_dispatches",
    "keyed_path",
    "keyed_chunks",
    "tpu_fallback",
    "highcard_fallback",
    "join_fallback",
)


def _canon(tbl: pa.Table):
    """Columns canonicalized to one row order via the non-float columns
    (group keys/counts — unique per row here, so the order is total)."""
    cols = [
        np.ascontiguousarray(c.to_numpy(zero_copy_only=False))
        for c in tbl.columns
    ]
    keys = [v for v in cols if v.dtype.kind != "f"]
    order = np.lexsort(tuple(reversed(keys)))
    return [v[order] for v in cols]


def _fingerprint(tbl: pa.Table) -> str:
    """Order-independent sha of the EXACT row bytes (floats included
    bit-for-bit): equal fingerprints mean bit-identical results."""
    h = hashlib.sha256()
    for v in _canon(tbl):
        h.update(v.tobytes())
    return h.hexdigest()[:16]


def _tables_close(a: pa.Table, b: pa.Table, rel: float = 1e-9) -> bool:
    """Non-float columns exactly equal, floats within ``rel`` — for
    comparing against legs whose float REDUCTION ORDER differs (the
    gid-table route), where last-ulp drift is expected and a bitwise
    hash would flap."""
    if a.num_rows != b.num_rows:
        return False
    for va, vb in zip(_canon(a), _canon(b)):
        if va.dtype.kind == "f":
            if not np.allclose(va, vb, rtol=rel, atol=0, equal_nan=True):
                return False
        elif not np.array_equal(va, vb):
            return False
    return True


def _collect_metrics(plan) -> dict:
    from arrow_ballista_tpu.ops.stage_compiler import TpuStageExec

    agg: dict = {}
    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, TpuStageExec):
            for k, v in node.metrics.values.items():
                agg[k] = agg.get(k, 0) + v
        stack.extend(node.children())
    return agg


def _run_leg(tables: dict, sql: str, settings: dict, batch_rows: int,
             iters: int):
    """(best_s, result table, last-iter stage metrics) for one config."""
    from arrow_ballista_tpu import BallistaConfig, SessionContext
    from arrow_ballista_tpu.catalog import MemoryTable

    ctx = SessionContext(
        BallistaConfig({**BASE, "ballista.batch.size": str(batch_rows),
                        **settings})
    )
    for name, t in tables.items():
        ctx.register_table(
            name,
            MemoryTable([t.to_batches(max_chunksize=batch_rows)], t.schema),
        )
    best = None
    out = None
    metrics: dict = {}
    for _ in range(iters):
        plan = ctx.sql(sql).physical_plan()
        t0 = time.perf_counter()
        out = ctx.execute(plan)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
        metrics = _collect_metrics(plan)
    return best, out, {
        k: metrics[k] for k in _METRIC_KEYS if k in metrics
    }


def _ab(tables: dict, sql: str, n_rows: int, metric: str,
        batch_rows: int, iters: int, extra: dict) -> dict:
    times: dict = {}
    outs: dict = {}
    mets: dict = {}
    for leg, settings in LEGS.items():
        times[leg], outs[leg], mets[leg] = _run_leg(
            tables, sql, settings, batch_rows, iters
        )
    # fused vs host-encode keyed share the sort/scan reduction order, so
    # the sha row fingerprints must match EXACTLY (bit-identical); the
    # gid route reduces in a different order, so floats get a 1e-9
    # relative bar instead of a flapping bitwise hash
    identical = _fingerprint(outs["fused"]) == _fingerprint(
        outs["baseline"]
    )
    rec = {
        "metric": metric,
        "value": round(n_rows / times["fused"]),
        "unit": "rows/s",
        # the knob A/B the acceptance names: host-encode keyed baseline
        "vs_baseline": round(times["baseline"] / times["fused"], 3),
        # the gid-table route whose GroupTable hashing was q3's cost
        # center, as a second reference
        "vs_gid_baseline": round(times["gid"] / times["fused"], 3),
        "fused_s": round(times["fused"], 3),
        "baseline_s": round(times["baseline"], 3),
        "gid_s": round(times["gid"], 3),
        "rows": n_rows,
        "identical": identical,
        "matches_gid_1e-9": _tables_close(outs["fused"], outs["gid"]),
        "fused_metrics": mets["fused"],
        "baseline_metrics": mets["baseline"],
        **extra,
    }
    return rec


def run_keyed_agg_bench(
    n_rows: int = 2_000_000,
    n_groups: int = 1_000_000,
    batch_rows: int = 262_144,
    iters: int = 3,
    seed: int = 7,
) -> dict:
    rng = np.random.default_rng(seed)
    k = rng.integers(0, n_groups, n_rows).astype(np.int64)
    t = pa.table(
        {
            "k": pa.array(k),
            # q3 shape: orderdate / shippriority ride along as group
            # keys functionally dependent-ish on the hot key
            "d": pa.array(9000 + (k % 121).astype(np.int64)),
            "p": pa.array((k % 7).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 100, n_rows)),
            "w": pa.array(rng.integers(0, 1000, n_rows).astype(np.int64)),
        }
    )
    sql = (
        "select k, d, p, sum(v) as s, count(*) as c, min(w) as mn "
        "from t group by k, d, p"
    )
    return _ab(
        {"t": t}, sql, n_rows, "keyed_path_rows_per_sec", batch_rows,
        iters, {"groups": n_groups},
    )


def run_keyed_starjoin_bench(
    n_fact: int = 2_000_000,
    n_dim: int = 200_000,
    batch_rows: int = 262_144,
    iters: int = 3,
    seed: int = 11,
) -> dict:
    rng = np.random.default_rng(seed)
    dim = pa.table(
        {
            "dk": pa.array(np.arange(1, n_dim + 1).astype(np.int64)),
            "dv": pa.array(rng.uniform(0.5, 1.5, n_dim)),
        }
    )
    fact = pa.table(
        {
            "fk": pa.array(
                rng.integers(1, int(n_dim * 1.2), n_fact).astype(np.int64)
            ),
            "v": pa.array(rng.uniform(0, 100, n_fact)),
        }
    )
    sql = (
        "select fk, sum(v * dv) as s, count(*) as c "
        "from dim, fact where dk = fk group by fk"
    )
    return _ab(
        {"dim": dim, "fact": fact}, sql, n_fact,
        "keyed_starjoin_rows_per_sec", batch_rows, iters,
        {"dim_rows": n_dim},
    )


def run_keyed_smoke() -> dict:
    """Tiny-input smoke for dev/tier1.sh --bench-smoke: the fused and
    host-encode legs must be BIT-identical, the gid leg must match to
    1e-9, the fused leg must actually device-encode
    (``device_encode_batches`` >= 1, one fused dispatch) and must pay NO
    host group encode.  Shrinks the groups~rows detector (exactly like
    tests/test_keyed_agg.py) so the tiny inputs route keyed on the
    host-encode baseline leg too.  A compile/regression check, not a
    measurement."""
    from arrow_ballista_tpu.ops import stage_compiler as SC

    old = SC._HIGHCARD_MIN_GROUPS
    SC._HIGHCARD_MIN_GROUPS = 1024
    try:
        agg = run_keyed_agg_bench(
            n_rows=30_000, n_groups=6_000, batch_rows=8_192, iters=1
        )
        join = run_keyed_starjoin_bench(
            n_fact=20_000, n_dim=6_000, batch_rows=8_192, iters=1
        )
    finally:
        SC._HIGHCARD_MIN_GROUPS = old
    for rec in (agg, join):
        assert rec["identical"], f"{rec['metric']}: legs diverged"
        assert rec["matches_gid_1e-9"], f"{rec['metric']}: gid diverged"
        assert rec["baseline_metrics"].get("keyed_path", 0) >= 1, (
            "host-encode baseline leg did not route keyed",
            rec["baseline_metrics"],
        )
        fm = rec["fused_metrics"]
        assert fm.get("device_encode_batches", 0) >= 1, fm
        assert fm.get("fused_keyed_dispatches", 0) >= 1, fm
        assert fm.get("key_encode_time_ns", 0) == 0, (
            "fused leg paid a host group encode", fm,
        )
        assert fm.get("tpu_fallback", 0) == 0, fm
    return {
        "keyed_agg_vs_baseline": agg["vs_baseline"],
        "keyed_starjoin_vs_baseline": join["vs_baseline"],
        "device_encode_batches": (
            agg["fused_metrics"]["device_encode_batches"]
            + join["fused_metrics"]["device_encode_batches"]
        ),
        "identical": True,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_keyed_agg_bench()))
    print(json.dumps(run_keyed_starjoin_bench()))
