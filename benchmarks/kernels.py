"""Kernel-level microbenchmarks (VERDICT r3 item 8 — the conbench slot).

Counterpart of the reference's criterion→conbench micro-bench bridge
(``/root/reference/conbench/benchmarks.py:38-46``,
``conbench/_criterion.py``): where the reference benches DataFusion
kernels via cargo-criterion, this grids the TPU segment-reduction
strategies directly — strategy × capacity × rows — plus the host-side
group-encode paths they compete against, emitting one JSON line per
cell.  This is the tuning tool for the ROUTING TABLE
(``dev/analyze_grid.py --emit`` → ``ops/routing_table.json``: the
high-cardinality detector, ``keyed_route_auto``, and the
segment-algorithm bounds ``kernels.segment_algo`` reads).

``keyed_fused`` is the ISSUE-9 production shape — prep (with in-kernel
key encode) and the packed-u64 sort in ONE jitted dispatch — and is
what ``keyed_route_auto`` evidence should come from on a chip capture;
``keyed`` keeps the pre-fusion 3-dispatch form for comparison.

Usage:
    python benchmarks/kernels.py [--rows 1e6,8e6] [--caps 1024,65536,1048576]
        [--algos matmul,scatter,sort,keyed,keyed_fused] [--iters 3]
        [--out FILE]

Timing protocol: the packed device→host fetch is the only reliable sync
on the tunnel-attached TPU, so every timed run ends in one — times
include queue + compute + result fetch, matching the engine's
device_time_ns accounting.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _emit(rec: dict, out_path: str | None) -> None:
    line = json.dumps(rec)
    print(line, flush=True)
    if out_path:
        with open(out_path, "a") as f:
            f.write(line + "\n")


def bench_segment_reduce(rows: int, capacity: int, algo: str, iters: int):
    """One grid cell: fused sum+count segment reduction at (rows, cap)."""
    import jax

    from arrow_ballista_tpu.ops import kernels as K

    mode = K.precision_mode()
    rng = np.random.default_rng(42)
    seg = rng.integers(0, capacity, rows).astype(np.int32)
    v = rng.uniform(0, 100, rows).astype(
        np.float32 if mode == "x32" else np.float64
    )
    valid = np.ones(rows, dtype=bool)
    specs = [K.KernelAggSpec("sum", True), K.KernelAggSpec("count_star", False)]
    flat_names = ["c0", "c0__valid"]
    closures = [lambda env: (env["c0"], env["c0__valid"]), None]

    if algo == "keyed":
        # keys ARE the segment ids: sort + boundary gids + scan + pack
        holder: dict = {}
        prep = jax.jit(
            K.make_keyed_prep_kernel(None, closures, specs, flat_names, holder)
        )
        sortk = K.keyed_sort_kernel(1)
        keys_d = jax.device_put(seg)
        valid_d = jax.device_put(valid)
        v_d = jax.device_put(v)

        def run():
            pre = prep((keys_d,), valid_d, v_d, valid_d)
            mask, key = pre[0], pre[1]
            flat = pre[2:]
            out = sortk(mask, key)
            s2, perm, sk = out[0], out[1], out[2:-1]
            n_groups = int(np.asarray(out[-1]))
            cap2 = max(64, 1 << (max(n_groups, 1) - 1).bit_length())
            finish = K.keyed_finish_kernel(
                holder["kinds"], holder["plan"], specs, 1, cap2, mode
            )
            packed = finish(s2, perm, tuple(sk), tuple(flat))
            return np.asarray(packed)

    elif algo == "keyed_fused":
        # ISSUE-9 production shape: device key encode + prep + packed
        # sort in ONE dispatch, then the capacity-sized finish — the
        # two-dispatch pipeline stage_compiler._keyed_reduce_fused runs
        holder: dict = {}
        prep_raw = K.make_keyed_prep_kernel(
            None, closures, specs, flat_names, holder,
            key_kinds=("ident",),
        )
        sort_body = K.keyed_sort_body(1)

        def fused(keys, valid_a, *args):
            pre = prep_raw(keys, valid_a, *args)
            return pre + sort_body(pre[0], pre[1])

        ffn = jax.jit(fused)
        # raw key values; identity codes (value+1) are the segment
        # ids shifted by one — same cardinality, same sort shape
        keys_d = jax.device_put(seg)
        valid_d = jax.device_put(valid)
        v_d = jax.device_put(v)

        def run():
            outs = ffn(((keys_d, valid_d),), valid_d, v_d, valid_d)
            flat = outs[2:-4]
            s2, perm, sk = outs[-4], outs[-3], (outs[-2],)
            n_groups = int(np.asarray(outs[-1]))
            cap2 = max(64, 1 << (max(n_groups, 1) - 1).bit_length())
            finish = K.keyed_finish_kernel(
                holder["kinds"], holder["plan"], specs, 1, cap2, mode
            )
            packed = finish(s2, perm, sk, tuple(flat))
            return np.asarray(packed)

    else:
        K.set_agg_algorithm(algo)
        try:
            kernel = jax.jit(
                K.make_partial_agg_kernel(
                    None, closures, specs, capacity, flat_names
                )
            )
        finally:
            K.set_agg_algorithm(None)
        seg_d = jax.device_put(seg)
        valid_d = jax.device_put(valid)
        v_d = jax.device_put(v)

        def run():
            K.set_agg_algorithm(algo)
            try:
                out = kernel(seg_d, valid_d, v_d, valid_d)
                packed = K.pack_for_fetch(specs, out, mode)
                return np.asarray(packed)
            finally:
                K.set_agg_algorithm(None)

    run()  # compile + warm
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_host_encode(rows: int, capacity: int, iters: int, strings: bool):
    """Host group-encode the keyed path replaces: GroupTable hash probe +
    factorize (ints) or DictEncoder (strings)."""
    from arrow_ballista_tpu.ops.bridge import DictEncoder
    from arrow_ballista_tpu.ops.groups import GroupTable

    import pyarrow as pa

    rng = np.random.default_rng(42)
    keys = rng.integers(0, capacity, rows)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        if strings:
            arr = pa.array(np.char.add("k", keys.astype("U10")))
            enc = DictEncoder()
            codes = enc.encode(arr)
            gt = GroupTable(1)
            gt.encode([codes])
        else:
            gt = GroupTable(1)
            gt.encode([keys.astype(np.int64)])
        best = min(best, time.perf_counter() - t0)
    return best


def bench_sort_operands(rows: int, n_operands: int, iters: int, u64: bool):
    """Pure lax.sort cost vs operand count — the r05 chip capture showed
    stream-wide multi-operand sorts losing 10-100x (q3 keyed 0.036x, the
    2e7 window sort never returning), and every sort-based path
    (keyed/window/median) carries 2+n_keys operands through each bitonic
    pass.  This family answers whether BYTES MOVED or per-pass overhead
    dominates, i.e. whether packing keys+iota into one u64 operand is
    worth building.  ``u64=True`` benches that packed candidate: one
    u64 key operand (num_keys=1) vs the same total key bits as i32s."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(42)
    if u64:
        ops = (rng.integers(0, 1 << 62, rows, dtype=np.uint64),)
        num_keys = 1
    else:
        ops = tuple(
            rng.integers(0, 1 << 30, rows).astype(np.int32)
            for _ in range(n_operands - 1)
        ) + (np.arange(rows, dtype=np.int32),)  # iota payload
        num_keys = n_operands - 1
    ops_d = tuple(jax.device_put(o) for o in ops)
    fn = jax.jit(lambda *a: jax.lax.sort(a, num_keys=num_keys))

    def run():
        out = fn(*ops_d)
        return np.asarray(out[0][:64])  # tiny fetch: sync without volume

    run()  # compile + warm
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_tunnel_latency(iters: int):
    """Dispatch + fetch round-trip floors (the q6 latency story): time a
    near-no-op jitted call synced by a 1-element fetch, and a chain of K
    dependent dispatches before one fetch — separates per-dispatch from
    per-fetch cost.  Returns (one_dispatch_fetch_s, chained8_fetch_s)."""
    import jax
    import jax.numpy as jnp

    x = jax.device_put(np.arange(1024, dtype=np.float32))
    one = jax.jit(lambda v: (v * 2.0).sum())
    step = jax.jit(lambda v: v * 1.000001)

    def run_one():
        return float(np.asarray(one(x)))

    def run_chain():
        v = x
        for _ in range(8):
            v = step(v)
        return float(np.asarray(v[0]))

    run_one(), run_chain()  # compile + warm
    best1 = best8 = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        run_one()
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_chain()
        best8 = min(best8, time.perf_counter() - t0)
    return best1, best8


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", default="1e6,8e6")
    ap.add_argument("--caps", default="1024,65536,1048576")
    ap.add_argument(
        "--algos", default="matmul,scatter,sort,keyed,keyed_fused"
    )
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--host-encode", action="store_true",
        help="also grid the host GroupTable/DictEncoder encode",
    )
    args = ap.parse_args()

    from benchmarks.device_guard import ensure_device

    platform, err = ensure_device()
    from arrow_ballista_tpu.ops import kernels as K

    base = {
        "device_platform": platform,
        "precision_mode": K.precision_mode(),
    }
    if err:
        base["error"] = err

    rows_list = [int(float(r)) for r in args.rows.split(",")]
    caps = [int(float(c)) for c in args.caps.split(",")]
    algos = args.algos.split(",")
    for rows in rows_list:
        for cap in caps:
            if cap > rows:
                continue
            for algo in algos:
                if (
                    algo == "matmul"
                    and (cap > K._matmul_max_cap()
                         or rows * cap > K._matmul_max_elems())
                ):
                    continue  # outside the strategy's own applicability
                try:
                    s = bench_segment_reduce(rows, cap, algo, args.iters)
                    _emit(
                        dict(
                            base,
                            bench="segment_reduce",
                            algo=algo,
                            rows=rows,
                            capacity=cap,
                            sec=round(s, 6),
                            rows_per_sec=round(rows / s),
                        ),
                        args.out,
                    )
                except Exception as e:  # keep the grid going
                    _emit(
                        dict(
                            base,
                            bench="segment_reduce",
                            algo=algo,
                            rows=rows,
                            capacity=cap,
                            error=str(e)[:200],
                        ),
                        args.out,
                    )
            if args.host_encode:
                for strings in (False, True):
                    s = bench_host_encode(rows, cap, args.iters, strings)
                    _emit(
                        dict(
                            base,
                            bench="host_encode",
                            algo="dict" if strings else "group_table",
                            rows=rows,
                            capacity=cap,
                            sec=round(s, 6),
                            rows_per_sec=round(rows / s),
                        ),
                        args.out,
                    )

    # sort-cost vs operand count + the packed-u64 candidate (r05: every
    # sort-based device path is suspect on the tunnel-attached chip)
    for rows in rows_list:
        for n_ops, u64 in [(2, False), (3, False), (5, False), (1, True)]:
            try:
                s = bench_sort_operands(rows, n_ops, args.iters, u64)
                _emit(
                    dict(
                        base,
                        bench="sort_operands",
                        operands=("u64x1" if u64 else f"i32x{n_ops}"),
                        rows=rows,
                        sec=round(s, 6),
                        rows_per_sec=round(rows / s),
                    ),
                    args.out,
                )
            except Exception as e:
                _emit(
                    dict(
                        base,
                        bench="sort_operands",
                        operands=("u64x1" if u64 else f"i32x{n_ops}"),
                        rows=rows,
                        error=str(e)[:200],
                    ),
                    args.out,
                )

    # dispatch/fetch round-trip floors (the q6 latency story, versioned)
    try:
        one_s, chain8_s = bench_tunnel_latency(max(args.iters, 5))
        _emit(
            dict(base, bench="tunnel_latency", metric="dispatch_plus_fetch",
                 sec=round(one_s, 6)),
            args.out,
        )
        _emit(
            dict(base, bench="tunnel_latency", metric="chained8_plus_fetch",
                 sec=round(chain8_s, 6)),
            args.out,
        )
    except Exception as e:
        _emit(dict(base, bench="tunnel_latency", error=str(e)[:200]), args.out)


if __name__ == "__main__":
    main()
