"""h2o.ai db-benchmark JOIN harness (BASELINE gap: only groupby existed).

Counterpart of the reference's ``benchmarks/db-benchmark/join-datafusion.py``
(VERDICT round-2 missing #6): generates the J1 dataset family — x (n rows)
plus lookup tables small (n/1e6 rows), medium (n/1e3) and big (n) — and
runs the five standard join questions:

  q1 small inner on int (id1)     q2 medium inner on int (id2)
  q3 medium LEFT on int (id2)     q4 medium inner on factor (id5)
  q5 big inner on int (id3)

One JSON line per question (db-benchmark timings shape) plus a summary;
``python -m benchmarks.h2o join --n 1e7``.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np
import pyarrow as pa


def gen_join(n: int, seed: int = 42) -> dict[str, pa.Table]:
    """J1 datasets: x with keys drawn from each lookup table's key space."""
    rng = np.random.default_rng(seed)
    n_small = max(1, n // 1_000_000)
    n_medium = max(1, n // 1_000)
    n_big = n

    def idstr(vals, width):
        return np.char.add("id", np.char.zfill(vals.astype(str), width))

    x = pa.table(
        {
            "id1": pa.array(rng.integers(1, n_small + 1, n), pa.int32()),
            "id2": pa.array(rng.integers(1, n_medium + 1, n), pa.int32()),
            "id3": pa.array(rng.integers(1, n_big + 1, n), pa.int32()),
            "id4": pa.array(
                idstr(rng.integers(1, n_small + 1, n), 3).tolist(), pa.string()
            ),
            "id5": pa.array(
                idstr(rng.integers(1, n_medium + 1, n), 6).tolist(), pa.string()
            ),
            "id6": pa.array(
                idstr(rng.integers(1, n_big + 1, n), 10).tolist(), pa.string()
            ),
            "v1": pa.array(np.round(rng.uniform(0, 100, n), 6)),
        }
    )
    small = pa.table(
        {
            "id1": pa.array(np.arange(1, n_small + 1), pa.int32()),
            "id4": pa.array(
                idstr(np.arange(1, n_small + 1), 3).tolist(), pa.string()
            ),
            "v2": pa.array(np.round(rng.uniform(0, 100, n_small), 6)),
        }
    )
    medium = pa.table(
        {
            "id1": pa.array(
                rng.integers(1, n_small + 1, n_medium), pa.int32()
            ),
            "id2": pa.array(np.arange(1, n_medium + 1), pa.int32()),
            "id4": pa.array(
                idstr(rng.integers(1, n_small + 1, n_medium), 3).tolist(),
                pa.string(),
            ),
            "id5": pa.array(
                idstr(np.arange(1, n_medium + 1), 6).tolist(), pa.string()
            ),
            "v2": pa.array(np.round(rng.uniform(0, 100, n_medium), 6)),
        }
    )
    big = pa.table(
        {
            "id1": pa.array(rng.integers(1, n_small + 1, n_big), pa.int32()),
            "id2": pa.array(rng.integers(1, n_medium + 1, n_big), pa.int32()),
            "id3": pa.array(np.arange(1, n_big + 1), pa.int32()),
            "v2": pa.array(np.round(rng.uniform(0, 100, n_big), 6)),
        }
    )
    return {"x": x, "small": small, "medium": medium, "big": big}


QUESTIONS = [
    ("q1", "small inner on int",
     "select x.id1, x.v1, small.v2 from x inner join small on x.id1 = small.id1"),
    ("q2", "medium inner on int",
     "select x.id2, x.v1, medium.v2 from x inner join medium on x.id2 = medium.id2"),
    ("q3", "medium outer on int",
     "select x.id2, x.v1, medium.v2 from x left join medium on x.id2 = medium.id2"),
    ("q4", "medium inner on factor",
     "select x.id5, x.v1, medium.v2 from x inner join medium on x.id5 = medium.id5"),
    ("q5", "big inner on int",
     "select x.id3, x.v1, big.v2 from x inner join big on x.id3 = big.id3"),
]


def run_join(
    n: int, partitions: int, tpu: bool, iters: int, out=sys.stdout
) -> dict:
    from arrow_ballista_tpu import BallistaConfig, SessionContext
    from arrow_ballista_tpu.catalog import MemoryTable

    t0 = time.perf_counter()
    data = gen_join(n)
    gen_s = time.perf_counter() - t0

    ctx = SessionContext(
        BallistaConfig(
            {
                "ballista.tpu.enable": "true" if tpu else "false",
                "ballista.batch.size": str(1 << 21),
                "ballista.shuffle.partitions": str(partitions),
            }
        )
    )
    for name, tbl in data.items():
        ctx.register_table(name, MemoryTable.from_table(tbl, partitions))

    results = []
    for qid, desc, sql in QUESTIONS:
        times = []
        rows = 0
        chk = None
        for _ in range(iters):
            t0 = time.perf_counter()
            out_tbl = ctx.sql(sql).collect()
            times.append(time.perf_counter() - t0)
            rows = out_tbl.num_rows
            import pyarrow.compute as pc

            chk = round(
                (pc.sum(out_tbl.column("v1")).as_py() or 0)
                + (pc.sum(out_tbl.column("v2")).as_py() or 0),
                3,
            )
        rec = {
            "task": "join",
            "question": f"{qid}: {desc}",
            "data": f"J1_{n:.0e}".replace("+0", ""),
            "time_sec": round(min(times), 4),
            "out_rows": rows,
            "chk": chk,
            "engine": "tpu" if tpu else "cpu",
        }
        results.append(rec)
        print(json.dumps(rec), file=out, flush=True)
    summary = {
        "task": "join",
        "rows": n,
        "engine": "tpu" if tpu else "cpu",
        "gen_sec": round(gen_s, 2),
        "total_sec": round(sum(r["time_sec"] for r in results), 4),
        "questions": len(results),
    }
    print(json.dumps(summary), file=out, flush=True)
    return summary
