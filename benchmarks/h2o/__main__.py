"""h2o.ai db-benchmark harness: ``python -m benchmarks.h2o groupby --n 1e8``.

Counterpart of the reference's ``benchmarks/db-benchmark/groupby-datafusion.py``
(BASELINE.md config #5): generates the G1 dataset (n rows, k groups) and
runs ALL TEN standard groupby questions — sums, means, min/max, counts,
exact medians + stddev (q6), top-2 per group via row_number windows (q8)
and corr² (q9) — emitting one JSON line per question plus a summary line
in the db-benchmark timings shape.

The high-cardinality questions (id3, id6: ~n/k distinct groups) are
exactly the shapes that stress the adaptive segment-capacity growth of
the fused TPU aggregate path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

import numpy as np
import pyarrow as pa

QUESTIONS = [
    ("q1", "sum v1 by id1",
     "select id1, sum(v1) as v1 from x group by id1"),
    ("q2", "sum v1 by id1:id2",
     "select id1, id2, sum(v1) as v1 from x group by id1, id2"),
    ("q3", "sum v1 mean v3 by id3",
     "select id3, sum(v1) as v1, avg(v3) as v3 from x group by id3"),
    ("q4", "mean v1:v3 by id4",
     "select id4, avg(v1) as v1, avg(v2) as v2, avg(v3) as v3 "
     "from x group by id4"),
    ("q5", "sum v1:v3 by id6",
     "select id6, sum(v1) as v1, sum(v2) as v2, sum(v3) as v3 "
     "from x group by id6"),
    ("q6", "median v3 sd v3 by id4 id5",
     "select id4, id5, median(v3) as median_v3, stddev(v3) as sd_v3 "
     "from x group by id4, id5"),
    ("q7", "max v1 - min v2 by id3",
     "select id3, max(v1) - min(v2) as range_v1_v2 from x group by id3"),
    ("q8", "largest two v3 by id6",
     "select id6, largest2_v3 from ("
     "select id6, v3 as largest2_v3, "
     "row_number() over (partition by id6 order by v3 desc) as rn "
     "from x where v3 is not null) sub where rn <= 2"),
    ("q9", "regression v1 v2 by id2 id4",
     "select id2, id4, pow(corr(v1, v2), 2) as r2 from x group by id2, id4"),
    ("q10", "sum v3 count by id1:id6",
     "select id1, id2, id3, id4, id5, id6, sum(v3) as v3, count(*) as cnt "
     "from x group by id1, id2, id3, id4, id5, id6"),
]

SKIPPED: list = []


def gen_groupby(n: int, k: int, nas: int = 0, seed: int = 42) -> pa.Table:
    """G1 dataset: n rows, k low-card groups, n/k high-card groups."""
    rng = np.random.default_rng(seed)
    hi = max(1, n // k)
    id1 = rng.integers(1, k + 1, n)
    id2 = rng.integers(1, k + 1, n)
    id3 = rng.integers(1, hi + 1, n)

    def idstr(vals, width, card):
        # build the CARD distinct strings once, then one vectorized take —
        # np.char formatting of 1e8 rows ran for hours at G1_1e8
        import pyarrow.compute as pc

        dict_strs = pa.array(
            [f"id{str(i).zfill(width)}" for i in range(1, card + 1)],
            pa.string(),
        )
        return pc.take(dict_strs, pa.array((vals - 1).astype(np.int64)))

    tbl = pa.table(
        {
            "id1": idstr(id1, 3, k),
            "id2": idstr(id2, 3, k),
            "id3": idstr(id3, 10, hi),
            "id4": pa.array(rng.integers(1, k + 1, n), pa.int32()),
            "id5": pa.array(rng.integers(1, k + 1, n), pa.int32()),
            "id6": pa.array(rng.integers(1, hi + 1, n), pa.int32()),
            "v1": pa.array(rng.integers(1, 6, n), pa.int32()),
            "v2": pa.array(rng.integers(1, 16, n), pa.int32()),
            "v3": pa.array(np.round(rng.uniform(0, 100, n), 6)),
        }
    )
    return tbl


def run_groupby(
    n: int, k: int, partitions: int, tpu: bool, iters: int, out=sys.stdout
) -> dict:
    from arrow_ballista_tpu import BallistaConfig, SessionContext
    from arrow_ballista_tpu.catalog import MemoryTable

    t0 = time.perf_counter()
    data = gen_groupby(n, k)
    gen_s = time.perf_counter() - t0

    settings = {
        "ballista.tpu.enable": "true" if tpu else "false",
        "ballista.batch.size": str(1 << 21),
        "ballista.shuffle.partitions": str(partitions),
    }
    # A/B hook: route groups~rows aggregates to the keyed device path
    # (auto), the C++ hash aggregate (cpu), or pin the device (device)
    hc = os.environ.get("BENCH_HIGHCARD_MODE")
    if hc:
        settings["ballista.tpu.highcard_mode"] = hc
    ctx = SessionContext(BallistaConfig(settings))
    ctx.register_table("x", MemoryTable.from_table(data, partitions))

    results = []
    for qid, desc, sql in QUESTIONS:
        times = []
        rows = 0
        for _ in range(iters):
            t0 = time.perf_counter()
            out_tbl = ctx.sql(sql).collect()
            times.append(time.perf_counter() - t0)
            rows = out_tbl.num_rows
        rec = {
            "task": "groupby",
            "question": f"{qid}: {desc}",
            "data": f"G1_{n:.0e}_{k}_0_0".replace("+0", ""),
            "time_sec": round(min(times), 4),
            "out_rows": rows,
            "engine": "tpu" if tpu else "cpu",
        }
        results.append(rec)
        print(json.dumps(rec), file=out, flush=True)
    for qid, desc, why in SKIPPED:
        print(
            json.dumps(
                {"task": "groupby", "question": f"{qid}: {desc}", "skipped": why}
            ),
            file=out,
            flush=True,
        )
    summary = {
        "task": "groupby",
        "rows": n,
        "k": k,
        "engine": "tpu" if tpu else "cpu",
        "gen_sec": round(gen_s, 2),
        "total_sec": round(sum(r["time_sec"] for r in results), 4),
        "questions": len(results),
    }
    print(json.dumps(summary), file=out, flush=True)
    return summary


def main() -> None:
    p = argparse.ArgumentParser(prog="benchmarks.h2o")
    sub = p.add_subparsers(dest="cmd", required=True)
    g = sub.add_parser("groupby", help="G1 groupby benchmark")
    g.add_argument("--n", type=float, default=1e7, help="rows (e.g. 1e8)")
    g.add_argument("--k", type=int, default=100, help="group cardinality")
    g.add_argument("--partitions", type=int, default=2)
    g.add_argument("--iters", type=int, default=2)
    g.add_argument(
        "--engine", choices=["tpu", "cpu", "both"], default="both"
    )
    g.add_argument(
        "--jax-platform",
        default="",
        help="force a jax platform (e.g. 'cpu') before backend init — the "
        "config API override works where the JAX_PLATFORMS env var is "
        "pinned by the session",
    )
    j = sub.add_parser("join", help="J1 join benchmark")
    j.add_argument("--n", type=float, default=1e7, help="x rows (e.g. 1e8)")
    j.add_argument("--partitions", type=int, default=2)
    j.add_argument("--iters", type=int, default=2)
    j.add_argument("--engine", choices=["tpu", "cpu", "both"], default="both")
    j.add_argument("--jax-platform", default="")

    args = p.parse_args()

    if getattr(args, "jax_platform", ""):
        import jax

        jax.config.update("jax_platforms", args.jax_platform)

    engines = ["cpu", "tpu"] if args.engine == "both" else [args.engine]
    if args.cmd == "groupby":
        for eng in engines:
            run_groupby(
                int(args.n), args.k, args.partitions, eng == "tpu", args.iters
            )
    elif args.cmd == "join":
        from .join import run_join

        for eng in engines:
            run_join(int(args.n), args.partitions, eng == "tpu", args.iters)


if __name__ == "__main__":
    main()
