"""Elastic executor lifecycle A/B under a bursty open-loop load (ISSUE 17).

Two entry points:

* :func:`run_elastic_bench` — the BENCH_SUITE leg: an open-loop burst of
  identical group-by jobs (fixed arrival schedule, submitted whether or
  not earlier jobs finished — the honest way to measure a system under
  load it does not control) against (a) a FIXED cluster of 2 subprocess
  executors and (b) the same scheduler with the closed-loop autoscaler
  (``min=2, max=4``) on an IDENTICAL schedule.  Per-task service time is
  manufactured with the ``task.run`` delay fault (armed in the executor
  children via ``BALLISTA_FAULTS``), so the workload is slot-bound — the
  regime where capacity actually helps — rather than CPU-bound on the
  bench host.  The record reports per-job latency quantiles, the breathe
  cycle (peak alive executors, scale-out/in journal events), and the
  doctor's ``admission_queued_job`` count per leg; result identity is a
  sha256 multiset over every job's rows.

* :func:`run_autoscaler_smoke` — the tier-1 ``--bench-smoke`` gate: a
  tiny burst against ``min=1``, asserting one scale-out, one drain-based
  scale-in after the idle cooldown, zero failed tasks and the journal
  events (``autoscale_decision``/``executor_launched``/
  ``executor_retired``) present.

Both legs run real subprocess executors through the same
:class:`LocalProcessProvider` (the fixed leg just launches them once and
never again), so executor mechanics are identical and the ONLY variable
is the control loop.
"""

from __future__ import annotations

import hashlib
import tempfile
import threading
import time

import pyarrow as pa

BASE_CONFIG = {
    "ballista.mesh.enable": "false",
    "ballista.tpu.min_rows": "0",
    "ballista.shuffle.partitions": "4",
    "ballista.admission.enabled": "true",
}

SQL = "select g, sum(x) as s, count(x) as n from t group by g"

# fast policy for bench/smoke clusters: decisions in hundreds of ms, not
# the production-default tens of seconds
FAST_POLICY = {
    "ballista.autoscaler.enabled": "true",
    "ballista.autoscaler.scale_out_sustain_seconds": "0.5",
    "ballista.autoscaler.scale_in_idle_seconds": "2",
    "ballista.autoscaler.cooldown_seconds": "1",
    "ballista.autoscaler.launch_timeout_seconds": "60",
}


def _fingerprint(table: pa.Table) -> str:
    rows = sorted(zip(*[c.to_pylist() for c in table.columns]))
    h = hashlib.sha256()
    for row in rows:
        h.update(repr(row).encode())
    return h.hexdigest()


def _quantile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class _Cluster:
    """One leg's scheduler + subprocess executors.  ``max_executors=None``
    means FIXED: launch ``min_executors`` children directly through the
    provider and never touch them again (no autoscaler object at all —
    the knob-off scheduler)."""

    def __init__(
        self,
        min_executors: int,
        max_executors,
        task_delay_ms: int,
        task_slots: int = 2,
    ):
        from arrow_ballista_tpu.config import TaskSchedulingPolicy
        from arrow_ballista_tpu.scheduler.autoscaler import (
            ExecutorSpec,
            LocalProcessProvider,
        )
        from arrow_ballista_tpu.scheduler.standalone import (
            new_standalone_scheduler,
        )

        self.journal_dir = tempfile.mkdtemp(prefix="ballista-burst-journal-")
        env = {}
        if task_delay_ms:
            # service time manufactured INSIDE the executor children: the
            # env-armed task.run delay makes every task slot-bound
            env["BALLISTA_FAULTS"] = f"task.run:-1:delay={task_delay_ms}"
        extra_args = ["--task-isolation", "thread"]
        elastic = max_executors is not None

        def factory(host, port):
            return LocalProcessProvider(
                host, port, task_slots=task_slots,
                env=env, extra_args=extra_args,
            )

        settings = None
        if elastic:
            settings = dict(FAST_POLICY)
            settings["ballista.autoscaler.min_executors"] = str(min_executors)
            settings["ballista.autoscaler.max_executors"] = str(max_executors)
        self.handle = new_standalone_scheduler(
            TaskSchedulingPolicy.PUSH_STAGED,
            event_journal_dir=self.journal_dir,
            speculation_interval_s=0.2,
            autoscaler_settings=settings,
            executor_provider_factory=factory if elastic else None,
        )
        self.server = self.handle.server
        self.provider = None
        if not elastic:
            self.provider = factory(self.handle.host, self.handle.port)
            for i in range(min_executors):
                self.provider.launch(ExecutorSpec(f"fixed-{i}", task_slots))
        self._wait_alive(min_executors)

    def _wait_alive(self, n: int, timeout_s: float = 90.0) -> None:
        em = self.server.state.executor_manager
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(em.get_alive_executors()) >= n:
                return
            time.sleep(0.2)
        raise RuntimeError(
            f"only {len(em.get_alive_executors())} of {n} executor(s) "
            f"registered within {timeout_s:.0f}s"
        )

    def events(self, kind: str):
        return [
            e for e in self.server.state.events.tail(10_000)
            if e.get("kind") == kind
        ]

    def close(self) -> None:
        try:
            self.handle.shutdown()
        finally:
            if self.provider is not None:
                self.provider.close()


def _run_leg(
    elastic: bool,
    n_jobs: int,
    interarrival_s: float,
    task_delay_ms: int,
    n_rows: int,
    min_executors: int = 2,
    max_executors: int = 4,
) -> dict:
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.context import MemoryTable
    from arrow_ballista_tpu.obs.doctor import job_report

    cluster = _Cluster(
        min_executors, max_executors if elastic else None, task_delay_ms
    )
    srv = cluster.server
    peak_alive = min_executors
    try:
        ctx = BallistaContext.remote(
            "127.0.0.1", cluster.handle.port, BallistaConfig(dict(BASE_CONFIG))
        )
        ctx.register_table(
            "t",
            MemoryTable.from_table(
                pa.table(
                    {
                        "g": pa.array([f"g{i % 23}" for i in range(n_rows)]),
                        "x": pa.array(
                            [float(i % 251) for i in range(n_rows)]
                        ),
                    }
                ),
                4,
            ),
        )
        latencies, fingerprints, errors = [], [], []
        lock = threading.Lock()

        def one_job() -> None:
            t0 = time.perf_counter()
            try:
                result = ctx.sql(SQL).collect()
            except Exception as e:  # noqa: BLE001 - recorded, asserted later
                with lock:
                    errors.append(repr(e))
                return
            wall = time.perf_counter() - t0
            with lock:
                latencies.append(wall)
                fingerprints.append(_fingerprint(result))

        threads = []
        t_start = time.perf_counter()
        for i in range(n_jobs):
            # open loop: arrivals follow the schedule, not the completions
            target = t_start + i * interarrival_s
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=one_job, name=f"burst-{i}")
            th.start()
            threads.append(th)
            alive = len(srv.state.executor_manager.get_alive_executors())
            peak_alive = max(peak_alive, alive)
        while any(th.is_alive() for th in threads):
            alive = len(srv.state.executor_manager.get_alive_executors())
            peak_alive = max(peak_alive, alive)
            time.sleep(0.1)
        for th in threads:
            th.join()
        burst_wall = time.perf_counter() - t_start
        srv.drain()

        # per-job diagnosis with the LIVE cluster context — the doctor's
        # admission_queued_job count is the "did users feel the queue"
        # signal the elastic leg must silence
        admission_findings = 0
        task_retries = 0
        resets = 0
        for job_id in sorted(ctx._job_ids):
            detail = srv.state.task_manager.get_job_detail(job_id)
            if detail is None or "stages" not in detail:
                continue
            events = srv.state.events.for_job(job_id)
            report = job_report(
                detail, [], events, cluster=srv.doctor_cluster_context()
            )
            admission_findings += sum(
                1 for f in report["doctor"]
                if f["code"] == "admission_queued_job"
            )
            task_retries += sum(
                r.get("task_retries") or 0 for r in detail["stages"]
            )
            resets += srv.state.task_manager._with_graph(
                job_id, lambda g: sum(g.stage_reset_counts.values())
            ) or 0

        # scale-in back to the floor: wait out the idle window so the
        # breathe cycle completes inside the leg
        if elastic:
            deadline = time.monotonic() + 60
            em = srv.state.executor_manager
            while time.monotonic() < deadline:
                # a draining victim is still "alive" until ExecutorStopped:
                # wait for the whole retire, not just the decision
                if len(em.get_alive_executors()) <= min_executors:
                    break
                time.sleep(0.3)
            # executor_retired is emitted when poll() observes the drained
            # child's exit — a tick or two after ExecutorStopped
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                snap = srv.autoscaler.snapshot()
                if snap["draining"] == 0 and snap["launching"] == 0:
                    break
                time.sleep(0.3)
        latencies.sort()
        return {
            "errors": errors,
            "fingerprints": sorted(fingerprints),
            "latency_p50_s": round(_quantile(latencies, 0.50), 3),
            "latency_p99_s": round(_quantile(latencies, 0.99), 3),
            "latency_max_s": round(max(latencies), 3) if latencies else 0.0,
            "burst_wall_s": round(burst_wall, 3),
            "peak_alive_executors": peak_alive,
            "final_alive_executors": len(
                srv.state.executor_manager.get_alive_executors()
            ),
            "admission_queued_findings": admission_findings,
            "task_retries": task_retries,
            "stage_resets": resets,
            "scale_out_events": len(
                [e for e in cluster.events("autoscale_decision")
                 if e.get("action") == "scale_out"]
            ),
            "scale_in_events": len(
                [e for e in cluster.events("autoscale_decision")
                 if e.get("action") == "scale_in"]
            ),
            "launched_events": len(cluster.events("executor_launched")),
            "retired_events": len(cluster.events("executor_retired")),
        }
    finally:
        try:
            ctx.close()
        except Exception:  # noqa: BLE001
            pass
        cluster.close()


def run_elastic_bench(
    n_jobs: int = 18,
    interarrival_s: float = 0.7,
    task_delay_ms: int = 600,
    n_rows: int = 40_000,
) -> dict:
    """Fixed-2 vs elastic (2→4) on an identical open-loop burst; returns
    the bench record (``metric: elastic_burst_p99_speedup``)."""
    fixed = _run_leg(
        False, n_jobs, interarrival_s, task_delay_ms, n_rows
    )
    elastic = _run_leg(
        True, n_jobs, interarrival_s, task_delay_ms, n_rows
    )
    assert not fixed["errors"], f"fixed leg had job errors: {fixed['errors']}"
    assert not elastic["errors"], (
        f"elastic leg had job errors: {elastic['errors']}"
    )
    assert fixed["fingerprints"] == elastic["fingerprints"], (
        "elastic leg changed the results"
    )
    # the breathe cycle: 2 → >2 → 2
    assert elastic["peak_alive_executors"] > 2, (
        f"cluster never scaled out (peak {elastic['peak_alive_executors']})"
    )
    assert elastic["final_alive_executors"] <= 2, (
        f"cluster never scaled back in "
        f"({elastic['final_alive_executors']} alive at end)"
    )
    # scale-in must be invisible to the work: zero failures, zero recompute
    assert elastic["task_retries"] == 0, (
        f"elastic leg retried {elastic['task_retries']} task(s)"
    )
    assert elastic["stage_resets"] == 0, (
        f"elastic leg recomputed {elastic['stage_resets']} stage(s)"
    )
    # bounded interactive latency: the elastic leg must not be slower
    # (small tolerance: the legs share a host and a clock)
    # the doctor's queue finding quiets down with the autoscaler: fewer
    # jobs feel the admission queue than on the fixed cluster
    assert (
        elastic["admission_queued_findings"]
        < max(1, fixed["admission_queued_findings"])
    ), (
        f"admission_queued findings not reduced: elastic "
        f"{elastic['admission_queued_findings']} vs fixed "
        f"{fixed['admission_queued_findings']}"
    )
    assert elastic["latency_p99_s"] <= fixed["latency_p99_s"] * 1.10, (
        f"elastic p99 {elastic['latency_p99_s']}s worse than fixed "
        f"{fixed['latency_p99_s']}s"
    )
    speedup = (
        fixed["latency_p99_s"] / elastic["latency_p99_s"]
        if elastic["latency_p99_s"]
        else 0.0
    )
    return {
        "metric": "elastic_burst_p99_speedup",
        "value": round(speedup, 3),
        "unit": "x (fixed-2 p99 / elastic p99, identical open-loop burst)",
        "vs_baseline": round(speedup, 3),
        "fixed_p50_s": fixed["latency_p50_s"],
        "fixed_p99_s": fixed["latency_p99_s"],
        "elastic_p50_s": elastic["latency_p50_s"],
        "elastic_p99_s": elastic["latency_p99_s"],
        "peak_alive_executors": elastic["peak_alive_executors"],
        "final_alive_executors": elastic["final_alive_executors"],
        "scale_out_events": elastic["scale_out_events"],
        "scale_in_events": elastic["scale_in_events"],
        "admission_queued_findings_fixed": fixed["admission_queued_findings"],
        "admission_queued_findings_elastic": elastic[
            "admission_queued_findings"
        ],
        "elastic_task_retries": elastic["task_retries"],
        "elastic_stage_resets": elastic["stage_resets"],
        "n_jobs": n_jobs,
        "interarrival_s": interarrival_s,
        "task_delay_ms": task_delay_ms,
    }


def run_autoscaler_smoke(
    n_jobs: int = 4,
    task_delay_ms: int = 300,
    n_rows: int = 8_000,
) -> dict:
    """Tier-1 ``--bench-smoke`` gate: tiny burst against 1 executor —
    one scale-out observed, one drain-based scale-in after the idle
    cooldown, zero failed tasks, journal events present.  Assertions run
    inside; the returned record is informational."""
    leg = _run_leg(
        True, n_jobs, 0.2, task_delay_ms, n_rows,
        min_executors=1, max_executors=2,
    )
    assert not leg["errors"], f"smoke jobs failed: {leg['errors']}"
    assert leg["peak_alive_executors"] >= 2, (
        f"no scale-out observed (peak {leg['peak_alive_executors']})"
    )
    assert leg["final_alive_executors"] <= 1, (
        f"no scale-in observed ({leg['final_alive_executors']} alive)"
    )
    assert leg["scale_out_events"] >= 1, "no scale_out journal decision"
    assert leg["scale_in_events"] >= 1, "no scale_in journal decision"
    assert leg["launched_events"] >= 2, "executor_launched events missing"
    assert leg["retired_events"] >= 1, "executor_retired event missing"
    assert leg["task_retries"] == 0, (
        f"{leg['task_retries']} task(s) retried during the breathe cycle"
    )
    return {
        "breathe_cycle": "1->%d->%d" % (
            leg["peak_alive_executors"], leg["final_alive_executors"]
        ),
        "scale_out_events": leg["scale_out_events"],
        "scale_in_events": leg["scale_in_events"],
        "launched": leg["launched_events"],
        "retired": leg["retired_events"],
        "p99_s": leg["latency_p99_s"],
    }
