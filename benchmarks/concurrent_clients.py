"""Concurrency benchmark: N clients of mixed priority against one
standalone cluster (ISSUE 12 — the bench leg of multi-tenant admission).

Everything the suite measured before ran one job at a time; "millions of
users" means many concurrent queries contending for the same slots, the
way the Flight benchmarking literature measures many parallel DoGets
against one data plane.  Three legs, all over the real gRPC/Flight wire:

* **latency** — a closed-loop batch herd keeping the cluster at >=4x
  slot oversubscription plus an open-loop interactive trickle
  (submission clock independent of completions), measured A/B with
  admission off (FIFO free-for-all) vs on (priority lanes + fair
  release).  Reports p50/p99 job latency per lane, scheduler
  event-loop throughput, failures.  Acceptance: admission-on
  interactive p99 <= 0.5x the admission-off p99 (or admission-off
  failed jobs where admission-on completed them).
* **weighted** — two tenants with weights 2:1, closed-loop saturation;
  completed-job throughput must land within 25% of the 2:1 target.
* **shed** — a burst far past ``max_queued_jobs``: the overflow sheds
  with structured ClusterSaturated errors while every admitted job
  completes — zero non-shed failures.

``run_admission_smoke()`` is the tiny-N CI variant wired into
``dev/tier1.sh --bench-smoke``: saturate 2 slots with 6 jobs from two
weighted pools and assert fair-share ordering, zero failures and
``job_queued`` journal events.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

BASE_SETTINGS = {
    "ballista.tpu.enable": "false",
    "ballista.shuffle.partitions": "2",
    "ballista.client.job_timeout_seconds": "240",
}

# the batch shape is deliberately heavy (high-cardinality group by,
# several aggregates): service time must dominate scheduling overhead
# or the queue never forms and there is nothing to arbitrate
BATCH_SQL = (
    "select g, sum(v) as s, count(v) as c, min(w) as mn, max(w) as mx, "
    "avg(v) as av from big group by g"
)
INTERACTIVE_SQL = "select g, sum(v) as s from small group by g"
# the weighted leg wants MANY completions (the 2:1 ratio is measured in
# whole jobs), so it runs a lighter single-aggregate shape
WEIGHTED_SQL = "select g, sum(v) as s from big group by g"


def _gen_data(root: str, batch_rows: int, interactive_rows: int) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    for name, rows in (("big", batch_rows), ("small", interactive_rows)):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        n_parts = 2
        per = rows // n_parts
        cardinality = max(2, min(500_000, rows // 3))
        for i in range(n_parts):
            tbl = pa.table(
                {
                    "g": pa.array(
                        rng.integers(0, cardinality, size=per), pa.int64()
                    ),
                    "v": pa.array(rng.random(per), pa.float64()),
                    "w": pa.array(rng.random(per), pa.float64()),
                }
            )
            pq.write_table(tbl, os.path.join(d, f"part-{i}.parquet"))


def _make_cluster(slots: int, journal_dir: str = ""):
    from arrow_ballista_tpu.client import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig

    return BallistaContext.standalone(
        config=BallistaConfig(dict(BASE_SETTINGS)),
        num_executors=1,
        concurrent_tasks=slots,
        event_journal_dir=journal_dir,
    )


def _remote(primary, settings: Dict[str, str], data_dir: str):
    """A fresh client session against the primary's scheduler, with the
    bench tables registered client-side."""
    from arrow_ballista_tpu.client import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig

    ctx = BallistaContext.remote(
        primary.host, primary.port,
        BallistaConfig({**BASE_SETTINGS, **settings}),
    )
    ctx.register_parquet("big", os.path.join(data_dir, "big"))
    ctx.register_parquet("small", os.path.join(data_dir, "small"))
    return ctx


class _LaneResults:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: Dict[str, List[float]] = {}
        self.failures: Dict[str, List[str]] = {}

    def record(self, lane: str, latency_s: float, error: Optional[str]):
        with self.lock:
            if error is None:
                self.latencies.setdefault(lane, []).append(latency_s)
            else:
                self.failures.setdefault(lane, []).append(error)

    def pct(self, lane: str, q: float) -> float:
        vals = sorted(self.latencies.get(lane, []))
        if not vals:
            return float("nan")
        idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
        return vals[idx]


def _submit_closed_loop(
    ctx, sql: str, lane: str, results: _LaneResults, duration_s: float,
    timeout_s: float,
) -> int:
    """One closed-loop client: submit, wait, repeat — keeps exactly one
    job in flight, the standard sustained-background-load generator."""
    plan = ctx.sql(sql).logical_plan()
    t_end = time.monotonic() + duration_s
    n = 0
    while time.monotonic() < t_end:
        t0 = time.monotonic()
        try:
            job_id = ctx.execute_logical_plan(plan)
            ctx.wait_for_job(job_id, timeout_s=timeout_s)
            results.record(lane, time.monotonic() - t0, None)
            n += 1
        except Exception as e:  # noqa: BLE001
            results.record(lane, time.monotonic() - t0, str(e))
    return n


def _submit_open_loop(
    ctx, sql: str, lane: str, results: _LaneResults,
    interval_s: float, duration_s: float, waiters: List[threading.Thread],
    timeout_s: float,
) -> int:
    """One open-loop client: submit on a fixed clock regardless of
    completions; a waiter thread per job observes its terminal state so
    latency is measured at completion, not at collection time."""
    plan = ctx.sql(sql).logical_plan()
    t_end = time.monotonic() + duration_s
    n = 0
    while True:
        tick = time.monotonic()
        if tick >= t_end:
            break
        t0 = time.monotonic()
        try:
            job_id = ctx.execute_logical_plan(plan)
        except Exception as e:  # noqa: BLE001 - submission refused counts too
            results.record(lane, time.monotonic() - t0, f"submit: {e}")
            job_id = None
        if job_id:
            n += 1

            def wait(job_id=job_id, t0=t0):
                try:
                    ctx.wait_for_job(job_id, timeout_s=timeout_s)
                    results.record(lane, time.monotonic() - t0, None)
                except Exception as e:  # noqa: BLE001
                    results.record(lane, time.monotonic() - t0, str(e))

            w = threading.Thread(target=wait, daemon=True)
            w.start()
            waiters.append(w)
        sleep = interval_s - (time.monotonic() - tick)
        if sleep > 0:
            time.sleep(sleep)
    return n


def _event_loop_stats(primary) -> Dict[str, float]:
    server = primary._standalone_handles[0].server
    snap = server.state.metrics.snapshot()
    hist = snap.get("scheduler_event_handle_seconds") or {}
    return {
        "events_total": float(snap.get("scheduler_events_total", 0)),
        "handle_sum_s": float(hist.get("sum", 0.0)),
        "handle_count": float(hist.get("count", 0)),
    }


def _run_latency_leg(
    admission: bool,
    slots: int,
    batch_clients: int,
    interactive_clients: int,
    duration_s: float,
    interactive_interval_s: float,
    data_dir: str,
) -> dict:
    primary = _make_cluster(slots)
    try:
        adm = {"ballista.admission.enabled": "true"} if admission else {}
        batch_ctxs = [
            _remote(primary, {**adm, "ballista.tenant.id": "batch"}, data_dir)
            for _ in range(batch_clients)
        ]
        inter_ctxs = [
            _remote(
                primary,
                {
                    **adm,
                    "ballista.tenant.id": "interactive",
                    **(
                        {"ballista.tenant.priority": "interactive"}
                        if admission
                        else {}
                    ),
                },
                data_dir,
            )
            for _ in range(interactive_clients)
        ]
        results = _LaneResults()
        waiters: List[threading.Thread] = []
        ev0 = _event_loop_stats(primary)
        t0 = time.monotonic()
        # batch: closed-loop herd (one job each always in flight —
        # sustained oversubscription); interactive: open-loop trickle
        # (arrival clock independent of completions)
        clients = [
            threading.Thread(
                target=_submit_closed_loop,
                args=(ctx, BATCH_SQL, "batch", results, duration_s, 240.0),
            )
            for ctx in batch_ctxs
        ] + [
            threading.Thread(
                target=_submit_open_loop,
                args=(ctx, INTERACTIVE_SQL, "interactive", results,
                      interactive_interval_s, duration_s, waiters, 240.0),
            )
            for ctx in inter_ctxs
        ]
        for c in clients:
            c.start()
        for c in clients:
            c.join()
        for w in list(waiters):
            w.join(300)
        wall = time.monotonic() - t0
        ev1 = _event_loop_stats(primary)
        events = ev1["events_total"] - ev0["events_total"]
        out = {
            "admission": admission,
            "wall_s": round(wall, 2),
            "scheduler_events_per_sec": round(events / max(wall, 1e-9), 1),
            "failures": {
                lane: len(errs) for lane, errs in results.failures.items()
            },
        }
        for lane in ("interactive", "batch"):
            out[f"{lane}_jobs"] = len(results.latencies.get(lane, []))
            out[f"{lane}_p50_s"] = round(results.pct(lane, 0.50), 3)
            out[f"{lane}_p99_s"] = round(results.pct(lane, 0.99), 3)
        for ctx in batch_ctxs + inter_ctxs:
            ctx._standalone_handles = None  # only the primary owns the cluster
            ctx.close()
        return out
    finally:
        primary.close()


def run_latency_ab(
    slots: int = 2,
    batch_clients: int = 8,
    interactive_clients: int = 2,
    duration_s: float = 12.0,
    data_dir: Optional[str] = None,
    batch_rows: int = 1_500_000,
    interactive_rows: int = 2_000,
) -> dict:
    """The A/B latency leg at >= 4x slot oversubscription (default:
    10 clients against 2 slots — 8 closed-loop batch + 2 open-loop
    interactive)."""
    own = data_dir is None
    if own:
        data_dir = tempfile.mkdtemp(prefix="abt-conc-")
        _gen_data(data_dir, batch_rows, interactive_rows)
    kw = dict(
        slots=slots,
        batch_clients=batch_clients,
        interactive_clients=interactive_clients,
        duration_s=duration_s,
        interactive_interval_s=1.0,
        data_dir=data_dir,
    )
    off = _run_latency_leg(admission=False, **kw)
    on = _run_latency_leg(admission=True, **kw)
    off_p99 = off["interactive_p99_s"]
    on_p99 = on["interactive_p99_s"]
    off_failed = sum(off["failures"].values())
    on_failed = sum(on["failures"].values())
    accepted = bool(
        (on_p99 == on_p99 and off_p99 == off_p99 and on_p99 <= 0.5 * off_p99)
        or (off_failed > 0 and on_failed == 0)
    )
    return {
        "metric": "concurrent_interactive_p99_s",
        "value": on_p99,
        "unit": "s",
        "vs_baseline": round(off_p99 / on_p99, 3) if on_p99 else None,
        "oversubscription_x": round(
            (batch_clients + interactive_clients) / slots, 1
        ),
        "admission_on": on,
        "admission_off": off,
        "accepted": accepted,
    }


def run_weighted_leg(
    slots: int = 2,
    workers_per_pool: int = 4,
    duration_s: float = 12.0,
    data_dir: Optional[str] = None,
) -> dict:
    """Two tenants, weights 2:1, closed-loop saturation: completed-job
    throughput must land within 25% of the 2:1 target.  The admission
    gate is pinned to one running job so completions track the
    deficit-weighted release order exactly (enough workers per pool
    keep both queues non-empty throughout)."""
    own = data_dir is None
    if own:
        data_dir = tempfile.mkdtemp(prefix="abt-conc-")
        _gen_data(data_dir, 60_000, 2_000)
    primary = _make_cluster(slots)
    try:
        completed = {"a": 0, "b": 0}
        lock = threading.Lock()
        stop = time.monotonic() + duration_s

        def worker(pool: str, weight: str):
            ctx = _remote(
                primary,
                {
                    "ballista.admission.enabled": "true",
                    "ballista.admission.max_running_jobs": "1",
                    "ballista.tenant.id": pool,
                    "ballista.tenant.weight": weight,
                },
                data_dir,
            )
            plan = ctx.sql(WEIGHTED_SQL).logical_plan()
            while time.monotonic() < stop:
                try:
                    job_id = ctx.execute_logical_plan(plan)
                    ctx.wait_for_job(job_id, timeout_s=240)
                except Exception:  # noqa: BLE001 - counted as non-completion
                    continue
                with lock:
                    completed[pool] += 1

        threads = [
            threading.Thread(target=worker, args=(pool, weight))
            for pool, weight in (("a", "2"), ("b", "1"))
            for _ in range(workers_per_pool)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        a, b = completed["a"], completed["b"]
        ratio = a / b if b else float("inf")
        return {
            "metric": "concurrent_weighted_throughput_ratio",
            "value": round(ratio, 3),
            "unit": "a:b completions (weights 2:1)",
            "completed_a": a,
            "completed_b": b,
            "target": 2.0,
            # within 25% of the 2:1 target
            "accepted": bool(b and 1.5 <= ratio <= 2.5),
        }
    finally:
        primary.close()


def run_shed_leg(
    slots: int = 2,
    burst: int = 12,
    max_queued: int = 3,
    data_dir: Optional[str] = None,
) -> dict:
    """Burst far past max_queued_jobs: the overflow sheds with
    structured ClusterSaturated errors, every admitted job completes,
    zero non-shed failures."""
    own = data_dir is None
    if own:
        data_dir = tempfile.mkdtemp(prefix="abt-conc-")
        _gen_data(data_dir, 60_000, 2_000)
    primary = _make_cluster(slots)
    try:
        ctx = _remote(
            primary,
            {
                "ballista.admission.enabled": "true",
                "ballista.admission.max_running_jobs": "1",
                "ballista.admission.max_queued_jobs": str(max_queued),
            },
            data_dir,
        )
        plan = ctx.sql(BATCH_SQL).logical_plan()
        outcomes: List[str] = []
        lock = threading.Lock()

        def one():
            try:
                job_id = ctx.execute_logical_plan(plan)
                ctx.wait_for_job(job_id, timeout_s=240)
                result = "completed"
            except Exception as e:  # noqa: BLE001
                result = (
                    "shed" if "ClusterSaturated" in str(e) else f"failed: {e}"
                )
            with lock:
                outcomes.append(result)

        threads = [threading.Thread(target=one) for _ in range(burst)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        done = outcomes.count("completed")
        shed = outcomes.count("shed")
        other = [o for o in outcomes if o not in ("completed", "shed")]
        return {
            "metric": "concurrent_shed_jobs",
            "value": shed,
            "unit": "jobs shed of %d burst" % burst,
            "completed": done,
            "non_shed_failures": len(other),
            "non_shed_failure_samples": other[:3],
            # graceful degradation: overflow sheds, admitted work all
            # completes, nothing fails for any other reason
            "accepted": bool(shed > 0 and done > 0 and not other),
        }
    finally:
        primary.close()


def run_concurrency_bench(**kw) -> List[dict]:
    """All three legs on one shared data set (the bench_suite entry)."""
    data_dir = tempfile.mkdtemp(prefix="abt-conc-")
    _gen_data(
        data_dir,
        int(os.environ.get("BENCH_CONC_BATCH_ROWS", "1500000")),
        int(os.environ.get("BENCH_CONC_INTERACTIVE_ROWS", "2000")),
    )
    duration = float(os.environ.get("BENCH_CONC_DURATION_S", "12"))
    return [
        run_latency_ab(duration_s=duration, data_dir=data_dir, **kw),
        run_weighted_leg(duration_s=duration, data_dir=data_dir),
        run_shed_leg(data_dir=data_dir),
    ]


def run_admission_smoke() -> dict:
    """Tiny-N CI smoke (dev/tier1.sh --bench-smoke): saturate 2 slots
    with 6 jobs from two weighted pools; assert fair-share ordering,
    zero failures and job_queued journal events."""
    data_dir = tempfile.mkdtemp(prefix="abt-adm-smoke-")
    _gen_data(data_dir, 24_000, 2_000)
    journal_dir = tempfile.mkdtemp(prefix="abt-adm-smoke-journal-")
    primary = _make_cluster(slots=2, journal_dir=journal_dir)
    try:
        ctx_a = _remote(
            primary,
            {
                "ballista.admission.enabled": "true",
                "ballista.admission.max_running_jobs": "1",
                "ballista.tenant.id": "a",
                "ballista.tenant.weight": "2",
            },
            data_dir,
        )
        ctx_b = _remote(
            primary,
            {
                "ballista.admission.enabled": "true",
                "ballista.admission.max_running_jobs": "1",
                "ballista.tenant.id": "b",
                "ballista.tenant.weight": "1",
            },
            data_dir,
        )
        outcomes: List[str] = []
        lock = threading.Lock()

        def one(ctx):
            plan = ctx.sql(BATCH_SQL).logical_plan()
            try:
                job_id = ctx.execute_logical_plan(plan)
                ctx.wait_for_job(job_id, timeout_s=240)
                result = "completed"
            except Exception as e:  # noqa: BLE001
                result = f"failed: {e}"
            with lock:
                outcomes.append(result)

        threads = [
            threading.Thread(target=one, args=(ctx,))
            for ctx in ([ctx_a] * 4 + [ctx_b] * 2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert outcomes.count("completed") == 6, outcomes
        journal = primary._standalone_handles[0].server.state.events
        queued = journal.tail(1000, kind="job_queued")
        admitted = journal.tail(1000, kind="job_admitted")
        # max_running_jobs=1: at least 5 of the 6 burst jobs queued
        assert len(queued) >= 5, queued
        assert len(admitted) == len(queued), (queued, admitted)
        by_pool = {"a": 0, "b": 0}
        for e in admitted:
            by_pool[e["pool"]] = by_pool.get(e["pool"], 0) + 1
        # fair share: every submitted job of both pools was admitted,
        # and the weight-1 pool was not starved behind the weight-2
        # pool's whole backlog (DRR interleaves it into the first three
        # releases whenever both pools had work queued)
        assert by_pool["a"] == 4 and by_pool["b"] == 2, admitted
        first_b = next(
            i for i, e in enumerate(admitted) if e["pool"] == "b"
        )
        assert first_b <= 3, [e["pool"] for e in admitted]
        snapshot = primary._standalone_handles[0].server.state.admission.snapshot()
        return {
            "jobs": 6,
            "completed": outcomes.count("completed"),
            "queued_events": len(queued),
            "admitted_by_pool": by_pool,
            "first_b_admission_index": first_b,
            "pools": sorted(snapshot["pools"]),
        }
    finally:
        primary.close()


if __name__ == "__main__":
    import json

    for rec in run_concurrency_bench():
        print(json.dumps(rec))
