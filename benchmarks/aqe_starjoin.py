"""AQE benchmark legs: skewed star join + tiny-partition aggregate.

Two workloads on a real standalone cluster (scheduler + executors over
gRPC/Flight), each run twice on IDENTICAL inputs — ``ballista.aqe.
enabled=false`` (static plans, the A/B baseline) vs ``true`` — so the
emitted ``vs_baseline`` isolates exactly the re-planning effect:

* ``run_aqe_starjoin`` — a fact table whose join key is heavily skewed
  (a tunable fraction of all rows share one hot key) joined against a
  small dim and aggregated.  Static plans serialize the hot reduce
  partition into one straggler task (BENCH_SUITE_r05's starjoin at
  0.592x vs CPU is exactly this shape).  The ``on`` config is the full
  production policy with skew splitting opted in — default-on
  coalescing packs the many near-empty reduce partitions (usually the
  bigger win at bench scale) and skew splitting spreads the hot
  partition's map-side fragments across tasks; the emitted record
  carries the most-rewritten stage's task counts plus a separate
  ``skew_splits`` count so the two rewrites stay distinguishable.
* ``run_aqe_tiny_agg`` — a small group-by shuffled over many reduce
  partitions; AQE coalescing collapses the reduce side to
  ceil(total_bytes / target_partition_bytes) tasks.

Both verify bit-identical results between the two runs (multiset of
rows) and report the before/after reduce-task counts read from the
job's AQE stage summary, so ``dev/bench_report.py`` can render the
plan-shape trajectory.

Usage: via ``bench_suite.py aqe`` (measurement) or ``dev/tier1.sh
--bench-smoke`` (tiny-input compile/regression smoke via
:func:`run_aqe_smoke`, NOT a measurement).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

BASE = {
    "ballista.tpu.enable": "false",
    # jax 0.4.37 in this image lacks shard_map; mesh stages cannot run
    "ballista.mesh.enable": "false",
}


def _write_parts(table: pa.Table, d: str, n_parts: int) -> None:
    os.makedirs(d, exist_ok=True)
    per = (table.num_rows + n_parts - 1) // n_parts
    for i in range(n_parts):
        pq.write_table(table.slice(i * per, per), os.path.join(d, f"p{i}.parquet"))


def _gen_star(root: str, n_fact: int, n_dim: int, skew: float, seed: int = 7):
    rng = np.random.default_rng(seed)
    hot = np.where(
        rng.random(n_fact) < skew, 0, rng.integers(0, n_dim, n_fact)
    ).astype(np.int64)
    fact = pa.table(
        {
            "k": hot,
            "v": rng.random(n_fact),
            "g": pa.array((np.arange(n_fact) % 13).astype(np.int64)),
        }
    )
    dim = pa.table(
        {
            "k": pa.array(np.arange(n_dim, dtype=np.int64)),
            "w": pa.array([f"w{i % 29}" for i in range(n_dim)]),
        }
    )
    fact_dir, dim_dir = os.path.join(root, "fact"), os.path.join(root, "dim")
    _write_parts(fact, fact_dir, 4)
    _write_parts(dim, dim_dir, 1)
    return fact_dir, dim_dir


def _rows_fingerprint(tbl: pa.Table) -> str:
    import hashlib

    rows = sorted(
        tuple(round(x, 9) if isinstance(x, float) else x for x in r)
        for r in zip(*[c.to_pylist() for c in tbl.columns])
    )
    return hashlib.sha256(repr(rows).encode()).hexdigest()[:16]


def _run_once(
    tables: dict,
    sql: str,
    settings: dict,
    executors: int,
    slots: int,
):
    """One clustered run; returns (elapsed_s, result table, aqe summary
    of the most-rewritten stage or None)."""
    from arrow_ballista_tpu.client import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig

    ctx = BallistaContext.standalone(
        config=BallistaConfig(settings),
        num_executors=executors,
        concurrent_tasks=slots,
    )
    try:
        for name, path in tables.items():
            ctx.register_parquet(name, path)
        t0 = time.perf_counter()
        out = ctx.sql(sql).collect()
        elapsed = time.perf_counter() - t0
        sched, _ = ctx._standalone_handles
        detail = sched.server.state.task_manager.get_job_detail(
            next(iter(ctx._job_ids))
        )
        aqe = [
            row["aqe"] for row in detail.get("stages", []) if row.get("aqe")
        ]
        return elapsed, out, aqe
    finally:
        ctx.close()


def _ab(tables, sql, on_settings, off_settings, executors, slots, iters):
    """A/B the two configs; best-of-``iters`` wall time each."""
    best_off = best_on = None
    fp_off = fp_on = None
    aqe = None
    for _ in range(iters):
        t, out, _ = _run_once(tables, sql, off_settings, executors, slots)
        best_off = t if best_off is None else min(best_off, t)
        fp_off = _rows_fingerprint(out)
    for _ in range(iters):
        t, out, info = _run_once(tables, sql, on_settings, executors, slots)
        best_on = t if best_on is None else min(best_on, t)
        fp_on = _rows_fingerprint(out)
        aqe = info or aqe
    return best_off, best_on, fp_off == fp_on, aqe


def run_aqe_starjoin(
    n_fact: int = 300_000,
    n_dim: int = 2_000,
    skew: float = 0.5,
    partitions: int = 24,
    executors: int = 2,
    slots: int = 2,
    iters: int = 2,
    data_dir: str | None = None,
) -> dict:
    root = data_dir or tempfile.mkdtemp(prefix="aqe-starjoin-")
    made = data_dir is None
    try:
        fact_dir, dim_dir = _gen_star(root, n_fact, n_dim, skew)
        sql = (
            "select d.w, sum(f.v) as s, count(*) as c "
            "from fact f join dim d on f.k = d.k group by d.w"
        )
        common = {**BASE, "ballista.shuffle.partitions": str(partitions)}
        on = {
            **common,
            "ballista.aqe.enabled": "true",
            "ballista.aqe.skew_enabled": "true",
            "ballista.aqe.skew_factor": "2.0",
            # the hot partition should split well below the default
            # 16 MiB on bench-sized inputs
            "ballista.aqe.target_partition_bytes": str(256 << 10),
        }
        off = {**common, "ballista.aqe.enabled": "false"}
        t_off, t_on, identical, aqe = _ab(
            {"fact": fact_dir, "dim": dim_dir}, sql, on, off,
            executors, slots, iters,
        )
        out = {
            "metric": "aqe_starjoin_rows_per_sec",
            "value": round(n_fact / t_on),
            "unit": "rows/sec",
            "vs_baseline": round(t_off / t_on, 3),
            "baseline_s": round(t_off, 3),
            "aqe_s": round(t_on, 3),
            "rows": n_fact,
            "skew": skew,
            "identical": identical,
        }
        if aqe:
            top = max(
                aqe,
                key=lambda i: abs(i["tasks_after"] - i["tasks_before"]),
            )
            out["tasks_before"] = top["tasks_before"]
            out["tasks_after"] = top["tasks_after"]
            # most of the task-count delta above is coalescing; report
            # the split rewrite separately so it isn't conflated
            splits = sum(i.get("skew_splits", 0) for i in aqe)
            if splits:
                out["skew_splits"] = splits
                out["skewed_partitions"] = sum(
                    i.get("skewed_partitions", 0) for i in aqe
                )
        return out
    finally:
        if made:
            shutil.rmtree(root, ignore_errors=True)


def run_aqe_tiny_agg(
    n_rows: int = 60_000,
    partitions: int = 64,
    executors: int = 2,
    slots: int = 2,
    iters: int = 2,
    data_dir: str | None = None,
) -> dict:
    root = data_dir or tempfile.mkdtemp(prefix="aqe-tinyagg-")
    made = data_dir is None
    try:
        rng = np.random.default_rng(3)
        tbl = pa.table(
            {
                "g": pa.array(rng.integers(0, 500, n_rows).astype(np.int64)),
                "v": rng.random(n_rows),
            }
        )
        td = os.path.join(root, "t")
        _write_parts(tbl, td, 2)
        sql = "select g, sum(v) as s, count(*) as c from t group by g"
        common = {**BASE, "ballista.shuffle.partitions": str(partitions)}
        on = {**common, "ballista.aqe.enabled": "true"}
        off = {**common, "ballista.aqe.enabled": "false"}
        t_off, t_on, identical, aqe = _ab(
            {"t": td}, sql, on, off, executors, slots, iters
        )
        out = {
            "metric": "aqe_tiny_agg_rows_per_sec",
            "value": round(n_rows / t_on),
            "unit": "rows/sec",
            "vs_baseline": round(t_off / t_on, 3),
            "baseline_s": round(t_off, 3),
            "aqe_s": round(t_on, 3),
            "rows": n_rows,
            "identical": identical,
        }
        if aqe:
            top = max(
                aqe,
                key=lambda i: abs(i["tasks_after"] - i["tasks_before"]),
            )
            out["tasks_before"] = top["tasks_before"]
            out["tasks_after"] = top["tasks_after"]
        return out
    finally:
        if made:
            shutil.rmtree(root, ignore_errors=True)


def run_aqe_smoke() -> dict:
    """Tiny-input smoke for dev/tier1.sh --bench-smoke: both legs must
    produce IDENTICAL results with and without AQE and at least one
    replan must fire.  A compile/regression check, not a measurement."""
    star = run_aqe_starjoin(
        n_fact=20_000, n_dim=200, partitions=12, executors=1, slots=2,
        iters=1,
    )
    agg = run_aqe_tiny_agg(
        n_rows=8_000, partitions=16, executors=1, slots=2, iters=1
    )
    assert star["identical"], "AQE starjoin results diverged from static"
    assert agg["identical"], "AQE tiny-agg results diverged from static"
    assert agg.get("tasks_after", 99) < agg.get("tasks_before", 0), (
        "tiny-partition aggregate did not coalesce"
    )
    return {
        "starjoin_vs_baseline": star["vs_baseline"],
        "starjoin_tasks": f"{star.get('tasks_before')}→{star.get('tasks_after')}",
        "tiny_agg_vs_baseline": agg["vs_baseline"],
        "tiny_agg_tasks": f"{agg.get('tasks_before')}→{agg.get('tasks_after')}",
        "identical": True,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_aqe_starjoin()))
    print(json.dumps(run_aqe_tiny_agg()))
