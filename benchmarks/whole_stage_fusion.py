"""Whole-stage fusion A/B: one jitted dispatch per map task vs the
per-batch dispatch sequence (ISSUE 19).

Two workloads, each run on IDENTICAL inputs across two configurations:

* ``fused``  — ``ballista.tpu.whole_stage_fusion=true``: the fusion
  planner (``ops/fusion.py``) walks the stage's operator list, finds no
  cut, and ``_run_fused`` executes every retained batch's kernel, the
  cross-batch combine tree and the state pack as ONE ``_timed_jit``
  dispatch (``fused_dispatches == 1`` per task).
* ``per_op`` — knob off: today's sequence, one kernel dispatch + one
  combine per batch, then the separate pack/fetch.  This is the knob
  A/B the acceptance criterion names.

``ballista.tpu.cache_columns=false`` keeps both legs off the
device-resident result cache (whose retained path was already fused for
cache-ELIGIBLE stages) so the A/B isolates exactly what ISSUE 19
generalizes: whole-stage fusion for ordinary, non-cacheable map stages.

Workloads:

* ``run_fusion_q3_bench`` — q3's map-stage shape: scan → date filter →
  revenue projection (``v * (1 - d)``) → partial agg grouped by small
  keys.  Fusion-eligible end to end, so the planner emits ONE segment.
* ``run_fusion_scan_bench`` — scan-heavy scalar shape: selective filter
  + arithmetic projection feeding a global sum/count/min (no groups),
  many small batches — the dispatch-overhead-dominated profile where
  per-batch dispatch costs the most.

Both verify bit-identical results across the legs via a sha-256 row
fingerprint.  Runs on the CPU JAX backend (CI) and on chip unchanged.

Usage: via ``bench_suite.py fusion`` (measurement) or ``dev/tier1.sh
--bench-smoke`` (tiny-input identity/compile smoke via
:func:`run_fusion_smoke`, NOT a measurement).
"""

from __future__ import annotations

import hashlib
import time

import numpy as np
import pyarrow as pa

BASE = {
    "ballista.tpu.enable": "true",
    "ballista.tpu.min_rows": "0",
    # keep both legs off the device result cache: its retained path was
    # already one fused dispatch, and the A/B measures the GENERALIZED
    # fusion for non-cache-eligible stages
    "ballista.tpu.cache_columns": "false",
    # jax 0.4.37 in this image lacks shard_map; mesh stages cannot run
    "ballista.mesh.enable": "false",
    "ballista.shuffle.partitions": "1",
}

LEGS = {
    "fused": {"ballista.tpu.whole_stage_fusion": "true"},
    "per_op": {"ballista.tpu.whole_stage_fusion": "false"},
}

_METRIC_KEYS = (
    "fused_segments",
    "fused_ops_per_dispatch",
    "fused_dispatches",
    "fused_degraded",
    "device_time_ns",
    "bridge_time_ns",
    "tpu_stage_time_ns",
    "tpu_fallback",
)


def _canon(tbl: pa.Table):
    cols = [
        np.ascontiguousarray(c.to_numpy(zero_copy_only=False))
        for c in tbl.columns
    ]
    keys = [v for v in cols if v.dtype.kind != "f"]
    if not keys:  # scalar-agg shapes: single row, any order is total
        return cols
    order = np.lexsort(tuple(reversed(keys)))
    return [v[order] for v in cols]


def _fingerprint(tbl: pa.Table) -> str:
    """Order-independent sha of the EXACT row bytes (floats included
    bit-for-bit): equal fingerprints mean bit-identical results."""
    h = hashlib.sha256()
    for v in _canon(tbl):
        h.update(v.tobytes())
    return h.hexdigest()[:16]


def _collect_metrics(plan) -> dict:
    from arrow_ballista_tpu.ops.stage_compiler import TpuStageExec

    agg: dict = {}
    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, TpuStageExec):
            for k, v in node.metrics.values.items():
                agg[k] = agg.get(k, 0) + v
        stack.extend(node.children())
    return agg


def _run_leg(tables: dict, sql: str, settings: dict, batch_rows: int,
             iters: int):
    """(best_s, result table, last-iter stage metrics) for one config."""
    from arrow_ballista_tpu import BallistaConfig, SessionContext
    from arrow_ballista_tpu.catalog import MemoryTable

    ctx = SessionContext(
        BallistaConfig({**BASE, "ballista.batch.size": str(batch_rows),
                        **settings})
    )
    for name, t in tables.items():
        ctx.register_table(
            name,
            MemoryTable([t.to_batches(max_chunksize=batch_rows)], t.schema),
        )
    best = None
    out = None
    metrics: dict = {}
    for _ in range(iters):
        plan = ctx.sql(sql).physical_plan()
        t0 = time.perf_counter()
        out = ctx.execute(plan)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
        metrics = _collect_metrics(plan)
    return best, out, {
        k: metrics[k] for k in _METRIC_KEYS if k in metrics
    }


def _ab(tables: dict, sql: str, n_rows: int, metric: str,
        batch_rows: int, iters: int, extra: dict) -> dict:
    times: dict = {}
    outs: dict = {}
    mets: dict = {}
    for leg, settings in LEGS.items():
        times[leg], outs[leg], mets[leg] = _run_leg(
            tables, sql, settings, batch_rows, iters
        )
    # both legs run the SAME per-batch kernels and the same combine tree
    # (fusion changes how many dispatches carry them, not the math), so
    # the sha row fingerprints must match EXACTLY
    identical = _fingerprint(outs["fused"]) == _fingerprint(outs["per_op"])
    return {
        "metric": metric,
        "value": round(n_rows / times["fused"]),
        "unit": "rows/s",
        "vs_baseline": round(times["per_op"] / times["fused"], 3),
        "fused_s": round(times["fused"], 4),
        "per_op_s": round(times["per_op"], 4),
        "rows": n_rows,
        "identical": identical,
        "fused_metrics": mets["fused"],
        "per_op_metrics": mets["per_op"],
        **extra,
    }


def run_fusion_q3_bench(
    n_rows: int = 131_072,
    batch_rows: int = 4_096,
    iters: int = 3,
    seed: int = 7,
) -> dict:
    """q3's map-stage shape: date filter → revenue projection → grouped
    partial agg, in one fused segment.  Small batches on purpose — the
    per-batch leg pays one dispatch + one combine per batch, the fused
    leg pays one dispatch total (<= _FUSED_MAX_ENTRIES batches so the
    unroll discipline admits the whole partition)."""
    rng = np.random.default_rng(seed)
    t = pa.table({
        "p": pa.array(rng.integers(0, 7, n_rows).astype(np.int64)),
        "d": pa.array(rng.uniform(0, 0.1, n_rows)),
        "v": pa.array(rng.uniform(1, 100, n_rows)),
        "ship": pa.array(rng.integers(9000, 9400, n_rows).astype(np.int64)),
    })
    sql = (
        "select p, sum(v * (1 - d)) as revenue, count(*) as c "
        "from t where ship < 9200 group by p"
    )
    return _ab(
        {"t": t}, sql, n_rows, "fusion_q3_rows_per_sec", batch_rows,
        iters, {"shape": "q3_map"},
    )


def run_fusion_scan_bench(
    n_rows: int = 32_768,
    batch_rows: int = 1_024,
    iters: int = 3,
    seed: int = 11,
) -> dict:
    """Scan-heavy scalar shape: selective filter + projection into a
    global aggregate — no groups, dispatch overhead dominates."""
    rng = np.random.default_rng(seed)
    t = pa.table({
        "q": pa.array(rng.integers(1, 50, n_rows).astype(np.float64)),
        "v": pa.array(rng.uniform(-100, 100, n_rows)),
        "w": pa.array(rng.uniform(0, 1, n_rows)),
    })
    sql = (
        "select sum(v * w) as s, count(*) as c, min(v) as mn "
        "from t where q < 24"
    )
    return _ab(
        {"t": t}, sql, n_rows, "fusion_scan_rows_per_sec", batch_rows,
        iters, {"shape": "scan_heavy"},
    )


def run_fusion_smoke() -> dict:
    """Tiny-input smoke for dev/tier1.sh --bench-smoke: the fused and
    per-op legs must be BIT-identical, the fused leg must plan ONE
    segment covering >1 operator and execute it as ONE dispatch per task
    (zero host round-trips between fused ops — a second segment or a
    degrade counter would betray one), with no CPU fallback.  A
    compile/regression check, not a measurement."""
    q3 = run_fusion_q3_bench(n_rows=24_576, batch_rows=4_096, iters=1)
    scan = run_fusion_scan_bench(n_rows=24_576, batch_rows=4_096, iters=1)
    for rec in (q3, scan):
        assert rec["identical"], f"{rec['metric']}: legs diverged"
        fm = rec["fused_metrics"]
        # one segment, one dispatch: no host hop between fused operators
        assert fm.get("fused_segments", 0) == 1, fm
        assert fm.get("fused_ops_per_dispatch", 0) > 1, fm
        assert fm.get("fused_dispatches", 0) == 1, fm
        assert fm.get("fused_degraded", 0) == 0, fm
        assert fm.get("tpu_fallback", 0) == 0, fm
        # knob off: the planner never ran
        assert rec["per_op_metrics"].get("fused_segments", 0) == 0, rec
    return {
        "fusion_q3_vs_per_op": q3["vs_baseline"],
        "fusion_scan_vs_per_op": scan["vs_baseline"],
        "fused_ops_per_dispatch": (
            q3["fused_metrics"]["fused_ops_per_dispatch"]
        ),
        "identical": True,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_fusion_q3_bench()))
    print(json.dumps(run_fusion_scan_bench()))
