"""Shuffle write data-plane micro-benchmark.

Measures MB/s through the map-side write path — the pre-pipelining
baseline (argsort permutation + synchronous uncoalesced per-run sink
writes, ``ballista.shuffle.write_pipelined=false``) vs the slab-buffered
async writer pool — over a real multi-partition hash shuffle, no query
plan in the way.  Also reports the lz4/zstd compression ratio and the
fragment count per output partition (the baseline writes one IPC batch
per (input batch, output partition); the pipelined path coalesces to
``ballista.shuffle.write_coalesce_rows``).  Reported by
``bench_suite.py shuffle`` as ``shuffle_write_mb_per_sec`` and exercised
by ``tests/test_shuffle_writer.py``.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from arrow_ballista_tpu.exec.operators import ExecutionPlan, Partitioning


class _BatchesExec(ExecutionPlan):
    """Leaf yielding a fixed batch list — the bench controls batch
    structure exactly instead of inheriting a provider's chunking."""

    def __init__(self, batches: list[pa.RecordBatch]):
        super().__init__()
        self._batches = batches

    @property
    def schema(self) -> pa.Schema:
        return self._batches[0].schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def execute(self, partition: int, ctx) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        yield from iter(self._batches)

    def with_new_children(self, children):
        assert not children
        return self


def _make_batches(n_batches: int, rows_per_batch: int) -> list[pa.RecordBatch]:
    rng = np.random.default_rng(13)
    out = []
    for _ in range(n_batches):
        out.append(
            pa.record_batch(
                {
                    "k": pa.array(
                        rng.integers(0, 1 << 30, rows_per_batch), pa.int64()
                    ),
                    "a": pa.array(rng.normal(size=rows_per_batch)),
                    "b": pa.array(rng.normal(size=rows_per_batch)),
                }
            )
        )
    return out


def _run_leg(
    batches: list[pa.RecordBatch],
    n_out: int,
    work_dir: str,
    pipelined: bool,
    compression: str = "none",
) -> dict:
    """One write of every batch through a fresh ShuffleWriterExec;
    returns elapsed seconds, per-partition key multiset and stats."""
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.exec.expressions import Col
    from arrow_ballista_tpu.exec.operators import TaskContext
    from arrow_ballista_tpu.shuffle import ShuffleWriterExec

    writer = ShuffleWriterExec(
        "bench-write",
        1,
        _BatchesExec(batches),
        work_dir,
        Partitioning.hash((Col(0, "k"),), n_out),
    )
    ctx = TaskContext(
        config=BallistaConfig(
            {
                "ballista.shuffle.write_pipelined": str(pipelined).lower(),
                "ballista.shuffle.compression": compression,
            }
        ),
        work_dir=work_dir,
    )
    t0 = time.perf_counter()
    stats = writer.execute_shuffle_write(0, ctx)
    elapsed = time.perf_counter() - t0
    keys = []
    for s in stats:
        with pa.OSFile(s.path, "rb") as f:
            r = pa.ipc.open_file(f)
            for i in range(r.num_record_batches):
                keys.append(np.asarray(r.get_batch(i).column(0)))
    return {
        "elapsed_s": elapsed,
        "stats": stats,
        "keys": np.sort(np.concatenate(keys)) if keys else np.array([]),
        "metrics": writer.metrics.to_dict(),
    }


def run_write_bench(
    n_batches: int = 32,
    rows_per_batch: int = 65536,
    n_out: int = 8,
    compression: str = "none",
    iters: int = 3,
    work_dir: Optional[str] = None,
) -> dict:
    """Baseline vs pipelined write throughput + a compressed leg.

    Readback verifies the two paths produce identical per-partition row
    multisets; the returned fragment counts show the coalescing win
    (baseline: one fragment per input batch per partition)."""
    batches = _make_batches(n_batches, rows_per_batch)
    total_bytes = sum(b.nbytes for b in batches)
    total_mb = total_bytes / (1 << 20)

    def best(pipelined: bool, compression: str = "none") -> dict:
        out = None
        for _ in range(iters):
            with tempfile.TemporaryDirectory(
                prefix="shuffle-write-bench-", dir=work_dir
            ) as td:
                leg = _run_leg(batches, n_out, td, pipelined, compression)
            if out is None or leg["elapsed_s"] < out["elapsed_s"]:
                out = leg
        return out

    base = best(False)
    pipe = best(True)
    if not np.array_equal(base["keys"], pipe["keys"]):
        raise AssertionError(
            "baseline and pipelined write paths produced different rows"
        )
    comp = best(True, compression) if compression != "none" else None

    def frags(leg) -> int:
        return max(s.num_batches for s in leg["stats"])

    rec = {
        "total_mb": round(total_mb, 2),
        "n_batches": n_batches,
        "rows_per_batch": rows_per_batch,
        "n_out": n_out,
        "baseline_s": round(base["elapsed_s"], 4),
        "pipelined_s": round(pipe["elapsed_s"], 4),
        "baseline_mb_per_sec": round(total_mb / base["elapsed_s"], 2),
        "pipelined_mb_per_sec": round(total_mb / pipe["elapsed_s"], 2),
        "speedup": round(base["elapsed_s"] / pipe["elapsed_s"], 3),
        "fragments_per_partition_baseline": frags(base),
        "fragments_per_partition_pipelined": frags(pipe),
    }
    if comp is not None:
        raw = comp["metrics"].get("bytes_written_raw", 0)
        wire = comp["metrics"].get("bytes_written_wire", 0)
        rec.update(
            {
                "compression": compression,
                "compressed_mb_per_sec": round(
                    total_mb / comp["elapsed_s"], 2
                ),
                "compression_ratio": round(raw / wire, 3) if wire else None,
            }
        )
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run_write_bench(compression=os.environ.get(
        "BENCH_SHUFFLE_COMPRESSION", "zstd"
    )), indent=2))
