"""Shuffle fetch data-plane micro-benchmark.

Measures MB/s through the reduce-side read path — sequential
(location-by-location) vs the concurrent pipelined fetcher — over real
Arrow IPC partition files, no query plan in the way.  Reported by
``bench_suite.py shuffle`` as ``shuffle_fetch_mb_per_sec`` and exercised
tier-2 by ``tests/test_shuffle_fetch_bench.py`` (marked ``slow``).
"""

from __future__ import annotations

import os
import tempfile
import time

import numpy as np
import pyarrow as pa


def _make_partition_files(
    work_dir: str, n_locations: int, mb_per_location: float, batch_rows: int
):
    """One IPC file per map-side location, ~mb_per_location each."""
    from arrow_ballista_tpu.serde.scheduler_types import (
        ExecutorMetadata,
        PartitionId,
        PartitionLocation,
        PartitionStats,
    )

    rng = np.random.default_rng(11)
    schema = pa.schema(
        [
            pa.field("k", pa.int64()),
            pa.field("a", pa.float64()),
            pa.field("b", pa.float64()),
        ]
    )
    bytes_per_row = 24
    rows = max(batch_rows, int(mb_per_location * (1 << 20)) // bytes_per_row)
    meta = ExecutorMetadata("bench", "127.0.0.1", 1)
    locs = []
    total_bytes = 0
    for i in range(n_locations):
        path = os.path.join(work_dir, f"bench-loc-{i}.arrow")
        with pa.OSFile(path, "wb") as f:
            with pa.ipc.new_file(f, schema) as w:
                for lo in range(0, rows, batch_rows):
                    n = min(batch_rows, rows - lo)
                    w.write_batch(
                        pa.record_batch(
                            {
                                "k": pa.array(
                                    rng.integers(0, 1 << 30, n), pa.int64()
                                ),
                                "a": pa.array(rng.normal(size=n)),
                                "b": pa.array(rng.normal(size=n)),
                            },
                            schema=schema,
                        )
                    )
        total_bytes += os.path.getsize(path)
        locs.append(
            PartitionLocation(
                PartitionId("bench", 1, 0), meta, PartitionStats(rows, 1, 0), path
            )
        )
    return schema, locs, total_bytes


def run_fetch_bench(
    n_locations: int = 16,
    mb_per_location: float = 4.0,
    batch_rows: int = 65536,
    concurrency: int = 8,
    work_dir: str | None = None,
) -> dict:
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.exec.operators import TaskContext
    from arrow_ballista_tpu.shuffle import ShuffleReaderExec

    own_dir = None
    if work_dir is None:
        own_dir = tempfile.TemporaryDirectory(prefix="shuffle-fetch-bench-")
        work_dir = own_dir.name
    try:
        schema, locs, total_bytes = _make_partition_files(
            work_dir, n_locations, mb_per_location, batch_rows
        )

        def run(n_conc: int) -> float:
            ctx = TaskContext(
                config=BallistaConfig(
                    {"ballista.shuffle.fetch_concurrency": str(n_conc)}
                )
            )
            reader = ShuffleReaderExec(1, schema, [locs])
            t0 = time.perf_counter()
            rows = sum(b.num_rows for b in reader.execute(0, ctx))
            elapsed = time.perf_counter() - t0
            assert rows > 0
            return elapsed

        run(1)  # warm the page cache so both legs read warm files
        seq_s = run(1)
        conc_s = run(concurrency)
        total_mb = total_bytes / (1 << 20)
        return {
            "total_mb": round(total_mb, 2),
            "n_locations": n_locations,
            "concurrency": concurrency,
            "sequential_s": round(seq_s, 4),
            "pipelined_s": round(conc_s, 4),
            "sequential_mb_per_sec": round(total_mb / seq_s, 2),
            "pipelined_mb_per_sec": round(total_mb / conc_s, 2),
        }
    finally:
        if own_dir is not None:
            own_dir.cleanup()
